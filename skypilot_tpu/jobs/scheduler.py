"""Controller-side launch-slot scheduler.

Role of reference ``sky/jobs/scheduler.py`` (``:71``, slot caps
``:249-268``): provisioning a cluster is the expensive, bursty phase of a
managed job — cap how many controller processes may be launching at once
so a wave of submissions doesn't fork-bomb the controller host. Monitoring
(ALIVE) is cheap and uncapped.
"""
from __future__ import annotations

import contextlib
import os
import random
import time

from skypilot_tpu.jobs import state


def max_parallel_launches() -> int:
    return int(os.environ.get('SKYTPU_JOBS_MAX_PARALLEL_LAUNCHES', '8'))


@contextlib.contextmanager
def launch_slot(job_id: int, poll_seconds: float = 0.5):
    """Block until a launch slot is free, hold it for the with-body.

    Slot accounting lives in the state DB (schedule_state LAUNCHING),
    guarded by the DB file lock so concurrent controllers serialize.
    The slot check runs entirely under ``db_lock`` (count + set must be
    atomic — two controllers passing the count check together would
    both take the last slot); the sleep happens OUTSIDE it
    (graftcheck GC102), jittered so a burst of waiting controllers
    doesn't re-contend the file lock in lockstep every tick."""
    from skypilot_tpu import telemetry
    t0 = time.monotonic()
    while True:
        with state.db_lock():
            if state.count_in_launch_phase() < max_parallel_launches():
                state.set_schedule_state(job_id,
                                         state.ScheduleState.LAUNCHING)
                break
        time.sleep(poll_seconds * (0.5 + random.random()))
    # Slot-wait pressure: how long controllers queue behind the
    # parallel-launch cap (the autoscaling/capacity-planning signal).
    telemetry.get_registry().histogram(
        'skytpu_jobs_launch_slot_wait_seconds',
        'Wait for a controller launch slot',
        buckets=(.01, .1, .5, 1, 5, 15, 60, 300, 900)).observe(
            time.monotonic() - t0)
    try:
        yield
    finally:
        state.set_schedule_state(job_id, state.ScheduleState.ALIVE)
