"""Controller-side launch-slot scheduler.

Role of reference ``sky/jobs/scheduler.py`` (``:71``, slot caps
``:249-268``): provisioning a cluster is the expensive, bursty phase of a
managed job — cap how many controller processes may be launching at once
so a wave of submissions doesn't fork-bomb the controller host. Monitoring
(ALIVE) is cheap and uncapped.
"""
from __future__ import annotations

import contextlib
import os
import time

from skypilot_tpu.jobs import state


def max_parallel_launches() -> int:
    return int(os.environ.get('SKYTPU_JOBS_MAX_PARALLEL_LAUNCHES', '8'))


@contextlib.contextmanager
def launch_slot(job_id: int, poll_seconds: float = 0.5):
    """Block until a launch slot is free, hold it for the with-body.

    Slot accounting lives in the state DB (schedule_state LAUNCHING),
    guarded by the DB file lock so concurrent controllers serialize."""
    while True:
        with state.db_lock():
            if state.count_in_launch_phase() < max_parallel_launches():
                state.set_schedule_state(job_id,
                                         state.ScheduleState.LAUNCHING)
                break
        time.sleep(poll_seconds)
    try:
        yield
    finally:
        state.set_schedule_state(job_id, state.ScheduleState.ALIVE)
