"""Managed jobs: preemption-recovering jobs under a controller cluster
(reference ``sky/jobs/``)."""
from skypilot_tpu.jobs.core import (cancel, job_status, launch, logs, queue,
                                    tail_logs)
from skypilot_tpu.jobs.state import ManagedJobStatus

__all__ = ['launch', 'queue', 'job_status', 'cancel', 'logs', 'tail_logs',
           'ManagedJobStatus']
