"""Managed-jobs controller: one process per managed job, running ON the
jobs-controller cluster (as an ordinary agent job, so it gets logs/queue
for free — SURVEY key idea #2).

Role of reference ``sky/jobs/controller.py`` (``JobsController`` ``:50``,
``_run_one_task`` ``:116``, ``run`` ``:369``): launch the task cluster via
a recovery strategy, then poll the task's job status; distinguish *user
failure* (job FAILED on a healthy cluster) from *preemption* (cluster gone
or unreachable, or driver died) and recover the latter by relaunching —
the checkpoint contract (a MOUNT-mode bucket, or any stable path the task
resumes from) makes recovery resume-not-restart.
"""
from __future__ import annotations

import argparse
import os
import random
import sys
import time
import traceback
from typing import Optional

from skypilot_tpu import core
from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import tpu_logging
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.jobs import state
from skypilot_tpu.task import Task

logger = tpu_logging.init_logger(__name__)

# Task-job poll period (reference polls every ~30s; env-overridable so
# tests run fast).
JOB_STATUS_CHECK_GAP_SECONDS = float(
    os.environ.get('SKYTPU_JOBS_POLL', '15'))

_AGENT_TERMINAL_FAILED = ('FAILED',)
_AGENT_FAILED_SETUP = ('FAILED_SETUP',)
# FAILED_DRIVER means the head agent's driver died — host-level trouble,
# treated as preemption (relaunch), not user failure.
_AGENT_PREEMPTION_STATUSES = ('FAILED_DRIVER',)


def _best_effort_down(cluster_name: str) -> None:
    """Teardown after a terminal task status must not change the job's
    outcome — a cloud 5xx here would otherwise turn SUCCEEDED into
    FAILED_CONTROLLER."""
    try:
        core.down(cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Teardown of {cluster_name} failed (job outcome '
                       f'unchanged): {type(e).__name__}: {e}')


class JobsController:

    def __init__(self, job_id: int):
        self.job_id = job_id
        record = state.get_job(job_id)
        if record is None:
            raise exceptions.JobNotFoundError(
                f'managed job {job_id} not in state db')
        self.record = record
        dag_config = record['dag_config']
        self.tasks = [Task.from_yaml_config(tc)
                      for tc in dag_config['tasks']]
        self.name = record['name']

    # ------------------------------------------------------------ naming
    def task_cluster_name(self, task_idx: int) -> str:
        base = f'{self.name}-{self.job_id}'
        if len(self.tasks) > 1:
            base += f'-{task_idx}'
        return base

    # ------------------------------------------------------------ cancel
    def _check_cancel(self) -> None:
        if state.cancel_requested(self.job_id):
            raise exceptions.ServeUserTerminatedError('cancel requested')

    # ------------------------------------------------------------ monitor
    def _job_status_or_preemption(self, cluster_name: str,
                                  agent_job_id: int) -> Optional[str]:
        """Returns the agent job status, or None on *preemption* (cluster
        unreachable / gone / not UP). Reference discrimination logic:
        ``sky/jobs/controller.py:209-330``."""
        try:
            # fast=True: one RPC per poll tick; an RPC failure routes
            # into the full health/preemption discrimination below.
            return core.job_status(cluster_name, agent_job_id, fast=True)
        except Exception as e:  # pylint: disable=broad-except
            logger.info(f'Status poll on {cluster_name} failed '
                        f'({type(e).__name__}: {e}); checking cluster '
                        'health.')
        # The poll failed — consult cloud truth before declaring
        # preemption (transient SSH hiccups must not trigger relaunch).
        from skypilot_tpu.backend import backend_utils
        try:
            record, _ = backend_utils.refresh_cluster_status(cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.info(f'Status refresh of {cluster_name} failed '
                        f'({type(e).__name__}: {e}); treating as '
                        'preemption.')
            return None
        if record is None or record['status'] != \
                global_state.ClusterStatus.UP:
            return None
        # Cluster looks UP; retry the poll once before giving up on it.
        try:
            return core.job_status(cluster_name, agent_job_id)
        except Exception as e:  # pylint: disable=broad-except
            logger.info(f'Retried status poll on {cluster_name} failed '
                        f'({type(e).__name__}: {e}); treating as '
                        'preemption.')
            return None

    def _run_one_task(self, task_idx: int, task: Task) -> bool:
        """Launch + monitor + recover one task. True = SUCCEEDED."""
        cluster_name = self.task_cluster_name(task_idx)
        strategy = recovery_strategy.StrategyExecutor.make(
            cluster_name, task)

        state.set_status(self.job_id, state.ManagedJobStatus.STARTING)
        with scheduler.launch_slot(self.job_id):
            agent_job_id = strategy.launch()
        state.set_task_cluster(self.job_id, task_idx, cluster_name,
                               agent_job_id)
        state.set_status(self.job_id, state.ManagedJobStatus.RUNNING)

        while True:
            self._check_cancel()
            status = self._job_status_or_preemption(cluster_name,
                                                    agent_job_id)
            if status == 'SUCCEEDED':
                _best_effort_down(cluster_name)
                return True
            if status in _AGENT_TERMINAL_FAILED:
                state.set_status(
                    self.job_id, state.ManagedJobStatus.FAILED,
                    failure_reason=self._failure_tail(cluster_name,
                                                      agent_job_id))
                _best_effort_down(cluster_name)
                return False
            if status in _AGENT_FAILED_SETUP:
                state.set_status(
                    self.job_id, state.ManagedJobStatus.FAILED_SETUP,
                    failure_reason=self._failure_tail(cluster_name,
                                                      agent_job_id))
                _best_effort_down(cluster_name)
                return False
            if status == 'CANCELLED':
                # Cancelled out-of-band on the task cluster: honor it.
                state.set_status(self.job_id,
                                 state.ManagedJobStatus.CANCELLED)
                _best_effort_down(cluster_name)
                return False
            if status is None or status in _AGENT_PREEMPTION_STATUSES:
                logger.info(
                    f'Preemption/failure of {cluster_name} detected '
                    f'(status={status}); recovering.')
                state.set_recovering(self.job_id)
                with scheduler.launch_slot(self.job_id):
                    agent_job_id = strategy.recover()
                state.set_task_cluster(self.job_id, task_idx,
                                       cluster_name, agent_job_id)
                state.set_recovered(self.job_id)
                continue
            # PENDING/STARTING/RUNNING: keep polling — jittered
            # (graftcheck GC112) so many concurrent job controllers
            # don't hit the agent RPC in lockstep.
            time.sleep(JOB_STATUS_CHECK_GAP_SECONDS
                       * (0.75 + random.random() * 0.5))

    def _failure_tail(self, cluster_name: str, agent_job_id: int) -> str:
        try:
            from skypilot_tpu.backend import tpu_backend
            handle = global_state.get_handle_from_cluster_name(cluster_name)
            if handle is None:
                return ''
            backend = tpu_backend.TpuVmBackend()
            return backend.get_job_logs(handle, agent_job_id, tail=20)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'Could not fetch failure-log tail from '
                         f'{cluster_name}: {type(e).__name__}: {e}')
            return ''

    # ------------------------------------------------------------ run
    def run(self) -> None:
        """Run the task chain (reference ``JobsController.run`` ``:369``)."""
        final: Optional[state.ManagedJobStatus] = None
        reason: Optional[str] = None
        try:
            for task_idx, task in enumerate(self.tasks):
                self._check_cancel()
                if not self._run_one_task(task_idx, task):
                    return          # terminal status already recorded
            final = state.ManagedJobStatus.SUCCEEDED
        except exceptions.ServeUserTerminatedError:
            self._cleanup_current_cluster()
            final = state.ManagedJobStatus.CANCELLED
        except exceptions.ManagedJobReachedMaxRetriesError as e:
            final = state.ManagedJobStatus.FAILED_NO_RESOURCE
            reason = str(e)
        except Exception:  # pylint: disable=broad-except
            traceback.print_exc()
            self._cleanup_current_cluster()
            final = state.ManagedJobStatus.FAILED_CONTROLLER
            reason = traceback.format_exc()
        finally:
            if final is not None:
                state.set_status(self.job_id, final,
                                 failure_reason=reason)

    def _cleanup_current_cluster(self) -> None:
        record = state.get_job(self.job_id)
        if record and record['cluster_name']:
            _best_effort_down(record['cluster_name'])


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    args = parser.parse_args()
    state.set_status(args.job_id, state.ManagedJobStatus.SUBMITTED)
    controller = JobsController(args.job_id)
    controller.run()
    # Controllers exit 0 even when the *job* failed: the controller itself
    # did its work; the managed-job status carries the outcome.
    sys.exit(0)


if __name__ == '__main__':
    main()
