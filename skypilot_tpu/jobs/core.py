"""Managed jobs client API: ``jobs.launch/queue/cancel/logs``.

Role of reference ``sky/jobs/core.py`` (``launch`` ``:39``): wrap the user
dag, ensure the jobs-controller cluster is up (an ordinary cluster — the
whole stack recursively, SURVEY key idea #2), and queue a controller
process there via the jobs RPC.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_state
from skypilot_tpu import tpu_logging
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)

CONTROLLER_CLUSTER_NAME = 'skytpu-jobs-controller'


def _to_dag(task_or_dag: Union[Task, Dag]) -> Dag:
    if isinstance(task_or_dag, Dag):
        return task_or_dag
    dag = Dag(name=task_or_dag.name)
    dag.add(task_or_dag)
    return dag


def _controller_resources(dag: Dag) -> Resources:
    """Controller sizing: config override, else a small CPU VM on the same
    cloud as the first task (so local tasks get a local controller —
    reference ``controller_utils.get_controller_resources``)."""
    cfg = config_lib.get_nested(('jobs', 'controller', 'resources'), None)
    if cfg:
        return Resources.from_yaml_config(dict(cfg))
    first = dag.topological_order()[0]
    cloud = None
    for res in first.resources:
        if res.cloud:
            cloud = res.cloud
            break
    return Resources(cloud=cloud or 'gcp', cpus='4+')


def _ensure_controller(dag: Dag) -> Any:
    """Launch (or reuse) the controller cluster; returns its handle."""
    record = global_state.get_cluster_from_name(CONTROLLER_CLUSTER_NAME)
    if record is not None and record['handle'] is not None:
        from skypilot_tpu.backend import backend_utils
        rec, handle = backend_utils.refresh_cluster_status(
            CONTROLLER_CLUSTER_NAME)
        if (rec is not None and handle is not None
                and rec['status'] == global_state.ClusterStatus.UP):
            return handle
    controller_task = Task(name='jobs-controller')
    controller_task.set_resources(_controller_resources(dag))
    _, handle = execution.launch(controller_task,
                                 cluster_name=CONTROLLER_CLUSTER_NAME,
                                 detach_run=True, stream_logs=False)
    return handle


def _controller_request(handle, request: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.provision import provisioner
    return provisioner.agent_request(handle.head_runner(), request,
                                     module='skypilot_tpu.jobs.rpc',
                                     error_cls=exceptions.ApiError)


def _get_controller_handle() -> Any:
    record = global_state.get_cluster_from_name(CONTROLLER_CLUSTER_NAME)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterNotUpError(
            'No jobs controller is running (no managed jobs launched '
            'yet, or it was torn down).')
    return record['handle']


# ------------------------------------------------------------------- API
def launch(task_or_dag: Union[Task, Dag],
           name: Optional[str] = None) -> int:
    """Submit a managed job; returns the managed job id.

    The job runs under a controller that recovers it from preemptions
    (reference ``sky.jobs.launch`` ``sky/jobs/core.py:39``)."""
    dag = _to_dag(task_or_dag)
    if not dag.is_chain():
        raise exceptions.InvalidDagError(
            'Managed jobs support chain dags only (reference parity).')
    tasks = dag.topological_order()
    for t in tasks:
        if t.run is not None and not isinstance(t.run, str):
            raise exceptions.InvalidTaskError(
                'Managed-job tasks must have string run commands.')
    job_name = name or dag.name or tasks[0].name or 'managed'
    # The controller may live on another machine: client-local
    # workdir/file_mounts must be uploaded to a bucket and the dag
    # rewritten to pull from it (reference
    # ``sky/utils/controller_utils.py:663``).
    from skypilot_tpu.utils import controller_utils
    run_timestamp = common_utils.make_run_timestamp()
    controller_utils.translate_local_file_mounts(dag, job_name,
                                                 run_timestamp)
    dag_config = {
        'name': job_name,
        'tasks': [t.to_yaml_config() for t in tasks],
    }
    handle = _ensure_controller(dag)
    resp = _controller_request(handle, {
        'op': 'queue',
        'name': dag_config['name'],
        'username': common_utils.get_cleaned_username(),
        'run_timestamp': run_timestamp,
        'dag_config': dag_config,
    })
    job_id = int(resp['job_id'])
    logger.info(f'Managed job {job_id} ({dag_config["name"]}) submitted.')
    return job_id


def queue(refresh: bool = False) -> List[Dict[str, Any]]:
    """Managed-job table (reference ``sky jobs queue``)."""
    del refresh
    handle = _get_controller_handle()
    return _controller_request(handle, {'op': 'job_table'})['jobs']


def job_status(job_id: int) -> Optional[str]:
    handle = _get_controller_handle()
    return _controller_request(
        handle, {'op': 'job_status', 'job_id': job_id})['status']


def cancel(job_id: int) -> bool:
    """Request cancellation; the controller tears the task cluster down
    (reference signal-based cancel ``sky/jobs/controller.py:446``)."""
    handle = _get_controller_handle()
    return _controller_request(
        handle, {'op': 'cancel', 'job_id': job_id})['cancelled']


def logs(job_id: int, tail: int = 0) -> str:
    """Controller-process log for the job (launch/monitor/recovery
    trace)."""
    handle = _get_controller_handle()
    return _controller_request(
        handle, {'op': 'logs', 'job_id': job_id, 'tail': tail})['logs']


def tail_logs(job_id: int, follow: bool = True) -> None:
    """Stream the controller log for a managed job."""
    from skypilot_tpu.backend import tpu_backend
    handle = _get_controller_handle()
    backend = tpu_backend.TpuVmBackend()
    for j in backend.get_job_queue(handle):
        if j['name'] == f'controller-{job_id}':
            backend.tail_logs(handle, j['job_id'], follow=follow)
            return
    raise exceptions.JobNotFoundError(
        f'No controller job found for managed job {job_id}.')
