"""Recovery strategies: how a managed job (re)launches its task cluster.

Role of reference ``sky/jobs/recovery_strategy.py`` (``StrategyExecutor``
``:46``, ``FailoverStrategyExecutor`` ``:388``,
``EagerFailoverStrategyExecutor`` ``:471``). The launch path already
failovers across zones/regions internally (the backend's blocklist +
re-optimize loop), so the strategy layer decides only what to do *after a
preemption*: retry in place first (FAILOVER) or immediately move on
(EAGER_NEXT_REGION).
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Type

from skypilot_tpu import core
from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_state
from skypilot_tpu import tpu_logging
from skypilot_tpu.task import Task

logger = tpu_logging.init_logger(__name__)

RECOVERY_STRATEGIES: Dict[str, Type['StrategyExecutor']] = {}
DEFAULT_RECOVERY_STRATEGY = 'FAILOVER'

# Max consecutive launch attempts before the job is declared
# FAILED_NO_RESOURCE (each attempt itself failovers across all candidate
# zones/regions; reference ``_MAX_RETRY_CNT`` semantics).
MAX_LAUNCH_RETRIES = 3
LAUNCH_RETRY_GAP_SECONDS = 5.0


def _register(name: str):
    def deco(cls):
        RECOVERY_STRATEGIES[name] = cls
        cls.NAME = name
        return cls
    return deco


class StrategyExecutor:
    """Launch/recover the cluster for one task of a managed job."""

    NAME = 'base'

    def __init__(self, cluster_name: str, task: Task,
                 retry_until_up: bool = False):
        self.cluster_name = cluster_name
        self.task = task
        self.retry_until_up = retry_until_up

    @classmethod
    def make(cls, cluster_name: str, task: Task) -> 'StrategyExecutor':
        name = None
        for res in task.resources:
            if res.spot_recovery is not None:
                name = str(res.spot_recovery).upper()
                break
        name = name or DEFAULT_RECOVERY_STRATEGY
        if name not in RECOVERY_STRATEGIES:
            raise exceptions.InvalidTaskError(
                f'Unknown recovery strategy {name!r}; available: '
                f'{sorted(RECOVERY_STRATEGIES)}')
        return RECOVERY_STRATEGIES[name](cluster_name, task)

    # ------------------------------------------------------------ launch
    def launch(self) -> int:
        """First launch. Returns the agent job id on the task cluster."""
        job_id = self._launch_with_retries()
        if job_id is None:
            raise exceptions.ManagedJobReachedMaxRetriesError(
                f'Failed to launch {self.cluster_name} after '
                f'{MAX_LAUNCH_RETRIES} attempts (each attempt tried every '
                'candidate zone/region).')
        return job_id

    def recover(self) -> int:
        """Relaunch after a preemption; returns the new agent job id."""
        raise NotImplementedError

    # ------------------------------------------------------------ helpers
    def _launch_once(self) -> Optional[int]:
        try:
            job_id, _ = execution.launch(
                self.task, cluster_name=self.cluster_name,
                detach_run=True, stream_logs=False,
                retry_until_up=False)
            return job_id
        except (exceptions.ResourcesUnavailableError,
                exceptions.ProvisionError) as e:
            logger.warning(f'Launch attempt for {self.cluster_name} '
                           f'failed: {e}')
            return None

    def _launch_with_retries(self,
                             max_retries: int = MAX_LAUNCH_RETRIES
                             ) -> Optional[int]:
        gap = LAUNCH_RETRY_GAP_SECONDS
        attempts = 0
        while True:
            attempts += 1
            job_id = self._launch_once()
            if job_id is not None:
                return job_id
            if not self.retry_until_up and attempts >= max_retries:
                return None
            logger.info(f'Retrying launch of {self.cluster_name} in '
                        f'{gap:.0f}s (attempt {attempts}).')
            time.sleep(gap)
            gap = min(gap * 2, 300)

    def _terminate_cluster(self) -> None:
        """Best-effort teardown of the (possibly half-dead) task cluster."""
        try:
            record = global_state.get_cluster_from_name(self.cluster_name)
            if record is not None:
                core.down(self.cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'teardown of {self.cluster_name} during recovery '
                         f'failed (continuing): {e}')

    def _resubmit_on_existing(self) -> Optional[int]:
        """If the cluster still exists and is UP (e.g. only the job died,
        or a same-cluster restart succeeded), re-exec the task on it."""
        from skypilot_tpu.backend import backend_utils
        try:
            record, handle = backend_utils.refresh_cluster_status(
                self.cluster_name)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'Status refresh of {self.cluster_name} failed '
                         f'({type(e).__name__}: {e}); relaunching '
                         'instead of reusing.')
            return None
        if record is None or handle is None:
            return None
        if record['status'] != global_state.ClusterStatus.UP:
            return None
        try:
            # Cancel any still-running copy first: a false-positive
            # preemption (transient poll failure) must not end up with two
            # concurrent copies of the task contending for the chips.
            from skypilot_tpu.backend import tpu_backend
            tpu_backend.TpuVmBackend().cancel_jobs(handle, None)
            job_id, _ = execution.exec_cmd(self.task, self.cluster_name,
                                           detach_run=True)
            return job_id
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'resubmit on existing {self.cluster_name} '
                         f'failed: {e}')
            return None


@_register('FAILOVER')
class FailoverStrategyExecutor(StrategyExecutor):
    """Try the same cluster first (the preemption may be transient /
    partial), then terminate and relaunch — the relaunch itself walks the
    zone→region→cloud failover (reference ``FailoverStrategyExecutor``
    ``sky/jobs/recovery_strategy.py:388``)."""

    def recover(self) -> int:
        job_id = self._resubmit_on_existing()
        if job_id is not None:
            return job_id
        self._terminate_cluster()
        job_id = self._launch_with_retries()
        if job_id is None:
            raise exceptions.ManagedJobReachedMaxRetriesError(
                f'Recovery of {self.cluster_name} exhausted all candidate '
                'resources.')
        return job_id


@_register('EAGER_NEXT_REGION')
class EagerFailoverStrategyExecutor(StrategyExecutor):
    """Skip the same-cluster retry: terminate immediately and relaunch
    elsewhere. Best when same-zone re-preemption is likely (reference
    ``EagerFailoverStrategyExecutor`` ``:471``)."""

    def recover(self) -> int:
        self._terminate_cluster()
        job_id = self._launch_with_retries()
        if job_id is None:
            raise exceptions.ManagedJobReachedMaxRetriesError(
                f'Recovery of {self.cluster_name} exhausted all candidate '
                'resources.')
        return job_id
