"""Managed-job state machine + SQLite table (lives on the controller).

Role of reference ``sky/jobs/state.py`` (``ManagedJobStatus`` ``:186``,
``ManagedJobScheduleState`` ``:312``): one row per managed job, written by
the controller process and read by the client via the jobs RPC. TPU-first
simplification: one DB file under the controller host's HOME; pipeline
(chain-dag) jobs advance ``task_idx`` through the same row.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

import filelock


class ManagedJobStatus(enum.Enum):
    """Managed-job lifecycle (reference ``sky/jobs/state.py:186``).

    Terminal: SUCCEEDED / FAILED / FAILED_SETUP / FAILED_NO_RESOURCE /
    FAILED_CONTROLLER / CANCELLED.
    """
    PENDING = 'PENDING'            # queued, controller not started yet
    SUBMITTED = 'SUBMITTED'        # controller process scheduled
    STARTING = 'STARTING'          # provisioning the task cluster
    RUNNING = 'RUNNING'            # task job running
    RECOVERING = 'RECOVERING'      # preemption detected; relaunching
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'              # user code failed
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'   # exhausted all candidates
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'     # controller crashed
    CANCELLING = 'CANCELLING'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in _TERMINAL


_TERMINAL = {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
             ManagedJobStatus.FAILED_SETUP,
             ManagedJobStatus.FAILED_NO_RESOURCE,
             ManagedJobStatus.FAILED_CONTROLLER,
             ManagedJobStatus.CANCELLED}


class ScheduleState(enum.Enum):
    """Controller-process scheduling state (reference
    ``ManagedJobScheduleState`` ``sky/jobs/state.py:312``): caps how many
    controller processes may be inside their launch phase at once."""
    WAITING = 'WAITING'            # queued for a launch slot
    LAUNCHING = 'LAUNCHING'        # holds a launch slot
    ALIVE = 'ALIVE'                # running/monitoring (slot released)
    DONE = 'DONE'


def jobs_dir() -> str:
    d = os.environ.get('SKYTPU_MANAGED_JOBS_DIR',
                       os.path.expanduser('~/.skytpu_managed_jobs'))
    os.makedirs(d, exist_ok=True)
    return d


def _db_path() -> str:
    return os.path.join(jobs_dir(), 'state.db')


def db_lock() -> filelock.FileLock:
    return filelock.FileLock(os.path.join(jobs_dir(), '.state.lock'))


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(), timeout=10)
    # WAL + busy timeout (round 15, mirrors serve_state): a restarted
    # controller racing a straggler writer gets a bounded retry
    # instead of 'database is locked'.
    conn.execute('PRAGMA busy_timeout=10000')
    try:
        conn.execute('PRAGMA journal_mode=WAL')
    except sqlite3.OperationalError:
        pass      # exotic filesystems without WAL: keep the default
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS managed_jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            dag_config TEXT,
            status TEXT,
            schedule_state TEXT,
            task_idx INTEGER DEFAULT 0,
            num_tasks INTEGER DEFAULT 1,
            cluster_name TEXT,
            agent_job_id INTEGER,
            run_timestamp TEXT,
            submitted_at REAL,
            start_at REAL,
            end_at REAL,
            last_recovered_at REAL,
            recovery_count INTEGER DEFAULT 0,
            failure_reason TEXT,
            cancel_requested INTEGER DEFAULT 0)""")
    conn.commit()
    return conn


_FIELDS = ('job_id', 'name', 'dag_config', 'status', 'schedule_state',
           'task_idx', 'num_tasks', 'cluster_name', 'agent_job_id',
           'run_timestamp', 'submitted_at', 'start_at', 'end_at',
           'last_recovered_at', 'recovery_count', 'failure_reason',
           'cancel_requested')


def _row_to_record(row) -> Dict[str, Any]:
    rec = dict(zip(_FIELDS, row))
    rec['status'] = ManagedJobStatus(rec['status'])
    rec['schedule_state'] = ScheduleState(rec['schedule_state'])
    rec['dag_config'] = (json.loads(rec['dag_config'])
                         if rec['dag_config'] else None)
    rec['cancel_requested'] = bool(rec['cancel_requested'])
    return rec


def add_job(name: str, dag_config: Dict[str, Any], num_tasks: int,
            run_timestamp: str) -> int:
    conn = _conn()
    with conn:
        cur = conn.execute(
            'INSERT INTO managed_jobs (name, dag_config, status, '
            'schedule_state, num_tasks, run_timestamp, submitted_at) '
            'VALUES (?,?,?,?,?,?,?)',
            (name, json.dumps(dag_config), ManagedJobStatus.PENDING.value,
             ScheduleState.WAITING.value, num_tasks, run_timestamp,
             time.time()))
        job_id = cur.lastrowid
    conn.close()
    return int(job_id)


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    conn = _conn()
    row = conn.execute(
        f'SELECT {", ".join(_FIELDS)} FROM managed_jobs WHERE job_id=?',
        (job_id,)).fetchone()
    conn.close()
    return _row_to_record(row) if row else None


def get_jobs(statuses: Optional[List[ManagedJobStatus]] = None
             ) -> List[Dict[str, Any]]:
    conn = _conn()
    q = f'SELECT {", ".join(_FIELDS)} FROM managed_jobs'
    args: tuple = ()
    if statuses:
        q += ' WHERE status IN (' + ','.join('?' * len(statuses)) + ')'
        args = tuple(s.value for s in statuses)
    q += ' ORDER BY job_id DESC'
    rows = conn.execute(q, args).fetchall()
    conn.close()
    return [_row_to_record(r) for r in rows]


def _update(job_id: int, **cols: Any) -> None:
    conn = _conn()
    with conn:
        sets = ', '.join(f'{k}=?' for k in cols)
        conn.execute(f'UPDATE managed_jobs SET {sets} WHERE job_id=?',
                     (*cols.values(), job_id))
    conn.close()


def set_status(job_id: int, status: ManagedJobStatus,
               failure_reason: Optional[str] = None) -> None:
    # One transition counter per target status in the shared process
    # registry — the jobs controller's state machine becomes visible
    # on the telemetry surface (dashboard /metrics) without parsing
    # logs.
    from skypilot_tpu import telemetry
    telemetry.get_registry().counter(
        'skytpu_jobs_transitions_total',
        'Managed-job status transitions', to=status.value).inc()
    cols: Dict[str, Any] = {'status': status.value}
    if status == ManagedJobStatus.RUNNING:
        record = get_job(job_id)
        if record and record['start_at'] is None:
            cols['start_at'] = time.time()
    if status.is_terminal():
        cols['end_at'] = time.time()
        cols['schedule_state'] = ScheduleState.DONE.value
    if failure_reason is not None:
        cols['failure_reason'] = failure_reason[-2000:]
    _update(job_id, **cols)


def get_status(job_id: int) -> Optional[ManagedJobStatus]:
    record = get_job(job_id)
    return record['status'] if record else None


def set_schedule_state(job_id: int, state: ScheduleState) -> None:
    _update(job_id, schedule_state=state.value)


def count_in_launch_phase() -> int:
    """Jobs currently holding a launch slot (LAUNCHING)."""
    conn = _conn()
    n = conn.execute(
        'SELECT COUNT(*) FROM managed_jobs WHERE schedule_state=?',
        (ScheduleState.LAUNCHING.value,)).fetchone()[0]
    conn.close()
    return int(n)


def set_task_cluster(job_id: int, task_idx: int, cluster_name: str,
                     agent_job_id: Optional[int]) -> None:
    _update(job_id, task_idx=task_idx, cluster_name=cluster_name,
            agent_job_id=agent_job_id)


def set_recovering(job_id: int) -> None:
    record = get_job(job_id)
    _update(job_id, status=ManagedJobStatus.RECOVERING.value,
            recovery_count=(record['recovery_count'] + 1 if record else 1))


def set_recovered(job_id: int) -> None:
    _update(job_id, status=ManagedJobStatus.RUNNING.value,
            last_recovered_at=time.time())


def request_cancel(job_id: int) -> bool:
    record = get_job(job_id)
    if record is None or record['status'].is_terminal():
        return False
    _update(job_id, cancel_requested=1)
    return True


def cancel_requested(job_id: int) -> bool:
    record = get_job(job_id)
    return bool(record and record['cancel_requested'])


def record_to_json(record: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(record)
    out['status'] = record['status'].value
    out['schedule_state'] = record['schedule_state'].value
    return out
