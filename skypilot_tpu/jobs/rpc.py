"""Managed-jobs RPC: runs on the controller cluster's head, driven by the
client via the command runner (same fixed-command-surface pattern as
:mod:`skypilot_tpu.agent.rpc`; replaces reference ``ManagedJobCodeGen``
``sky/jobs/utils.py:1121``).

Ops: queue (add job + submit controller process to the agent),
job_table, job_status, cancel, logs.
"""
from __future__ import annotations

import json
import sys
from typing import Any, Dict

from skypilot_tpu.agent import job_lib as agent_job_lib
from skypilot_tpu.agent import log_lib as agent_log_lib
from skypilot_tpu.jobs import state

PAYLOAD_PREFIX = 'SKYTPU_RPC_PAYLOAD:'


def _ok(**kwargs) -> Dict[str, Any]:
    return {'ok': True, **kwargs}


def _reconcile_dead_controllers() -> None:
    """A controller process that died uncleanly (OOM/SIGKILL) leaves its
    managed job non-terminal and may leak its LAUNCHING slot forever. The
    agent layer already marks the controller's agent job terminal
    (FAILED_DRIVER via pid-liveness); map that back to the managed-job
    table here, on every client poll (reference: skylet's
    ``ManagedJobEvent`` reconciles dead controllers,
    ``sky/skylet/events.py:72``)."""
    nonterminal = [r for r in state.get_jobs()
                   if not r['status'].is_terminal()]
    if not nonterminal:
        return
    agent_jobs = {j['name']: j for j in agent_job_lib.get_jobs()}
    for rec in nonterminal:
        agent_job = agent_jobs.get(f'controller-{rec["job_id"]}')
        if agent_job is None:
            continue   # queued but not yet visible; leave it
        if agent_job['status'].is_terminal() and \
                agent_job['status'].value != 'SUCCEEDED':
            state.set_status(
                rec['job_id'], state.ManagedJobStatus.FAILED_CONTROLLER,
                failure_reason=(f'controller process ended with '
                                f'{agent_job["status"].value}'))


def handle(request: Dict[str, Any]) -> Dict[str, Any]:
    op = request.get('op')
    if op == 'queue':
        dag_config = request['dag_config']
        run_timestamp = request['run_timestamp']
        name = request.get('name') or 'managed'
        job_id = state.add_job(name, dag_config,
                               num_tasks=len(dag_config['tasks']),
                               run_timestamp=run_timestamp)
        # The controller process runs as an ordinary agent job on this
        # cluster — logs/queue/liveness for free.
        agent_job_id = agent_job_lib.add_job(
            name=f'controller-{job_id}',
            username=request.get('username') or 'unknown',
            run_timestamp=run_timestamp,
            resources_str='controller',
            spec={
                'run': (f'{sys.executable} -m skypilot_tpu.jobs.controller '
                        f'--job-id {job_id}'),
                'env': {},
                'workdir_target': None,
                # Controller process is control plane: no accelerator
                # runtime env (it must not claim the chip).
                'control_plane': True,
            })
        agent_job_lib.schedule_step()
        return _ok(job_id=job_id, agent_job_id=agent_job_id)
    if op == 'job_table':
        _reconcile_dead_controllers()
        jobs = [state.record_to_json(r) for r in state.get_jobs()]
        return _ok(jobs=jobs)
    if op == 'job_status':
        _reconcile_dead_controllers()
        status = state.get_status(int(request['job_id']))
        return _ok(status=status.value if status else None)
    if op == 'cancel':
        return _ok(cancelled=state.request_cancel(int(request['job_id'])))
    if op == 'logs':
        # Controller-process log (launch/monitor/recovery trace). The
        # controller runs as agent job `controller-<id>`; find it by name.
        job_id = int(request['job_id'])
        for j in agent_job_lib.get_jobs():
            if j['name'] == f'controller-{job_id}':
                text = agent_log_lib.read_job_logs(
                    j['job_id'], tail=int(request.get('tail', 0)))
                return _ok(logs=text)
        return _ok(logs='')
    raise ValueError(f'Unknown jobs RPC op: {op!r}')


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == '--serve':
        # Persistent stdio channel (agent/channel.py): same wire
        # protocol as the agent RPC's --serve loop.
        from skypilot_tpu.agent import rpc as agent_rpc
        agent_rpc.serve(handle)
        return
    raw = sys.argv[1] if len(sys.argv) > 1 else sys.stdin.read()
    request = json.loads(raw)
    try:
        response = handle(request)
    except Exception as e:  # pylint: disable=broad-except
        response = {'ok': False, 'error': f'{type(e).__name__}: {e}'}
    print(PAYLOAD_PREFIX + json.dumps(response))


if __name__ == '__main__':
    main()
