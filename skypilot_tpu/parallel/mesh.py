"""Device mesh + logical-axis sharding rules.

This is the TPU-native replacement for the reference's delegated parallelism
(SURVEY.md §2.3/§2.4): instead of exporting torchrun/NCCL env vars for an
external framework, the in-tree engines shard over a `jax.sharding.Mesh` and
let XLA insert ICI/DCN collectives.

Axes (any may be size 1):
  slice : outer data-parallel axis across pod slices (DCN; multislice)
  pp    : pipeline parallel (layer stack split into stages; GPipe
          microbatching in parallel/pipeline.py)
  dp    : data parallel (pure replication of params)
  fsdp  : fully-sharded data parallel (params sharded, gathered per layer)
  sp    : sequence/context parallel (ring attention partitions the sequence)
  tp    : tensor parallel (heads/mlp sharded; collectives per layer)
  ep    : expert parallel (MoE experts sharded)

``ep`` is folded over ``fsdp×sp`` at use-site (MoE layers reshape), keeping
the physical mesh 6-D and collectives on ICI neighbors.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ('slice', 'pp', 'dp', 'fsdp', 'sp', 'tp')


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Product must equal the device count."""
    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    num_slices: int = 1
    pp: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.num_slices, self.pp, self.dp, self.fsdp, self.sp,
                self.tp)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    @classmethod
    def auto(cls, num_devices: int, *, num_slices: int = 1,
             tp: Optional[int] = None, sp: int = 1) -> 'MeshSpec':
        """Default: everything not TP/SP goes to FSDP (ZeRO-3-style), the
        dominant TPU training layout. TP defaults to 1 within reason — FSDP
        over fast ICI usually wins until per-chip batch gets tiny."""
        per_slice = num_devices // num_slices
        if num_devices % num_slices:
            raise ValueError(f'{num_devices} devices not divisible into '
                             f'{num_slices} slices')
        tp = tp or 1
        if per_slice % (tp * sp):
            raise ValueError(f'tp*sp={tp * sp} must divide per-slice device '
                             f'count {per_slice}')
        return cls(dp=1, fsdp=per_slice // (tp * sp), sp=sp, tp=tp,
                   num_slices=num_slices)

    @classmethod
    def for_serving(cls, tp: int = 1, dp: int = 1) -> 'MeshSpec':
        """The serving layout: params/KV heads sharded over ``tp``
        (innermost — collectives on nearest-neighbor ICI), the decode
        batch replicated-or-sharded over ``dp``. No fsdp/sp/pp —
        inference keeps whole layers resident and decode reads are
        latency-bound, so the only profitable axes are tensor split
        (TPOT) and batch split (tok/s)."""
        if tp < 1 or dp < 1:
            raise ValueError(f'tp/dp must be >= 1, got tp={tp} dp={dp}')
        return cls(dp=dp, tp=tp)


def spec_from_env(*, tp: Optional[int] = None, sp: int = 1,
                  num_devices: Optional[int] = None) -> MeshSpec:
    """MeshSpec honoring the launch env contract: SKYTPU_NUM_SLICES (set
    by the job driver from the provisioned topology) becomes the DCN
    mesh axis. Falls back to a single slice outside a launched job."""
    import os
    num_slices = int(os.environ.get('SKYTPU_NUM_SLICES', '1') or 1)
    if num_devices is None:
        num_devices = jax.device_count()
    return MeshSpec.auto(num_devices, num_slices=num_slices, tp=tp, sp=sp)


def serving_spec_from_env(*, tp: Optional[int] = None,
                          dp: Optional[int] = None) -> MeshSpec:
    """Serving MeshSpec from the launch env contract: the controller's
    adaptive-TP placement exports ``SKYTPU_TP``/``SKYTPU_DP`` on the
    replica, and explicit args (``--tp/--dp``) override. Absent both,
    tp=dp=1 — the single-chip path stays the default."""
    import os
    if tp is None:
        tp = int(os.environ.get('SKYTPU_TP', '1') or 1)
    if dp is None:
        dp = int(os.environ.get('SKYTPU_DP', '1') or 1)
    return MeshSpec.for_serving(tp=tp, dp=dp)


def serving_mesh(tp: int = 1, dp: int = 1,
                 devices: Optional[Sequence[jax.Device]] = None
                 ) -> Optional[Mesh]:
    """Build the (tp, dp) serving mesh over the first ``tp*dp`` visible
    devices. Returns None for tp=dp=1: the engines' meshless path skips
    sharding entirely (and keeps the Pallas decode kernel eligible), so
    single-chip serving must not pay for an over-general 1-device mesh."""
    spec = MeshSpec.for_serving(tp=tp, dp=dp)
    if spec.num_devices == 1:
        return None
    if devices is None:
        devices = jax.devices()
    if len(devices) < spec.num_devices:
        raise ValueError(
            f'serving mesh tp={tp} x dp={dp} needs {spec.num_devices} '
            f'devices, but only {len(devices)} are visible')
    return make_mesh(spec, devices[:spec.num_devices])


def mesh_axis_sizes(mesh: Optional[Mesh]) -> Dict[str, int]:
    """{axis: size} for every logical mesh axis — the stable-schema
    payload behind the ``skytpu_mesh_shape{axis=...}`` gauges and the
    LB's replica view. All 1s for a meshless (single-chip) engine, so
    the series exist with sane values from the first scrape."""
    if mesh is None:
        return {a: 1 for a in MESH_AXES}
    return {a: int(mesh.shape[a]) for a in MESH_AXES}


def axis_shard_degree(mesh: Optional[Mesh], axes, dim: int) -> int:
    """Effective shard count of a tensor dimension of size ``dim``
    mapped to mesh ``axes`` (a name or tuple), mirroring ``spec_for``'s
    divisibility fallback: trailing axes that do not divide ``dim``
    drop to replication. THE divisor per-shard byte accounting must use
    — sizing with the raw axis product would overstate sharding exactly
    where spec_for silently replicated (e.g. MQA's n_kv_heads < tp)."""
    if mesh is None:
        return 1
    if isinstance(axes, str):
        axes = (axes,)
    keep = tuple(axes)
    while keep and dim % math.prod(mesh.shape[a] for a in keep):
        keep = keep[:-1]
    return math.prod(mesh.shape[a] for a in keep) if keep else 1


_distributed_initialized = False

# Port offset from the gang bus (rank 0's HTTP front end) to the JAX
# distributed-runtime coordinator: the two services share a host but
# not a protocol (gang bus = HTTP long-poll sync; JAX = gRPC).
_GANG_JAX_PORT_OFFSET = 1000


def jax_coordinator_from_url(url: str) -> str:
    """``host:port`` for ``jax.distributed.initialize`` derived from
    the gang's SKYTPU_COORDINATOR HTTP URL (rank 0's model server):
    same host, HTTP port + a fixed offset."""
    import urllib.parse
    parsed = urllib.parse.urlparse(url if '//' in url else f'//{url}')
    host = parsed.hostname or 'localhost'
    port = (parsed.port or 8081) + _GANG_JAX_PORT_OFFSET
    return f'{host}:{port}'


def initialize_gang_distributed(coordinator_url: str, rank: int,
                                world: int, *,
                                timeout_s: float = 120.0) -> bool:
    """Multi-process serving-mesh bootstrap from the gang launch-env
    contract (SKYTPU_COORDINATOR/SKYTPU_RANK/SKYTPU_WORLD — the
    serving twin of the SKYTPU_COORDINATOR_ADDRESS training contract
    above): ``jax.distributed.initialize`` with rank 0's derived gRPC
    address, so ``jax.devices()`` spans every gang process and the
    (tp, dp) serving mesh shards one model across hosts.

    The join is BOUNDED by ``timeout_s`` (graftcheck GC116: no
    unbounded distributed joins — a member that never comes up must
    fail the gang, not hang it). No-op (False) for world <= 1; only
    attempted on multi-host-capable backends — single-process CPU
    serving (tests, bench) keeps the ``replicated`` data plane, where
    each rank holds a full model copy and lockstep is digest-verified
    by the gang bus instead. Idempotent."""
    global _distributed_initialized
    if world <= 1:
        return False
    if _distributed_initialized:
        return True
    addr = jax_coordinator_from_url(coordinator_url)
    try:
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=world,
            process_id=rank,
            initialization_timeout=int(max(1, timeout_s)))
    except RuntimeError as e:
        # Benign re-init only; a coordinator-connect failure fails
        # LOUDLY — swallowing it would leave a half-alive gang whose
        # ranks each serve a disconnected model shard.
        if 'already initialized' not in str(e).lower():
            raise
    _distributed_initialized = True
    return True


def initialize_distributed_from_env() -> bool:
    """Multi-host bootstrap from the SKYTPU_* env contract: calls
    jax.distributed.initialize(coordinator, num_processes, process_id)
    when launched on a multi-host cluster; no-op (returns False) when
    the contract is absent or single-host. Idempotent — safe to call
    from every Trainer/engine constructor."""
    global _distributed_initialized
    import os
    coord = os.environ.get('SKYTPU_COORDINATOR_ADDRESS')
    n = int(os.environ.get('SKYTPU_NUM_NODES', '1') or 1)
    if not coord or n <= 1:
        return False
    if _distributed_initialized:
        return True
    rank = int(os.environ.get('SKYTPU_NODE_RANK', '0') or 0)
    try:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n, process_id=rank)
    except RuntimeError as e:
        # Only the benign re-init case may pass; a coordinator-connect
        # failure must fail LOUDLY — swallowing it would leave every
        # host training a disconnected replica.
        if 'already initialized' not in str(e).lower():
            raise
    _distributed_initialized = True
    return True


def make_mesh(spec: MeshSpec,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the 5-D mesh. Axis order puts `tp` innermost so tensor-parallel
    collectives ride nearest-neighbor ICI links; `slice` outermost so only
    the pure-DP gradient all-reduce crosses DCN (multislice)."""
    if devices is None:
        devices = jax.devices()
    if len(devices) != spec.num_devices:
        raise ValueError(
            f'MeshSpec {spec.shape} needs {spec.num_devices} devices, '
            f'got {len(devices)}')
    arr = np.asarray(devices).reshape(spec.shape)
    return Mesh(arr, MESH_AXES)


# --- Logical axis rules ----------------------------------------------------
# logical axis -> mesh axis (str), tuple of mesh axes, or None (replicated).
LogicalRules = Dict[str, Any]

# Default rules (MaxText-style): params shard embed-dim over fsdp and
# heads/mlp over tp; activations shard batch over all data axes and sequence
# over sp.
DEFAULT_RULES: LogicalRules = {
    'batch': ('slice', 'dp', 'fsdp'),
    'seq': 'sp',
    'embed': 'fsdp',
    'heads': 'tp',
    'kv_heads': 'tp',
    'head_dim': None,
    'mlp': 'tp',
    'vocab': 'tp',
    # Input embedding table: vocab dim unsharded (a tp-sharded table turns
    # the token gather into an SPMD full-rematerialization; the table's
    # memory is carried by the fsdp-sharded embed dim instead).
    'vocab_in': None,
    'expert': ('fsdp', 'sp'),   # ep folded over fsdp×sp
    'norm': None,
    # Layer stack sharded over pipeline stages (no-op at pp=1).
    'layers': 'pp',
}


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[LogicalRules] = None,
             *,
             shape: Optional[Sequence[int]] = None,
             mesh: Optional[Mesh] = None) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec.

    Custom ``rules`` are OVERRIDES merged onto DEFAULT_RULES, so a user
    dict doesn't break when the model layer introduces a new logical
    axis (e.g. 'vocab_in'); unknown axes still raise (typo guard).

    When ``shape`` and ``mesh`` are given, the mapping is
    divisibility-aware: mesh axes that do not evenly divide the tensor
    dimension are dropped (trailing-first), falling back to replication.
    This is what lets MQA/GQA models with ``n_kv_heads < tp`` run under
    tensor parallelism — KV heads are replicated over the tp axis instead
    of pjit rejecting the layout (MaxText does the same)."""
    rules = {**DEFAULT_RULES, **rules} if rules else DEFAULT_RULES
    parts = []
    used = set()
    for i, ax in enumerate(logical_axes):
        if ax is None:
            parts.append(None)
            continue
        if ax not in rules:
            raise ValueError(f'No sharding rule for logical axis {ax!r}')
        mesh_ax = rules[ax]
        # Drop mesh axes already used by an earlier dimension (a mesh axis
        # may shard at most one tensor dimension).
        if mesh_ax is None:
            keep = ()
        elif isinstance(mesh_ax, (tuple, list)):
            keep = tuple(a for a in mesh_ax if a not in used)
        else:
            keep = (mesh_ax,) if mesh_ax not in used else ()
        if keep and shape is not None and mesh is not None:
            dim = shape[i]
            while keep and dim % math.prod(
                    mesh.shape[a] for a in keep):
                keep = keep[:-1]
        used.update(keep)
        if not keep:
            parts.append(None)
        elif len(keep) == 1 and not isinstance(rules[ax], (tuple, list)):
            parts.append(keep[0])
        else:
            parts.append(keep)
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


def tree_shardings(logical_tree: Any, mesh: Mesh,
                   rules: Optional[LogicalRules] = None,
                   shapes: Optional[Any] = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings.

    ``shapes`` (optional) is a matching pytree of arrays or
    ShapeDtypeStructs; when given, shardings are divisibility-aware (see
    ``spec_for``)."""
    is_leaf = lambda x: isinstance(x, tuple) and all(
        a is None or isinstance(a, str) for a in x)
    if shapes is None:
        return jax.tree.map(
            lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
            logical_tree, is_leaf=is_leaf)
    return jax.tree.map(
        lambda axes, s: NamedSharding(
            mesh, spec_for(axes, rules, shape=s.shape, mesh=mesh)),
        logical_tree, shapes, is_leaf=is_leaf)


def batch_sharding(mesh: Mesh,
                   rules: Optional[LogicalRules] = None) -> NamedSharding:
    """Sharding for [batch, seq] token arrays."""
    return NamedSharding(mesh, spec_for(('batch', 'seq'), rules))


def data_axis_size(mesh: Mesh) -> int:
    """Global data-parallel degree (batch must be divisible by this)."""
    return (mesh.shape['slice'] * mesh.shape['dp'] * mesh.shape['fsdp'])
