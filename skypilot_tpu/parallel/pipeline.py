"""Pipeline parallelism: microbatch schedule over the ``pp`` mesh axis.

SURVEY §2.3 row "Pipeline (PP)": the reference delegates PP to launched
frameworks (DeepSpeed recipes); here it is a first-class op. The layer
stack's leading axis is sharded over ``pp`` (rule ``layers: pp``), so
each stage holds L/P contiguous layers; microbatched activations flow
stage-to-stage via ``lax.ppermute`` (nearest-neighbor ICI hops) in a
``jax.shard_map`` that is manual over ONLY the pp axis — fsdp/tp/sp
sharding inside each stage remains compiler-managed (``axis_names``).

Schedule: GPipe ticks (M microbatches drain through P stages in
M + P - 1 ticks) with **bubble compute skipped**: a stage whose tick
carries no live microbatch takes the identity branch of a ``lax.cond``
instead of running the stage body, so bubble ticks cost a branch, not a
forward pass (the round-2/3 implementation computed every tick on every
rank). The (P-1)/M bubble *latency* remains — that is the schedule;
1F1B-style interleaving changes peak activation memory, not the bubble
— but the wasted FLOPs are gone.

MoE: the stage body returns (activations, aux_scalar); aux accumulates
over live ticks and psums across stages, so MoE load-balancing loss
flows through the pipeline (round-3 gap).

Boundary dtype: activations cross stages in the model dtype on TPU. On
the CPU backend the boundary rides fp32 — a bf16 psum inside a
partially-manual shard_map trips an XLA-CPU internal check ("Invalid
binary instruction opcode copy"); that workaround is now gated to CPU
instead of taxing TPU with 2x boundary traffic.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _boundary_dtype(x_dtype):
    if jax.default_backend() == 'cpu':
        return jnp.float32
    return x_dtype


def pipeline_layers(
    layer_params: Any,                # pytree; leaves [L, ...] over pp
    x: jax.Array,                     # [batch, seq, d] activations
    stage_fn: Callable[[Any, jax.Array], Any],
    mesh: jax.sharding.Mesh,
    *,
    num_microbatches: Optional[int] = None,
    axis_name: str = 'pp',
    with_aux: bool = False,
    skip_bubbles: Optional[bool] = None,   # None = auto from mesh axes
) -> Any:
    """Apply the full layer stack to ``x`` through the pipeline.

    ``stage_fn(stage_params, x_mb)`` applies ONE stage's local layers to
    one microbatch (it sees leaves with leading axis L/P). With
    ``with_aux`` it returns ``(y_mb, aux_scalar)`` and
    ``pipeline_layers`` returns ``(y, aux_mean_over_stages_and_mbs)``.
    """
    pp = mesh.shape[axis_name]
    if pp == 1:
        return stage_fn(layer_params, x)
    batch = x.shape[0]
    n_micro = num_microbatches or pp
    if batch % n_micro:
        raise ValueError(f'batch {batch} not divisible into '
                         f'{n_micro} microbatches')

    param_specs = jax.tree.map(lambda _: P(axis_name), layer_params)
    x_dtype = x.dtype
    bdt = _boundary_dtype(x_dtype)
    # Bubble skip is a lax.cond whose predicate differs across pp ranks.
    # If the SPMD partitioner inserts collectives INSIDE the stage body
    # (fsdp param all-gathers, tp psums), ranks in different branches
    # execute different collective streams and the runtime deadlocks
    # (observed on XLA:CPU: half the devices at permute N, half at N+1).
    #
    # fsdp is handled by making the collective schedule UNIFORM: the
    # stage's param all-gather is hoisted OUT of the cond (an explicit
    # replication constraint per tick, executed by every rank on every
    # tick — bubbles included), so the cond branches contain no
    # collectives at all. The gather itself is the same traffic the
    # non-skip path paid (the partitioner gathered per stage body);
    # only the bubble FLOPs are skipped. tp/sp still disable the skip:
    # their psums ride inside the layer math where no such hoist
    # exists.
    safe_to_skip = all(mesh.shape.get(a, 1) == 1 for a in ('tp', 'sp'))
    if skip_bubbles is None:
        skip_bubbles = safe_to_skip
    elif skip_bubbles and not safe_to_skip:
        raise ValueError(
            'skip_bubbles=True is unsafe with tp/sp > 1: the stage '
            'body contains tp/sp collectives that would diverge across '
            "the cond's branches and deadlock the rendezvous")
    hoist_gather = (skip_bubbles and mesh.shape.get('fsdp', 1) > 1)

    def body(params_local, x_full):
        x_full = x_full.astype(x_dtype)
        rank = lax.axis_index(axis_name)
        mbs = x_full.reshape(n_micro, batch // n_micro, *x_full.shape[1:])
        outputs = jnp.zeros(mbs.shape, bdt)
        recv = jnp.zeros(mbs.shape[1:], bdt)
        aux_acc = jnp.zeros((), jnp.float32)
        fwd = [(i, (i + 1) % pp) for i in range(pp)]

        def run_stage_with(params, x_in):
            out = stage_fn(params, x_in)
            if with_aux:
                y, aux = out
            else:
                y, aux = out, jnp.zeros((), jnp.float32)
            return y.astype(bdt), aux.astype(jnp.float32)

        def run_stage(x_in):
            return run_stage_with(params_local, x_in)

        def skip_stage(x_in):
            # Bubble tick: no live microbatch here — identity, no
            # compute. (cond executes ONE branch at runtime.)
            return x_in.astype(bdt), jnp.zeros((), jnp.float32)

        def tick(carry, t):
            recv, outputs, aux_acc = carry
            # Stage `rank` processes microbatch (t - rank) at tick t.
            mb_idx = jnp.clip(t - rank, 0, n_micro - 1)
            active = (t - rank >= 0) & (t - rank < n_micro)
            x_in = jnp.where(rank == 0,
                             mbs[jnp.clip(t, 0, n_micro - 1)].astype(bdt),
                             recv)
            if hoist_gather:
                # Uniform per-tick param gather (see skip_bubbles note):
                # every rank executes this all-gather every tick, so the
                # cond below is collective-free on both branches. Peak
                # memory holds one stage's params unsharded over fsdp —
                # the same transient the stage body's own gather created.
                gathered = jax.tree.map(
                    lambda p: lax.with_sharding_constraint(p, P()),
                    params_local)
                y, aux = lax.cond(
                    active,
                    lambda xi: run_stage_with(gathered, xi),
                    skip_stage, x_in.astype(x_dtype))
            elif skip_bubbles:
                y, aux = lax.cond(active, run_stage, skip_stage,
                                  x_in.astype(x_dtype))
            else:
                y, aux = run_stage(x_in.astype(x_dtype))
                y = jnp.where(active, y, x_in)
            aux_acc = aux_acc + jnp.where(active, aux, 0.0)
            # Last stage banks its finished microbatch.
            prev = lax.dynamic_index_in_dim(outputs, mb_idx, 0,
                                            keepdims=False)
            banked = jnp.where(active & (rank == pp - 1), y, prev)
            outputs = lax.dynamic_update_index_in_dim(outputs, banked,
                                                      mb_idx, 0)
            recv = lax.ppermute(y, axis_name, fwd)
            return (recv, outputs, aux_acc), None

        (recv, outputs, aux_acc), _ = lax.scan(
            tick, (recv, outputs, aux_acc),
            jnp.arange(n_micro + pp - 1))
        del recv
        # Only the last stage holds real outputs; broadcast to the ring
        # so downstream (final norm / unembed / loss) is replicated over
        # pp.
        outputs = jnp.where(rank == pp - 1, outputs,
                            jnp.zeros_like(outputs))
        outputs = lax.psum(outputs, axis_name)
        # aux: each live (stage, microbatch) contributed one scalar;
        # mean over all of them = psum / (pp * n_micro).
        aux_mean = lax.psum(aux_acc, axis_name) / (pp * n_micro)
        out = outputs.reshape(x_full.shape)
        if with_aux:
            return out, aux_mean
        return out

    out_specs = (P(), P()) if with_aux else P()
    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(param_specs, P()),
                       out_specs=out_specs,
                       axis_names={axis_name},
                       check_vma=False)
    result = fn(layer_params, x.astype(bdt))
    if with_aux:
        y, aux = result
        return y.astype(x_dtype), aux
    return result.astype(x_dtype)


def pipeline_decode_layers(
    layer_params: Any,                # pytree; leaves [L, ...] over pp
    caches: Tuple[Any, ...],          # cache pytrees; leaves [L, ...] over pp
    x: jax.Array,                     # [b, s, d] current-token activations
    stage_fn: Callable[..., Any],
    mesh: jax.sharding.Mesh,
    *,
    extras: Any = (),                 # replicated pytree handed to stage_fn
    axis_name: str = 'pp',
):
    """Single-wave pipelined DECODE: the activation chains through the
    P stages (P-1 ppermute hops), each stage scanning its LOCAL layers
    against its LOCAL cache shard — pp-sharded params and caches are
    honored at decode instead of being all-gathered (round-3 gap:
    "decode ignores pp").

    ``stage_fn(stage_params, stage_caches, x, extras) -> (y,
    stage_new_kv)`` where ``stage_new_kv`` leaves have leading axis L/P.
    Returns ``(y_replicated, new_kv)`` with new_kv leaves [L, ...]
    sharded over pp — ready to merge into the pp-sharded cache.

    No microbatching: a decode token is latency-bound through the
    stage chain anyway; the win is that each rank only reads 1/P of the
    weights and cache (HBM), which is what pp buys at decode.
    """
    pp = mesh.shape[axis_name]
    if pp == 1:
        return stage_fn(layer_params, caches, x, extras)
    param_specs = jax.tree.map(lambda _: P(axis_name), layer_params)
    cache_specs = jax.tree.map(lambda _: P(axis_name), caches)
    x_dtype = x.dtype
    bdt = _boundary_dtype(x_dtype)
    fwd = [(i, (i + 1) % pp) for i in range(pp)]

    def body(params_local, caches_local, x_in, extras_in):
        rank = lax.axis_index(axis_name)
        act = x_in.astype(bdt)

        def _astype_tree(out):
            y, kv = out
            return y.astype(bdt), kv

        def _zeros_kv(a):
            shapes = jax.eval_shape(
                lambda p, c, xx, e: stage_fn(p, c, xx, e)[1],
                params_local, caches_local, a.astype(x_dtype), extras_in)
            return jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes)

        def hop(carry, stage):
            # lax.scan (strict hop ordering) and NO lax.cond around the
            # stage body: a cond whose predicate differs across pp ranks
            # plus a collective in the stream deadlocks the XLA:CPU
            # rendezvous (half the devices at permute N, half at N+1).
            # Every rank runs its LOCAL layers each hop and a `where`
            # keeps only the live stage's result — decode is HBM-bound
            # and each rank re-reads only its 1/P weight shard, so the
            # redundant hops cost idle FLOPs, not bandwidth.
            act, kv_acc = carry
            live = rank == stage
            y, new_kv = _astype_tree(
                stage_fn(params_local, caches_local,
                         act.astype(x_dtype), extras_in))
            y = jnp.where(live, y, act)
            # Each rank keeps real rows only from its own stage's hop.
            kv_acc = jax.tree.map(
                lambda acc, kv: acc + jnp.where(live, kv,
                                                jnp.zeros_like(kv)),
                kv_acc, new_kv)
            return (lax.ppermute(y, axis_name, fwd), kv_acc), None

        kv0 = _zeros_kv(act)
        (act, new_kvs), _ = lax.scan(hop, (act, kv0), jnp.arange(pp))
        # After pp hops the activation is back at rank 0 holding the
        # final stage's output; broadcast it.
        act = jnp.where(rank == 0, act, jnp.zeros_like(act))
        act = lax.psum(act, axis_name)
        return act.astype(x_dtype), new_kvs

    kv_shapes = jax.eval_shape(
        lambda p, c, xx, e: stage_fn(
            jax.tree.map(lambda a: a[:a.shape[0] // pp], p),
            jax.tree.map(lambda a: a[:a.shape[0] // pp], c),
            xx, e)[1],
        layer_params, caches, x, extras)
    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(param_specs, cache_specs, P(), P()),
                       out_specs=(P(), jax.tree.map(
                           lambda _: P(axis_name), kv_shapes)),
                       axis_names={axis_name},
                       check_vma=False)
    return fn(layer_params, caches, x, extras)
