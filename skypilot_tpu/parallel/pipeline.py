"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh
axis.

SURVEY §2.3 row "Pipeline (PP)": the reference delegates PP to launched
frameworks (DeepSpeed recipes); here it is a first-class op. The layer
stack's leading axis is sharded over ``pp`` (rule ``layers: pp``), so
each stage holds L/P contiguous layers; microbatched activations flow
stage-to-stage via ``lax.ppermute`` (nearest-neighbor ICI hops) in a
``jax.shard_map`` that is manual over ONLY the pp axis — fsdp/tp/sp
sharding inside each stage remains compiler-managed (``axis_names``).

Schedule: plain GPipe — M microbatches drain through P stages in
M + P - 1 ticks; the (P-1)/M bubble shrinks as M grows. Activations for
the backward pass are kept by scan autodiff (remat of the stage body
applies as usual via the model's remat policy).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def pipeline_layers(
    layer_params: Any,                # pytree; leaves [L, ...] over pp
    x: jax.Array,                     # [batch, seq, d] activations
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: jax.sharding.Mesh,
    *,
    num_microbatches: Optional[int] = None,
    axis_name: str = 'pp',
) -> jax.Array:
    """Apply the full layer stack to ``x`` through the pipeline.

    ``stage_fn(stage_params, x_mb)`` applies ONE stage's local layers to
    one microbatch (it sees leaves with leading axis L/P)."""
    pp = mesh.shape[axis_name]
    if pp == 1:
        return stage_fn(layer_params, x)
    batch = x.shape[0]
    n_micro = num_microbatches or pp
    if batch % n_micro:
        raise ValueError(f'batch {batch} not divisible into '
                         f'{n_micro} microbatches')

    param_specs = jax.tree.map(lambda _: P(axis_name), layer_params)
    # The shard_map boundary rides fp32: replicated (P()) inputs get a
    # psum over pp in the TRANSPOSE (cotangent accumulation), and a bf16
    # all-reduce inside a partially-manual shard_map trips an XLA-CPU
    # internal check. Stage compute still runs in the model dtype.
    x_dtype = x.dtype

    def body(params_local, x_full):
        x_full = x_full.astype(x_dtype)
        rank = lax.axis_index(axis_name)
        mbs = x_full.reshape(n_micro, batch // n_micro, *x_full.shape[1:])
        outputs = jnp.zeros_like(mbs)
        recv = jnp.zeros_like(mbs[0])
        fwd = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            recv, outputs = carry
            # Stage `rank` processes microbatch (t - rank) at tick t.
            mb_idx = jnp.clip(t - rank, 0, n_micro - 1)
            active = (t - rank >= 0) & (t - rank < n_micro)
            x_in = jnp.where(rank == 0,
                             mbs[jnp.clip(t, 0, n_micro - 1)], recv)
            y = stage_fn(params_local, x_in)
            # Last stage banks its finished microbatch.
            prev = lax.dynamic_index_in_dim(outputs, mb_idx, 0,
                                            keepdims=False)
            banked = jnp.where(active & (rank == pp - 1), y, prev)
            outputs = lax.dynamic_update_index_in_dim(outputs, banked,
                                                      mb_idx, 0)
            recv = lax.ppermute(y, axis_name, fwd)
            return (recv, outputs), None

        (recv, outputs), _ = lax.scan(
            tick, (recv, outputs), jnp.arange(n_micro + pp - 1))
        del recv
        # Only the last stage holds real outputs; broadcast to the ring
        # so downstream (final norm / unembed / loss) is replicated over
        # pp. The psum rides fp32: a bf16 all-reduce inside a
        # partially-manual shard_map trips an XLA-CPU internal check
        # ("Invalid binary instruction opcode copy").
        outputs = jnp.where(rank == pp - 1, outputs,
                            jnp.zeros_like(outputs))
        outputs = lax.psum(outputs.astype(jnp.float32), axis_name)
        return outputs.reshape(x_full.shape)

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(param_specs, P()),
                       out_specs=P(),
                       axis_names={axis_name},
                       check_vma=False)
    return fn(layer_params, x.astype(jnp.float32)).astype(x_dtype)
