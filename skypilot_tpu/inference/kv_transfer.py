"""KV wire codec for disaggregated prefill/decode serving.

A *handoff* moves one request's finished-prefill state from a prefill
worker to a decode worker: the original prompt, the tokens generated so
far, the sampling/finish parameters, and the KV rows for every context
position except the current token (whose row the next decode step
writes). The decode worker lands the rows into its own pool/cache and
resumes at the EXACT original bytes — the greedy continuation is
byte-identical to a colocated run.

Wire format (version 1)::

    b'SKKV' | uint32_be header_len | header JSON |
    for each header['buffers'] entry: uint64_be byte_len | raw bytes

The header carries the request (prompt/output/sampling/budget), the
model shape fields the receiver validates against its own config, and
the buffer manifest (name/dtype/shape). Buffers are raw C-order array
bytes:

- ``kv_cache_dtype='int8'``: ``k_codes``/``v_codes`` int8
  ``[L, n, hkv, d]`` plus ``k_scales``/``v_scales`` float32
  ``[L, n, hkv]`` — the pool's native (codes, absmax/127 scales)
  representation. **int8 stays int8 on the wire**: the codec never
  dequantizes (graftcheck GC114 bans any wide-float ``astype`` /
  ``dequant`` spelling on transfer paths), so an int8 handoff moves
  ~half the bytes of a bf16 one — the saving that makes disaggregation
  cheap enough to win.
- ``kv_cache_dtype='bf16'``: ``k_rows``/``v_rows`` bfloat16
  ``[L, n, hkv, d]`` (``ml_dtypes.bfloat16`` raw bytes).

Decoding is strict: magic/version/header/manifest/shape mismatches all
raise ``ValueError`` with the reason — a truncated or corrupt handoff
must be rejected loudly at the wire (and again at
``PageAllocator.register_prefix``), never landed as garbage KV.

Integrity (wire version 2, PR 13): every buffer manifest entry carries
a ``crc32`` of its raw bytes, and the container appends a trailing
CRC32 of the header JSON — a single flipped bit anywhere (magic,
header, any buffer, the checksums themselves) is a ``ValueError``, so
a bit-flipped handoff or checkpoint becomes a *retryable refusal*
(fallback-local / cold-boot) instead of a byte-wrong continuation.
Verification is ALL-OR-NOTHING: every structural claim and every
checksum is validated before a single row is returned to the caller,
so a corrupt body can never partially land. Version-1 containers
(pre-checksum) still decode — old checkpoints stay readable — they
just get no integrity cover.

Spot-resilience additions (PR 10):

- **Prefix-chain blobs** (magic ``SKPF``): a hot prefix-cache page
  chain — ``tokens`` (exactly ``n_rows + 1`` of them: the rows plus
  the next token, matching how the paged allocator content-addresses
  full pages) and the same stored-dtype KV buffers. No request fields:
  a prefix is cache warmth, not work.
- **Checkpoint containers** (magic ``SKCK``): a length-prefixed
  sequence of SKKV and/or SKPF blobs — what a spot replica exports on
  a preemption warning and a replacement replica lands via
  ``/kv/warmup`` (``register_prefix`` before it enters rotation, so
  post-recovery TTFT is near-warm instead of cold). Request entries in
  a checkpoint are landed as prefix warmth only, never re-executed —
  the LB's in-flight recovery owns re-execution.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Tuple

import numpy as np

MAGIC = b'SKKV'
WIRE_VERSION = 2
# Version 1 (pre-checksum) containers stay decodable: a checkpoint
# written by an older replica must still warm a new one.
_SUPPORTED_WIRE_VERSIONS = (1, 2)
PREFIX_MAGIC = b'SKPF'
CKPT_MAGIC = b'SKCK'
CKPT_VERSION = 2
_SUPPORTED_CKPT_VERSIONS = (1, 2)


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xffffffff


class HandoffCapacityError(RuntimeError):
    """A KV-handoff ingest found no free slot / no pool pages. The
    serving layer answers it with a RETRYABLE refusal (HTTP 503 +
    Retry-After) — the router then picks another decode worker or the
    prefill worker falls back to decoding locally. Distinct from
    ``ValueError`` (malformed/mismatched handoff: permanent, HTTP
    400). Lives here (not ``engine.py``) so the serve layer can catch
    it without importing the jax-heavy engine module."""

# Buffer manifest per kv dtype: (name, numpy dtype string, rank).
_INT8_BUFFERS: Tuple[Tuple[str, str, int], ...] = (
    ('k_codes', 'int8', 4), ('v_codes', 'int8', 4),
    ('k_scales', 'float32', 3), ('v_scales', 'float32', 3))
# int4 rides the int8 wire layout with PACKED uint8 nibble codes
# (head_dim/2 bytes per row) — codes+scales ship verbatim (GC114),
# never unpacked or widened on the wire.
_INT4_BUFFERS: Tuple[Tuple[str, str, int], ...] = (
    ('k_codes', 'uint8', 4), ('v_codes', 'uint8', 4),
    ('k_scales', 'float32', 3), ('v_scales', 'float32', 3))
_BF16_BUFFERS: Tuple[Tuple[str, str, int], ...] = (
    ('k_rows', 'bfloat16', 4), ('v_rows', 'bfloat16', 4))

# Request fields carried verbatim through the handoff (the decode
# worker recreates the engine Request from exactly these).
REQUEST_FIELDS = ('prompt', 'output', 'max_new_tokens', 'temperature',
                  'top_k', 'top_p', 'eos_id', 'stop', 'priority')


def _np_dtype(name: str):
    if name == 'bfloat16':
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if name in ('int8', 'uint8', 'float32'):
        return np.dtype(name)
    raise ValueError(f'unsupported wire buffer dtype {name!r}')


def _manifest(kv_cache_dtype: str) -> Tuple[Tuple[str, str, int], ...]:
    if kv_cache_dtype == 'int8':
        return _INT8_BUFFERS
    if kv_cache_dtype == 'int4':
        return _INT4_BUFFERS
    if kv_cache_dtype == 'bf16':
        return _BF16_BUFFERS
    raise ValueError(
        f'unsupported kv_cache_dtype on the wire: {kv_cache_dtype!r}')


def snapshot_buffers(snapshot: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """The snapshot's KV arrays keyed by wire buffer name."""
    if snapshot['kv_cache_dtype'] in ('int8', 'int4'):
        return {'k_codes': snapshot['k'], 'v_codes': snapshot['v'],
                'k_scales': snapshot['k_scale'],
                'v_scales': snapshot['v_scale']}
    return {'k_rows': snapshot['k'], 'v_rows': snapshot['v']}


def encode_handoff(snapshot: Dict[str, Any]) -> bytes:
    """Serialize an engine ``export_kv_snapshot`` dict to wire bytes.

    The KV arrays ride in their STORED dtype — int8 codes are written
    as int8 (scales as their native fp32), bf16 rows as bf16; no
    dtype conversion happens here (the GC114 contract)."""
    kv_dtype = snapshot['kv_cache_dtype']
    manifest = _manifest(kv_dtype)
    arrays = snapshot_buffers(snapshot)
    buffers: List[bytes] = []
    buf_meta: List[Dict[str, Any]] = []
    for name, dtype, rank in manifest:
        arr = np.ascontiguousarray(arrays[name], dtype=_np_dtype(dtype))
        if arr.ndim != rank:
            raise ValueError(
                f'{name}: expected rank {rank}, got shape {arr.shape}')
        raw = arr.tobytes()
        buffers.append(raw)
        buf_meta.append({'name': name, 'dtype': dtype,
                         'shape': list(arr.shape), 'crc32': _crc(raw)})
    header = {
        'version': WIRE_VERSION,
        'kv_cache_dtype': kv_dtype,
        'n_rows': int(snapshot['n_rows']),
        'model': {k: int(v) for k, v in snapshot['model'].items()},
        'request': {k: snapshot[k] for k in REQUEST_FIELDS},
        'buffers': buf_meta,
    }
    hj = json.dumps(header).encode()
    out = [MAGIC, struct.pack('>I', len(hj)), hj]
    for b in buffers:
        out.append(struct.pack('>Q', len(b)))
        out.append(b)
    # v2 trailer: CRC of the header JSON. The buffer CRCs live in the
    # header, so this closes the integrity cover over the request
    # fields and the manifest itself — a flipped token id in the header
    # is as fatal as a flipped KV byte.
    out.append(struct.pack('>I', _crc(hj)))
    return b''.join(out)


def _check(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f'malformed KV handoff: {msg}')


def decode_handoff(data: bytes) -> Dict[str, Any]:
    """Parse wire bytes back into a snapshot dict (numpy arrays).

    Strict: every structural claim the header makes is validated
    against the actual payload before anything is returned — a
    truncated row batch or a shape lie raises ``ValueError`` here, so
    the receiver never lands partial rows into its pool."""
    _check(len(data) >= len(MAGIC) + 4, 'short blob')
    _check(data[:len(MAGIC)] == MAGIC,
           f'bad magic {data[:len(MAGIC)]!r}')
    off = len(MAGIC)
    (hlen,) = struct.unpack_from('>I', data, off)
    off += 4
    _check(len(data) >= off + hlen, 'truncated header')
    try:
        header = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise ValueError(f'malformed KV handoff: bad header JSON ({e})'
                         ) from None
    hj = data[off:off + hlen]
    off += hlen
    _check(isinstance(header, dict), 'header is not an object')
    _check(header.get('version') in _SUPPORTED_WIRE_VERSIONS,
           f'unsupported wire version {header.get("version")!r}')
    checksummed = int(header['version']) >= 2
    kv_dtype = header.get('kv_cache_dtype')
    manifest = _manifest(kv_dtype)
    buf_meta = header.get('buffers')
    _check(isinstance(buf_meta, list)
           and [b.get('name') for b in buf_meta]
           == [name for name, _, _ in manifest],
           f'buffer manifest does not match {kv_dtype} layout')
    req = header.get('request')
    _check(isinstance(req, dict)
           and all(k in req for k in REQUEST_FIELDS),
           'incomplete request fields')
    prompt = req['prompt']
    output = req['output']
    _check(isinstance(prompt, list) and prompt
           and all(isinstance(t, int) for t in prompt),
           'prompt must be a non-empty token-id list')
    _check(isinstance(output, list) and output
           and all(isinstance(t, int) for t in output),
           'output must carry at least the first generated token')
    n_rows = header.get('n_rows')
    _check(isinstance(n_rows, int) and n_rows >= 1, 'bad n_rows')
    _check(n_rows == len(prompt) + len(output) - 1,
           f'n_rows {n_rows} != context rows '
           f'{len(prompt) + len(output) - 1} '
           '(truncated or inconsistent row batch)')
    model = header.get('model')
    _check(isinstance(model, dict) and all(
        isinstance(model.get(k), int)
        for k in ('n_layers', 'n_kv_heads', 'head_dim')),
        'missing model shape fields')
    arrays: Dict[str, np.ndarray] = {}
    for (name, dtype, rank), meta in zip(manifest, buf_meta):
        _check(meta.get('dtype') == dtype,
               f'{name}: dtype {meta.get("dtype")!r} != {dtype}')
        shape = meta.get('shape')
        _check(isinstance(shape, list) and len(shape) == rank
               and all(isinstance(s, int) and s > 0 for s in shape),
               f'{name}: bad shape {shape!r}')
        expect = [model['n_layers'], n_rows, model['n_kv_heads']]
        if rank == 4:
            # Packed int4 code rows carry head_dim/2 bytes.
            expect.append(model['head_dim'] // 2 if kv_dtype == 'int4'
                          else model['head_dim'])
        _check(shape == expect,
               f'{name}: shape {shape} != expected {expect}')
        _check(len(data) >= off + 8, f'{name}: truncated length prefix')
        (blen,) = struct.unpack_from('>Q', data, off)
        off += 8
        np_dtype = _np_dtype(dtype)
        want = int(np.prod(shape)) * np_dtype.itemsize
        _check(blen == want,
               f'{name}: {blen} bytes on the wire != {want} for shape '
               f'{shape} ({dtype})')
        _check(len(data) >= off + blen, f'{name}: truncated payload')
        if checksummed:
            _check(isinstance(meta.get('crc32'), int),
                   f'{name}: v2 buffer carries no crc32')
            _check(_crc(data[off:off + blen]) == meta['crc32'],
                   f'{name}: checksum mismatch (corrupted buffer — '
                   'refusing to land any row)')
        arrays[name] = np.frombuffer(
            data, dtype=np_dtype, count=int(np.prod(shape)),
            offset=off).reshape(shape)
        off += blen
    if checksummed:
        _check(len(data) == off + 4,
               f'{len(data) - off} trailing byte(s) != 4-byte header '
               'checksum')
        (hcrc,) = struct.unpack_from('>I', data, off)
        _check(_crc(hj) == hcrc,
               'header checksum mismatch (corrupted header — refusing '
               'to land any row)')
    else:
        _check(off == len(data), f'{len(data) - off} trailing bytes')
    snap: Dict[str, Any] = {
        'kv_cache_dtype': kv_dtype,
        'n_rows': n_rows,
        'model': {k: int(model[k])
                  for k in ('n_layers', 'n_kv_heads', 'head_dim')},
    }
    snap.update({k: req[k] for k in REQUEST_FIELDS})
    if kv_dtype in ('int8', 'int4'):
        snap.update(k=arrays['k_codes'], v=arrays['v_codes'],
                    k_scale=arrays['k_scales'],
                    v_scale=arrays['v_scales'])
    else:
        snap.update(k=arrays['k_rows'], v=arrays['v_rows'],
                    k_scale=None, v_scale=None)
    return snap


# ---------------------------------------------------------------------------
# Prefix-chain blobs + checkpoint containers (spot resilience)
# ---------------------------------------------------------------------------
def _kv_arrays(entry: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """The entry's KV arrays keyed by wire buffer name (same layout as
    :func:`snapshot_buffers`; prefix entries use the same keys)."""
    return snapshot_buffers(entry)


def as_prefix_entry(snap: Dict[str, Any]) -> Dict[str, Any]:
    """View a request snapshot (``export_kv_snapshot`` / decoded SKKV)
    as a prefix entry: tokens = prompt + output (exactly ``n_rows + 1``
    — the context rows plus the current token), same KV buffers in
    their stored dtype. Used when a checkpointed in-flight request is
    landed as cache warmth rather than re-executed."""
    if 'tokens' in snap:
        return snap
    return {
        'kv_cache_dtype': snap['kv_cache_dtype'],
        'n_rows': int(snap['n_rows']),
        'model': dict(snap['model']),
        'tokens': list(snap['prompt']) + list(snap['output']),
        'k': snap['k'], 'v': snap['v'],
        'k_scale': snap.get('k_scale'), 'v_scale': snap.get('v_scale'),
    }


def encode_prefix_chain(entry: Dict[str, Any]) -> bytes:
    """Serialize a prefix-cache chain to wire bytes (magic ``SKPF``).
    Same stored-dtype buffer discipline as :func:`encode_handoff` —
    int8 codes + fp32 scales never widen (GC114)."""
    kv_dtype = entry['kv_cache_dtype']
    manifest = _manifest(kv_dtype)
    arrays = _kv_arrays(entry)
    tokens = [int(t) for t in entry['tokens']]
    n_rows = int(entry['n_rows'])
    if len(tokens) != n_rows + 1:
        raise ValueError(
            f'prefix entry carries {len(tokens)} token(s) for {n_rows} '
            'row(s); exactly n_rows + 1 are required (the rows plus '
            'the next token)')
    buffers: List[bytes] = []
    buf_meta: List[Dict[str, Any]] = []
    for name, dtype, rank in manifest:
        arr = np.ascontiguousarray(arrays[name], dtype=_np_dtype(dtype))
        if arr.ndim != rank:
            raise ValueError(
                f'{name}: expected rank {rank}, got shape {arr.shape}')
        raw = arr.tobytes()
        buffers.append(raw)
        buf_meta.append({'name': name, 'dtype': dtype,
                         'shape': list(arr.shape), 'crc32': _crc(raw)})
    header = {
        'version': WIRE_VERSION,
        'kv_cache_dtype': kv_dtype,
        'n_rows': n_rows,
        'model': {k: int(v) for k, v in entry['model'].items()},
        'tokens': tokens,
        'buffers': buf_meta,
    }
    hj = json.dumps(header).encode()
    out = [PREFIX_MAGIC, struct.pack('>I', len(hj)), hj]
    for b in buffers:
        out.append(struct.pack('>Q', len(b)))
        out.append(b)
    out.append(struct.pack('>I', _crc(hj)))
    return b''.join(out)


def decode_prefix_chain(data: bytes) -> Dict[str, Any]:
    """Parse a prefix-chain blob. Strict, like :func:`decode_handoff`:
    shape/length lies raise ``ValueError`` before anything lands."""
    _check(len(data) >= len(PREFIX_MAGIC) + 4, 'short prefix blob')
    _check(data[:len(PREFIX_MAGIC)] == PREFIX_MAGIC,
           f'bad prefix magic {data[:len(PREFIX_MAGIC)]!r}')
    off = len(PREFIX_MAGIC)
    (hlen,) = struct.unpack_from('>I', data, off)
    off += 4
    _check(len(data) >= off + hlen, 'truncated prefix header')
    try:
        header = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise ValueError(f'malformed KV handoff: bad header JSON ({e})'
                         ) from None
    hj = data[off:off + hlen]
    off += hlen
    _check(isinstance(header, dict), 'header is not an object')
    _check(header.get('version') in _SUPPORTED_WIRE_VERSIONS,
           f'unsupported wire version {header.get("version")!r}')
    checksummed = int(header['version']) >= 2
    kv_dtype = header.get('kv_cache_dtype')
    manifest = _manifest(kv_dtype)
    buf_meta = header.get('buffers')
    _check(isinstance(buf_meta, list)
           and [b.get('name') for b in buf_meta]
           == [name for name, _, _ in manifest],
           f'buffer manifest does not match {kv_dtype} layout')
    tokens = header.get('tokens')
    _check(isinstance(tokens, list) and tokens
           and all(isinstance(t, int) for t in tokens),
           'tokens must be a non-empty token-id list')
    n_rows = header.get('n_rows')
    _check(isinstance(n_rows, int) and n_rows >= 1, 'bad n_rows')
    _check(len(tokens) == n_rows + 1,
           f'{len(tokens)} token(s) != n_rows + 1 '
           f'({n_rows + 1}) (truncated or inconsistent prefix chain)')
    model = header.get('model')
    _check(isinstance(model, dict) and all(
        isinstance(model.get(k), int)
        for k in ('n_layers', 'n_kv_heads', 'head_dim')),
        'missing model shape fields')
    arrays: Dict[str, np.ndarray] = {}
    for (name, dtype, rank), meta in zip(manifest, buf_meta):
        _check(meta.get('dtype') == dtype,
               f'{name}: dtype {meta.get("dtype")!r} != {dtype}')
        shape = meta.get('shape')
        _check(isinstance(shape, list) and len(shape) == rank
               and all(isinstance(s, int) and s > 0 for s in shape),
               f'{name}: bad shape {shape!r}')
        expect = [model['n_layers'], n_rows, model['n_kv_heads']]
        if rank == 4:
            # Packed int4 code rows carry head_dim/2 bytes.
            expect.append(model['head_dim'] // 2 if kv_dtype == 'int4'
                          else model['head_dim'])
        _check(shape == expect,
               f'{name}: shape {shape} != expected {expect}')
        _check(len(data) >= off + 8, f'{name}: truncated length prefix')
        (blen,) = struct.unpack_from('>Q', data, off)
        off += 8
        np_dtype = _np_dtype(dtype)
        want = int(np.prod(shape)) * np_dtype.itemsize
        _check(blen == want,
               f'{name}: {blen} bytes on the wire != {want} for shape '
               f'{shape} ({dtype})')
        _check(len(data) >= off + blen, f'{name}: truncated payload')
        if checksummed:
            _check(isinstance(meta.get('crc32'), int),
                   f'{name}: v2 buffer carries no crc32')
            _check(_crc(data[off:off + blen]) == meta['crc32'],
                   f'{name}: checksum mismatch (corrupted buffer — '
                   'refusing to land any row)')
        arrays[name] = np.frombuffer(
            data, dtype=np_dtype, count=int(np.prod(shape)),
            offset=off).reshape(shape)
        off += blen
    if checksummed:
        _check(len(data) == off + 4,
               f'{len(data) - off} trailing byte(s) != 4-byte header '
               'checksum')
        (hcrc,) = struct.unpack_from('>I', data, off)
        _check(_crc(hj) == hcrc,
               'header checksum mismatch (corrupted header — refusing '
               'to land any row)')
    else:
        _check(off == len(data), f'{len(data) - off} trailing bytes')
    entry: Dict[str, Any] = {
        'kv_cache_dtype': kv_dtype,
        'n_rows': n_rows,
        'model': {k: int(model[k])
                  for k in ('n_layers', 'n_kv_heads', 'head_dim')},
        'tokens': tokens,
    }
    if kv_dtype in ('int8', 'int4'):
        entry.update(k=arrays['k_codes'], v=arrays['v_codes'],
                     k_scale=arrays['k_scales'],
                     v_scale=arrays['v_scales'])
    else:
        entry.update(k=arrays['k_rows'], v=arrays['v_rows'],
                     k_scale=None, v_scale=None)
    return entry


def encode_checkpoint(entries: List[Dict[str, Any]]) -> bytes:
    """Serialize a prefix-cache checkpoint: a container of SKKV
    (request snapshot — has ``prompt``) and SKPF (prefix chain — has
    ``tokens``) blobs. An empty checkpoint is valid (a replica with a
    cold cache still answers the preemption warning)."""
    blobs: List[bytes] = []
    for entry in entries:
        if 'tokens' in entry:
            blobs.append(encode_prefix_chain(entry))
        else:
            blobs.append(encode_handoff(entry))
    out = [CKPT_MAGIC, struct.pack('>I', CKPT_VERSION),
           struct.pack('>I', len(blobs))]
    for b in blobs:
        # v2 per-entry CRC ahead of the blob: catches corruption of
        # the length prefixes/count words the embedded blobs' own
        # checksums can't see.
        out.append(struct.pack('>QI', len(b), _crc(b)))
        out.append(b)
    return b''.join(out)


def decode_checkpoint(data: bytes) -> List[Dict[str, Any]]:
    """Parse a checkpoint container into its entries. Each entry dict
    gains ``entry_kind``: ``'request'`` (SKKV — a checkpointed
    in-flight request) or ``'prefix'`` (SKPF — a hot prefix chain).
    Strict end to end: every embedded blob re-validates fully."""
    _check(len(data) >= len(CKPT_MAGIC) + 8, 'short checkpoint blob')
    _check(data[:len(CKPT_MAGIC)] == CKPT_MAGIC,
           f'bad checkpoint magic {data[:len(CKPT_MAGIC)]!r}')
    off = len(CKPT_MAGIC)
    (version,) = struct.unpack_from('>I', data, off)
    off += 4
    _check(version in _SUPPORTED_CKPT_VERSIONS,
           f'unsupported checkpoint version {version}')
    (count,) = struct.unpack_from('>I', data, off)
    off += 4
    prefix_len = 12 if version >= 2 else 8
    entries: List[Dict[str, Any]] = []
    for i in range(count):
        _check(len(data) >= off + prefix_len,
               f'entry {i}: truncated length prefix')
        if version >= 2:
            blen, crc = struct.unpack_from('>QI', data, off)
        else:
            (blen,) = struct.unpack_from('>Q', data, off)
            crc = None
        off += prefix_len
        _check(len(data) >= off + blen, f'entry {i}: truncated blob')
        blob = data[off:off + blen]
        _check(crc is None or _crc(blob) == crc,
               f'entry {i}: checksum mismatch (corrupted checkpoint '
               'entry — refusing to land any row)')
        off += blen
        if blob[:len(PREFIX_MAGIC)] == PREFIX_MAGIC:
            entry = decode_prefix_chain(blob)
            entry['entry_kind'] = 'prefix'
        elif blob[:len(MAGIC)] == MAGIC:
            entry = decode_handoff(blob)
            entry['entry_kind'] = 'request'
        else:
            raise ValueError(
                f'malformed KV handoff: entry {i} has unknown magic '
                f'{blob[:4]!r}')
        entries.append(entry)
    _check(off == len(data), f'{len(data) - off} trailing bytes')
    return entries
