"""Paged KV cache with prefix caching and chunked prefill.

The slot cache (``engine.py``) reserves ``max_seq`` rows per slot and
re-prefills shared prefixes. This module is the vLLM-class capability
(the reference's serving recipes lean on vLLM's paged attention,
``llm/vllm/README.md:10``) designed for XLA's static-shape world:

- **Page pool**: one ``[L, n_pages, hkv, page, d]`` tensor shared by all
  slots; a slot holds a host-side list of page ids. HBM is proportional
  to LIVE tokens (rounded to pages), not slots × max_seq — longer
  contexts / more slots fit the same chip. Pages are HEAD-MAJOR so the
  Pallas decode kernel contracts straight off each DMA'd page with no
  in-kernel relayout (see ``ops/paged_attention.py``'s layout note).
- **Static shapes everywhere**: decode gathers each slot's first ``P``
  pages where ``P`` is a power-of-two bucket of the live maximum — the
  same compiled-program-count bound as the slot cache's ``kv_bucket``.
  Unused table entries point at page 0, a reserved null/trash page.
- **Prefix caching**: full pages are content-addressed by the hash of
  the token prefix they complete; a new request reuses the longest
  cached chain (no recompute, no duplicate storage — TTFT win for
  shared system prompts). Freed registered pages retire into an LRU
  that allocation evicts last.
- **Chunked prefill**: prompts prefill in fixed ``chunk`` slices against
  the pages written so far — one compiled program regardless of prompt
  length, bounded scratch memory (long-prompt serving).

int8 (``kv_cache_dtype='int8'``, its own knob — decoupled from the
weight quantize mode, which it follows only when left on auto): the
pool quantizes per-row like the slot cache (``k_scale``
[L, n_pages, hkv, page] fp32, head-major like the pool — the kernel
DMAs scale pages contiguously and the old per-horizon-call relayout
of the whole scale pool is gone). Every capacity decision — auto pool
sizing, preemption pressure, prefill stack caps, telemetry — costs
tokens at the QUANTIZED per-token byte width, so int8 KV ~doubles pool
token capacity as well as halving the decode KV stream.
"""
from __future__ import annotations

import functools
import hashlib
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from skypilot_tpu.models import llama
from skypilot_tpu.models.configs import ModelConfig
from skypilot_tpu.ops.attention import cached_attention, ring_decode_attention
from skypilot_tpu.telemetry import clock
from skypilot_tpu.utils.host import device_upload, host_sync

Params = Dict[str, Any]


class PagedKVCache(NamedTuple):
    """Device state. Page 0 is reserved (null/trash target for masked
    writes); the allocator never hands it out. Per-slot lengths are
    HOST state (the engine controls every admit/advance), passed as a
    small per-call argument — no device length bookkeeping."""
    pool_k: jax.Array                      # [L, n_pages, hkv, page, d]
    pool_v: jax.Array
    k_scale: Optional[jax.Array] = None    # [L, n_pages, hkv, page]
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def packed(self) -> bool:
        """int4 pools: two nibble codes per byte along head_dim
        (uint8, head_dim/2); scales ride the int8 layout."""
        return self.pool_k.dtype == jnp.uint8

    @property
    def quant_mode(self):
        """False | True (int8) | 'int4' — the mode every write-side
        quantizer keys on (``_maybe_quantize_rows``)."""
        if self.packed:
            return 'int4'
        return self.quantized

    @property
    def page_size(self) -> int:
        return self.pool_k.shape[3]

    @property
    def n_pages(self) -> int:
        return self.pool_k.shape[1]

    @classmethod
    def create(cls, cfg: ModelConfig, *, n_pages: int,
               page_size: int = 128, quantized: bool = False,
               kv_dtype: Optional[str] = None) -> 'PagedKVCache':
        if kv_dtype is None:
            kv_dtype = 'int8' if quantized else 'bf16'
        shape = (cfg.n_layers, n_pages, cfg.n_kv_heads, page_size,
                 cfg.head_dim)
        if kv_dtype == 'int4':
            if cfg.head_dim % 2:
                raise ValueError('int4 KV needs an even head_dim')
            pshape = shape[:-1] + (cfg.head_dim // 2,)
            sshape = shape[:-1]
            return cls(pool_k=jnp.zeros(pshape, jnp.uint8),
                       pool_v=jnp.zeros(pshape, jnp.uint8),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
        if kv_dtype == 'int8' or quantized:
            sshape = shape[:-1]
            return cls(pool_k=jnp.zeros(shape, jnp.int8),
                       pool_v=jnp.zeros(shape, jnp.int8),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
        return cls(pool_k=jnp.zeros(shape, cfg.dtype),
                   pool_v=jnp.zeros(shape, cfg.dtype))


def paged_cache_logical_axes(quantized: bool = False) -> PagedKVCache:
    pool = ('layers', None, 'kv_heads', None, 'head_dim')
    if quantized:
        scale = ('layers', None, 'kv_heads', None)
        return PagedKVCache(pool_k=pool, pool_v=pool,
                            k_scale=scale, v_scale=scale)
    return PagedKVCache(pool_k=pool, pool_v=pool)


# ---------------------------------------------------------------------------
# Device functions
# ---------------------------------------------------------------------------
def _flat_write_indices(table: jax.Array, starts: jax.Array, n: int,
                        valid_len: jax.Array, page: int) -> jax.Array:
    """Flat pool row index for each of ``n`` tokens appended per slot:
    token j of slot b lands at table[b, (starts_b+j)//page]*page +
    (starts_b+j)%page. Tokens past ``valid_len_b`` are redirected to the
    trash rows of page 0. Returns [slots, n] int32."""
    j = jnp.arange(n)[None, :]
    pos = starts[:, None] + j
    page_idx = pos // page
    page_id = jnp.take_along_axis(table, page_idx, axis=1)
    flat = page_id * page + pos % page
    return jnp.where(j < valid_len[:, None], flat,
                     j % page)                 # page 0 = trash


def _scatter_rows(pool: jax.Array, rows: jax.Array,
                  flat_idx: jax.Array) -> jax.Array:
    """pool [L, n_pages, hkv, page] (+ optional trailing [d]); rows
    [L, slots, n, hkv] (+ the same tail); flat_idx [slots, n] logical
    token indices (page_id * page + pos). The pool's head-major layout
    interleaves heads between a page's token rows, so each (LAYER,
    token, head) triple scatters to its own flattened row:
    layer * n_pages*hkv*page + (tok // page) * hkv*page + head * page
    + tok % page.

    Why fully flat (the layer axis folded into the scatter indices
    rather than ridden as a batch dim): every batched formulation that
    leaves L as a window dim makes XLA's layout assignment relay the
    whole pool around the scatter (a measured 3.77 GB HLO-temp copy of
    the 7B pool — an instant OOM with the pool + weights resident),
    because the scatter windows [L, ..] span the operand's major dim.
    Fully flat windows are [page-row] = the operand's own minor layout,
    the scatter runs IN PLACE (0-byte temps, donation holds), at the
    price of a slower per-row scatter (~3-4x the token-major merge,
    bounded at ~3% of a decode-horizon program)."""
    L, n_pages, hkv, page = pool.shape[:4]
    tail = pool.shape[4:]
    rows_per_layer = n_pages * hkv * page
    flat_pool = pool.reshape((L * rows_per_layer,) + tail)
    f = flat_idx.reshape(-1)                            # [slots*n]
    tok = ((f[:, None] // page) * (hkv * page)
           + jnp.arange(hkv)[None, :] * page
           + f[:, None] % page)                         # [slots*n, hkv]
    idx = (jnp.arange(L)[:, None, None] * rows_per_layer
           + tok[None]).reshape(-1)                     # [L*slots*n*hkv]
    flat_rows = rows.reshape((idx.size,) + tail)
    flat_pool = flat_pool.at[idx].set(
        flat_rows.astype(flat_pool.dtype), mode='drop')
    return flat_pool.reshape(pool.shape)


def merge_rows_into_pool(cache: PagedKVCache, k_rows, v_rows,
                         table: jax.Array, starts: jax.Array,
                         valid_len: jax.Array,
                         mesh=None) -> PagedKVCache:
    """Scatter [L, slots, n, hkv, d] new rows into the pool through the
    page table. For int8 pools the rows arrive PRE-quantized as
    ``(codes, scales)`` tuples — quantizing per layer inside the caller's
    scan keeps the stacked transient int8 (a 7B prefill chunk's bf16
    [L, n, chunk] rows alone are ~4 GB; int8 is ~1 GB).

    ``mesh``: REQUIRED whenever the pool is tp-sharded. The fully-flat
    scatter below folds the head dim into its indices, which GSPMD
    cannot keep sharded — left to propagation it ALL-GATHERS the whole
    pool every merge (measured on the CPU tp=2 audit: a pool-shaped
    all-gather per decode step — the exact resharding collective the
    paged-tp audit preset exists to ban). With a mesh the merge runs
    under ``shard_map`` instead: each tp shard scatters its local head
    slice of the rows into its local pool shard (indices are
    head-uniform, so the flat in-place scatter is unchanged per
    shard), and a dp-sharded row batch is first all-gathered over dp
    INSIDE the body — ring-rows-sized, the one known dp collective —
    so every dp shard's pool replica stays identical."""
    axes = _pool_shard_axes(cache, table, mesh)
    if axes is not None:
        return _merge_rows_sharded(cache, k_rows, v_rows, table, starts,
                                   valid_len, mesh, *axes)
    if cache.quantized:
        kq, ks = k_rows
        vq, vs = v_rows
        n = kq.shape[2]
        flat_idx = _flat_write_indices(table, starts, n, valid_len,
                                       cache.page_size)
        return cache._replace(
            pool_k=_scatter_rows(cache.pool_k, kq, flat_idx),
            pool_v=_scatter_rows(cache.pool_v, vq, flat_idx),
            k_scale=_scatter_rows(cache.k_scale, ks, flat_idx),
            v_scale=_scatter_rows(cache.v_scale, vs, flat_idx))
    n = k_rows.shape[2]
    flat_idx = _flat_write_indices(table, starts, n, valid_len,
                                   cache.page_size)
    return cache._replace(
        pool_k=_scatter_rows(cache.pool_k, k_rows, flat_idx),
        pool_v=_scatter_rows(cache.pool_v, v_rows, flat_idx))


def _pool_shard_axes(cache: PagedKVCache, table: jax.Array, mesh):
    """(tp_axis, dp_axes) the sharded merge should map over, or None
    for the plain local path (no mesh, or nothing actually shards).
    Mirrors the divisibility rules the cache shardings were built
    with: tp only when it divides the head dim, dp only when the data
    axes divide the row batch (``table``'s slot dim)."""
    if mesh is None:
        return None
    import math as _math
    hkv = cache.pool_k.shape[2]
    tp = ('tp' if mesh.shape['tp'] > 1 and hkv % mesh.shape['tp'] == 0
          else None)
    data = tuple(a for a in ('slice', 'dp', 'fsdp') if mesh.shape[a] > 1)
    dp = (data if data and table.shape[0] % _math.prod(
        mesh.shape[a] for a in data) == 0 else None)
    if tp is None and dp is None:
        return None
    return tp, dp


def _compat_shard_map(body, mesh, in_specs, out_specs):
    """shard_map across jax generations: ``jax.shard_map`` (new api,
    ``check_vma``) when present, else the 0.4.x
    ``jax.experimental.shard_map`` (``check_rep``). Replication
    checking is off either way: with a dp-sharded row batch the pool
    outputs ARE replicated over dp — every shard gathers the full row
    set before scattering — but the checker cannot see through the
    explicit all_gather."""
    if hasattr(jax, 'shard_map'):
        try:
            return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:           # older spelling of the new api
            return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def _merge_rows_sharded(cache: PagedKVCache, k_rows, v_rows,
                        table: jax.Array, starts: jax.Array,
                        valid_len: jax.Array, mesh, tp, dp
                        ) -> PagedKVCache:
    """``merge_rows_into_pool`` under ``shard_map``: per-shard flat
    scatters (in place, zero cross-shard traffic for tp) plus one
    ring-rows-sized all-gather over dp when the row batch is
    dp-sharded. See the caller's docstring for why GSPMD alone cannot
    do this without all-gathering the pool."""
    from jax.sharding import PartitionSpec as P
    quantized = cache.quantized
    pool_s = P(None, None, tp, None, None)
    spool_s = P(None, None, tp, None)
    rows_s = P(None, dp, None, tp, None)      # codes AND rank-5 scales
    args: List[Any] = [cache.pool_k, cache.pool_v]
    specs: List[Any] = [pool_s, pool_s]
    if quantized:
        kq, ks = k_rows
        vq, vs = v_rows
        args += [cache.k_scale, cache.v_scale, kq, ks, vq, vs]
        specs += [spool_s, spool_s, rows_s, rows_s, rows_s, rows_s]
    else:
        args += [k_rows, v_rows]
        specs += [rows_s, rows_s]
    args += [table, starts, valid_len]
    specs += [P(dp, None), P(dp), P(dp)]
    out_s = ((pool_s, pool_s, spool_s, spool_s) if quantized
             else (pool_s, pool_s))

    def body(*flat):
        if quantized:
            pk, pv, ksc, vsc, akq, aks, avq, avs, tbl, st, vl = flat
            rows = [akq, aks, avq, avs]
        else:
            pk, pv, akr, avr, tbl, st, vl = flat
            ksc = vsc = None
            rows = [akr, avr]
        if dp is not None:
            # Regroup the dp-sharded row batch so EVERY dp shard
            # applies every slot's updates — the pool replicates over
            # dp and must not diverge. Ring-rows-sized: the one known
            # dp collective of the decode chain.
            rows = [lax.all_gather(r, dp, axis=1, tiled=True)
                    for r in rows]
            tbl = lax.all_gather(tbl, dp, axis=0, tiled=True)
            st = lax.all_gather(st, dp, axis=0, tiled=True)
            vl = lax.all_gather(vl, dp, axis=0, tiled=True)
        local = PagedKVCache(pool_k=pk, pool_v=pv, k_scale=ksc,
                             v_scale=vsc)
        n = rows[0].shape[2]
        flat_idx = _flat_write_indices(tbl, st, n, vl, local.page_size)
        if quantized:
            akq, aks, avq, avs = rows
            return (_scatter_rows(pk, akq, flat_idx),
                    _scatter_rows(pv, avq, flat_idx),
                    _scatter_rows(ksc, aks, flat_idx),
                    _scatter_rows(vsc, avs, flat_idx))
        akr, avr = rows
        return (_scatter_rows(pk, akr, flat_idx),
                _scatter_rows(pv, avr, flat_idx))

    out = _compat_shard_map(body, mesh, tuple(specs), out_s)(*args)
    if quantized:
        return cache._replace(pool_k=out[0], pool_v=out[1],
                              k_scale=out[2], v_scale=out[3])
    return cache._replace(pool_k=out[0], pool_v=out[1])


def _maybe_quantize_rows(new_kv, quantized):
    """(k_rows, v_rows) bf16 -> ((kq, ks), (vq, vs)) when the pool is
    quantized (``quantized``: False | True/int8 | 'int4' — the cache's
    ``quant_mode``); identity otherwise. Runs INSIDE the per-layer
    scan."""
    if not quantized:
        return new_kv
    quant = (llama.quantize_kv_rows4 if quantized == 'int4'
             else llama.quantize_kv_rows)
    k_rows, v_rows = new_kv
    return (quant(k_rows), quant(v_rows))


def _gather_layer(pool_layer: jax.Array, scale_layer, table_p: jax.Array):
    """pool_layer [n_pages, hkv, page, d] -> ([slots, P*page, hkv, d],
    scales or None): contiguous token-major view of each slot's first P
    pages (the XLA attention ops are token-major; the permute fuses
    into the gather's copy — this is the fallback path, the Pallas
    kernel reads the head-major pool directly). int8 pools return
    CODES + gathered scales — the gathered copy stays int8 (half the
    write+read traffic of a dequantized gather) and the attention op
    folds the scales into logits/probs."""
    g = pool_layer[table_p]                     # [slots, P, hkv, page, d]
    slots, P, hkv, page = g.shape[:4]
    g = g.transpose(0, 1, 3, 2, 4).reshape(
        (slots, P * page, hkv) + g.shape[4:])
    if scale_layer is not None:
        s = scale_layer[table_p]                # [slots, P, hkv, page]
        s = s.transpose(0, 1, 3, 2).reshape(slots, P * page, hkv, 1)
        return g, s
    return g, None


def paged_decode_horizon(
    params: Params,
    cache: PagedKVCache,
    table_p: jax.Array,                # [slots, P] first-P page ids (static P)
    tokens: jax.Array,                 # [slots]
    lengths: jax.Array,                # [slots] live tokens (host truth)
    cfg: ModelConfig,
    *,
    horizon: int,
    sample_fn=None,
    rngs: Optional[jax.Array] = None,
    active: Optional[jax.Array] = None,
    decode_impl: str = 'gather',       # 'gather' | 'pallas' | 'cross_layer'
    pages_per_block: int = 1,          # pallas path: K pages per DMA loop
    mlora_idx: Optional[jax.Array] = None,   # [slots] adapter slot per
                                       # row (-1 = none): multi-LoRA
                                       # bank gather inside the scan
    vocab_mask: Optional[jax.Array] = None,  # [slots, vocab] bool
                                       # constrained-decoding mask
):
    """``horizon`` fused decode steps over the paged pool — the twin of
    ``llama.decode_horizon`` with the contiguous cache read replaced by
    either a per-layer page gather or the Pallas paged-attention kernel
    (``ops/paged_attention.py``: page table as scalar prefetch, pages
    DMA'd straight from HBM, length-exact per slot — the gather path
    measured 0.37x the slot cache on a v5e because the gather
    materializes a full KV copy per layer). table_p must cover
    lengths+horizon for active slots.

    READ-ONLY on the cache: returns (tokens [slots, horizon],
    ring_k, ring_v [L, slots, horizon, hkv, d]); the caller scatters
    the ring into the pool via ``merge_ring_into_pool`` in a separate
    donated program (see its docstring for why)."""
    b = tokens.shape[0]
    n_layers, n_kv, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    len0 = lengths
    pool_k, pool_v = cache.pool_k, cache.pool_v
    ks_pool, vs_pool = cache.k_scale, cache.v_scale
    # Scales are STORED head-major [L, n_pages, hkv, page] (like the
    # pool): the kernel DMAs them per page with no relayout — the old
    # token-major storage cost one full scale-pool relayout (~0.5 GB
    # on a 7B) per horizon program, scaling with pool capacity.
    layer_params = params['layers']
    ring_k = jnp.zeros((n_layers, b, horizon, n_kv, hd), cfg.dtype)
    ring_v = jnp.zeros_like(ring_k)
    if rngs is None:
        rngs = jnp.zeros((horizon, 2), jnp.uint32)

    def one_step(carry, step_in):
        ring_k, ring_v, tok = carry
        i, rng = step_in
        x = llama._embed_tokens(params, tok[:, None], cfg)
        positions = (len0 + i)[:, None]

        def layer_body(xc, layer_and_idx):
            layer, li = layer_and_idx
            rk = lax.dynamic_index_in_dim(ring_k, li, 0, keepdims=False)
            rv = lax.dynamic_index_in_dim(ring_v, li, 0, keepdims=False)

            if decode_impl == 'pallas':
                # The kernel takes the FULL stacked pool with the layer
                # as a scalar-prefetch block index: slicing the pool
                # here (dynamic_index_in_dim) would force XLA to
                # materialize a copy of the layer's pool as the
                # pallas_call operand — one extra read+write of the
                # whole KV stream per decode step (measured 0.4x the
                # slot cache on a 7B before this change).
                from skypilot_tpu.ops.paged_attention import (
                    merge_partial_with_ring_self, paged_decode_attention)
                interp = jax.default_backend() != 'tpu'

                def attn_fn(q, k, v):
                    partial = paged_decode_attention(
                        q[:, 0], pool_k, pool_v, table_p, len0,
                        ks_pool, vs_pool, layer=li, interpret=interp,
                        pages_per_block=pages_per_block)
                    return merge_partial_with_ring_self(
                        partial, q, k, v, rk, rv, i)
            elif decode_impl == 'cross_layer':
                # Fused-merge kernel: the ring + current-token blocks
                # fold into the cache softmax INSIDE the kernel, so the
                # per-layer XLA merge program (and its f32 partial
                # triple bouncing through HBM every layer of every
                # step) disappears from the scan. Same scalar-prefetch
                # pool discipline as 'pallas'.
                from skypilot_tpu.ops.paged_attention import (
                    paged_decode_attention_fused)
                interp = jax.default_backend() != 'tpu'

                def attn_fn(q, k, v):
                    out = paged_decode_attention_fused(
                        q[:, 0], k[:, 0], v[:, 0], rk, rv, i,
                        pool_k, pool_v, table_p, len0,
                        ks_pool, vs_pool, layer=li, interpret=interp)
                    return out[:, None]
            else:
                # The ONE grandfathered per-layer gather on the decode
                # path (GC121): the XLA-only fallback for backends /
                # head_dims the kernels don't cover. Every suppression
                # below is deliberate — a new gather-per-layer site
                # anywhere else on the decode path hard-fails
                # graftcheck.
                pk = lax.dynamic_index_in_dim(pool_k, li, 0,  # graftcheck: disable=GC121
                                              keepdims=False)
                pv = lax.dynamic_index_in_dim(pool_v, li, 0,  # graftcheck: disable=GC121
                                              keepdims=False)
                sk = (lax.dynamic_index_in_dim(ks_pool, li, 0,  # graftcheck: disable=GC121
                                               keepdims=False)
                      if cache.quantized else None)
                sv = (lax.dynamic_index_in_dim(vs_pool, li, 0,  # graftcheck: disable=GC121
                                               keepdims=False)
                      if cache.quantized else None)
                ck, sck = _gather_layer(pk, sk, table_p)  # graftcheck: disable=GC121
                cv, scv = _gather_layer(pv, sv, table_p)  # graftcheck: disable=GC121

                def attn_fn(q, k, v):
                    return ring_decode_attention(q, k, v, ck, cv, len0,
                                                 rk, rv, i, k_scale=sck,
                                                 v_scale=scv)

            xc, new_kv, _ = llama._layer_core(layer, xc, cfg, positions,
                                              attn_fn,
                                              mlora_idx=mlora_idx)
            return xc, new_kv

        x, (k_rows, v_rows) = lax.scan(
            layer_body, x, (layer_params, jnp.arange(n_layers)))
        ring_k = lax.dynamic_update_slice(
            ring_k, k_rows.astype(ring_k.dtype), (0, 0, i, 0, 0))
        ring_v = lax.dynamic_update_slice(
            ring_v, v_rows.astype(ring_v.dtype), (0, 0, i, 0, 0))
        x = llama.rms_norm(x, params['final_norm'], cfg.norm_eps,
                           cfg.norm_plus_one)
        logits = llama._unembed_logits(params, x, cfg)[:, 0]
        # Constrained decoding at logits production (covers the raw
        # greedy argmax branch too).
        logits = llama.apply_vocab_mask(logits, vocab_mask)
        if sample_fn is None:
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        else:
            nxt = sample_fn(logits, rng)
        # NaN blast-radius isolation: poisoned rows emit the sentinel
        # (host evicts exactly that request at readback; co-batched
        # slots continue) — see llama.mask_nonfinite_tokens.
        nxt = llama.mask_nonfinite_tokens(logits, nxt)
        return (ring_k, ring_v, nxt), nxt

    (ring_k, ring_v, _), toks = lax.scan(
        one_step, (ring_k, ring_v, tokens), (jnp.arange(horizon), rngs))
    return toks.T, ring_k, ring_v


def merge_ring_into_pool(cache: PagedKVCache, ring_k, ring_v,
                         table_p: jax.Array, lengths: jax.Array,
                         active: Optional[jax.Array],
                         mesh=None) -> PagedKVCache:
    """Scatter a decode horizon's ring rows into the pool — a SEPARATE
    jitted program from the token computation (engine donates the cache
    here). Keeping the pool update out of the program whose layer scan
    feeds the pool to pallas_call is what lets XLA alias the donated
    pool buffers in place; fused, the pool double-buffers (+4.4 GB on
    the 7B bench — an OOM)."""
    horizon = ring_k.shape[2]
    act = (active.astype(jnp.int32) if active is not None
           else jnp.ones_like(lengths))
    rk, rv = _maybe_quantize_rows((ring_k, ring_v), cache.quant_mode)
    return merge_rows_into_pool(cache, rk, rv, table_p, lengths,
                                valid_len=act * horizon, mesh=mesh)


def paged_prefill_chunk(
    params: Params,
    cache: PagedKVCache,
    table_p: jax.Array,                # [n, P] pages covering ctx+chunk
    tokens: jax.Array,                 # [n, chunk] (padded)
    lengths: jax.Array,                # [n] context already in the pool
    valid: jax.Array,                  # [n] tokens of this chunk in use
    want_idx: jax.Array,               # [n] in-chunk index of the row whose
                                       #     next token the caller needs
                                       #     (-1: none)
    cfg: ModelConfig,
    temps: jax.Array = None,           # [n] per-row sampling params
    topks: jax.Array = None,
    topps: jax.Array = None,
    rng: jax.Array = None,
    w8a8: bool = False,
    mesh=None,
    mlora_idx: Optional[jax.Array] = None,   # [n] adapter slot per row
    vocab_mask: Optional[jax.Array] = None,  # [n, vocab] bool mask for
                                       # the completing rows' first token
):
    """One fixed-size prefill chunk for ``n`` slots: attends against the
    pages written so far (each slot's ``lengths``) plus causal
    self-attention within the chunk, scatters the new rows into the
    pool, and SAMPLES each completing row's next token ON DEVICE with
    that row's params (temperature/top-k/top-p; ``engine.
    sample_tokens`` — greedy rows take temp<=0's argmax path).

    Returns (first_tokens [n] int32, new cache). Device-side sampling
    is what lets the engine feed a completing slot's token straight
    into the device token vector at ENQUEUE time: the slot starts
    decoding on the very next horizon instead of idling 1-2 pipelined
    calls for a host logits readback + host sampling (measured: that
    idle was a double-digit share of sustained-serving slot time once
    decode itself got fast). ``w8a8`` quantizes the layer-matmul
    activations per token (prefill is compute-bound; see
    ``quantization.w8a8_region``) — the unembed stays W8A16."""
    n, chunk = tokens.shape
    len0 = lengths
    pool_k, pool_v = cache.pool_k, cache.pool_v
    ks_pool, vs_pool = cache.k_scale, cache.v_scale
    x = llama._embed_tokens(params, tokens, cfg)
    positions = len0[:, None] + jnp.arange(chunk)[None, :]

    def layer_body(xc, layer_and_idx):
        layer, li = layer_and_idx
        pk = lax.dynamic_index_in_dim(pool_k, li, 0, keepdims=False)
        pv = lax.dynamic_index_in_dim(pool_v, li, 0, keepdims=False)
        sk = (lax.dynamic_index_in_dim(ks_pool, li, 0, keepdims=False)
              if cache.quantized else None)
        sv = (lax.dynamic_index_in_dim(vs_pool, li, 0, keepdims=False)
              if cache.quantized else None)
        ck, sck = _gather_layer(pk, sk, table_p)
        cv, scv = _gather_layer(pv, sv, table_p)

        def attn_fn(q, k, v):
            return cached_attention(q, k, v, ck, cv, len0,
                                    k_scale=sck, v_scale=scv)

        xc, new_kv, _ = llama._layer_core(layer, xc, cfg, positions,
                                          attn_fn, mlora_idx=mlora_idx)
        # Quantize inside the scan: the stacked [L, n, chunk] ys stay
        # int8 (the bf16 stack is the 7B prefill's biggest transient).
        return xc, _maybe_quantize_rows(new_kv, cache.quant_mode)

    import contextlib
    from skypilot_tpu.models.quantization import w8a8_region
    with (w8a8_region() if w8a8 else contextlib.nullcontext()):
        x, (k_rows, v_rows) = lax.scan(
            layer_body, x, (params['layers'], jnp.arange(cfg.n_layers)))
    x = llama.rms_norm(x, params['final_norm'], cfg.norm_eps,
                       cfg.norm_plus_one)
    idx = jnp.clip(want_idx, 0, chunk - 1)
    last_x = jnp.take_along_axis(x, idx[:, None, None], axis=1)
    logits = llama._unembed_logits(params, last_x, cfg)[:, 0]
    logits = llama.apply_vocab_mask(logits, vocab_mask)
    # All-greedy batches (the common case) take the argmax path
    # STATICALLY: sample_tokens sorts the [n, vocab] logits, and a TPU
    # sort over vocab=32k costs hundreds of ms — compiled into every
    # admission step, it halved sustained serving before this gate.
    if temps is None:                      # static: all rows greedy
        first = jnp.argmax(logits, -1).astype(jnp.int32)
    else:
        from skypilot_tpu.inference.engine import sample_tokens
        first = sample_tokens(logits, rng, temps, topks, topps)
    # NaN guard on the first-token sample too: a prompt that blows up
    # in prefill must evict at readback, not stream argmax-of-NaN.
    first = llama.mask_nonfinite_tokens(logits, first)

    new_cache = merge_rows_into_pool(cache, k_rows, v_rows, table_p,
                                     len0, valid_len=valid, mesh=mesh)
    return first, new_cache


def paged_spec_verify(
    params: Params,
    cache: PagedKVCache,
    table_p: jax.Array,                # [n, P] pages covering len+k+1
    tokens: jax.Array,                 # [n] current token per slot (t0)
    proposals: jax.Array,              # [n, k] drafted continuations
    n_prop: jax.Array,                 # [n] valid drafts per slot
    lengths: jax.Array,                # [n] context already in the pool
    active: jax.Array,                 # [n] bool decodable mask
    cfg: ModelConfig,
    *,
    sample: bool,
    temps: jax.Array = None,
    topks: jax.Array = None,
    topps: jax.Array = None,
    rng: jax.Array = None,
    w8a8: bool = False,
    mesh=None,
    mlora_idx: Optional[jax.Array] = None,   # [n] adapter slot per row
    vocab_mask: Optional[jax.Array] = None,  # [n, vocab] bool mask
                                       # (broadcast over the k+1 verify
                                       # positions)
):
    """Speculative verify over the paged pool: one forward over the
    ``k+1`` positions ``[t0, d1..dk]`` per slot against the pages
    written so far (``paged_prefill_chunk``'s attention math with
    every position's logits kept), device-side acceptance
    (``speculative.verify_tokens``), and a MASKED merge of the accepted
    rows — ``merge_rows_into_pool``'s ``valid_len`` mask redirects rows
    past each slot's commit count to the trash page, so per-slot
    variable acceptance never changes a program shape.

    Returns ``(commit [n, k+1], n_commit [n], new_tok [n], new_cache)``
    where ``new_tok`` is each slot's next-round current token (the last
    committed one; unchanged for inactive slots)."""
    from skypilot_tpu.inference import speculative
    n, k = proposals.shape
    seq = jnp.concatenate([tokens[:, None], proposals], axis=1)
    len0 = lengths
    pool_k, pool_v = cache.pool_k, cache.pool_v
    ks_pool, vs_pool = cache.k_scale, cache.v_scale
    x = llama._embed_tokens(params, seq, cfg)
    positions = len0[:, None] + jnp.arange(k + 1)[None, :]

    def layer_body(xc, layer_and_idx):
        layer, li = layer_and_idx
        pk = lax.dynamic_index_in_dim(pool_k, li, 0, keepdims=False)
        pv = lax.dynamic_index_in_dim(pool_v, li, 0, keepdims=False)
        sk = (lax.dynamic_index_in_dim(ks_pool, li, 0, keepdims=False)
              if cache.quantized else None)
        sv = (lax.dynamic_index_in_dim(vs_pool, li, 0, keepdims=False)
              if cache.quantized else None)
        ck, sck = _gather_layer(pk, sk, table_p)
        cv, scv = _gather_layer(pv, sv, table_p)

        def attn_fn(q, kk, vv):
            return cached_attention(q, kk, vv, ck, cv, len0,
                                    k_scale=sck, v_scale=scv)

        xc, new_kv, _ = llama._layer_core(layer, xc, cfg, positions,
                                          attn_fn, mlora_idx=mlora_idx)
        return xc, _maybe_quantize_rows(new_kv, cache.quant_mode)

    import contextlib
    from skypilot_tpu.models.quantization import w8a8_region
    with (w8a8_region() if w8a8 else contextlib.nullcontext()):
        x, (k_rows, v_rows) = lax.scan(
            layer_body, x, (params['layers'], jnp.arange(cfg.n_layers)))
    x = llama.rms_norm(x, params['final_norm'], cfg.norm_eps,
                       cfg.norm_plus_one)
    logits = llama._unembed_logits(params, x, cfg)      # [n, k+1, v]
    # Constrained rows verify against the MASKED distribution: both the
    # acceptance test and the bonus/resample draw obey the grammar.
    logits = llama.apply_vocab_mask(logits, vocab_mask)
    commit, n_commit = speculative.verify_tokens(
        logits, proposals, n_prop, rng, temps, topks, topps,
        sample=sample)
    n_commit = jnp.where(active, n_commit, 0)
    new_cache = merge_rows_into_pool(cache, k_rows, v_rows, table_p,
                                     len0, valid_len=n_commit, mesh=mesh)
    nxt = jnp.take_along_axis(
        commit, jnp.maximum(n_commit - 1, 0)[:, None], axis=1)[:, 0]
    new_tok = jnp.where(active, nxt, tokens)
    return commit, n_commit, new_tok, new_cache


# ---------------------------------------------------------------------------
# Host-side allocator + prefix index
# ---------------------------------------------------------------------------
class PageAllocator:
    """Free-list + refcount + content-addressed prefix index.

    Pages: 1..n_pages-1 allocatable (0 reserved). A *registered* page
    completes a full token prefix and carries its hash; when its
    refcount hits 0 it retires into an LRU (``retained``) that stays
    hit-able for prefix reuse until pool pressure evicts it."""

    def __init__(self, n_pages: int, page_size: int):
        self.page_size = page_size
        self.n_pages = n_pages
        self.free: List[int] = list(range(n_pages - 1, 0, -1))
        self.refcount = np.zeros(n_pages, np.int32)
        self.page_hash: Dict[int, bytes] = {}      # page -> prefix hash
        self.by_hash: Dict[bytes, int] = {}        # prefix hash -> page
        # insertion-ordered dict as LRU: oldest first
        self.retained: Dict[int, None] = {}
        self.prefix_hits = 0
        self.prefix_misses = 0

    # -------------------------------------------------- alloc/free
    @property
    def available(self) -> int:
        return len(self.free) + len(self.retained)

    def alloc(self) -> int:
        if self.free:
            page = self.free.pop()
        elif self.retained:
            page = next(iter(self.retained))       # LRU victim
            del self.retained[page]
            self._forget(page)
        else:
            raise MemoryError('KV page pool exhausted')
        self.refcount[page] = 1
        return page

    def retain(self, page: int) -> None:
        if page in self.retained:                  # revive from LRU
            del self.retained[page]
        self.refcount[page] += 1

    def release(self, page: int) -> None:
        self.refcount[page] -= 1
        assert self.refcount[page] >= 0, page
        if self.refcount[page] == 0:
            if page in self.page_hash:
                self.retained[page] = None         # prefix-reusable, LRU
            else:
                self.free.append(page)

    def _forget(self, page: int) -> None:
        h = self.page_hash.pop(page, None)
        if h is not None and self.by_hash.get(h) == page:
            del self.by_hash[h]

    # -------------------------------------------------- prefix index
    def _chain_hashes(self, prompt: List[int], upto: int):
        """Rolling per-page chain digests: h_i = sha1(h_{i-1} ||
        tokens of page i). O(len) total — hashing full prefixes per
        boundary would be O(len^2) on long prompts."""
        ps = self.page_size
        h = b''
        arr = np.asarray(prompt, np.int32)
        for i in range(upto):
            h = hashlib.sha1(h + arr[i * ps:(i + 1) * ps].tobytes()
                             ).digest()
            yield i, h

    def match_prefix(self, prompt: List[int]) -> List[int]:
        """Longest chain of cached full pages covering the prompt's
        *reusable* prefix (never the final token — its logits must be
        computed). Retains every matched page for the caller."""
        matched: List[int] = []
        max_full = (len(prompt) - 1) // self.page_size
        for _, h in self._chain_hashes(prompt, max_full):
            page = self.by_hash.get(h)
            if page is None or (self.refcount[page] == 0
                                and page not in self.retained):
                break
            matched.append(page)
        for p in matched:
            self.retain(p)
        if matched:
            self.prefix_hits += 1
        else:
            self.prefix_misses += 1
        return matched

    def register_prefix(self, prompt: List[int], pages: List[int],
                        n_matched: int) -> None:
        """Content-address the full pages a prefill just wrote (pages
        beyond ``n_matched``); an existing entry for the same hash keeps
        the older page (already shared).

        Validates BEFORE touching the index that ``pages`` actually
        covers every full page of ``prompt``: callers used to be
        locally-written pages only (count always matched by
        construction), but a KV-handoff ingest registers pages built
        from wire bytes — a truncated row batch whose token length
        claims more pages than were landed would otherwise
        content-address pages that hold other (or no) data, silently
        poisoning every future prefix hit on that chain."""
        max_full = (len(prompt) - 1) // self.page_size
        if len(pages) < max_full:
            raise ValueError(
                f'register_prefix: {len(pages)} page(s) cannot cover '
                f'the {max_full} full page(s) of a {len(prompt)}-token '
                'context (truncated row batch?)')
        for i, h in self._chain_hashes(prompt, max_full):
            if i < n_matched:
                continue
            page = pages[i]
            if h not in self.by_hash:
                self.by_hash[h] = page
                self.page_hash[page] = h


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------
from skypilot_tpu.inference.engine import _EngineBase
from skypilot_tpu.inference.speculative import SpeculativeMixin


class PagedInferenceEngine(SpeculativeMixin, _EngineBase):
    """Continuous-batching engine over the paged pool. Same public API
    as ``engine.InferenceEngine`` (the serve layer treats them
    interchangeably — both extend ``_EngineBase``); differs inside:

    - admission matches cached prefix pages, then chunk-prefills only
      the uncached tail (one compiled program per (n, P) bucket pair,
      any prompt length);
    - decode gathers pages instead of slicing a per-slot reservation;
    - HBM = page pool sized by TOTAL live tokens, not slots x max_seq;
    - ``speculate_k > 0``: decode runs the speculative
      propose→verify→commit loop (``inference/speculative.py``) with
      masked page-pool commits.
    """

    _PREFILL_N_BUCKETS = (1, 2, 4, 8, 16, 32)
    _HORIZON_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)
    # Stacked-chunk KV transient budget. Sized so n_max reaches 16 on a
    # 7B (chunk 256, ~270 KB/token): with decode at ~1800 tok/s/chip a
    # 32-step horizon completes ~9.5 req/s, and the old 8-wide chunk
    # batches (~9.4 admits/s) were the sustained-serving bottleneck —
    # slots idled waiting on admission while decode ran 2x faster than
    # round 4. The pool auto-size reserves this same constant, so the
    # pool shrinks ~0.75 GB (~22 pages) to pay for it.
    _PREFILL_STACK_BUDGET = int(1.5e9)
    # Ring-buffer byte cap. At batch 48 on a 7B this admits horizon 32
    # (ring 1.6 GB, k+v): _auto_n_pages reserves 2*row*h_max so the
    # pool shrinks to pay for it — a LONGER horizon halves the
    # admission interleaves and fixed per-call costs per token, which
    # measured as ~40% of sustained-serving device time at h=16. (The
    # old 512 MB cap predates the reserve accounting: h=32 at batch 48
    # OOM'd when the pool was sized ignoring the ring.)
    _RING_BYTES_CAP_PAGED = int(1.7e9)

    def __init__(self, cfg: ModelConfig, params=None, *,
                 max_batch: int = 8, max_seq: int = 1024,
                 page_size: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 chunk: int = 256,
                 prefill_chunk_tokens: Optional[int] = None,
                 decode_priority_ratio: Optional[float] = None,
                 decode_steps_per_call: Optional[int] = None,
                 mesh=None, rng_seed: int = 0, attn_impl: str = 'auto',
                 quantize: Optional[str] = None,
                 kv_cache_dtype: Optional[str] = None,
                 donate_params: bool = False,
                 decode_impl: str = 'auto',
                 prefill_w8a8: bool = False,
                 pages_per_block: int = 1,
                 speculate_k: int = 0,
                 adapter_slots: int = 0,
                 adapter_dir: Optional[str] = None,
                 adapter_rank: int = 8,
                 adapter_targets: Optional[Any] = None,
                 telemetry: bool = True):
        from skypilot_tpu.inference.engine import prepare_params
        from skypilot_tpu.parallel import mesh as mesh_lib
        self._init_telemetry(telemetry)
        self.max_batch = max_batch
        self.max_seq = max_seq
        # page_size=None auto-selects a FAST-PATH size after the
        # quantize mode is known (see below); explicit values are the
        # user's to keep (with the misalignment warning).
        self._page_user = page_size is not None
        # ``prefill_chunk_tokens`` is the cross-engine spelling of the
        # chunk knob (the slot engine and serve layer use it); it wins
        # over ``chunk`` when given.
        if prefill_chunk_tokens is not None:
            chunk = prefill_chunk_tokens
        self.chunk = chunk
        # Decode share of the interleaved token budget while prompts
        # are mid-prefill (see _EngineBase._interleave_horizon). None
        # keeps this engine's measured-best fixed interleave horizon.
        self.decode_priority_ratio = decode_priority_ratio
        # Multi-step on-device decode (see _EngineBase): pin every
        # decode call at exactly k fused steps.
        self.decode_steps_per_call = self._validate_decode_steps(
            decode_steps_per_call)
        self.mesh = mesh
        self.attn_impl = attn_impl
        # Opt-in W8A8 prefill (int8 activations on the compute-bound
        # chunk prefill; decode unaffected) — see quantization.w8a8_region.
        self.prefill_w8a8 = prefill_w8a8
        # Pallas decode: K pages DMA'd/computed per loop iteration.
        # With the kernel's conditional tail-page DMAs reads are
        # length-exact at ANY K, so K only trades fori_loop/DMA-issue
        # overhead against double-buffer granularity. Measured on the
        # 7B int8 at batch 48 (anchor workload, steady): K=1 1790,
        # K=2 1724, K=4 1625, K=8 1620 tok/s/chip — single-page blocks
        # win now that no transpose hides in the loop body.
        self.pages_per_block = pages_per_block
        self._rng = jax.random.PRNGKey(rng_seed)
        self._host_rng = np.random.default_rng(rng_seed)
        cfg, self.params, quantize = prepare_params(
            cfg, params, quantize=quantize, mesh=mesh,
            donate_params=donate_params)
        self.cfg = cfg
        # KV storage dtype is its OWN knob, decoupled from the weight
        # quantize mode (None/'auto' follows it — the historical
        # coupling). Resolved AFTER prepare_params so pre-quantized
        # param trees (load_checkpoint(quantize='int8')) resolve 'auto'
        # correctly too. The resulting flag drives the pool dtype,
        # page-size selection, pool sizing, and every capacity surface.
        from skypilot_tpu.inference.engine import resolve_kv_cache_dtype
        self.kv_cache_dtype = resolve_kv_cache_dtype(kv_cache_dtype,
                                                     quantize)
        kv_int8 = self.kv_cache_dtype == 'int8'
        if page_size is None:
            page_size = self._auto_page_size(cfg, max_seq, kv_int8,
                                             mesh)
        if self._page_user and page_size % 128 != 0 and kv_int8 \
                and self._int8_fast_path_reachable(cfg, mesh):
            # The manual-DMA int8 kernel's per-page scale blocks need a
            # 128-aligned minor dim; off the fast path decode drops to
            # the per-page-grid kernel (~0.71x measured). Where that
            # kernel is actually reachable, an explicit misaligned size
            # is a pure footgun (the multichip dryrun's page_size=8 int8
            # pool shipped the 0.7x path for weeks) — so it is ROUNDED
            # UP to the next fast-path size, loudly. Elsewhere (CPU
            # tests, gather path, meshes) alignment is free and the
            # explicit size is the user's to keep.
            adjusted = self._fast_path_page_size(page_size)
            import warnings
            warnings.warn(
                f'page_size={page_size} is not a multiple of 128: int8 '
                'paged decode would fall off the manual-DMA fast path '
                f'(~0.7x throughput). Auto-adjusted to {adjusted}; '
                'pass a multiple of 128 to silence this.')
            page_size = adjusted
        self.page = page_size
        from skypilot_tpu.models import quantization
        # PER-DEVICE stored parameter bytes (sharded leaves count their
        # local shard; dp-replicated leaves count in full) — the floor
        # pool auto-sizing subtracts and the weight stream the ring cap
        # is sized against. Dividing global bytes by mesh.size was
        # wrong in both directions once dp>1 exists: dp REPLICATES the
        # weights, so a (tp=1, dp=2) mesh would have claimed half the
        # resident bytes and oversized the pool into an OOM.
        self._param_bytes = quantization.per_device_bytes(self.params)

        # Auto-sized pools reserve HBM for the long-horizon ring (see
        # _auto_n_pages); an EXPLICIT n_pages made no such bargain, so
        # its ring budget stays at the conservative cap — a user pool
        # sized to fill HBM under the old 512 MB assumption must not
        # suddenly meet a 3x ring at runtime.
        self._pool_auto_sized = n_pages is None
        if n_pages is None:
            n_pages = self._auto_n_pages(cfg, max_batch, max_seq,
                                         page_size)
        self.alloc = PageAllocator(n_pages, page_size)
        self.cache = PagedKVCache.create(cfg, n_pages=n_pages,
                                         page_size=page_size,
                                         kv_dtype=self.kv_cache_dtype)
        # Pre-partitioned pool + pinned output shardings: the pool is
        # device_put ONCE (kv heads over tp; pages replicated — the
        # page table indexes them dynamically, so a page-sharded pool
        # would turn every gather into a collective), and every jitted
        # step that returns it pins this same tree as out_shardings.
        # The decode ring rows are pinned too (``_ring_sh``): the
        # decode program's ring OUTPUT sharding is exactly the merge
        # program's ring INPUT sharding, so the decode→merge chain has
        # no resharding between programs.
        self._cache_sh = None
        self._ring_sh = None
        if mesh is not None:
            self._cache_sh = mesh_lib.tree_shardings(
                paged_cache_logical_axes(self.cache.quantized), mesh,
                shapes=self.cache)
            self.cache = jax.device_put(self.cache, self._cache_sh)
            from jax.sharding import NamedSharding
            self._ring_sh = NamedSharding(mesh, mesh_lib.spec_for(
                ('layers', 'batch', None, 'kv_heads', 'head_dim'),
                shape=(cfg.n_layers, max_batch, 1, cfg.n_kv_heads,
                       cfg.head_dim),
                mesh=mesh))

        if decode_impl == 'auto':
            # The Pallas kernel needs 128-lane head_dim; on CPU its
            # interpret mode is correct but slow, so auto picks it only
            # on a real TPU backend (tests opt in explicitly). int4
            # pools stay on the gather path under auto for now: the
            # packed uint8 page blocks halve the minor dim below the
            # 128-lane tile (explicit 'pallas'/'cross_layer' still
            # work — interpret-validated — for users who opt in).
            decode_impl = ('pallas' if cfg.head_dim % 128 == 0
                           and jax.default_backend() == 'tpu'
                           and self.kv_cache_dtype != 'int4'
                           and mesh is None else 'gather')
        self.decode_impl = decode_impl

        # host slot state (queue/slots/finish from _EngineBase)
        self._init_slots(max_batch)
        self._pages: List[List[int]] = [[] for _ in range(max_batch)]
        # slot -> tokens of its prompt TAIL prefilled so far; a slot in
        # this dict is assigned but not yet decodable (continuous
        # admission interleaves its remaining chunks with decode).
        self._prefill_off: Dict[int, int] = {}
        # Extra async-pipeline state beyond _EngineBase's (_tok_dev /
        # _pending live there): slots whose DEVICE-sampled first token
        # hasn't surfaced to the host yet sit in _await_first. They
        # DECODE meanwhile (the token merged into the device token
        # vector at prefill enqueue); membership only gates the
        # first-token event + finish bookkeeping, and the preemption
        # path drains the pipeline before acting so requeued contexts
        # stay complete.
        self._await_first: set = set()
        self._slot_inflight = np.zeros(max_batch, np.int64)
        # Fixed-shape first-token merge: padding entries scatter to the
        # out-of-range sentinel max_batch and are dropped.
        self._merge_tokens_drop = jax.jit(
            lambda tok, slots, vals: tok.at[slots].set(vals,
                                                       mode='drop'))
        # Early-recycled requests whose tail tokens are still in the
        # pipeline: in neither _queue nor _slots, so has_work() and
        # cancel() must consult this registry (a serve loop that slept
        # on queue+slots alone stranded the final tokens forever, and
        # a disconnecting client's request leaked uncancellable).
        self._lagging: Dict[int, Any] = {}
        self._eager_drain = True       # see step()'s opportunistic drain
        # Bumped when a slot is freed: an in-flight call enqueued for a
        # previous occupant must not decrement the NEW occupant's
        # inflight count at processing time.
        self._slot_epoch = np.zeros(max_batch, np.int64)
        self._deferred_events: List[Tuple[int, int, bool]] = []
        # Multi-tenant adapter bank (adapter_slots > 0): the stacked
        # multi-LoRA bank installs into params['layers']['mlora']
        # BEFORE any program traces; adapter_slots=0 leaves the params
        # tree — and every traced program — byte-identical to before.
        self.adapters = None
        if adapter_slots > 0:
            from skypilot_tpu.inference import adapters as adapters_lib
            self.adapters = adapters_lib.AdapterRegistry(
                self, slots=adapter_slots, rank=adapter_rank,
                adapter_dir=adapter_dir, targets=adapter_targets)
        self._decode_fn = self._build_decode()
        self._prefill_fns: Dict[Tuple[int, int], Any] = {}
        # A prefill chunk-batch stacks [L, n, chunk] KV rows as a scan
        # transient; cap n so the stack stays within
        # _PREFILL_STACK_BUDGET (at n=32 x chunk=256 on a 7B the two
        # stacks alone are 2 GB — the compile OOM'd the chip).
        # _auto_n_pages reserves the same budget.
        tok_bytes = self._page_bytes(self.cfg, 1, self.kv_cache_dtype,
                                     mesh=self.mesh)
        n_fit = int(self._PREFILL_STACK_BUDGET // max(1, chunk *
                                                      tok_bytes))
        self._prefill_n_max = 1
        for b in self._PREFILL_N_BUCKETS:
            if b <= n_fit:
                self._prefill_n_max = b
        self.chunks_prefilled = 0          # diagnostics (prefix-hit wins)
        self.preemptions = 0               # pool-pressure recomputes
        # KV handoff programs (disaggregated serving): export page
        # gathers keyed by P bucket, ingest merges keyed by (rows, P).
        self._export_fns: Dict[int, Any] = {}
        self._ingest_fns: Dict[Tuple[int, int], Any] = {}
        # Hot-prefix heat tracker (spot resilience): chain digest ->
        # {'tokens', 'hits'} for recently registered/matched prefix
        # chains, bounded LRU-by-heat. The preemption checkpoint
        # exports the hottest chains' page bytes so a replacement
        # replica boots near-warm (export_prefix_snapshots /
        # warm_prefix).
        self._prefix_heat: Dict[bytes, Dict[str, Any]] = {}
        self._PREFIX_HEAT_MAX = 64
        # Speculative decoding (0 = off): n-gram propose + batched
        # verify with masked page-pool commits.
        self._init_spec(speculate_k)

    @staticmethod
    def _int8_fast_path_reachable(cfg: ModelConfig, mesh) -> bool:
        """True when ``decode_impl='auto'`` would pick the Pallas
        manual-DMA int8 kernel — the one condition under which page
        alignment matters (its per-page scale blocks need a 128-aligned
        minor dim)."""
        return (cfg.head_dim % 128 == 0
                and jax.default_backend() == 'tpu' and mesh is None)

    @staticmethod
    def _fast_path_page_size(page_size: int) -> int:
        """Smallest fast-path-compatible (128-multiple) page size that
        holds at least ``page_size`` tokens."""
        return max(128, -(-page_size // 128) * 128)

    @classmethod
    def _auto_page_size(cls, cfg: ModelConfig, max_seq: int,
                        kv_int8: bool, mesh) -> int:
        """Default page size: stay on the decode fast path. Wherever
        the Pallas manual-DMA int8 kernel is reachable (the same
        condition ``decode_impl='auto'`` uses to pick it), pages must
        be 128-aligned — the multichip dryrun's explicit page_size=8
        int8 pool tripped the ~0.7x per-page-grid fallback this guard
        exists to catch (explicit misaligned sizes are now auto-rounded
        up in ``__init__`` under the same condition). Elsewhere (bf16
        pools, CPU tests, gather path) alignment is free, so
        short-context configs get smaller pages instead of one page per
        slot."""
        if kv_int8 and cls._int8_fast_path_reachable(cfg, mesh):
            return 128
        from skypilot_tpu.inference.engine import _bucket_len
        return min(128, _bucket_len(max(8, max_seq // 8), minimum=8))

    @staticmethod
    def _page_bytes(cfg: ModelConfig, page_size: int,
                    quantized, mesh=None) -> int:
        """Stored bytes of one page; with ``mesh``, PER-DEVICE bytes
        (kv heads shard over tp — the pool's pages replicate over dp,
        so dp never divides). HBM sizing passes the mesh; reporting
        surfaces keep the global cost."""
        from skypilot_tpu.inference.engine import kv_token_bytes
        return kv_token_bytes(cfg, quantized, mesh=mesh) * page_size

    def _auto_n_pages(self, cfg: ModelConfig, max_batch: int,
                      max_seq: int, page_size: int) -> int:
        """Size the pool from FREE HBM after the weights landed, not
        from slot-cache parity: the pool is the paged engine's whole
        advantage (HBM proportional to live tokens -> more concurrent
        long contexts on the same chip), so idle HBM is wasted
        capacity. A reserve covers decode transients (the horizon ring,
        unembed logits, prefill activations) and XLA workspace. Falls
        back to slot parity when the backend has no memory stats (CPU
        tests, interpret mode)."""
        parity = max_batch * -(-max_seq // page_size) + 1
        # Per-page byte cost follows the KV CACHE dtype, not the weight
        # dtype — with the flags decoupled (int8 weights + bf16 KV or
        # vice versa) sizing the pool off the params would mis-state
        # capacity by 2x in either direction.
        quantized = self.kv_cache_dtype
        try:
            stats = jax.devices()[0].memory_stats()
            limit = stats['bytes_limit']
            used = stats['bytes_in_use']
        except Exception:  # pylint: disable=broad-except
            # memory_stats is unavailable through some PJRT transports
            # (observed: the remote-tunnel TPU backend returns none —
            # and the silent parity fallback left a 7B serving config
            # at 241 pages with an UNRESERVED ring: horizon 32 OOM'd).
            # Fall back to the static per-generation HBM table; the
            # usable fraction matches the observed bytes_limit/total
            # on a v5e (15.75/16 GB).
            limit = used = None
            if jax.default_backend() == 'tpu':
                from skypilot_tpu.accelerators import TPU_GENERATIONS
                kind = jax.devices()[0].device_kind.lower()
                for gen in TPU_GENERATIONS.values():
                    gen_key = (gen.name.replace('e', ' lite')
                               if gen.name.endswith('e') else gen.name)
                    if gen.name in kind or gen_key in kind:
                        limit = int(gen.hbm_gb_per_chip * 0.984e9)
                        used = 0          # floor applied below
            if limit is None:
                # Parity fallback reserves NOTHING for the long ring:
                # decode must keep the conservative ring budget, or a
                # large-batch config meets a 1.7 GB ring the pool
                # never paid for.
                self._pool_auto_sized = False
                return parity
        # bytes_in_use can lag async transfers (observed right after the
        # parallel checkpoint puts: the pool then oversized by ~3 GB and
        # decode OOM'd at runtime); the weights are a known floor —
        # _param_bytes is already the exact PER-DEVICE resident bytes
        # (sharded leaves count their local shard, dp-replicated leaves
        # in full — dividing by mesh.size here was the dp>1 oversizing
        # bug).
        used = max(used, self._param_bytes + int(0.15e9))
        # The reserve must cover the decode transients at the LONGEST
        # horizon the ring budget allows — sizing the pool without
        # them compiled programs past HBM at batch=48 on a 7B. The
        # ring (decode program) and the stacked prefill-chunk KV
        # (prefill program) are transients of DIFFERENT programs and
        # never peak together, so the reserve takes their MAX on top
        # of a fixed workspace: summing them shrank a 7B pool to 65
        # pages (2.2 GB) where 170 pages ran h=32 clean — the
        # empirically-safe reserve on that config is ~3.1 GB. h_max
        # rounds DOWN to the horizon bucket decode will actually pick.
        from skypilot_tpu.inference.engine import _ring_row_bytes
        row = _ring_row_bytes(cfg, max_batch, self.mesh)
        h_max = self._ring_horizon_bucket(self._RING_BYTES_CAP_PAGED)
        reserve = (int(1.6e9) + max(2 * row * h_max,
                                    self._PREFILL_STACK_BUDGET))
        # Per-DEVICE page cost: a tp-sharded pool stores 1/tp of each
        # page's rows per chip, so the same free HBM fits tp x the
        # pages (the whole point of sharding the pool) — while a dp>1
        # mesh replicates the pool and gets NO page-count credit.
        page_bytes = self._page_bytes(cfg, page_size, quantized,
                                      mesh=self.mesh)
        fit = max(0, (limit - used - reserve)) // page_bytes
        # Take what fits, capped at 4x slot parity (prefix-cache
        # headroom without letting a tiny model grab the whole chip);
        # under pool pressure admission backs off, so a sub-parity fit
        # still serves. Never below 2 (page 0 is reserved).
        return int(max(min(fit, 4 * parity), 2))

    @classmethod
    def from_pretrained(cls, path: str, *, dtype=None,
                        **kwargs) -> 'PagedInferenceEngine':
        """Build a paged engine from an HF checkpoint directory (see
        ``models/weights.py``; quantization happens host-side during
        load, int8 cache reused)."""
        from skypilot_tpu.models import weights
        cfg, params = weights.load_checkpoint(
            path, dtype=dtype if dtype is not None else jnp.bfloat16,
            quantize=kwargs.get('quantize'))
        kwargs.setdefault('donate_params', True)
        return cls(cfg, params, **kwargs)

    # ---------------------------------------------------------- compiled
    def _build_decode(self):
        """Two programs per horizon, enqueued back to back with ONE host
        sync: token computation reads the pool (pallas blocks DMA from
        it directly), then the ring scatter runs with the cache donated
        so the pool updates in place — see merge_ring_into_pool."""
        cfg = self.cfg
        decode_impl = self.decode_impl
        # Pinned ring output shardings: the decode program emits the
        # ring rows in exactly the layout the merge program consumes
        # them in (out_axis_resources == next in_axis_resources), and
        # the merge returns the pool in its own resident sharding —
        # the decode→merge chain reshards nothing in steady state.
        ring_kwargs = ({'out_shardings': (None, self._ring_sh,
                                          self._ring_sh)}
                       if self._ring_sh is not None else {})
        merge_kwargs = ({'out_shardings': self._cache_sh}
                        if self._cache_sh is not None else {})

        @functools.partial(jax.jit,
                           static_argnames=('horizon', 'sample'),
                           **ring_kwargs)
        def decode_steps(params, cache, table_p, tokens, lengths, rng,
                         temps, topks, topps, active, adp, vmask,
                         horizon, sample):
            if sample:
                def sample_fn(logits, step_rng):
                    from skypilot_tpu.inference.engine import sample_tokens
                    return sample_tokens(logits, step_rng, temps, topks,
                                         topps)
                rngs = jax.random.split(rng, horizon)
            else:
                sample_fn, rngs = None, None
            return paged_decode_horizon(
                params, cache, table_p, tokens, lengths, cfg,
                horizon=horizon, sample_fn=sample_fn, rngs=rngs,
                active=active, decode_impl=decode_impl,
                pages_per_block=self.pages_per_block,
                mlora_idx=adp, vocab_mask=vmask)

        merge = jax.jit(functools.partial(merge_ring_into_pool,
                                          mesh=self.mesh),
                        donate_argnums=(0,), **merge_kwargs)

        def decode_and_merge(params, cache, table_p, tokens, lengths,
                             rng, temps, topks, topps, active, adp,
                             vmask, horizon, sample):
            toks, ring_k, ring_v = decode_steps(
                params, cache, table_p, tokens, lengths, rng, temps,
                topks, topps, active, adp, vmask, horizon, sample)
            new_cache = merge(cache, ring_k, ring_v, table_p, lengths,
                              active)
            return toks, new_cache

        return decode_and_merge

    def _get_prefill(self, n: int, P: int, sample: bool,
                     chunk_w: Optional[int] = None):
        key = (n, P, sample, chunk_w or self.chunk)
        if key not in self._prefill_fns:
            cfg = self.cfg
            w8a8 = self.prefill_w8a8

            mesh = self.mesh

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._step_out_shardings(1))
            def prefill(params, cache, table_p, tokens, lengths, valid,
                        want_idx, adp, vmask, temps, topks, topps, rng):
                return paged_prefill_chunk(
                    params, cache, table_p, tokens, lengths, valid,
                    want_idx, cfg, temps=temps if sample else None,
                    topks=topks, topps=topps, rng=rng, w8a8=w8a8,
                    mesh=mesh, mlora_idx=adp, vocab_mask=vmask)

            self._prefill_fns[key] = prefill
        return self._prefill_fns[key]

    # ---------------------------------------------------------- public
    def _validate_request(self, prompt: List[int],
                          max_new_tokens: int) -> None:
        super()._validate_request(prompt, max_new_tokens)
        # A prompt the pool can NEVER hold must fail loudly here — a
        # silent requeue would spin run_to_completion forever.
        need = self._pages_needed(len(prompt) + max_new_tokens)
        if need > self.alloc.n_pages - 1:
            raise ValueError(
                f'request needs {need} pages but the pool has only '
                f'{self.alloc.n_pages - 1}; raise n_pages')

    def memory_stats(self) -> Dict[str, Any]:
        page_bytes = self._page_bytes(self.cfg, self.page,
                                      self.kv_cache_dtype)
        used = self.alloc.n_pages - 1 - len(self.alloc.free) \
            - len(self.alloc.retained)
        return {
            'n_pages': self.alloc.n_pages,
            'pages_in_use': used,
            'pages_retained_prefix': len(self.alloc.retained),
            'pages_free': len(self.alloc.free),
            'page_bytes': page_bytes,
            'pool_bytes': page_bytes * self.alloc.n_pages,
            'kv_cache_dtype': self.kv_cache_dtype,
            # Allocatable tokens (page 0 is the reserved trash page).
            'pool_token_capacity': (self.alloc.n_pages - 1) * self.page,
            'prefix_hits': self.alloc.prefix_hits,
            'prefix_misses': self.alloc.prefix_misses,
        }

    def kv_token_capacity(self) -> int:
        """Token rows the pool arrays physically hold (trash page
        included — the cost model divides pool AVAL bytes, and page 0
        is part of the aval). Distinct from ``memory_stats``'s
        allocatable capacity, which excludes the reserved page."""
        return self.alloc.n_pages * self.page

    def kv_pool_stats(self) -> Dict[str, Any]:
        """KV capacity/pressure in TOKENS (page-granular: a partially
        filled page counts as used) — the schema shared with the slot
        engine for the telemetry gauges and bench. Prefix-retained
        pages count as FREE: allocation evicts them on demand."""
        from skypilot_tpu.inference.engine import (kv_shard_degree,
                                                   kv_token_bytes)
        stats = self.memory_stats()
        cap = stats['pool_token_capacity']
        used = stats['pages_in_use'] * self.page
        return {
            'kv_cache_dtype': self.kv_cache_dtype,
            'pool_token_capacity': cap,
            'tokens_used': used,
            'tokens_free': cap - used,
            'preemptions': int(self.preemptions),
            'kv_token_bytes': kv_token_bytes(self.cfg,
                                             self.kv_cache_dtype),
            # Per-DEVICE byte view (kv heads shard over tp; pages
            # replicate over dp): token counts above stay GLOBAL so
            # scheduler bounds and preemption pressure mean the same
            # thing at any mesh shape.
            'kv_token_bytes_per_shard': kv_token_bytes(
                self.cfg, self.kv_cache_dtype, mesh=self.mesh),
            'kv_shards': kv_shard_degree(self.cfg, self.mesh),
        }

    # ---------------------------------------------------------- admission
    def _pages_needed(self, tokens: int) -> int:
        return -(-tokens // self.page)

    def _ensure_pages(self, slot: int, upto_tokens: int) -> bool:
        """Grow the slot's page list to cover ``upto_tokens``; False if
        the pool is exhausted (caller backs off)."""
        need = self._pages_needed(upto_tokens)
        pages = self._pages[slot]
        grabbed = []
        try:
            while len(pages) < need:
                p = self.alloc.alloc()
                pages.append(p)
                grabbed.append(p)
            return True
        except MemoryError:
            for p in grabbed:
                pages.remove(p)
                self.alloc.release(p)
            return False

    def _free_slot(self, slot: int) -> None:
        for p in self._pages[slot]:
            self.alloc.release(p)
        self._pages[slot] = []
        self._prefill_off.pop(slot, None)        # cancel mid-prefill
        self._await_first.discard(slot)
        self._slot_inflight[slot] = 0
        self._slot_epoch[slot] += 1
        super()._free_slot(slot)

    def has_work(self) -> bool:
        self._purge_lagging()
        return super().has_work() or bool(self._lagging)

    def has_runnable_work(self) -> bool:
        # Purge finished early-freed stragglers FIRST: a finished
        # request parked in _lagging is not runnable work, and
        # counting it busy-spins the serve loop after every
        # budget-bound completion (and floods the gang op log with
        # no-op steps) until something else happened to call
        # has_work() and purge. The base check then sees the live
        # truth.
        self._purge_lagging()
        return super().has_runnable_work()

    def cancel(self, request_id: int) -> bool:
        if super().cancel(request_id):
            return True
        req = self._lagging.pop(request_id, None)
        if req is not None and req.finish_time is None:
            # Early-recycled: the slot/pages are already released; the
            # pipeline's remaining tail tokens are dropped at readback
            # by the finish_time check. NOT recorded as finished —
            # same contract as a slot cancel.
            req.finish_time = clock.now()
            self._trace_finish(req, cancelled=True)
            return True
        return False

    def _slot_remaining_prefill(self, slot: int) -> int:
        """Uncached prompt-tail tokens still to prefill: the context
        minus prefix-matched pages minus the chunk cursor."""
        off = self._prefill_off.get(slot)
        if off is None:
            return 0
        req = self._slots[slot]
        tail = len(req._ctx) - req._n_matched * self.page
        return max(0, tail - off)

    def _purge_lagging(self) -> None:
        if self._lagging:
            for rid in [rid for rid, r in self._lagging.items()
                        if r.finish_time is not None]:
                del self._lagging[rid]

    def _ring_horizon_bucket(self, ring_bytes: int) -> int:
        """The horizon BUCKET the ring budget admits — the one place
        this is computed: _auto_n_pages sizes the pool reserve with it
        and _enqueue_decode caps live horizons with it, and the two
        drifting apart re-creates the under/over-reserve OOMs (see the
        reserve note in _auto_n_pages)."""
        from skypilot_tpu.inference.engine import (_ring_horizon_cap,
                                                   _ring_row_bytes)
        row = _ring_row_bytes(self.cfg, self.max_batch, self.mesh)
        cap = min(self._HORIZON_BUCKETS[-1],
                  _ring_horizon_cap(self.cfg, self.max_batch,
                                    self._param_bytes, self.mesh),
                  max(8, ring_bytes // row))
        return next((b for b in reversed(self._HORIZON_BUCKETS)
                     if b <= cap), 8)

    def _maybe_early_free(self, slot: int, req) -> None:
        """Recycle the slot the moment the request's whole output is
        covered by ENQUEUED device calls. Only budget-bound requests
        qualify — stop sequences / eos make completion data-dependent,
        so those free at readback like before. The tail tokens surface
        later through the pipeline (entries hold the request object;
        ``_finish_req`` never touches a recycled slot), and the pages
        released here are only ever re-written by programs enqueued
        AFTER the in-flight reads/merges — the single device stream
        orders them. Without this, a finished slot decoded garbage for
        ~PIPELINE_DEPTH more horizons and then idled until readback:
        measured at 1790 tok/s steady, that waste held the sustained
        token YIELD (counted / issued slot-steps) at 0.44."""
        if req.stop or req.eos_id is not None or req._early_freed:
            return
        budget = min(req.max_new_tokens,
                     max(1, self.max_seq - len(req.prompt)))
        if req._enq_out >= budget:
            req._early_freed = True
            self._lagging[req.request_id] = req
            self._free_slot(slot)

    def _preempt_slot(self, slot: int) -> None:
        """Pool pressure: push a live request back to the FRONT of the
        queue, releasing its pages. It re-enters through _assign_slots
        with prompt+output as context (recompute) — generated tokens
        are kept, TTFT is not reset."""
        req = self._slots[slot]
        self.preemptions += 1
        # Content-address the full pages ALREADY WRITTEN for this
        # context before releasing them: re-admission then re-matches
        # them (refcount-0 registered pages retire into the LRU, which
        # allocation evicts only on demand) instead of recomputing the
        # whole context. Besides the work saved, the resumed KV is the
        # ORIGINAL bytes — a full recompute re-derives the generated
        # tokens' rows through the chunk-prefill program, whose bf16
        # rounding differs from the decode ring's by a few ULPs, enough
        # to flip near-tie argmaxes on resume. Rows are written for
        # ctx[:_slot_len] only (the current token's row rides the next
        # decode call), so registration is capped there — a mid-prefill
        # victim must not register pages it never filled.
        written = (req.prompt + req.output)[:int(self._slot_len[slot]) + 1]
        if self._pages[slot]:
            self.alloc.register_prefix(written, self._pages[slot],
                                       getattr(req, '_n_matched', 0))
        if req.trace is not None:
            # Close the in-slot spans; the re-admission re-opens
            # queue → prefill → decode, preserving the real timeline.
            req.trace.end('decode')
            req.trace.end('prefill')
            req.trace.begin('queue', preempted=True)
        self._free_slot(slot)
        self._requeue_front([req])

    def _admit(self) -> List[Tuple[int, int, bool]]:
        """Continuous admission: assign free slots immediately, then run
        at most ONE prefill chunk-batch before decode resumes. The
        round-4 wave-synchronous admission ran *every* chunk of a wave
        before any decode step — running requests stalled for the whole
        wave (the measured 7.8 s burst TTFT was this architecture).
        Interleaving one chunk per step bounds active-request TPOT at
        one chunk time while prompts stream in (the JetStream/vLLM
        continuous-batching admission contract, the capability the
        reference serves through those engines).

        BURST exception: while the batch is mostly EMPTY (cold start /
        arrival burst), the one-chunk-per-step TPOT bound protects
        almost nobody — so admission keeps running chunk batches until
        the DECODING population reaches a QUARTER of the batch. A
        2x-batch burst's median TTFT was ~7 s with strictly one
        chunk-batch per ~0.8 s horizon; filling the first slots
        back-to-back cuts the queue wait for everyone, while the low
        threshold keeps the loop from stalling a half-full batch of
        live streams behind a run of long prompts."""
        self._assign_slots()
        events = self._prefill_chunk_batch()
        while (self._prefill_off
               and sum(r is not None for r in self._slots)
               - len(self._prefill_off) < self.max_batch // 4):
            events += self._prefill_chunk_batch()
        return events

    def _assign_slots(self) -> None:
        for slot in range(self.max_batch):
            if self._slots[slot] is not None:
                continue
            req = self._queue_pop()
            if req is None:
                return
            # A preempted request re-enters with its generated tokens as
            # part of the context (preemption-by-recompute): prefilling
            # prompt+output resumes generation exactly where it stopped,
            # and the completed-prefill logits ARE its next token.
            ctx = req.prompt + req.output
            matched = self.alloc.match_prefix(ctx)
            # Quantize the resume point to the canonical chunk grid.
            # A cold prefill chunks from offset 0, so its boundaries are
            # exact multiples of ``self.chunk``; resuming a prefix hit at
            # an arbitrary page boundary regroups the same attention
            # terms across cached_attention's two softmax blocks
            # (cache-sum + in-chunk-sum), and the few-ULP denominator
            # difference flips greedy argmax on near-tie logits — the
            # hit path would emit different bytes than the cold path for
            # the SAME request. Keeping only matched pages up to a
            # chunk-multiple boundary makes every hit-path chunk run the
            # byte-identical program on byte-identical operands (same
            # rationale as _preempt_slot registering original bytes).
            # ``alloc.prefix_hits`` still counts the match; surplus
            # pages return to the retained LRU, not the free list.
            # Preemption re-entry (req.output non-empty) is exempt: it
            # resumes from its OWN pages registered by _preempt_slot
            # with the original bytes, so the restore is exact and the
            # uninterrupted-run contract needs the mid-grid resume.
            if not req.output:
                keep = len(matched)
                while keep and (keep * self.page) % self.chunk:
                    keep -= 1
                for p in matched[keep:]:
                    self.alloc.release(p)
                matched = matched[:keep]
            self._pages[slot] = list(matched)
            if not self._ensure_pages(slot, len(ctx)):
                # Pool pressure: back to the FRONT of the queue (tail
                # requeue would let later small requests starve it) and
                # stop admitting.
                for p in self._pages[slot]:
                    self.alloc.release(p)
                self._pages[slot] = []
                self._requeue_front([req])
                return
            self._slots[slot] = req
            self._slot_len[slot] = len(matched) * self.page
            req._n_matched = len(matched)        # host-only annotations
            req._ctx = ctx
            if matched:
                # A prefix HIT is the strongest heat signal — shared
                # prefixes are exactly what the preemption checkpoint
                # should carry.
                self._note_hot_prefix(ctx)
            self._prefill_off[slot] = 0          # tail tokens done so far
            self._trace_sched(req)
            if req.trace is not None and matched:
                req.trace.instant('prefix_cache_hit',
                                  pages=len(matched))

    def _prefill_chunk_batch(self) -> List[Tuple[int, int, bool]]:
        """One fixed-size chunk across up to a compiled n-bucket of
        mid-prefill slots. ALWAYS returns [] — completing slots'
        first tokens are sampled ON DEVICE (per-request params) and
        merged into the device token vector before this returns, so
        they decode next horizon; the first-token EVENT surfaces via
        ``_process_one`` up to ``_PIPELINE_DEPTH`` calls later."""
        pending = sorted(self._prefill_off)
        if not pending:
            return []
        batch = pending[:self._prefill_n_max]
        n = next(b for b in self._PREFILL_N_BUCKETS if b >= len(batch))
        # Chunk-width variant: when every pending piece fits 128
        # tokens (the common case with a prefix-cache hit — e.g. a
        # 220-token prompt whose first page is cached leaves a <=92
        # token tail), the half-width program does half the prefill
        # FLOPs. Mixed batches fall back to the full chunk. Pure
        # arithmetic — no tail slicing here (a list copy per slot per
        # chunk made long-prompt prefill O(len^2/chunk) host work).
        rest_max = max(
            len(self._slots[s]._ctx)
            - self._slots[s]._n_matched * self.page
            - self._prefill_off[s]
            for s in batch)
        chunk_w = (128 if self.chunk > 128 and rest_max <= 128
                   else self.chunk)
        tokens = np.zeros((n, chunk_w), np.int32)
        lengths = np.zeros(n, np.int32)
        valid = np.zeros(n, np.int32)
        want = np.full(n, -1, np.int32)
        P_needed = 1
        pieces: List[List[int]] = []
        for i, slot in enumerate(batch):
            req = self._slots[slot]
            tail = req._ctx[req._n_matched * self.page:]
            off = self._prefill_off[slot]
            piece = tail[off:off + chunk_w]
            pieces.append(piece)
            lengths[i] = self._slot_len[slot]
            tokens[i, :len(piece)] = piece
            valid[i] = len(piece)
            if off + len(piece) == len(tail):
                want[i] = len(piece) - 1
            P_needed = max(P_needed, self._pages_needed(
                int(lengths[i]) + int(valid[i])))
        for i in range(len(batch), n):           # padding rows: valid=0
            lengths[i] = self._slot_len[batch[0]]   # rows write to trash
        from skypilot_tpu.inference.engine import _bucket_len
        P = _bucket_len(P_needed, minimum=1)
        table_p = np.zeros((n, P), np.int32)
        for i, slot in enumerate(batch):
            ps = self._pages[slot][:P]
            table_p[i, :len(ps)] = ps
        # Per-row sampling params: completing rows sample their first
        # token ON DEVICE inside the prefill program (padding and
        # mid-prompt rows run greedy on garbage logits — discarded).
        temps = np.zeros(n, np.float32)
        topks = np.zeros(n, np.int32)
        topps = np.ones(n, np.float32)
        adp_h = (np.full(n, -1, np.int32)
                 if self.adapters is not None else None)
        vm_h = (np.ones((n, self.cfg.vocab_size), bool)
                if self._vmask_any else None)
        for i, slot in enumerate(batch):
            req = self._slots[slot]
            temps[i] = req.temperature
            topks[i] = req.top_k or 0
            topps[i] = req.top_p
            if adp_h is not None:
                adp_h[i] = req._adapter_slot
            if vm_h is not None and req._vocab_mask is not None:
                vm_h[i] = req._vocab_mask
        self._rng, prng = jax.random.split(self._rng)   # device op
        # ONE batched host->device transfer for every host-built
        # operand: each separate jnp.asarray is its own dispatch round
        # trip (~100-600 ms through the remote tunnel) — nine of them
        # measured as multi-second admission spikes that halved
        # sustained throughput.
        extras = tuple(x for x in (adp_h, vm_h) if x is not None)
        uploaded = device_upload(
            (table_p, tokens, lengths, valid, want, temps, topks,
             topps) + extras)
        (table_d, tokens_d, lengths_d, valid_d, want_d, temps_d,
         topks_d, topps_d) = uploaded[:8]
        rest = list(uploaded[8:])
        adp_d = rest.pop(0) if adp_h is not None else None
        vm_d = rest.pop(0) if vm_h is not None else None
        # Sampling variant only when a row COMPLETING this chunk needs
        # it: sample_tokens sorts the [n, vocab] logits (hundreds of ms
        # on TPU at vocab 32k) — mid-prompt chunks and greedy
        # completions must not pay it.
        sample = any(self._slots[s].temperature > 0
                     for i, s in enumerate(batch) if want[i] >= 0)
        prefill = self._get_prefill(n, P, sample, chunk_w)
        chunk_t0 = clock.monotonic()
        with self._prof.phase('prefill_chunk'), \
                self._prof.jit_key('prefill', (n, P, sample, chunk_w)):
            first, self.cache = prefill(
                self.params, self.cache, table_d, tokens_d, lengths_d,
                valid_d, want_d, adp_d, vm_d, temps_d, topks_d,
                topps_d, prng)
        chunk_t1 = clock.monotonic()
        self.chunks_prefilled += 1
        for i, slot in enumerate(batch):
            r = self._slots[slot]
            if r.trace is not None:
                r.trace.add('prefill_chunk', chunk_t0, chunk_t1,
                            offset=self._prefill_off[slot],
                            tokens=int(valid[i]))
        # Async: host bookkeeping advances NOW (the device writes are
        # program-ordered). Completing slots' sampled tokens merge into
        # the device token vector IMMEDIATELY (device-to-device, no
        # sync) so they decode on the very next horizon; _await_first
        # now gates only the first-token EVENT (host readback of the
        # token value rides the pipeline).
        done_rows: List[Tuple[int, int]] = []    # (row i, slot)
        for i, slot in enumerate(batch):
            req = self._slots[slot]
            self._slot_len[slot] += int(valid[i])
            self._prefill_off[slot] += int(valid[i])
            if want[i] < 0:
                continue                         # more chunks to go
            del self._prefill_off[slot]
            self._await_first.add(slot)
            self.alloc.register_prefix(req._ctx, self._pages[slot],
                                       req._n_matched)
            self._note_hot_prefix(req._ctx)
            done_rows.append((i, slot))
        if done_rows:
            # FIXED [n] shapes for the token gather + merge: a
            # len(done_rows)-shaped array would compile a fresh tiny
            # gather/scatter program per distinct count (measured:
            # ~0.9 s per remote compile, dozens across a serving run —
            # the dominant admission cost). Padding rows point at row
            # 0 and scatter to the out-of-range sentinel max_batch,
            # which mode='drop' discards.
            rows_p = np.zeros(n, np.int32)
            slots_p = np.full(n, self.max_batch, np.int32)
            for j, (i, slot) in enumerate(done_rows):
                rows_p[j], slots_p[j] = i, slot
            rows_d, slots_d = device_upload((rows_p, slots_p))
            self._tok_dev = self._merge_tokens_drop(
                self._tok_dev, slots_d, jnp.take(first, rows_d))
            self._meta_dirty = True          # slots become decodable
            self._pending.append({
                'kind': 'prefill', 'toks': first,
                'batch': [(slot, self._slots[slot], i)
                          for i, slot in done_rows]})
            for i, slot in done_rows:
                req = self._slots[slot]
                # re-admission resumes with output already present
                req._enq_out = len(req.output) + 1
                self._maybe_early_free(slot, req)
        return []

    # ------------------------------------------------ prefix heat
    def _note_hot_prefix(self, tokens: List[int]) -> None:
        """Record one use (registration or future-worthy context) of
        the prefix chain covering ``tokens``' full pages — the
        preemption checkpoint exports the hottest. Host-side dict ops
        only; bounded at _PREFIX_HEAT_MAX entries (coldest evicted)."""
        full = (len(tokens) - 1) // self.page
        if full < 1:
            return
        covered = full * self.page
        key = hashlib.sha1(np.asarray(
            tokens[:covered], np.int32).tobytes()).digest()
        rec = self._prefix_heat.get(key)
        if rec is not None:
            rec['hits'] += 1
            return
        if len(self._prefix_heat) >= self._PREFIX_HEAT_MAX:
            coldest = min(self._prefix_heat,
                          key=lambda k: self._prefix_heat[k]['hits'])
            del self._prefix_heat[coldest]
        self._prefix_heat[key] = {'tokens': list(tokens[:covered + 1]),
                                  'hits': 1}

    def hot_prefix_digest(self, max_entries: int = 16):
        """The hottest prefix chains as a bounded, wire-cheap digest:
        ``[{'hash': <sha1 hex of the page-grid token bytes>, 'len':
        <covered token count>, 'hits': n}, ...]`` hottest-first, at
        most ``max_entries``. Built from the host-side heat tracker
        ONLY — no allocator matching, no device gather, zero d2h —
        so the /metrics probe path can ship it on every scrape. The
        LB recomputes the same sha1 over a prompt's page-grid
        prefixes to find the longest match (prefix-affinity
        routing). A hash may name a chain the allocator has since
        evicted; affinity is a routing hint, not a guarantee."""
        by_heat = sorted(self._prefix_heat.items(),
                         key=lambda kv: -kv[1]['hits'])
        return [{'hash': key.hex(),
                 'len': len(rec['tokens']) - 1,
                 'hits': int(rec['hits'])}
                for key, rec in by_heat[:max_entries]]

    def drain_pipeline(self):
        """Gang ``flush`` op (see ``_EngineBase.drain_pipeline``): on
        top of syncing the in-flight device calls, the paged engine
        must also surface its pool-pressure deferred-event stash —
        otherwise a leader that flushed before a checkpoint and a
        follower that didn't would emit the same tokens in different
        step batches and the finished-digest comparison would be
        comparing mid-stream states."""
        events = super().drain_pipeline()
        if self._deferred_events:
            events.extend(self._deferred_events)
            self._deferred_events = []
        return events

    def export_prefix_snapshots(self, max_entries: int = 8):
        """The hottest still-cached prefix chains as prefix entries
        (``kv_transfer`` SKPF dicts): per chain, re-match its pages in
        the allocator (a chain evicted since it was hot exports
        nothing) and gather the page rows in the pool's STORED dtype
        through the same compiled gather the KV handoff uses. Returns
        ``(entries, drained_events)`` — the async pipeline is drained
        first so the pool rows are final; the caller routes the events
        exactly like ``step()`` events."""
        from skypilot_tpu.inference.engine import _bucket_len
        events: List[Tuple[int, int, bool]] = []
        while self._pending:
            events.extend(self._process_one())
        entries: List[Dict[str, Any]] = []
        by_heat = sorted(self._prefix_heat.values(),
                         key=lambda r: -r['hits'])
        for rec in by_heat:
            if len(entries) >= max_entries:
                break
            entry = self._export_prefix_record(rec)
            if entry is not None:
                entries.append(entry)
        return entries, events

    def _export_prefix_record(self, rec: Dict[str, Any]
                              ) -> Optional[Dict[str, Any]]:
        """Gather one heat record's still-cached chain as a prefix
        entry (None if the allocator evicted it). Callers own pipeline
        draining."""
        from skypilot_tpu.inference.engine import _bucket_len
        cfg = self.cfg
        tokens = rec['tokens']
        pages = self.alloc.match_prefix(tokens)
        if not pages:
            return None
        n_rows = len(pages) * self.page
        try:
            P = _bucket_len(len(pages), minimum=1)
            table = np.zeros((P,), np.int32)
            table[:len(pages)] = pages
            out = self._get_export(P)(self.cache,
                                      device_upload(table))
            # Sanctioned d2h: the checkpoint export IS a host
            # readback by design (the rows leave on the wire or
            # land in a checkpoint file).
            host = host_sync(out)
        finally:
            for p in pages:
                self.alloc.release(p)
        if self.cache.quantized:
            k, v, ks, vs = host
            k, v = k[:, :n_rows], v[:, :n_rows]
            ks, vs = ks[:, :n_rows], vs[:, :n_rows]
        else:
            k, v = host
            k, v = k[:, :n_rows], v[:, :n_rows]
            ks = vs = None
        return {
            'kv_cache_dtype': self.kv_cache_dtype,
            'n_rows': n_rows,
            'model': {'n_layers': cfg.n_layers,
                      'n_kv_heads': cfg.n_kv_heads,
                      'head_dim': cfg.head_dim},
            'tokens': list(tokens[:n_rows + 1]),
            'k': k, 'v': v, 'k_scale': ks, 'v_scale': vs,
        }

    def export_prefix_entry(self, hash_hex: str):
        """One hot chain — named by its digest hash — as a prefix
        entry: ``(entry_or_None, drained_events)``. The proactive
        affinity migration path: the LB asks the source replica for
        exactly the chain whose digest match lost to load, ships the
        blob to the target's warmup endpoint, and the prefix is warm
        there without a single recomputed token. None when the heat
        record or its pages are gone (the digest was a stale hint)."""
        try:
            key = bytes.fromhex(hash_hex)
        except ValueError:
            return None, []
        rec = self._prefix_heat.get(key)
        if rec is None:
            return None, []
        events: List[Tuple[int, int, bool]] = []
        while self._pending:
            events.extend(self._process_one())
        return self._export_prefix_record(rec), events

    def warm_prefix(self, entry: Dict[str, Any]) -> int:
        """Land a prefix entry into the prefix cache without seating a
        request: allocate pages, scatter the rows at their exact
        original bytes, ``register_prefix`` the chain, then release
        the pages into the reusable LRU — future prompts sharing the
        prefix hit the ORIGINAL KV. Idempotent: a chain already fully
        cached lands nothing. Returns rows landed; raises
        ``ValueError`` on mismatch (permanent) and
        ``HandoffCapacityError`` on pool pressure (retryable)."""
        from skypilot_tpu.inference.engine import HandoffCapacityError
        if 'tokens' not in entry:
            from skypilot_tpu.inference import kv_transfer
            entry = kv_transfer.as_prefix_entry(entry)
        n_rows = int(entry['n_rows'])
        tokens = [int(t) for t in entry['tokens']]
        if len(tokens) < n_rows + 1:
            raise ValueError(
                f'prefix entry carries {len(tokens)} token(s) for '
                f'{n_rows} row(s); need n_rows + 1')
        self._validate_kv_entry(entry, n_rows)
        # Land whole pages only (this engine's page size — normally
        # identical to the exporter's, but a partial tail page cannot
        # be content-addressed either way).
        full = n_rows // self.page
        if full < 1:
            return 0
        rows_used = full * self.page
        prefix_tokens = tokens[:rows_used + 1]
        matched = self.alloc.match_prefix(prefix_tokens)
        already = len(matched)
        for p in matched:
            self.alloc.release(p)
        if already >= full:
            return 0                       # already warm
        if self.alloc.available < full:
            raise HandoffCapacityError(
                f'KV page pool exhausted ({self.alloc.available} '
                f'page(s) free, {full} needed for prefix warmup)')
        pages = [self.alloc.alloc() for _ in range(full)]
        try:
            self._scatter_snapshot_rows(pages, entry, rows_used)
            self.alloc.register_prefix(prefix_tokens, pages, 0)
        except Exception:
            for p in pages:
                self.alloc.release(p)
            raise
        # refcount -> 0: freshly hashed pages retire into the
        # prefix-reusable LRU (warm); pages whose hash already existed
        # (shared with a cached chain) recycle to the free list.
        for p in pages:
            self.alloc.release(p)
        self._note_hot_prefix(prefix_tokens)
        return rows_used

    # ---------------------------------------------------- KV handoff
    def _get_export(self, P: int):
        """Compiled page gather for one slot's handoff export: the
        first ``P`` pages as token-major [L, P*page, hkv, d] rows (+
        [L, P*page, hkv] scales), in the pool's STORED dtype — int8
        codes and fp32 scales leave exactly as resident, never
        dequantized (the int8-on-the-wire contract GC114 gates)."""
        if P in self._export_fns:
            return self._export_fns[P]
        page = self.page
        quantized = self.cache.quantized

        @jax.jit
        def export(cache, table):          # table [P] page ids
            def tok_major(pool):
                g = pool[:, table]         # [L, P, hkv, page(, d)]
                if g.ndim == 5:
                    g = g.transpose(0, 1, 3, 2, 4)
                else:
                    g = g.transpose(0, 1, 3, 2)
                return g.reshape((g.shape[0], P * page) + g.shape[3:])

            k, v = tok_major(cache.pool_k), tok_major(cache.pool_v)
            if quantized:
                return (k, v, tok_major(cache.k_scale),
                        tok_major(cache.v_scale))
            return k, v

        self._export_fns[P] = export
        return export

    def _gather_kv_rows(self, slot: int, n_rows: int):
        from skypilot_tpu.inference.engine import _bucket_len
        P = _bucket_len(self._pages_needed(max(1, n_rows)), minimum=1)
        table = np.zeros((P,), np.int32)
        ps = self._pages[slot][:P]
        table[:len(ps)] = ps
        table_d = device_upload(table)
        out = self._get_export(P)(self.cache, table_d)
        # Sanctioned d2h: the handoff export IS a host readback by
        # design (the rows leave this process on the wire).
        host = host_sync(out)
        if self.cache.quantized:
            k, v, ks, vs = host
            return (k[:, :n_rows], v[:, :n_rows], ks[:, :n_rows],
                    vs[:, :n_rows])
        k, v = host
        return k[:, :n_rows], v[:, :n_rows], None, None

    def _get_ingest(self, nb: int, P: int):
        """Compiled handoff merge: land a [L, 1, nb, hkv(, d)] row
        batch into the pool through a [1, P] page table (padding rows
        past ``valid`` redirect to the trash page). Donates the pool —
        the scatter runs in place like every other merge."""
        key = (nb, P)
        if key in self._ingest_fns:
            return self._ingest_fns[key]
        quantized = self.cache.quantized
        mesh = self.mesh

        if quantized:
            @functools.partial(jax.jit, donate_argnums=(0,),
                               **self._step_out_shardings(0))
            def ingest(cache, kq, ks, vq, vs, table, starts, valid):
                return merge_rows_into_pool(cache, (kq, ks), (vq, vs),
                                            table, starts, valid,
                                            mesh=mesh)
        else:
            @functools.partial(jax.jit, donate_argnums=(0,),
                               **self._step_out_shardings(0))
            def ingest(cache, kr, vr, table, starts, valid):
                return merge_rows_into_pool(cache, kr, vr, table,
                                            starts, valid, mesh=mesh)

        self._ingest_fns[key] = ingest
        return ingest

    def _scatter_snapshot_rows(self, pages: List[int], snap,
                               n_rows: int) -> None:
        """Compiled scatter of ``n_rows`` stored-dtype snapshot rows
        into ``pages`` (shared by the KV-handoff land and the prefix
        warmup — both land wire bytes at their exact original
        values)."""
        from skypilot_tpu.inference.engine import _bucket_len
        cfg = self.cfg
        P = _bucket_len(len(pages), minimum=1)
        # Row bucket: bounded compiled-program count. nb may exceed
        # P*page for non-power-of-two page sizes; padding rows past
        # ``valid`` mask to the trash page (their clamped table
        # lookups are discarded), so the overshoot is harmless.
        nb = _bucket_len(n_rows, minimum=8)
        table = np.zeros((1, P), np.int32)
        table[0, :len(pages)] = pages

        def pad(arr, tail):
            out = np.zeros((cfg.n_layers, 1, nb, cfg.n_kv_heads)
                           + tail, dtype=arr.dtype)
            out[:, 0, :n_rows] = np.asarray(arr, dtype=arr.dtype)[
                :, :n_rows].reshape(
                (cfg.n_layers, n_rows, cfg.n_kv_heads) + tail)
            return out

        starts = np.zeros(1, np.int32)
        valid = np.array([n_rows], np.int32)
        ingest = self._get_ingest(nb, P)
        # Packed int4 rows carry head_dim/2 code bytes per row — the
        # scatter is tail-shape-generic, only the pad buffer cares.
        code_d = cfg.head_dim // 2 if self.cache.packed else cfg.head_dim
        if self.cache.quantized:
            (kq, ks, vq, vs, table_d, starts_d,
             valid_d) = device_upload(
                (pad(snap['k'], (code_d,)),
                 pad(snap['k_scale'], (1,)),
                 pad(snap['v'], (code_d,)),
                 pad(snap['v_scale'], (1,)), table, starts, valid))
            self.cache = ingest(self.cache, kq, ks, vq, vs,
                                table_d, starts_d, valid_d)
        else:
            kr, vr, table_d, starts_d, valid_d = device_upload(
                (pad(snap['k'], (cfg.head_dim,)),
                 pad(snap['v'], (cfg.head_dim,)), table, starts,
                 valid))
            self.cache = ingest(self.cache, kr, vr, table_d,
                                starts_d, valid_d)

    def _land_kv_rows(self, slot: int, req, snap) -> None:
        from skypilot_tpu.inference.engine import HandoffCapacityError
        n_rows = int(snap['n_rows'])
        ctx = req.prompt + req.output
        self._pages[slot] = []
        if not self._ensure_pages(slot, max(1, n_rows)):
            raise HandoffCapacityError(
                f'KV page pool exhausted ({self.alloc.available} '
                f'page(s) free, {self._pages_needed(n_rows)} needed)')
        try:
            self._scatter_snapshot_rows(self._pages[slot], snap, n_rows)
            # Content-address the landed full pages: future prompts
            # sharing the prefix hit them, and a preempt/resume of
            # THIS request re-matches the original bytes.
            # register_prefix validates page-count vs token-length —
            # the truncated-handoff guard.
            self.alloc.register_prefix(ctx, self._pages[slot], 0)
            self._note_hot_prefix(ctx)
        except Exception:
            for p in self._pages[slot]:
                self.alloc.release(p)
            self._pages[slot] = []
            raise
        req._ctx = ctx
        req._n_matched = 0

    # ------------------------------------------------------- speculative
    def _spec_room(self, slot: int) -> int:
        """Proposal cap from page availability: reserve pages for
        len + k + 1 rows; under pool pressure shrink the cover (masked
        commits write at most that many rows) down to 1; -1 when even
        one more token has no page (the mixin then routes the slot
        through ``_spec_starved``)."""
        base = int(self._slot_len[slot])
        for cover in range(self.speculate_k + 1, 0, -1):
            if self._ensure_pages(slot, base + cover):
                return cover - 1
        return -1

    def _spec_starved(self, slots: List[int]) -> None:
        """Pool exhausted for these slots even at one token: preempt
        them back to the queue (vLLM-style recompute — same contract as
        the decode path's pool-pressure preemption). The oldest live
        request is never in this set in practice: ``_spec_room`` is
        called in slot order after earlier slots reserved their pages,
        and ``_validate_request`` guarantees any single request fits
        the pool alone once the others release."""
        for slot in slots:
            if self._slots[slot] is not None:
                self._preempt_slot(slot)

    def _get_spec_verify(self, n: int, P: int, sample: bool):
        key = (self.speculate_k, sample, P)
        if key not in self._spec_verify_fns:
            cfg = self.cfg
            w8a8 = self.prefill_w8a8

            mesh = self.mesh

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._step_out_shardings(3))
            def verify(params, cache, table_p, tokens, proposals,
                       n_prop, lengths, active, adp, vmask, temps,
                       topks, topps, rng):
                return paged_spec_verify(
                    params, cache, table_p, tokens, proposals, n_prop,
                    lengths, active, cfg, sample=sample,
                    temps=temps, topks=topks, topps=topps, rng=rng,
                    w8a8=w8a8, mesh=mesh, mlora_idx=adp,
                    vocab_mask=vmask)

            self._spec_verify_fns[key] = verify
        return self._spec_verify_fns[key]

    def _spec_verify_call(self, ready, proposals, n_prop):
        from skypilot_tpu.inference.engine import _bucket_len
        temps_d, topks_d, topps_d, active_d, sample = \
            self._slot_meta(ready)
        P_needed = max(max((len(self._pages[s])
                            for s, r in enumerate(ready)
                            if r is not None), default=1), 1)
        P = _bucket_len(P_needed, minimum=1)
        table_p = np.zeros((self.max_batch, P), np.int32)
        for s in range(self.max_batch):
            ps = self._pages[s][:P]
            table_p[s, :len(ps)] = ps
        lengths = self._slot_len.astype(np.int32)
        self._rng, rng = jax.random.split(self._rng)
        table_d, prop_d, n_prop_d, lengths_d = device_upload(
            (table_p, proposals, n_prop, lengths))
        verify = self._get_spec_verify(self.max_batch, P, sample)
        with self._prof.jit_key('spec_verify',
                                (self.speculate_k, sample, P)):
            commit, n_commit, self._tok_dev, self.cache = verify(
                self.params, self.cache, table_d, self._tok_dev, prop_d,
                n_prop_d, lengths_d, active_d, self._adp_dev,
                self._vmask_dev, temps_d, topks_d, topps_d, rng)
        return commit, n_commit

    def _spec_can_fuse(self, slot: int, rounds: int) -> bool:
        """Up-front page reservation for the fused in-scan rounds: the
        device commits up to ``rounds * (k + 1)`` rows with no host
        between rounds, so every covering page must exist BEFORE
        dispatch. Returning False sends the mixin to the single-round
        ``_spec_step`` (which shrinks its cover per round under pool
        pressure). Pages reserved here stay with the slot either way
        and release at slot free."""
        base = int(self._slot_len[slot])
        return self._ensure_pages(
            slot, base + rounds * (self.speculate_k + 1))

    def _get_spec_fused(self, n: int, P: int, sample: bool,
                        rounds: int):
        """Compiled in-scan speculative rounds over the paged pool:
        ``rounds`` x (device n-gram propose → ``paged_spec_verify`` →
        masked merge) fused into ONE program via lax.scan, with the
        per-slot lengths, history window, and remaining-token budgets
        carried between rounds. jit key: (k, sample, P, rounds)."""
        key = ('fused', self.speculate_k, sample, P, rounds)
        if key not in self._spec_verify_fns:
            from skypilot_tpu.inference import speculative
            cfg = self.cfg
            w8a8 = self.prefill_w8a8
            mesh = self.mesh
            k = self.speculate_k
            max_ngram = self.spec_max_ngram
            H = self.spec_hist_window

            @functools.partial(jax.jit, donate_argnums=(1,),
                               **self._step_out_shardings(4))
            def fused(params, cache, table_p, tokens, hist, rem,
                      lengths, active, adp, vmask, temps, topks, topps,
                      rngs):
                def round_body(carry, rng):
                    cache, tok, hist, rem, lens = carry
                    prop, n_prop = speculative.ngram_propose_device(
                        hist, k, max_ngram=max_ngram)
                    # Budget carry: _spec_build_proposals's cap,
                    # applied round by round on device (n_commit <=
                    # n_prop + 1 <= rem never overshoots).
                    n_prop = jnp.minimum(n_prop,
                                         jnp.maximum(rem - 1, 0))
                    act = active & (rem >= 1)
                    commit, n_commit, new_tok, new_cache = \
                        paged_spec_verify(
                            params, cache, table_p, tok, prop, n_prop,
                            lens, act, cfg, sample=sample, temps=temps,
                            topks=topks, topps=topps, rng=rng,
                            w8a8=w8a8, mesh=mesh, mlora_idx=adp,
                            vocab_mask=vmask)
                    # History carry: append the commit row and
                    # re-right-align (shift left by n_commit).
                    combined = jnp.concatenate([hist, commit], axis=1)
                    gidx = (jnp.arange(H, dtype=jnp.int32)[None, :]
                            + n_commit[:, None])
                    new_hist = jnp.take_along_axis(combined, gidx,
                                                   axis=1)
                    return ((new_cache, new_tok, new_hist,
                             rem - n_commit, lens + n_commit),
                            (commit, n_commit, n_prop))

                (cache, tokens, hist, rem, lengths), stacked = \
                    lax.scan(round_body,
                             (cache, tokens, hist, rem, lengths), rngs)
                commits, n_commits, n_props = stacked
                return commits, n_commits, n_props, tokens, cache

            self._spec_verify_fns[key] = fused
        return self._spec_verify_fns[key]

    def _spec_fused_call(self, ready, rounds):
        """Dispatch ``rounds`` fused propose→verify→commit rounds in
        one jitted call (``_spec_step_fused``). ``_spec_can_fuse``
        already reserved pages covering the worst-case growth, so the
        page table built here spans every in-scan commit."""
        from skypilot_tpu.inference.engine import _bucket_len
        temps_d, topks_d, topps_d, active_d, sample = \
            self._slot_meta(ready)
        P_needed = max(max((len(self._pages[s])
                            for s, r in enumerate(ready)
                            if r is not None), default=1), 1)
        P = _bucket_len(P_needed, minimum=1)
        table_p = np.zeros((self.max_batch, P), np.int32)
        for s in range(self.max_batch):
            ps = self._pages[s][:P]
            table_p[s, :len(ps)] = ps
        lengths = self._slot_len.astype(np.int32)
        hist, rem = self._spec_hist_state(ready)
        keys = jax.random.split(self._rng, rounds + 1)
        self._rng = keys[0]
        table_d, hist_d, rem_d, lengths_d = device_upload(
            (table_p, hist, rem, lengths))
        fused = self._get_spec_fused(self.max_batch, P, sample, rounds)
        with self._prof.jit_key('spec_fused',
                                (self.speculate_k, sample, P, rounds)):
            commits, n_commits, n_props, self._tok_dev, self.cache = \
                fused(self.params, self.cache, table_d, self._tok_dev,
                      hist_d, rem_d, lengths_d, active_d, self._adp_dev,
                      self._vmask_dev, temps_d, topks_d, topps_d,
                      keys[1:])
        return commits, n_commits, n_props

    def step(self, horizon: int = 1) -> List[Tuple[int, int, bool]]:
        """Admit (one chunk max), then enqueue decode through the async
        pipeline (_EngineBase semantics: results lag enqueues by up to
        _PIPELINE_DEPTH calls). While prompts are still streaming in,
        the decode horizon is capped at ``interleave_horizon`` so the
        next chunk runs within a bounded number of decode steps
        (admission latency), and capped at a medium bucket while the
        queue is non-empty so freed slots are noticed promptly. Steady
        state (no queue, no prefill) runs the caller's full horizon.
        ``speculate_k > 0`` replaces the fused decode horizon with one
        synchronous propose→verify→commit round per step; adding
        ``decode_steps_per_call > 1`` fuses that many rounds into one
        dispatch instead (in-scan speculative verify)."""
        events: List[Tuple[int, int, bool]] = []
        with self._prof.phase('readback'):
            while len(self._pending) >= self._PIPELINE_DEPTH:
                events.extend(self._process_one())
        with self._prof.phase('admit'):
            events.extend(self._admit())
        if self.speculate_k:
            if (self.decode_steps_per_call or 0) > 1:
                events.extend(self._spec_step_fused())
            else:
                events.extend(self._spec_step())
            if self._deferred_events:
                events.extend(self._deferred_events)
                self._deferred_events = []
            return events
        if self.decode_steps_per_call:
            # Multi-step pin: exactly k fused steps per call (the
            # dispatch-amortization knob wins over interleave/queue
            # shrinks; capacity caps still apply in _enqueue_decode).
            horizon = self.decode_steps_per_call
        elif self._prefill_off:
            # decode_priority_ratio switches the fixed interleave
            # horizon to the Sarathi-style token-budget split (shared
            # with the slot engine); None keeps this engine's
            # measured-best fixed cap.
            horizon = min(horizon,
                          self.interleave_horizon
                          if self.decode_priority_ratio is None
                          else self._interleave_horizon())
        elif self._queue:
            horizon = min(horizon, 32)
        with self._prof.phase('decode_enqueue'):
            enqueued = self._enqueue_decode(horizon)
        if not enqueued and self._pending:
            with self._prof.phase('readback'):
                events.extend(self._process_one())
        # Opportunistic drain: surface any entry whose device results
        # are ALREADY ready (non-blocking probe) instead of letting it
        # age up to _PIPELINE_DEPTH calls — at a 32-step horizon that
        # lag added ~1.5 s to every first-token/finish event. (Tests
        # pinning recycle-window behavior turn it off: on CPU every
        # result is instantly ready and the window collapses.)
        if self._eager_drain:
            with self._prof.phase('readback'):
                while self._pending:
                    probe = getattr(self._pending[0]['toks'],
                                    'is_ready', None)
                    # Probe OUTSIDE any except: an exception from
                    # result processing itself must propagate (the
                    # entry is already popped — swallowing it would
                    # drop tokens and strand inflight counts).
                    if probe is None or not probe():
                        break
                    events.extend(self._process_one())
        if self._deferred_events:        # pool-pressure pipeline drain
            events.extend(self._deferred_events)
            self._deferred_events = []
        return events

    interleave_horizon = 8

    # ---------------------------------------------------------- decode
    def _enqueue_decode(self, horizon: int = 1) -> bool:
        # _await_first slots DO decode: their device-sampled first
        # token was merged into the token vector at prefill enqueue;
        # only the first-token EVENT is still in flight. Held slots
        # (disaggregated handoff pending) never decode.
        active_slots = [s for s in range(self.max_batch)
                        if self._slots[s] is not None
                        and s not in self._prefill_off
                        and not self._slots[s].hold]
        if not active_slots:
            return False
        cap = int(self.max_seq - 1 -
                  max(self._slot_len[s] + self._slot_inflight[s]
                      for s in active_slots))
        if cap < 1:
            return False
        horizon = max(1, min(horizon, cap))
        from skypilot_tpu.inference.engine import (_ring_horizon_cap,
                                                   _ring_row_bytes)
        # Ring budget: auto-sized pools reserved HBM for the full
        # _RING_BYTES_CAP_PAGED ring (see _auto_n_pages — horizon 32
        # on the 7B config), so they take it; explicit pools keep the
        # historical conservative 512 MB cap, since nothing shrank
        # them to pay for a bigger ring (h=32 at batch 48 on a 7B
        # OOM'd at runtime against a full-HBM pool where h=16 ran).
        ring_bytes = (self._RING_BYTES_CAP_PAGED
                      if self._pool_auto_sized else int(512e6))
        horizon = min(horizon, self._ring_horizon_bucket(ring_bytes))
        if self.decode_steps_per_call is None:
            for b in reversed(self._HORIZON_BUCKETS):
                if b <= horizon:
                    horizon = b
                    break
        # else: multi-step pin — run EXACTLY k (capacity-clamped) so
        # the jit key stays (k, sample, P) and the audit's
        # one-dispatch-per-k-tokens contract holds.
        # page capacity: every active slot must hold pages for
        # len+inflight+horizon; shrink the horizon under pool pressure,
        # and when even horizon=1 cannot fit, PREEMPT the newest request
        # back to the queue (vLLM-style recompute: it re-enters with
        # prompt+output as its context) instead of crashing — the
        # auto-sized pool may legitimately be smaller than
        # slots x max_seq. Preemption must see COMPLETE outputs (the
        # requeued context is prompt+output), so with calls in flight
        # the pipeline drains first and the step retries.
        def covered(s, extra):
            return self._ensure_pages(
                s, int(self._slot_len[s] + self._slot_inflight[s]) +
                extra)

        while True:
            while horizon > 1:
                if all(covered(s, horizon) for s in active_slots):
                    break
                horizon //= 2
            if horizon > 1 or all(covered(s, 1) for s in active_slots):
                break
            if self._pending:
                # In-flight tokens would be lost by preempting now:
                # drain into the deferred stash (step() flushes it into
                # its returned events) and retry next step.
                drained = list(self._deferred_events)
                self._deferred_events = []
                while self._pending:
                    drained.extend(self._process_one())
                self._deferred_events = drained
                return False
            # Victim pool: every occupied slot (mid-prefill ones hold
            # pages too) EXCEPT the oldest decodable request — keeping
            # that one guarantees progress, and _validate_request
            # guarantees it fits the pool alone.
            oldest = min(active_slots,
                         key=lambda s: self._slots[s].request_id)
            cands = [s for s in range(self.max_batch)
                     if self._slots[s] is not None and s != oldest]
            if not cands:
                raise MemoryError(
                    'KV page pool exhausted even at horizon=1 with one '
                    'active request; raise n_pages')
            victim = max(cands, key=lambda s: self._slots[s].request_id)
            self._preempt_slot(victim)
            if victim in active_slots:
                active_slots.remove(victim)

        ready = self._decode_ready()
        temps_d, topks_d, topps_d, active_d, sample = \
            self._slot_meta(ready)
        from skypilot_tpu.inference.engine import _bucket_len
        max_pages_live = max(
            self._pages_needed(int(self._slot_len[s] +
                                   self._slot_inflight[s]) + horizon)
            for s in active_slots)
        P = _bucket_len(max_pages_live, minimum=1)
        table_p = np.zeros((self.max_batch, P), np.int32)
        for s in range(self.max_batch):
            ps = self._pages[s][:P]
            table_p[s, :len(ps)] = ps
        # Device-truth lengths at this call = processed + in-flight.
        lengths = (self._slot_len + self._slot_inflight).astype(np.int32)
        self._rng, rng = jax.random.split(self._rng)
        table_dd, lengths_dd = device_upload((table_p, lengths))
        # Per-substep attribution: one dispatch covers ``horizon``
        # decode substeps (multi-step amortization; the profiler's
        # per_substep_ms split makes it visible).
        self._prof.note_substeps('decode_enqueue', horizon)
        t0 = clock.monotonic()
        with self._prof.jit_key('decode', (horizon, sample, P)):
            toks, self.cache = self._decode_fn(
                self.params, self.cache, table_dd,
                self._tok_dev, lengths_dd, rng,
                temps_d, topks_d, topps_d, active_d, self._adp_dev,
                self._vmask_dev, horizon, sample)
        live = int(sum(int(lengths[s]) for s in active_slots))
        self._note_decode_step(live, horizon, clock.monotonic() - t0)
        self._tok_dev = toks[:, -1]
        # Snapshot the epochs BEFORE any early free below bumps them:
        # the entry must record the epochs its tokens were produced
        # under, or a recycled slot's stale entry would pass the epoch
        # check at readback and decrement the NEW tenant's in-flight
        # count (understated lengths -> decode overwrites in-flight KV
        # positions).
        epochs = self._slot_epoch.copy()
        for s in range(self.max_batch):
            if ready[s] is not None:
                self._slot_inflight[s] += horizon
                ready[s]._enq_out += horizon
                self._maybe_early_free(s, ready[s])
        self._pending.append({'kind': 'decode', 'toks': toks,
                              'horizon': horizon,
                              'snapshot': list(ready),
                              'epochs': epochs})
        return True

    def _process_one(self) -> List[Tuple[int, int, bool]]:
        """Sync the oldest in-flight call into events. Prefill entries
        carry the DEVICE-sampled first tokens (already merged into the
        device token vector at enqueue — the slot has been decoding
        since the next horizon); this readback only surfaces the token
        VALUE for the first-token event, host bookkeeping, and finish
        checks."""
        events: List[Tuple[int, int, bool]] = []
        entry = self._pending.popleft()
        # THE sanctioned device->host readback of the async pipeline
        # (jaxpr-audit-gated; see engine.py._process_one).
        vals = host_sync(entry['toks'])
        now = clock.now()
        if entry['kind'] == 'prefill':
            for slot, req, row in entry['batch']:
                if req.finish_time is not None:
                    continue
                tenant = self._slots[slot] is req
                if not tenant and not req._early_freed:
                    continue                     # cancelled/preempted
                token = int(vals[row])
                if token < 0:
                    # Non-finite sentinel from prefill: evict exactly
                    # this request (frees its slot + pages when it is
                    # still the tenant); the other rows land normally.
                    if tenant:
                        self._await_first.discard(slot)
                    events.append(self._evict_nonfinite(slot, req))
                    continue
                if tenant:
                    self._await_first.discard(slot)
                if req.first_token_time is None:  # not on re-admission
                    req.first_token_time = now
                if req.trace is not None:
                    req.trace.end('prefill')
                    req.trace.begin('decode')
                req.output.append(token)
                finished = self._finish_req(slot, req, token)
                events.append((req.request_id, token, finished))
            return events
        for slot, req in enumerate(entry['snapshot']):
            if req is None:
                continue
            if entry['epochs'][slot] == self._slot_epoch[slot]:
                self._slot_inflight[slot] = max(
                    0, self._slot_inflight[slot] - entry['horizon'])
            if req.finish_time is not None:
                continue
            tenant = self._slots[slot] is req
            if not tenant and not req._early_freed:
                continue                         # cancelled/preempted
            for i in range(entry['horizon']):
                token = int(vals[slot, i])
                if token < 0:
                    # Non-finite sentinel mid-horizon: evict exactly
                    # this request; co-batched slots keep their
                    # tokens (blast radius = one request).
                    events.append(self._evict_nonfinite(slot, req))
                    break
                req.output.append(token)
                if tenant:
                    self._slot_len[slot] += 1
                finished = self._finish_req(slot, req, token)
                events.append((req.request_id, token, finished))
                if finished:
                    break
        return events
