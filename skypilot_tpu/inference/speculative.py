"""Speculative decoding: prompt-lookup (n-gram) proposer + batched
on-device verify, shared by the slot and paged engines.

Decode is HBM-bound: every generated token pays a full weight-stream
pass (BENCH_r05: 11.2 of 26.8 ms/step), so emitting ONE token per pass
caps throughput at the one-token-per-stream wall. Speculative decoding
breaks it without a draft model:

- **Propose** (host, numpy): match the last n-gram of each slot's
  prompt+generated history against its own earlier history and propose
  the ``k`` tokens that followed the most recent match (prompt-lookup
  decoding — free on repetitive/extractive text, harmless elsewhere).
  Pure host work; the serve loop runs it OUTSIDE the engine lock
  (``prepare_proposals`` — graftcheck rule GC108 enforces this).
- **Verify** (device, one program): one forward over the ``k+1``
  positions ``[t0, d1..dk]`` per slot — the nonzero-cache-offset
  prefill path from PR 1 — yields next-token logits at every position.
  Greedy rows accept the longest prefix of drafts matching the argmax;
  sampled rows rejection-sample against the filtered distribution and
  fall back to the verify model's own sample on first rejection, so
  the output distribution is exactly the non-speculative one.
- **Commit** (masked, fixed shapes): all ``k+1`` KV rows are computed;
  rows past each slot's accepted count scatter to a drop sentinel and
  the cache length advances by ``n_commit`` — per-slot variable
  acceptance never changes a program shape, so the jit key stays
  ``(k, sample, kv_bucket)`` (the jaxpr audit gates on it).

Each verify round emits between 1 (no/failed proposals — a plain
decode step) and k+1 tokens per slot for one weight-stream pass.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


# --------------------------------------------------------------------------
# Host-side proposer (pure numpy — no device work, no locks required)
# --------------------------------------------------------------------------
def ngram_propose(hist, k: int, max_ngram: int = 3,
                  min_ngram: int = 1) -> np.ndarray:
    """Prompt-lookup proposal: match the trailing ``m``-gram of ``hist``
    (longest ``m`` first, ``max_ngram`` down to ``min_ngram``) against
    its earlier occurrences and return up to ``k`` tokens that followed
    the MOST RECENT match. ``hist`` is the slot's prompt + generated
    tokens, last element = the current (not yet cache-consumed) token.
    Returns an int32 array of length 0..k (empty = nothing to propose).
    O(len(hist) * max_ngram) numpy work — host-only by design."""
    n = len(hist)
    if k <= 0 or n < min_ngram + 1:
        return np.zeros((0,), np.int32)
    arr = np.asarray(hist, np.int64)
    for m in range(min(max_ngram, n - 1), min_ngram - 1, -1):
        pattern = arr[-m:]
        windows = np.lib.stride_tricks.sliding_window_view(arr, m)
        starts = np.nonzero((windows == pattern).all(axis=1))[0]
        # A usable match must have a continuation strictly before the
        # trailing n-gram itself.
        starts = starts[starts + m < n]
        if len(starts):
            begin = int(starts[-1]) + m
            return arr[begin:begin + k].astype(np.int32)
    return np.zeros((0,), np.int32)


def ngram_propose_device(hist, k: int, max_ngram: int = 3,
                         min_ngram: int = 1):
    """Device-side prompt-lookup proposal over a RIGHT-ALIGNED history
    window — the in-scan analogue of :func:`ngram_propose`, used by the
    fused speculative rounds (``_spec_step_fused``) where the host
    cannot see mid-scan commits to propose from.

    ``hist`` is ``[b, H]`` int32, left-padded with ``-1`` (token ids
    are non-negative, so pad never matches), last column = the current
    (not yet cache-consumed) token. Matching is limited to the window —
    matches the host proposer would find further back are missed, which
    only costs acceptance rate, never correctness (greedy commits are
    the verify model's own argmax regardless of what was proposed).

    Returns ``(proposals [b, k] int32, n_prop [b] int32)`` with
    positions past each row's ``n_prop`` zeroed. Runs INSIDE the
    engines' jitted fused-rounds programs (traced, fixed shapes)."""
    import jax.numpy as jnp

    b, H = hist.shape
    best_begin = jnp.zeros((b,), jnp.int32)
    best_found = jnp.zeros((b,), bool)
    for m in range(min(max_ngram, H - 1), min_ngram - 1, -1):
        gram = hist[:, H - m:]                               # [b, m]
        # Every window hist[:, p:p+m] as stacked static slices (m is
        # tiny and static, so this is a handful of cheap views).
        win = jnp.stack([hist[:, j:H - m + 1 + j]
                         for j in range(m)], axis=-1)        # [b, W, m]
        p_idx = jnp.arange(H - m + 1, dtype=jnp.int32)
        # Usable: full match, continuation strictly before the trailing
        # gram itself (p + m < H), window clear of the left pad.
        ok = (jnp.all(win == gram[:, None, :], axis=-1)
              & (p_idx[None, :] + m < H) & (win[:, :, 0] >= 0))
        p_best = jnp.max(jnp.where(ok, p_idx[None, :], -1), axis=1)
        found_m = p_best >= 0
        take = found_m & ~best_found
        best_begin = jnp.where(take, p_best + m, best_begin)
        best_found = best_found | found_m
    idx = jnp.clip(best_begin[:, None] + jnp.arange(k)[None, :],
                   0, H - 1)
    prop = jnp.take_along_axis(hist, idx, axis=1).astype(jnp.int32)
    n_prop = jnp.where(best_found, jnp.minimum(k, H - best_begin),
                       0).astype(jnp.int32)
    prop = jnp.where(jnp.arange(k)[None, :] < n_prop[:, None], prop, 0)
    return prop, n_prop


# --------------------------------------------------------------------------
# Device-side acceptance (shared by both engines' verify programs)
# --------------------------------------------------------------------------
def verify_tokens(logits, proposals, n_prop, rng, temps, topks, topps,
                  *, sample: bool):
    """Batched draft acceptance. Runs INSIDE the engines' jitted verify
    programs.

    logits [b, k+1, vocab] fp32 — position ``i`` is the model's
    next-token distribution after consuming token ``i`` of
    ``[t0, d1..dk]``; proposals [b, k] int32; n_prop [b] valid drafts
    per row (padding positions always reject).

    Greedy rows (``temp <= 0`` or ``sample=False``): accept the longest
    draft prefix matching the per-position argmax; the token after the
    last accepted draft is the model's own argmax — byte-identical to
    vanilla greedy decode.

    Sampled rows: standard rejection sampling against the filtered
    (temperature/top-k/top-p) distribution. The proposer is a point
    mass, so draft ``d`` is accepted with probability ``p(d)`` and on
    first rejection the replacement is drawn from the residual
    ``p`` with ``d`` masked out — the committed stream is distributed
    exactly as non-speculative sampling.

    Returns ``(commit [b, k+1] int32, n_commit [b] int32)``:
    ``commit[:, :n_commit-1]`` are accepted drafts,
    ``commit[:, n_commit-1]`` is the verify model's own token
    (correction or bonus); 1 <= n_commit <= k+1."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import llama

    b, k1, vocab = logits.shape
    k = k1 - 1
    greedy = jnp.argmax(logits, -1).astype(jnp.int32)        # [b, k+1]
    valid = jnp.arange(k)[None, :] < n_prop[:, None]         # [b, k]
    match = (proposals == greedy[:, :-1]) & valid
    if sample:
        masked = llama.filtered_logits(logits, temps[:, None],
                                       topks[:, None], topps[:, None])
        probs = jax.nn.softmax(masked, axis=-1)              # [b,k+1,v]
        rng_u, rng_c = jax.random.split(rng)
        p_draft = jnp.take_along_axis(
            probs[:, :k], proposals[..., None], axis=-1)[..., 0]
        u = jax.random.uniform(rng_u, (b, k))
        match = jnp.where(temps[:, None] > 0,
                          (u < p_draft) & valid, match)
    # Accepted prefix length a: drafts 1..a all passed.
    a = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    greedy_corr = jnp.take_along_axis(greedy, a[:, None], axis=1)[:, 0]
    if sample:
        # Replacement at position a: the rejected draft (when one was
        # actually rejected, a < n_prop) is masked from the filtered
        # distribution — the rejection-sampling residual for a
        # point-mass proposer.
        row = jnp.take_along_axis(masked, a[:, None, None], axis=1)[:, 0]
        rej = jnp.take_along_axis(
            jnp.concatenate([proposals, jnp.zeros((b, 1), jnp.int32)],
                            axis=1), a[:, None], axis=1)[:, 0]
        mask_rej = ((a < n_prop)[:, None]
                    & (jnp.arange(vocab)[None, :] == rej[:, None]))
        sampled_corr = jax.random.categorical(
            rng_c, jnp.where(mask_rej, -jnp.inf, row)).astype(jnp.int32)
        corr = jnp.where(temps > 0, sampled_corr, greedy_corr)
    else:
        corr = greedy_corr
    j = jnp.arange(k + 1)[None, :]
    padded = jnp.concatenate([proposals, jnp.zeros((b, 1), jnp.int32)],
                             axis=1)
    commit = jnp.where(j < a[:, None], padded, corr[:, None])
    return commit, a + 1


# --------------------------------------------------------------------------
# Engine scaffolding
# --------------------------------------------------------------------------
class SpeculativeMixin:
    """Propose→verify→commit scaffolding shared by the slot and paged
    engines. Engines call ``_init_spec(speculate_k)`` from __init__,
    implement ``_spec_verify_call(ready, proposals, n_prop)`` (the
    jitted verify dispatch; returns (commit, n_commit) device arrays
    and updates the cache/token vector), and route ``step()`` through
    ``_spec_step()`` when ``speculate_k > 0``.

    The speculative loop is SYNCHRONOUS (one sanctioned host_sync per
    round): the proposer needs the committed tokens on the host before
    it can propose the next continuation, so the verify readback cannot
    lag like the fused-decode pipeline. Each round still amortizes the
    weight stream over up to k+1 tokens per slot.

    With ``decode_steps_per_call > 1`` set alongside ``speculate_k``,
    engines that also implement ``_spec_fused_call(ready, rounds)``
    route through ``_spec_step_fused()`` instead: the proposer moves ON
    DEVICE (``ngram_propose_device``) and ``rounds`` whole
    propose→verify→commit rounds fuse into one dispatch, so the
    host_sync amortizes ``rounds`` x on top of speculation's k+1 x."""

    # Longest n-gram the proposer tries to match (host-side knob; not
    # part of any jit key).
    spec_max_ngram = 3

    # History window the DEVICE proposer sees in fused rounds
    # (``_spec_step_fused``); host uploads the trailing ``H`` tokens
    # per slot each dispatch. Shapes a jitted program, so it is a
    # class-level constant, not a jit key.
    spec_hist_window = 64

    def _init_spec(self, speculate_k: Optional[int]) -> None:
        self.speculate_k = int(speculate_k or 0)
        if self.speculate_k < 0:
            raise ValueError(
                f'speculate_k must be >= 0, got {self.speculate_k}')
        self._spec_verify_fns: Dict[Tuple, Any] = {}
        self._spec_prepared: Optional[Dict[str, Dict[int, Any]]] = None
        self._spec_rounds = 0
        self._spec_slot_steps = 0     # (round, active slot) pairs
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_committed = 0

    # ------------------------------------------------------------ metrics
    def spec_metrics(self) -> Dict[str, Any]:
        """Stable-schema speculation gauges (all keys always present,
        zeros when speculation is off — scrapers see one schema)."""
        proposed = self._spec_proposed
        slot_steps = self._spec_slot_steps
        return {
            'speculate_k': self.speculate_k,
            'spec_rounds': self._spec_rounds,
            'spec_proposed': proposed,
            'spec_accepted': self._spec_accepted,
            'spec_committed': self._spec_committed,
            'spec_accept_rate': (self._spec_accepted / proposed
                                 if proposed else 0.0),
            # Mean tokens committed per slot per verify call (1..k+1):
            # the multiplier over one-token-per-pass decode.
            'spec_tokens_per_step': (self._spec_committed / slot_steps
                                     if slot_steps else 0.0),
        }

    # ----------------------------------------------------------- proposer
    def prepare_proposals(self) -> None:
        """Host-only n-gram matching for the current decodable slots.
        The serve loop calls this BEFORE taking the engine lock
        (graftcheck GC108: proposer host work never runs under the
        lock); results are keyed by (request_id, len(output)) and
        revalidated in ``_spec_build_proposals`` — a stale entry (the
        slot turned over or grew between prepare and use) is simply
        recomputed inline. Only the engine-loop thread mutates slot
        outputs, so the reads here are single-writer safe."""
        if not self.speculate_k:
            return
        prep: Dict[str, Dict[int, Any]] = {'key': {}, 'prop': {}}
        off = set(self._prefill_off)
        for slot, req in enumerate(list(self._slots)):
            if req is None or slot in off or req.hold \
                    or req.finish_time is not None:
                continue
            prep['key'][slot] = (req.request_id, len(req.output))
            prep['prop'][slot] = ngram_propose(
                req.prompt + req.output, self.speculate_k,
                max_ngram=self.spec_max_ngram)
        self._spec_prepared = prep

    def _spec_room(self, slot: int) -> int:
        """Extra per-engine cap on proposal count for ``slot`` (e.g.
        page availability); -1 = the slot cannot even take one more
        token (engine should preempt). Default: no extra cap."""
        del slot
        return self.speculate_k

    def _spec_starved(self, slots: List[int]) -> None:
        """Hook: slots whose ``_spec_room`` came back negative (cannot
        commit even one token). Default: nothing (the slot engine's
        capacity is enforced via the budget cap below)."""
        del slots

    def _spec_build_proposals(self, ready) -> Tuple[np.ndarray,
                                                    np.ndarray, List[int]]:
        """Fixed-shape [b, k] proposal matrix + per-slot valid counts.
        Each slot's count is capped by its remaining generation budget
        and sequence capacity (n_commit <= n_prop + 1 never overshoots
        either), and by the engine's ``_spec_room``. Returns
        (proposals, n_prop, starved_slots)."""
        k = self.speculate_k
        b = self.max_batch
        proposals = np.zeros((b, k), np.int32)
        n_prop = np.zeros(b, np.int32)
        starved: List[int] = []
        cached = self._spec_prepared
        self._spec_prepared = None
        for slot, req in enumerate(ready):
            if req is None:
                continue
            room = self._spec_room(slot)
            if room < 0:
                starved.append(slot)
                continue
            out = len(req.output)
            budget = min(req.max_new_tokens - out,
                         self.max_seq - len(req.prompt) - out) - 1
            room = min(room, max(0, budget))
            if room <= 0:
                continue
            if (cached is not None and cached['key'].get(slot)
                    == (req.request_id, out)):
                prop = cached['prop'][slot]
            else:
                prop = ngram_propose(req.prompt + req.output, k,
                                     max_ngram=self.spec_max_ngram)
            m = min(len(prop), room)
            proposals[slot, :m] = prop[:m]
            n_prop[slot] = m
        return proposals, n_prop, starved

    # ----------------------------------------------------------- the step
    def _spec_step(self) -> List[Tuple[int, int, bool]]:
        """One propose→verify→commit round over every decodable slot.
        Drains the async pipeline first (the proposer and the commit
        bookkeeping need host-complete outputs), then runs ONE verify
        program and commits its masked results. Emits 1..k+1 tokens per
        active slot."""
        from skypilot_tpu.telemetry import clock
        from skypilot_tpu.utils.host import host_sync
        events: List[Tuple[int, int, bool]] = []
        with self._prof.phase('readback'):
            while self._pending:
                events.extend(self._process_one())
        ready = self._decode_ready()
        if not any(r is not None for r in ready):
            return events
        round_t0 = clock.monotonic()
        with self._prof.phase('spec_verify'):
            proposals, n_prop, starved = \
                self._spec_build_proposals(ready)
            if starved:
                self._spec_starved(starved)
                ready = self._decode_ready()
                if not any(r is not None for r in ready):
                    return events
            commit, n_commit = self._spec_verify_call(ready, proposals,
                                                      n_prop)
            # THE sanctioned readback of the speculative loop (the
            # round is synchronous by design — see class docstring).
            commit_h = host_sync(commit)
            n_commit_h = host_sync(n_commit)
        round_t1 = clock.monotonic()
        self._spec_rounds += 1
        self._spec_proposed += int(n_prop.sum())
        for slot, req in enumerate(ready):
            if req is None or req.finish_time is not None:
                continue
            m = int(n_commit_h[slot])
            if m <= 0:
                continue
            self._spec_slot_steps += 1
            self._spec_accepted += m - 1
            self._spec_committed += m
            if req.trace is not None:
                req.trace.add('spec_round', round_t0, round_t1,
                              proposed=int(n_prop[slot]), committed=m)
            for j in range(m):
                token = int(commit_h[slot, j])
                req.output.append(token)
                self._slot_len[slot] += 1
                finished = self._finish_req(slot, req, token)
                events.append((req.request_id, token, finished))
                if finished:
                    break
        return events

    # ------------------------------------------------- fused (in-scan)
    def _spec_can_fuse(self, slot: int, rounds: int) -> bool:
        """Hook: can ``slot`` absorb ``rounds`` fused verify rounds of
        KV growth (up to ``rounds * (k + 1)`` rows) with no host
        intervention between rounds? Default yes — the slot engine's
        sentinel-masked scatter plus the in-scan ``rem`` budget carry
        already bound writes; the paged engine overrides this with an
        up-front page reservation."""
        del slot, rounds
        return True

    def _spec_hist_state(self, ready) -> Tuple[np.ndarray, np.ndarray]:
        """Device-proposer inputs for the fused rounds: right-aligned
        history window ``[b, H]`` (left-padded with -1) and per-slot
        remaining-token budgets ``[b]``. ``rem`` is the most tokens the
        slot may still emit (generation budget and sequence capacity),
        so the in-scan cap ``n_prop <= rem - 1`` reproduces
        ``_spec_build_proposals``'s budget math round by round and
        commits never overshoot."""
        H = self.spec_hist_window
        b = self.max_batch
        hist = np.full((b, H), -1, np.int32)
        rem = np.zeros((b,), np.int32)
        for slot, req in enumerate(ready):
            if req is None:
                continue
            toks = (req.prompt + req.output)[-H:]
            hist[slot, H - len(toks):] = toks
            out = len(req.output)
            rem[slot] = max(0, min(req.max_new_tokens - out,
                                   self.max_seq - len(req.prompt) - out))
        return hist, rem

    def _spec_step_fused(self) -> List[Tuple[int, int, bool]]:
        """In-scan speculative verify: ``decode_steps_per_call`` rounds
        of propose→verify→commit fused into ONE jitted dispatch (a
        ``lax.scan`` over rounds with the DEVICE n-gram proposer and a
        gather-carried history window), then one sanctioned host_sync
        for the stacked commits. Composes the two amortization knobs:
        speculation's up-to-``k+1`` tokens per weight stream AND the
        multi-step pin's one dispatch per ``rounds`` verify rounds.

        Tokens a slot commits after finishing mid-scan (EOS hit in an
        earlier round — the device cannot see host finish state) are
        discarded at readback, same as vanilla multi-step decode past
        EOS; the ``rem`` carry guarantees the device never writes past
        ``max_new_tokens`` or the sequence capacity. Falls back to the
        synchronous single-round ``_spec_step`` when any active slot
        cannot reserve the fused KV growth up front (``_spec_can_fuse``
        — paged pool pressure)."""
        from skypilot_tpu.telemetry import clock
        from skypilot_tpu.utils.host import host_sync
        rounds = self.decode_steps_per_call or 1
        if rounds <= 1:
            return self._spec_step()
        events: List[Tuple[int, int, bool]] = []
        with self._prof.phase('readback'):
            while self._pending:
                events.extend(self._process_one())
        ready = self._decode_ready()
        if not any(r is not None for r in ready):
            return events
        if not all(self._spec_can_fuse(slot, rounds)
                   for slot, r in enumerate(ready) if r is not None):
            events.extend(self._spec_step())
            return events
        round_t0 = clock.monotonic()
        with self._prof.phase('spec_verify'):
            commits, n_commits, n_props = \
                self._spec_fused_call(ready, rounds)
            # THE sanctioned readback: one host_sync per ``rounds``
            # verify rounds (vs one per round in _spec_step).
            commits_h = host_sync(commits)
            n_commits_h = host_sync(n_commits)
            n_props_h = host_sync(n_props)
        round_t1 = clock.monotonic()
        self._spec_rounds += rounds
        for slot, req in enumerate(ready):
            if req is None or req.finish_time is not None:
                continue
            finished = False
            for r in range(rounds):
                m = int(n_commits_h[r, slot])
                if m <= 0:
                    continue
                self._spec_slot_steps += 1
                self._spec_proposed += int(n_props_h[r, slot])
                self._spec_accepted += m - 1
                self._spec_committed += m
                if req.trace is not None:
                    req.trace.add('spec_round', round_t0, round_t1,
                                  proposed=int(n_props_h[r, slot]),
                                  committed=m)
                for j in range(m):
                    token = int(commits_h[r, slot, j])
                    req.output.append(token)
                    self._slot_len[slot] += 1
                    finished = self._finish_req(slot, req, token)
                    events.append((req.request_id, token, finished))
                    if finished:
                        break
                if finished:
                    break
        return events
