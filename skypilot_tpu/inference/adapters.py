"""Multi-tenant adapter serving: the host-side registry over the
device-resident multi-LoRA bank, plus the constrained-decoding grammar
masks that ride the same per-request plumbing.

The bank itself (stacked per-layer A/B factors + per-adapter scale,
``params['layers']['mlora']``) and its batched gather matmul live in
:mod:`skypilot_tpu.models.multilora`; this module owns WHICH adapter
occupies WHICH slot:

- **LRU load/evict, bank slots as the capacity unit** — the paged
  pool's discipline applied to adapters: a request naming a loaded
  adapter pins it (refcount); a miss loads the adapter's ``.npz``
  checkpoint from ``adapter_dir`` (or the in-memory store) into a free
  slot; under pressure the coldest UNPINNED adapter's slot is
  overwritten in place. Load and evict are the SAME donated device
  upload (:func:`multilora.set_bank_row`, traced slot index): adapter
  churn re-uploads bank rows and never recompiles or reallocates.
- **Per-tenant telemetry registered at construction** (zeros from the
  first scrape, the stable-schema contract):
  ``skytpu_adapter_bank_slots{state}``,
  ``skytpu_adapter_loads_total`` / ``skytpu_adapter_evictions_total``,
  and ``skytpu_requests_total{adapter}`` with a BOUNDED label set
  (names beyond ``4 x slots`` distinct values collapse into
  ``other`` — a tenant id must never be able to grow the scrape
  unboundedly).

Thread safety: calls run under the serve layer's engine lock, like
every other host-side engine call.
"""
from __future__ import annotations

import collections
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import multilora
from skypilot_tpu.telemetry import clock
from skypilot_tpu.utils import host

# Telemetry series (registered at construction; see module docstring).
ADAPTER_SLOTS_METRIC = 'skytpu_adapter_bank_slots'
ADAPTER_LOADS_METRIC = 'skytpu_adapter_loads_total'
ADAPTER_EVICTIONS_METRIC = 'skytpu_adapter_evictions_total'
REQUESTS_METRIC = 'skytpu_requests_total'

_NAME_RE = re.compile(r'^[A-Za-z0-9][A-Za-z0-9._-]*$')


def _check_name(name: str) -> str:
    """Adapter names double as checkpoint file stems and metric label
    values: reject path separators/traversal outright."""
    if not isinstance(name, str) or not _NAME_RE.match(name) \
            or '..' in name:
        raise ValueError(f'illegal adapter name {name!r}')
    return name


class AdapterBankFullError(RuntimeError):
    """Every bank slot is pinned by a live request; the new adapter
    cannot load until one finishes (the serve layer maps this to a
    retryable 503, like pool-pressure admission failures)."""


class AdapterRegistry:
    """Name -> bank-slot mapping with LRU eviction and request-pinned
    refcounts, bound to one engine's bank."""

    def __init__(self, engine, *, slots: int, rank: int,
                 adapter_dir: Optional[str] = None,
                 targets: Optional[Sequence[str]] = None):
        self.engine = engine
        cfg = engine.cfg
        self.slots = int(slots)
        self.rank = int(rank)
        self.adapter_dir = adapter_dir
        self.targets = (tuple(targets) if targets
                        else multilora.default_targets(cfg))
        bank = multilora.init_bank(cfg, self.slots, self.rank,
                                   targets=self.targets, dtype=cfg.dtype)
        mesh = getattr(engine, 'mesh', None)
        if mesh is not None:
            # The bank replicates (it is tiny next to the base weights;
            # the gather matmuls then need no collectives under tp).
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            bank = jax.device_put(
                bank, NamedSharding(mesh, PartitionSpec()))
        engine.params['layers']['mlora'] = bank

        # name -> slot, insertion order == LRU order (oldest first).
        self._loaded: 'collections.OrderedDict[str, int]' = \
            collections.OrderedDict()
        self._refs: Dict[str, int] = {}
        self._free: List[int] = list(range(self.slots))
        # In-memory adapter source (tests/bench; checkpoint-less).
        self._store: Dict[str, Tuple[Any, float]] = {}
        self.loads_total = 0
        self.evictions_total = 0
        self.last_load_ms = 0.0
        # Bounded requests_total{adapter} label set.
        self._label_cap = 4 * self.slots
        self._req_counters: Dict[str, Any] = {}

        self._slots_used_g = self._slots_free_g = None
        self._loads_c = self._evictions_c = None
        if getattr(engine, 'telemetry_enabled', False):
            from skypilot_tpu.telemetry import registry as registry_lib
            reg = registry_lib.get_registry()
            self._slots_used_g = reg.gauge(
                ADAPTER_SLOTS_METRIC,
                'Multi-LoRA bank slots by occupancy state',
                state='used')
            self._slots_free_g = reg.gauge(
                ADAPTER_SLOTS_METRIC, '', state='free')
            self._slots_free_g.set(self.slots)
            self._loads_c = reg.counter(
                ADAPTER_LOADS_METRIC,
                'Adapter checkpoint loads into the bank (LRU misses)')
            self._evictions_c = reg.counter(
                ADAPTER_EVICTIONS_METRIC,
                'Adapters evicted from the bank under slot pressure')
            # requests_total{adapter="none"} exists from the first
            # scrape; named labels join as adapters are first seen.
            self._req_counter('none')

    # ------------------------------------------------------------ sources
    def register(self, name: str, lora_tree: Any,
                 scale: Optional[float] = None) -> None:
        """In-memory adapter source (trainer-format tree, see
        ``lora.split_lora``); checkpoint-less path for tests/bench."""
        _check_name(name)
        if scale is None:
            first = next(iter(lora_tree.values()))
            r = int(np.shape(first['a'])[-1])
            scale = float(self.engine.cfg.lora_alpha) / r
        self._store[name] = (lora_tree, scale)

    def _load_source(self, name: str) -> Tuple[Any, float]:
        if name in self._store:
            return self._store[name]
        if self.adapter_dir:
            path = os.path.join(self.adapter_dir, f'{name}.npz')
            if os.path.exists(path):
                return multilora.load_adapter(path)
        raise ValueError(
            f'unknown adapter {name!r}: not registered and no '
            f'checkpoint under {self.adapter_dir!r}')

    # ------------------------------------------------------------ core
    def acquire(self, name: str) -> int:
        """Pin ``name`` for one request and return its bank slot,
        loading (and possibly evicting) on miss. Balanced by exactly
        one :meth:`release` when the request leaves the system."""
        _check_name(name)
        if name in self._loaded:
            self._loaded.move_to_end(name)
            self._refs[name] = self._refs.get(name, 0) + 1
            return self._loaded[name]
        # Load AND validate the row before touching the bank: a bad
        # checkpoint (over-rank, wrong layer count, shape mismatch)
        # must fail the one request without consuming a slot or
        # evicting a healthy adapter.
        tree, scale = self._load_source(name)
        row = multilora.adapter_row_from_tree(
            self.engine.cfg, tree, self.rank, scale,
            targets=self.targets)
        slot = self._take_slot()
        try:
            t0 = clock.monotonic()
            bank = self.engine.params['layers']['mlora']
            new_bank = multilora.set_bank_row(
                bank, row, jnp.asarray(slot, jnp.int32))
            # Block for an honest load-latency number (loads are rare
            # and off the steady-state decode path; this is a
            # device-side wait, not a transfer).
            host.host_block(new_bank['scale'])
            self.last_load_ms = (clock.monotonic() - t0) * 1e3
            self.engine.params['layers']['mlora'] = new_bank
        except BaseException:
            # The slot is genuinely free (any evicted victim already
            # left _loaded); without this, every failed upload would
            # leak one bank slot until AdapterBankFullError wedges
            # admission.
            self._free.append(slot)
            self._note_slots()
            raise
        self._loaded[name] = slot
        self._refs[name] = self._refs.get(name, 0) + 1
        self.loads_total += 1
        if self._loads_c is not None:
            self._loads_c.inc()
        self._note_slots()
        return slot

    def release(self, name: str) -> None:
        """Unpin one request's hold on ``name`` (the adapter STAYS
        loaded — only slot pressure evicts)."""
        if name in self._refs and self._refs[name] > 0:
            self._refs[name] -= 1

    def _take_slot(self) -> int:
        if self._free:
            return self._free.pop()
        # Evict the coldest unpinned adapter; its slot is overwritten
        # in place by the incoming row (evict+load = ONE bank upload).
        for victim, slot in self._loaded.items():
            if self._refs.get(victim, 0) <= 0:
                del self._loaded[victim]
                self._refs.pop(victim, None)
                self.evictions_total += 1
                if self._evictions_c is not None:
                    self._evictions_c.inc()
                return slot
        raise AdapterBankFullError(
            f'all {self.slots} adapter bank slots are pinned by live '
            f'requests')

    def slot_of(self, name: str) -> Optional[int]:
        return self._loaded.get(name)

    def loaded(self) -> List[str]:
        """Loaded adapter names, coldest first (LRU order)."""
        return list(self._loaded)

    # ------------------------------------------------------------ metrics
    def _note_slots(self) -> None:
        used = len(self._loaded)
        if self._slots_used_g is not None:
            self._slots_used_g.set(used)
            self._slots_free_g.set(self.slots - used)

    def _req_counter(self, label: str):
        c = self._req_counters.get(label)
        if c is None:
            from skypilot_tpu.telemetry import registry as registry_lib
            c = registry_lib.get_registry().counter(
                REQUESTS_METRIC,
                'Requests accepted, labeled by adapter (bounded set)',
                adapter=label)
            self._req_counters[label] = c
        return c

    def note_request(self, adapter: Optional[str]) -> None:
        """Count one accepted request against its adapter label —
        bounded: past ``4 x slots`` distinct names, new ones collapse
        into ``other``."""
        if self._loads_c is None and self._slots_used_g is None:
            return                       # telemetry off
        label = adapter or 'none'
        if label not in self._req_counters and \
                len(self._req_counters) >= self._label_cap:
            label = 'other'
        self._req_counter(label).inc()

    def stats(self) -> Dict[str, Any]:
        """The JSON ``lora`` block (``/metrics?format=json``, bench)."""
        return {
            'slots': self.slots,
            'used': len(self._loaded),
            'free': self.slots - len(self._loaded),
            'rank': self.rank,
            'targets': list(self.targets),
            'loads_total': self.loads_total,
            'evictions_total': self.evictions_total,
            'last_load_ms': self.last_load_ms,
            'loaded': list(self._loaded),
            'pinned': {n: r for n, r in self._refs.items() if r > 0},
        }


# ---------------------------------------------------------------------------
# Constrained decoding (grammar -> vocab mask)
# ---------------------------------------------------------------------------
def json_mode_mask(vocab_size: int,
                   eos_id: Optional[int] = None) -> np.ndarray:
    """Smoke-level JSON-mode token mask for byte-level vocabularies:
    printable ASCII plus JSON whitespace (tab/newline/CR) plus EOS.
    Token-set constraint, not a stateful grammar — it provably excludes
    non-JSON bytes (control chars, non-ASCII) while admitting every
    ASCII JSON document."""
    mask = np.zeros(vocab_size, bool)
    lo, hi = 0x20, min(0x7F, vocab_size)
    mask[lo:hi] = True
    for b in (0x09, 0x0A, 0x0D):
        if b < vocab_size:
            mask[b] = True
    if eos_id is not None and 0 <= eos_id < vocab_size:
        mask[eos_id] = True
    return mask


def compile_grammar(grammar: Any, vocab_size: int,
                    eos_id: Optional[int] = None
                    ) -> Optional[np.ndarray]:
    """Request ``grammar`` field -> [vocab] bool mask (True = allowed),
    or None for unconstrained. Accepted spellings:

    - ``None`` — no constraint;
    - ``'json'`` — :func:`json_mode_mask`;
    - a sequence of allowed token ids (EOS auto-allowed so constrained
      requests can still terminate);
    - a [vocab] bool array, used as-is (EOS auto-allowed).
    """
    if grammar is None:
        return None
    if isinstance(grammar, str):
        if grammar == 'json':
            return json_mode_mask(vocab_size, eos_id)
        raise ValueError(
            f'unknown grammar {grammar!r}; supported: "json", a token-id '
            f'list, or a [vocab] bool mask')
    # Host-side request payload (never a device array); dtype inferred
    # so the bool-mask and id-list spellings stay distinguishable.
    arr = np.asarray(grammar, dtype=None)
    if arr.dtype == np.bool_:
        if arr.shape != (vocab_size,):
            raise ValueError(
                f'grammar mask shape {arr.shape} != ({vocab_size},)')
        mask = arr.copy()
    else:
        ids = arr.astype(np.int64).reshape(-1)
        if ids.size == 0:
            raise ValueError('grammar token-id list is empty')
        if (ids < 0).any() or (ids >= vocab_size).any():
            raise ValueError('grammar token id out of vocab range')
        mask = np.zeros(vocab_size, bool)
        mask[ids] = True
    if eos_id is not None and 0 <= eos_id < vocab_size:
        mask[eos_id] = True
    return mask
