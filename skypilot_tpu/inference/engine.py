"""Continuous-batching inference engine (JetStream-class serving core).

The reference serves models by launching external engines (vLLM/SGLang/
JetStream recipes under ``llm/``); this is the in-tree TPU engine those
recipes become. Design:

- **Slot-based continuous batching**: a fixed decode batch of ``max_batch``
  slots over one batched KV cache ([layers, slots, max_seq, kv_heads, d],
  per-slot lengths). Finished slots are immediately refilled from the queue
  — the decode step shape never changes, so XLA compiles exactly two
  programs (prefill per length-bucket, decode) and the MXU sees a fixed
  [slots, 1] batch every step.
- **Prefill/decode split**: prefill runs per-request at bucketed lengths
  (powers of two — bounded compile count), writes its KV rows into the
  slot; decode advances all active slots one token per step.
- **Sampling**: greedy / temperature / top-k / top-p (nucleus), jitted
  with the decode step; per-request stop sequences checked host-side.
- **Sharding**: with a mesh, params shard by their logical axes (tp for
  serving) and the KV cache by ``cache_logical_axes`` — batch over data
  axes, kv heads over tp.

The cache-capacity contract (llama.forward docstring) is enforced here:
requests whose prompt+max_new_tokens exceed ``max_seq`` are rejected, and
decode stops at capacity.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu import telemetry
from skypilot_tpu.inference.speculative import SpeculativeMixin
from skypilot_tpu.models import llama
from skypilot_tpu.models.configs import ModelConfig
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.telemetry import clock
from skypilot_tpu.telemetry import tracing
from skypilot_tpu.utils.host import device_upload, host_sync


@dataclasses.dataclass
class Request:
    request_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    # Stop sequences (token-id lists): decode finishes when the output
    # ends with any of them; the matched suffix is trimmed from
    # ``output``. NOTE a multi-token stop may partially stream before it
    # matches — non-streaming callers always see the trimmed output.
    stop: Optional[List[List[int]]] = None
    stop_hit: bool = False
    # Admission priority hint (lower = more urgent; the serve
    # scheduler maps SLO tiers to these). Orders queue pops — FIFO
    # within a priority class — so an engine-internal requeue
    # (paged preemption backoff) cannot park a latency-tier request
    # behind newly queued throughput work.
    priority: int = 0
    # Disaggregated prefill: a held request runs admission + prefill
    # and samples its first token, then TAKES NO DECODE STEPS (every
    # decode-phase ready mask skips it) until the serve layer exports
    # its KV to a decode worker — or releases the hold on handoff
    # failure (colocated fallback). Keeps a prefill worker's chips on
    # prefill instead of racing the handoff with local decode.
    hold: bool = False
    # Multi-tenant serving: which bank adapter this request decodes
    # with (None = base model, byte-identical to an adapter-less
    # engine), which tenant submitted it (telemetry label only), and an
    # optional grammar constraint ('json' | token-id list | [vocab]
    # bool mask) compiled into a vocab logit mask at admission.
    adapter: Optional[str] = None
    tenant: Optional[str] = None
    grammar: Any = None
    # filled by the engine
    output: List[int] = dataclasses.field(default_factory=list)
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    # NaN blast-radius isolation: the device emitted the non-finite
    # sentinel for this request's logits row — it was evicted with a
    # retryable error instead of streaming argmax-of-NaN garbage.
    nan_evicted: bool = False
    # Paged-engine early slot recycle: output tokens covered by
    # ENQUEUED device calls, and whether the slot was freed before the
    # request's tail tokens surfaced through the async pipeline.
    _enq_out: int = 0
    _early_freed: bool = False
    # Adapter-bank pin state: the bank slot this request gathers
    # (-1 = none), the compiled [vocab] bool mask (host numpy) its
    # grammar produced, and whether its registry pin was released
    # (every exit path releases exactly once).
    _adapter_slot: int = -1
    _vocab_mask: Optional[Any] = None
    _adapter_released: bool = False
    # Per-request lifecycle trace (telemetry.tracing.RequestTrace;
    # None when engine telemetry is off).
    trace: Optional[Any] = None

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return (self.first_token_time - self.submit_time) * 1e3


def _bucket_len(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _ring_row_bytes(cfg, batch: int, mesh=None) -> int:
    """Bytes of ONE horizon-step's ring rows (k+v across all layers) —
    the ring stays in model dtype regardless of cache quantization.
    With a mesh, PER-DEVICE bytes: the ring's kv-head dim shards over
    tp like the cache it merges into (batch sharding is NOT credited —
    the paged ring rides a replicated batch, so dividing by dp would
    under-reserve)."""
    return (cfg.n_layers * batch * cfg.n_kv_heads * cfg.head_dim *
            jnp.dtype(cfg.dtype).itemsize * 2
            ) // kv_shard_degree(cfg, mesh)


_RING_BYTES_CAP = int(1e9)


# Re-exported for engine-side callers; defined in kv_transfer so the
# serve layer can catch it without importing this jax-heavy module.
from skypilot_tpu.inference.kv_transfer import HandoffCapacityError  # noqa: E402,F401 pylint: disable=wrong-import-position


def resolve_kv_cache_dtype(kv_cache_dtype: Optional[str],
                           quantize: Optional[str]) -> str:
    """Effective KV storage dtype ('bf16' | 'int8' | 'int4') from the
    engine flag. ``None``/``'auto'`` follows the WEIGHT quantization
    mode (int8 weights => int8 KV, int4 weights => int4 KV — with
    weights already 4-bit the KV stream is the dominant decode HBM
    traffic, so auto matches its width); an explicit value decouples
    them in either direction — int8/int4 KV over bf16 weights shrinks
    the dominant decode HBM stream (and grows pool token capacity) on
    its own, and bf16 KV over quantized weights is the ablation/debug
    spelling."""
    if kv_cache_dtype in (None, 'auto'):
        return {'int8': 'int8', 'int4': 'int4'}.get(quantize, 'bf16')
    if kv_cache_dtype not in ('bf16', 'int8', 'int4'):
        raise ValueError(
            f'unknown kv_cache_dtype {kv_cache_dtype!r}; supported: '
            "'bf16', 'int8', 'int4' (None/'auto' follows the weight "
            'quantize mode)')
    return kv_cache_dtype


def kv_shard_degree(cfg, mesh=None) -> int:
    """How many ways the stored KV-head dimension actually splits over
    the mesh: the tp axis size when it divides ``n_kv_heads``, else 1 —
    mirroring ``mesh_lib.spec_for``'s divisibility fallback, which
    replicates KV heads for MQA/GQA models with ``n_kv_heads < tp``.
    THE divisor per-shard KV byte accounting rides; using the raw tp
    size would claim HBM savings the sharding rules never delivered."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    return mesh_lib.axis_shard_degree(
        mesh, mesh_lib.DEFAULT_RULES['kv_heads'], cfg.n_kv_heads)


def kv_token_bytes(cfg, quantized: bool, mesh=None) -> int:
    """Stored bytes of ONE cached token: k+v rows across all layers,
    per-row fp32 scales included for int8 caches. THE per-token cost
    every capacity decision rides — paged pool sizing, the prefill
    stacked-rows caps, preemption accounting, and the telemetry
    capacity gauges — so int8 KV's halved cost shows up everywhere at
    once instead of drifting per call site.

    ``mesh`` (optional) makes the cost PER-SHARD: the kv-head dim
    shards over tp, so one device stores ``1/tp`` of every token's
    rows. HBM-budget decisions (pool auto-sizing, prefill stack caps)
    must pass the mesh; token-capacity surfaces (pool stats, scheduler
    bounds) stay global — a token is a token regardless of how many
    chips hold its rows.

    ``quantized`` accepts the historical bool (True == int8) or a kv
    dtype string: int4 rows are PACKED — two nibble codes per byte
    (head_dim/2) plus the same fp32 row scale."""
    if quantized == 'int4':
        row_w = cfg.head_dim // 2 + 4
    elif quantized and quantized != 'bf16':
        row_w = cfg.head_dim + 4
    else:
        row_w = cfg.head_dim * jnp.dtype(cfg.dtype).itemsize
    return (cfg.n_layers * cfg.n_kv_heads * row_w * 2
            ) // kv_shard_degree(cfg, mesh)


# Telemetry series every engine registers at construction (zeros from
# the first scrape): the decode step's KV read traffic and the
# attention-impl attribution of its wall time.
KV_READ_METRIC = 'skytpu_kv_read_bytes_per_step'
ATTN_MS_METRIC = 'skytpu_attn_kernel_ms'


def _ring_horizon_cap(cfg, batch: int, param_bytes: int,
                      mesh=None) -> int:
    """Longest sensible fused-decode horizon: the ring re-read must stay
    under ~15% of the weight stream AND the ring buffers under ~1 GB
    (at batch 48 on a 7B the 15% rule alone allowed a 1.6 GB ring that
    blew the HBM budget at runtime). The floor matters as much as the
    cap: per-call dispatch through the axon tunnel measured ~100 ms, so
    horizons below ~32 pay more in dispatch than a bigger ring costs in
    re-reads (a 512 MB cap that forced h=16 at batch 48 added ~5 ms to
    every step)."""
    row = _ring_row_bytes(cfg, batch, mesh)
    return max(8, min(int(0.15 * param_bytes / row),
                      _RING_BYTES_CAP // row))


def prepare_params(cfg: ModelConfig, params, *, quantize=None, mesh=None,
                   donate_params: bool = False):
    """Shared param preparation for the slot and paged engines:
    LoRA merge, init-if-absent, optional int8 quantization, mesh
    sharding. Returns (cfg, params, effective_quantize) — cfg changes
    when a LoRA checkpoint is folded (lora_rank drops to 0).

    Ordering matters twice: LoRA adapters fold BEFORE quantization
    (folding into an int8 base is refused), and on a mesh the bf16 tree
    is sharded FIRST so a 7B-class checkpoint never has to fit
    (bf16 + int8) on one chip; single-device quantization frees each
    bf16 leaf as its int8 replacement lands when ``donate_params``."""
    from skypilot_tpu.models import lora as lora_lib
    from skypilot_tpu.models import quantization
    # A LoRA checkpoint serves as its merged model: fold the adapters
    # into the base once; decode then runs the plain weight path.
    # ``donate_params`` lets the fold reuse the base buffers (peak HBM
    # = |W| + one layer's delta instead of 2|W|).
    cfg, params = lora_lib.maybe_merge(cfg, params,
                                       donate=donate_params)
    if params is None:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    if quantize is not None and quantize not in ('int8', 'int4'):
        raise ValueError(f'unknown quantize mode {quantize!r}; '
                         "supported: 'int8', 'int4'")
    premode = quantization.quantized_mode(params)
    prequantized = premode is not None
    if prequantized:
        # e.g. host-side quantization during checkpoint load
        # (weights.load_checkpoint(quantize='int8'|'int4')).
        quantize = premode
    if mesh is not None and not prequantized:
        bf16_sh = mesh_lib.tree_shardings(
            llama.param_logical_axes(cfg), mesh, shapes=params)
        params = jax.device_put(params, bf16_sh)
    if quantize is not None and not prequantized:
        # int8: the two biggest decode HBM streams each halve (weights
        # AND the auto-coupled int8 KV). int4: the weight stream halves
        # AGAIN — packed nibble codes cross HBM, dequant fused into
        # qeinsum; KV stays int8.
        params = quantization.quantize_params(params,
                                              donate=donate_params,
                                              mode=quantize)
    if mesh is not None and quantize is not None:
        # Canonicalize: quantized codes shard like their bf16 parents
        # (int4's packed axis is halved — the divisibility-aware
        # spec_for falls back to replication where a shard no longer
        # divides); per-channel/group scales follow the output axes and
        # replicate over the contracted dims.
        qaxes = quantization.quantize_logical_axes(
            llama.param_logical_axes(cfg), mode=quantize)
        params = jax.device_put(params, mesh_lib.tree_shardings(
            qaxes, mesh, shapes=params))
    return cfg, params, quantize


class _EngineBase:
    """Host-side request lifecycle shared by the slot engine (below) and
    the paged engine (``inference/paged.py``): queue, slot table,
    finish/cancel bookkeeping, the async step loop. Subclasses implement
    ``_admit()``, ``_enqueue_decode(horizon)`` and ``_process_one()``
    (the compiled paths + their lagged readback) and may override
    ``_free_slot``/``_validate_request``."""

    def _init_telemetry(self, enabled: bool = True) -> None:
        """Engine telemetry: the step-phase profiler + per-request
        traces. ``enabled`` ANDs with the process-wide kill switch
        (``SKYTPU_TELEMETRY=0``). All measurement is host-side around
        dispatches — the jaxpr audit's ``telemetry`` preset proves
        telemetry-on adds zero d2h transfers and zero compiles."""
        from skypilot_tpu.telemetry import profiler as profiler_lib
        self.telemetry_enabled = bool(enabled) and telemetry.enabled()
        self._prof = (profiler_lib.StepProfiler(
            engine=type(self).__name__) if self.telemetry_enabled
            else profiler_lib.NullProfiler())
        # KV-round-two gauges, registered AT CONSTRUCTION so both
        # series sit on the very first scrape (zeros) — the stable-
        # schema contract: dashboards never join against a series that
        # appears only after the first decode.
        self._kv_read_gauge = None
        self._attn_ms_gauges: Dict[str, Any] = {}
        if self.telemetry_enabled:
            from skypilot_tpu.telemetry import registry as registry_lib
            reg = registry_lib.get_registry()
            self._kv_read_gauge = reg.gauge(
                KV_READ_METRIC,
                'KV-cache bytes one decode substep streams from HBM '
                '(live context rows x per-token stored cost, per '
                'shard) — the bandwidth-wall numerator')
            for impl in ('per_layer', 'cross_layer'):
                self._attn_ms_gauges[impl] = reg.gauge(
                    ATTN_MS_METRIC,
                    'Host wall ms per decode substep attributed to '
                    'the attention impl serving the dispatch',
                    impl=impl)

    def _note_decode_step(self, live_tokens: int, substeps: int,
                          dt_s: float) -> None:
        """Per-dispatch attribution behind the two KV-round-two
        gauges: the HBM bytes the step's attention reads stream (live
        context rows x the same per-token cost every capacity decision
        uses) and host wall ms per device substep, labeled by the
        attention impl that served it (per_layer | cross_layer — the
        phase split the cross-layer fusion is supposed to flip). Host
        arithmetic only; nothing here touches the device."""
        if self._kv_read_gauge is None:
            return
        per_tok = kv_token_bytes(self.cfg, self.kv_cache_dtype,
                                 mesh=getattr(self, 'mesh', None))
        self._kv_read_gauge.set(live_tokens * per_tok)
        impl = ('cross_layer'
                if getattr(self, 'decode_impl', None) == 'cross_layer'
                else 'per_layer')
        self._attn_ms_gauges[impl].set(dt_s / max(1, substeps) * 1e3)

    # ------------------------------------------ cost-model boundary
    # Operand-class annotation at the decode program boundary: the
    # static cost model (analysis/costmodel.py) prices each dispatch
    # by attributing every jaxpr input to a byte stream — weights
    # (codes/scales split out for quantized trees), the KV pool, and
    # the per-call control tables. Both engines share the calling
    # convention (args[0]=params, args[1]=cache, control after), so
    # the base annotation covers them.
    def decode_operand_classes(self, args):
        from skypilot_tpu.analysis import costmodel
        return costmodel.classify_decode_args(args)

    def kv_token_capacity(self) -> int:
        """Token rows the resident KV arrays physically hold (the
        divisor that turns pool avals into stored bytes/token — the
        cost model's telemetry-comparable KV unit). The slot cache
        reserves every row up front; the paged pool overrides with
        its page count."""
        return self.max_batch * self.max_seq

    def phase_stats(self) -> Dict[str, Any]:
        """Step-phase latency decomposition + first-compile events for
        THIS engine (the bench and ``/debug`` surface)."""
        return self._prof.phase_stats()

    def _trace_finish(self, req: 'Request', **meta: Any) -> None:
        """Complete a request's trace and publish it to the process
        ring buffer (the ``/debug/requests`` surface)."""
        if req.trace is None:
            return
        req.trace.end('decode')
        req.trace.finish(output_tokens=len(req.output), **meta)
        tracing.get_trace_buffer().add(req.trace)
        req.trace = None            # publish exactly once

    def _trace_sched(self, req: 'Request') -> None:
        """Queue -> slot transition: close the queue-wait span, open
        the prefill span (re-admissions re-open both — the spans
        repeat, preserving the real timeline)."""
        if req.trace is not None:
            req.trace.end('queue')
            req.trace.begin('prefill')

    def _init_slots(self, max_batch: int) -> None:
        if not hasattr(self, '_prof'):       # engines call _init_telemetry
            self._init_telemetry(True)       # first; belt and braces
        self._slots: List[Optional[Request]] = [None] * max_batch
        # A deque, not queue.Queue: admission must be able to REQUEUE AT
        # THE HEAD (capacity backoff) without starving the request
        # behind later arrivals. Thread safety is the caller's job (the
        # serve layer serializes all engine calls under one lock).
        self._queue: 'collections.deque[Request]' = collections.deque()
        self._next_id = 0
        self._finished: Dict[int, Request] = {}
        self._slot_len = np.zeros(max_batch, np.int64)
        # Async dispatch pipeline (see step()): device calls whose
        # results have not been read back yet, oldest first. Each entry
        # is {'kind': 'prefill'|'decode', 'toks': device array, ...}.
        self._pending: 'collections.deque[dict]' = collections.deque()
        self._inflight_steps = 0     # sum of horizons of pending decodes
        self._meta_dirty = True      # slot table changed since upload
        self._meta_dev: Optional[Tuple[Any, ...]] = None
        # Device-resident current token per slot: decode call N+1 is
        # fed call N's last-token COLUMN without a host round trip (the
        # async pipeline's data path). Prefill tokens scatter in via
        # _merge_tokens.
        self._tok_dev = jnp.zeros((max_batch,), jnp.int32)
        self._merge_tokens = jax.jit(
            lambda tok, slots, vals: tok.at[slots].set(vals))
        # Multi-LoRA / grammar per-slot state: device adapter indices
        # ([b] int32, -1 = base) and vocab masks ([b, vocab] bool),
        # rebuilt with the slot-meta tuple. _vmask_any is STICKY: once
        # any grammar request is seen, decode programs keep receiving a
        # mask array (all-True for unconstrained rows) — flipping
        # None<->array changes the jit treedef, and one recompile per
        # program shape is the ceiling we accept.
        self._adp_dev: Optional[Any] = None
        self._vmask_dev: Optional[Any] = None
        self._vmask_any = False

    def _step_out_shardings(self, n_lead: int) -> Dict[str, Any]:
        """jit kwargs pinning a step program's CACHE output to the
        cache's own sharding tree (``_cache_sh``), preceded by
        ``n_lead`` unpinned outputs (tokens/commit counts — GSPMD
        infers those). This is the zero-resharding contract: every
        program that returns the cache emits it in exactly the layout
        the next program consumes it in, so chained steps never insert
        a resharding collective. Empty (no kwargs) for meshless
        engines — the single-chip path stays untouched."""
        sh = getattr(self, '_cache_sh', None)
        if sh is None:
            return {}
        out = sh if n_lead == 0 else (None,) * n_lead + (sh,)
        return {'out_shardings': out}

    def _slot_meta(self, ready: List[Optional[Request]]):
        """Device copies of the per-slot sampling params + active mask,
        rebuilt only when the slot table changed (``_meta_dirty``) —
        each host->device transfer costs a dispatch round trip, so the
        per-call rebuild the engines used to do defeated the async
        pipeline. Returns (temps, topks, topps, active, sample)."""
        if self._meta_dirty or self._meta_dev is None:
            temps = np.array([r.temperature if r else 0.0
                              for r in ready], np.float32)
            self._meta_dev = (
                jnp.asarray(temps),
                jnp.asarray([r.top_k if r else 0 for r in ready],
                            np.int32),
                jnp.asarray([r.top_p if r else 1.0 for r in ready],
                            np.float32),
                jnp.asarray(np.array([r is not None for r in ready])),
                bool((temps > 0).any()))
            if getattr(self, 'adapters', None) is not None:
                self._adp_dev = device_upload(np.array(
                    [r._adapter_slot if r is not None else -1
                     for r in ready], np.int32))
            if self._vmask_any:
                vm = np.ones((len(ready), self.cfg.vocab_size), bool)
                for i, r in enumerate(ready):
                    if r is not None and r._vocab_mask is not None:
                        vm[i] = r._vocab_mask
                self._vmask_dev = device_upload(vm)
            self._meta_dirty = False
        return self._meta_dev

    def _queue_pop(self) -> Optional[Request]:
        """Next request to admit: the FIRST queue entry of the most
        urgent (lowest) priority present — FIFO within a priority
        class, and requeue-at-front keeps its meaning for same-priority
        capacity backoff. O(n) scan; the serve scheduler keeps this
        queue at most a few entries deep (it holds its own backlog)."""
        if not self._queue:
            return None
        best_i = 0
        best_p = self._queue[0].priority
        if best_p > 0:              # a lower-priority head: scan for better
            for i, r in enumerate(self._queue):
                if r.priority < best_p:
                    best_i, best_p = i, r.priority
                    if best_p <= 0:
                        break
        if best_i == 0:
            return self._queue.popleft()
        req = self._queue[best_i]
        del self._queue[best_i]
        return req

    def _requeue_front(self, reqs: List[Request]) -> None:
        """Put not-yet-admitted requests back at the FRONT, preserving
        their original order (FIFO fairness under backpressure)."""
        self._queue.extendleft(reversed(reqs))

    # ------------------------------------------------------------- API
    def add_request(self, prompt: List[int], max_new_tokens: int = 128,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 1.0, eos_id: Optional[int] = None,
                    stop: Optional[List[List[int]]] = None,
                    priority: int = 0, hold: bool = False,
                    adapter: Optional[str] = None,
                    tenant: Optional[str] = None,
                    grammar: Any = None) -> int:
        if not prompt:
            raise ValueError('empty prompt')
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f'top_p must be in (0, 1], got {top_p}')
        if stop:
            stop = [list(s) for s in stop if s]
        self._validate_request(prompt, max_new_tokens)
        registry = getattr(self, 'adapters', None)
        if adapter is not None and registry is None:
            raise ValueError(
                f'request names adapter {adapter!r} but the engine has '
                f'no adapter bank (adapter_slots=0)')
        vocab_mask = None
        if grammar is not None:
            from skypilot_tpu.inference import adapters as adapters_lib
            vocab_mask = adapters_lib.compile_grammar(
                grammar, self.cfg.vocab_size, eos_id)
        # Pin the adapter BEFORE building the request: a bank-full /
        # unknown-adapter error must reject at admission, not mid-step.
        adapter_slot = -1
        if adapter is not None:
            adapter_slot = registry.acquire(adapter)
        req = Request(request_id=self._next_id, prompt=list(prompt),
                      max_new_tokens=max_new_tokens, temperature=temperature,
                      top_k=top_k, top_p=top_p, eos_id=eos_id,
                      stop=stop or None, priority=int(priority),
                      hold=bool(hold), adapter=adapter, tenant=tenant,
                      grammar=grammar, submit_time=clock.now())
        req._adapter_slot = adapter_slot
        req._vocab_mask = vocab_mask
        if vocab_mask is not None:
            self._vmask_any = True
            self._meta_dirty = True
        if registry is not None:
            registry.note_request(adapter)
        if self.telemetry_enabled:
            req.trace = tracing.RequestTrace(req.request_id)
            req.trace.begin('queue', prompt_tokens=len(prompt),
                            max_new_tokens=max_new_tokens)
        self._next_id += 1
        self._queue.append(req)
        return req.request_id

    def adopt_trace_context(self, request_id: int,
                            trace_id: Optional[str] = None,
                            parent_span: Optional[str] = None
                            ) -> Optional[str]:
        """Join a queued/running request to a wire-supplied trace
        context (the LB's ``X-Skytpu-Trace`` hop header). Returns the
        request's effective 128-bit trace id — locally minted when no
        wire context arrived — or None when the request is unknown or
        telemetry is off. Caller holds the engine lock (same contract
        as ``add_request``)."""
        for req in list(self._queue) + [r for r in self._slots
                                        if r is not None]:
            if req.request_id == request_id:
                if req.trace is None:
                    return None
                req.trace.adopt_wire_context(trace_id, parent_span)
                return req.trace.trace_id
        return None

    def _validate_request(self, prompt: List[int],
                          max_new_tokens: int) -> None:
        if len(prompt) + max_new_tokens > self.max_seq:
            raise ValueError(
                f'prompt ({len(prompt)}) + max_new_tokens '
                f'({max_new_tokens}) exceeds engine max_seq '
                f'({self.max_seq})')

    def has_work(self) -> bool:
        return (len(self._queue) > 0
                or any(r is not None for r in self._slots))

    def has_runnable_work(self) -> bool:
        """``has_work`` minus parked state: False when everything live
        is a HELD slot awaiting a KV handoff — stepping then does
        nothing, so the serve loop sleeps until a wake (submit /
        release_hold / drain all set it) instead of spinning."""
        if self._queue or self._pending:
            return True
        if getattr(self, '_lagging', None):
            return True
        return any(r is not None and not r.hold for r in self._slots)

    def _decode_ready(self) -> List[Optional['Request']]:
        """Per-slot request list for decode-phase programs: None for
        empty slots, mid-prefill slots, and HELD slots (a prefill-role
        handoff candidate stops after its prefill-sampled first token
        — it must not race the handoff with local decode steps)."""
        return [None if (r is None or s in self._prefill_off or r.hold)
                else r for s, r in enumerate(self._slots)]

    def release_hold(self, request_id: int) -> bool:
        """Resume local decoding of a held request (handoff failed or
        no decode worker available — the colocated fallback). True when
        a hold was actually cleared."""
        for r in list(self._queue) + [r for r in self._slots
                                      if r is not None]:
            if r.request_id == request_id and r.hold:
                r.hold = False
                self._meta_dirty = True
                return True
        return False

    # Pool-pressure recompute requeues. The slot engine reserves
    # max_seq rows per slot up front so it never preempts; the paged
    # engine overrides this with a live counter. One spelling so the
    # telemetry/bench surfaces read the same attribute off either.
    preemptions = 0

    # Requests evicted because their logits row went non-finite (the
    # device-side NaN sentinel, llama.NONFINITE_TOKEN). The serve
    # layer watches the delta to escalate repeated hits to a
    # replica-level alarm.
    nan_evictions = 0

    def _evict_nonfinite(self, slot: int,
                         req: 'Request') -> Tuple[int, int, bool]:
        """The device emitted the NaN sentinel for this request: evict
        it (free its slot, finish its trace) WITHOUT recording it as
        finished — the serve scheduler turns the sentinel event into a
        retryable per-request error, so co-batched requests continue
        untouched while this one fails over. Returns the event tuple
        the caller appends in place of a token event."""
        req.nan_evicted = True
        req.finish_time = clock.now()
        self.nan_evictions += 1
        self._release_adapter(req)
        self._trace_finish(req, nan_evicted=True)
        if 0 <= slot < len(self._slots) and self._slots[slot] is req:
            self._free_slot(slot)
        return (req.request_id, llama.NONFINITE_TOKEN, True)

    def mesh_axes(self) -> Dict[str, int]:
        """{axis: size} of this engine's mesh (all 1s when meshless) —
        the stable-schema payload behind ``skytpu_mesh_shape{axis=}``,
        the JSON ``mesh`` block, and the LB's replica view."""
        from skypilot_tpu.parallel import mesh as mesh_lib
        return mesh_lib.mesh_axis_sizes(getattr(self, 'mesh', None))

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a slot (the serve metrics surface)."""
        return len(self._queue)

    def _slot_remaining_prefill(self, slot: int) -> int:
        """Prompt tokens this slot still has to prefill (0 once
        decodable). Chunked engines override with their cursor."""
        del slot
        return 0

    def _remaining_decode(self, req: 'Request') -> int:
        """Decode tokens this request may still emit (budget- and
        capacity-clamped)."""
        ctx = len(req.prompt) + len(req.output)
        return max(0, min(req.max_new_tokens - len(req.output),
                          self.max_seq - ctx))

    def remaining_work_tokens(self) -> int:
        """Estimated TOKENS of work ahead of a new arrival: every
        queued request's full prefill+decode budget plus every live
        slot's unprefilled prompt tail and remaining decode budget.
        An upper bound (eos/stop finish early) — the serve scheduler's
        Retry-After and the queue-depth LB policy both read it, where
        overestimating by the early-stop margin only makes backoff
        slightly conservative."""
        total = 0
        for r in self._queue:
            # Recompute context (prompt+output) + decode remainder
            # telescopes to prompt + max_new_tokens.
            total += len(r.prompt) + min(r.max_new_tokens,
                                         self.max_seq - len(r.prompt))
        for slot, r in enumerate(self._slots):
            if r is None:
                continue
            total += self._slot_remaining_prefill(slot)
            total += self._remaining_decode(r)
        return total

    # Fraction of the interleaved scheduler's token budget spent on
    # decode while prompts are mid-prefill (None = engine default).
    _DEFAULT_DECODE_PRIORITY = 0.5

    def _interleave_horizon(self) -> int:
        """Decode horizon to run between prefill chunk batches, from the
        ``decode_priority_ratio`` token budget (Sarathi-style
        piggybacking): one scheduler iteration spends ``n x chunk``
        prompt tokens on the chunk batch and ``active x h`` tokens on
        decode, so ``h = r/(1-r) * chunk * n / active`` splits the
        budget r:(1-r). r -> 0 drains prefill monolithically (decode
        starves); r -> 1 starves prefill instead. The caller still caps
        by its own horizon and the ring/capacity limits."""
        r = self.decode_priority_ratio
        if r is None:
            r = self._DEFAULT_DECODE_PRIORITY
        if r >= 1.0:
            return self._HORIZON_BUCKETS[-1]
        active = self.num_active - len(self._prefill_off)
        if active <= 0:
            return 1
        n = max(1, min(len(self._prefill_off), self._prefill_n_max))
        want = r / max(1.0 - r, 1e-3) * self.chunk * n / active
        return max(1, int(want))

    # Multi-step on-device decode: when set (k >= 1), every decode
    # enqueue fuses EXACTLY k steps in one jitted call — on-device
    # sampling included — so per-call dispatch, readback lag, and
    # sampling host-syncs amortize k x. None (default) keeps the
    # caller-driven adaptive horizon. Pinning wins over the interleave
    # / queue-pressure shrinks (the knob is an explicit throughput
    # trade) but never over the capacity/ring safety caps; the jit key
    # stays static at (k, sample, bucket). Composes with
    # ``speculate_k``: when both are set the two knobs fuse into
    # in-scan speculative verify (``_spec_step_fused`` — k verify
    # rounds per dispatch); with ``decode_steps_per_call`` unset or 1,
    # speculation runs one synchronous verify round per step.
    decode_steps_per_call: Optional[int] = None

    @staticmethod
    def _validate_decode_steps(decode_steps_per_call):
        if decode_steps_per_call is None:
            return None
        k = int(decode_steps_per_call)
        if k < 1:
            raise ValueError(
                f'decode_steps_per_call must be >= 1, got {k}')
        return k

    def _pinned_horizon(self, horizon: int) -> int:
        """The fused horizon ``step()`` should run: the pinned k when
        the multi-step knob is set, else the caller's horizon."""
        return self.decode_steps_per_call or horizon

    # Depth of the async dispatch pipeline: device calls kept in flight
    # before the host reads results back. Depth 2 overlaps the per-call
    # dispatch round trip (measured ~100-600 ms through a remote PJRT
    # tunnel, ~0.1-1 ms locally) with device compute: the next decode is
    # enqueued with DEVICE-resident tokens/cache from the previous call,
    # so the host sync rides one call behind and the device never idles.
    _PIPELINE_DEPTH = 2

    def step(self, horizon: int = 1) -> List[Tuple[int, int, bool]]:
        """Admit waiting requests into free slots (prefill), enqueue up
        to ``horizon`` fused decode steps. Returns
        [(request_id, token, finished), ...] in emission order.

        Results lag enqueues by up to ``_PIPELINE_DEPTH`` calls — a
        request's tokens surface one or two step() calls after the
        device produced them; callers that need everything drained use
        run_to_completion()."""
        events: List[Tuple[int, int, bool]] = []
        # Make room in the pipeline (sync the oldest call) BEFORE
        # admitting: processing frees finished slots, so admission sees
        # the freshest slot table.
        with self._prof.phase('readback'):
            while len(self._pending) >= self._PIPELINE_DEPTH:
                events.extend(self._process_one())
        with self._prof.phase('admit'):
            events.extend(self._admit())
        with self._prof.phase('decode_enqueue'):
            enqueued = self._enqueue_decode(self._pinned_horizon(horizon))
        if not enqueued and self._pending:
            # Nothing to enqueue (no active slots, or capacity pinned
            # until in-flight calls land): drain one instead.
            with self._prof.phase('readback'):
                events.extend(self._process_one())
        return events

    def run_to_completion(self, horizon: int = 32) -> Dict[int, Request]:
        """Drive until queue + slots + in-flight calls drain. Returns
        finished requests."""
        while self.has_work() or self._pending:
            self.step(horizon)
        return dict(self._finished)

    def cancel(self, request_id: int) -> bool:
        """Abort a live request: drop it from the wait queue or free its
        decode slot so a disconnected client stops consuming capacity.
        Returns True if the request was still live (it is NOT recorded in
        the finished table). Safe no-op for finished/unknown ids."""
        dropped = [r for r in self._queue if r.request_id == request_id]
        self._queue = collections.deque(
            r for r in self._queue if r.request_id != request_id)
        if dropped:
            self._release_adapter(dropped[0])
            self._trace_finish(dropped[0], cancelled=True)
            return True
        for slot, req in enumerate(self._slots):
            if req is not None and req.request_id == request_id:
                req.finish_time = clock.now()
                self._release_adapter(req)
                self._trace_finish(req, cancelled=True)
                self._free_slot(slot)
                return True
        return False

    def export_inflight(self) -> List[Dict[str, Any]]:
        """Resubmittable snapshot of every live request (queued AND
        decoding), for the fault-tolerance layer: original prompt plus
        the tokens generated so far, so a surviving replica can
        continue from ``prompt + output`` (the prefix cache makes the
        recompute cheap) with the remaining decode budget. Greedy
        continuations are byte-identical to the uninterrupted run.
        Callers serialize engine access (the serve layer's engine
        lock), like every other host-side engine call."""
        out: List[Dict[str, Any]] = []
        live = list(self._queue) + [r for r in self._slots
                                    if r is not None]
        for req in live:
            if req.finish_time is not None:
                continue
            out.append({
                'request_id': req.request_id,
                'prompt': list(req.prompt),
                'output': list(req.output),
                'max_new_tokens': req.max_new_tokens,
                'remaining_new_tokens': max(
                    0, req.max_new_tokens - len(req.output)),
                'temperature': req.temperature,
                'top_k': req.top_k,
                'top_p': req.top_p,
                'eos_id': req.eos_id,
                'stop': ([list(s) for s in req.stop]
                         if req.stop else None),
                'priority': req.priority,
            })
        return out

    # ------------------------------------------------- disaggregation
    # KV handoff (disaggregated prefill/decode serving): a prefill
    # worker exports a live request's context rows in the cache's
    # STORED dtype (int8 codes+scales stay int8 — the wire codec never
    # dequantizes); a decode worker ingests them and resumes decoding
    # at the exact original bytes. Engine-specific gather/land live in
    # the subclasses (_gather_kv_rows / _land_kv_rows).

    def export_kv_snapshot(self, request_id: int):
        """Resumable handoff snapshot of a live DECODING request:
        (snapshot dict, drained events). The async pipeline is drained
        first so the host view (output tokens, row counts) is complete
        and the device rows are final — the drained token events are
        RETURNED, not dropped; the caller must route them to its
        consumers exactly like ``step()`` events. Returns
        ``(None, events)`` when the request is not in a decodable slot
        (finished, cancelled, still mid-prefill, or only queued)."""
        events: List[Tuple[int, int, bool]] = []
        while self._pending:
            events.extend(self._process_one())
        slot = next((s for s, r in enumerate(self._slots)
                     if r is not None and r.request_id == request_id),
                    None)
        if slot is None or slot in getattr(self, '_prefill_off', {}):
            return None, events
        req = self._slots[slot]
        if not req.output:
            return None, events        # no first token yet
        n_rows = int(self._slot_len[slot])
        if n_rows != len(req.prompt) + len(req.output) - 1:
            # Row/token bookkeeping out of sync (should not happen in
            # the greedy serving path): refuse the handoff rather than
            # ship an inconsistent snapshot.
            return None, events
        k, v, ks, vs = self._gather_kv_rows(slot, n_rows)
        cfg = self.cfg
        snapshot = {
            'kv_cache_dtype': self.kv_cache_dtype,
            'n_rows': n_rows,
            'model': {'n_layers': cfg.n_layers,
                      'n_kv_heads': cfg.n_kv_heads,
                      'head_dim': cfg.head_dim},
            'prompt': list(req.prompt),
            'output': list(req.output),
            'max_new_tokens': req.max_new_tokens,
            'temperature': req.temperature,
            'top_k': req.top_k,
            'top_p': req.top_p,
            'eos_id': req.eos_id,
            'stop': ([list(s) for s in req.stop] if req.stop else None),
            'priority': req.priority,
            'k': k, 'v': v, 'k_scale': ks, 'v_scale': vs,
        }
        return snapshot, events

    def _gather_kv_rows(self, slot: int, n_rows: int):
        """Engine-specific: the slot's first ``n_rows`` context rows as
        host numpy (k, v, k_scale|None, v_scale|None), token-major
        [L, n, hkv, d] (scales [L, n, hkv])."""
        raise NotImplementedError

    def decoding_request_ids(self) -> List[int]:
        """Request ids currently seated in decode slots (the set
        ``export_kv_snapshot`` can snapshot). Callers serialize engine
        access like every other host-side engine call."""
        return [r.request_id for r in self._slots if r is not None]

    # ------------------------------------------------- gang lockstep
    # Multi-host gang serving (serve/gang.py): rank 0 records every
    # engine mutation (add/step/cancel/flush/warmup) to an op log and
    # nonzero ranks replay it verbatim, so every process executes the
    # same jitted steps in the same order on its mesh shard. These two
    # entries are the follower side of that contract.

    def drain_pipeline(self) -> List[Tuple[int, int, bool]]:
        """Flush the async dispatch pipeline completely; returns the
        drained events (callers route them exactly like ``step()``
        events). The gang ``flush`` op: rank 0 drains before a
        checkpoint/handoff export and followers mirror it, so every
        rank's pipeline depth — and therefore its subsequent event
        stream — stays aligned."""
        events: List[Tuple[int, int, bool]] = []
        while self._pending:
            events.extend(self._process_one())
        return events

    def follower_step(self, horizon: int = 1, *,
                      prepared: bool = False
                      ) -> List[Tuple[int, int, bool]]:
        """Gang-follower step entry: execute exactly the step rank 0
        recorded — the same proposer preparation, the same fused
        horizon — and return the step's events for finished-request
        digest verification (the caller reaps finished requests; no
        scheduler runs on followers)."""
        if prepared and getattr(self, 'speculate_k', 0):
            self.prepare_proposals()
        return self.step(horizon=horizon)

    # ---------------------------------------------- prefix checkpoint
    # Spot resilience: on a preemption warning the serve layer
    # checkpoints the engine's hottest prefix-cache page chains (plus
    # in-flight request snapshots) through the SKKV/SKPF wire codec,
    # and a replacement replica lands them via warm_prefix BEFORE it
    # enters LB rotation — post-recovery TTFT is near-warm instead of
    # cold. The slot engine has no prefix cache, so the base
    # implementations are honest no-ops; the paged engine overrides
    # both.

    def export_prefix_snapshots(self, max_entries: int = 8):
        """Hottest prefix-cache page chains as prefix entries
        (``kv_transfer.encode_prefix_chain`` input dicts), plus any
        events drained from the async pipeline (routed by the caller
        exactly like ``step()`` events). Base: no prefix cache —
        ``([], [])``."""
        del max_entries
        return [], []

    def warm_prefix(self, entry: Dict[str, Any]) -> int:
        """Land a prefix entry (or a request snapshot viewed as one)
        into the prefix cache WITHOUT seating a request; returns the
        number of KV rows landed. Base: no prefix cache — 0 rows (the
        warmup endpoint reports it; callers must not treat 0 as an
        error)."""
        del entry
        return 0

    def hot_prefix_digest(self, max_entries: int = 16):
        """Bounded (chain-hash, token-length, hits) digest of the
        hottest cached prefix chains, for the LB's prefix-affinity
        routing. Host-side state only — the probe path ships it on
        every /metrics scrape, so it must never touch the device.
        Base: no prefix cache — empty."""
        del max_entries
        return []

    def export_prefix_entry(self, hash_hex: str):
        """One digest-named hot chain as ``(entry_or_None, events)``
        — the proactive affinity-migration export. Base: no prefix
        cache — ``(None, [])``."""
        del hash_hex
        return None, []

    def _validate_kv_entry(self, entry: Dict[str, Any],
                           n_rows: int) -> None:
        """Shared KV-payload validation for ingest/warmup: model
        shape, kv dtype (no transcoding) and row-array shapes. Raises
        ``ValueError`` (permanent refusal)."""
        cfg = self.cfg
        model = entry.get('model') or {}
        for key, want in (('n_layers', cfg.n_layers),
                          ('n_kv_heads', cfg.n_kv_heads),
                          ('head_dim', cfg.head_dim)):
            if int(model.get(key, -1)) != want:
                raise ValueError(
                    f'handoff model mismatch: {key}='
                    f'{model.get(key)} != engine {want}')
        if entry.get('kv_cache_dtype') != self.kv_cache_dtype:
            raise ValueError(
                'handoff kv_cache_dtype '
                f'{entry.get("kv_cache_dtype")!r} != engine '
                f'{self.kv_cache_dtype!r} (no wire transcoding: '
                'quantized KV must land in a same-dtype pool)')
        # int4 rows travel PACKED: two nibble codes per byte along
        # head_dim (uint8, head_dim/2) — exactly the resident layout.
        row_d = (cfg.head_dim // 2 if self.kv_cache_dtype == 'int4'
                 else cfg.head_dim)
        for arr, name in ((entry['k'], 'k'), (entry['v'], 'v')):
            shape = tuple(np.shape(arr))
            want_shape = (cfg.n_layers, n_rows, cfg.n_kv_heads, row_d)
            if shape != want_shape:
                raise ValueError(f'handoff {name} rows shape {shape} '
                                 f'!= {want_shape}')
        if self.kv_cache_dtype in ('int8', 'int4'):
            for arr, name in ((entry['k_scale'], 'k_scale'),
                              (entry['v_scale'], 'v_scale')):
                shape = tuple(np.shape(arr))
                if shape != (cfg.n_layers, n_rows, cfg.n_kv_heads):
                    raise ValueError(
                        f'handoff {name} shape {shape} != '
                        f'{(cfg.n_layers, n_rows, cfg.n_kv_heads)}')
            want_np = (np.uint8 if self.kv_cache_dtype == 'int4'
                       else np.int8)
            for arr, name in ((entry['k'], 'k'), (entry['v'], 'v')):
                if np.dtype(getattr(arr, 'dtype', None)) != want_np:
                    raise ValueError(
                        f'handoff {name} codes are '
                        f'{getattr(arr, "dtype", None)}, expected '
                        f'{np.dtype(want_np).name} (quantized KV '
                        'never widens on the wire)')

    def _validate_ingest(self, snap: Dict[str, Any]) -> None:
        """Shared ingest validation: model shape, kv dtype (no
        transcoding — int8 stays int8 end to end), row-count
        consistency, and the engine's own request limits. Raises
        ``ValueError`` (permanent refusal)."""
        prompt, output = snap['prompt'], snap['output']
        if not output:
            raise ValueError('handoff carries no generated token')
        n_rows = int(snap['n_rows'])
        if n_rows != len(prompt) + len(output) - 1:
            raise ValueError(
                f'handoff n_rows {n_rows} != context rows '
                f'{len(prompt) + len(output) - 1}')
        if len(output) >= int(snap['max_new_tokens']):
            raise ValueError('handoff request is already complete')
        self._validate_request(prompt, int(snap['max_new_tokens']))
        self._validate_kv_entry(snap, n_rows)

    def _ingest_request(self, snap: Dict[str, Any]) -> Request:
        """Recreate the engine Request a handoff snapshot describes
        (output prepopulated; finish checks then behave exactly as if
        the tokens had been generated here)."""
        req = Request(
            request_id=self._next_id, prompt=list(snap['prompt']),
            max_new_tokens=int(snap['max_new_tokens']),
            temperature=float(snap.get('temperature') or 0.0),
            top_k=int(snap.get('top_k') or 0),
            top_p=float(snap.get('top_p') or 1.0),
            eos_id=snap.get('eos_id'),
            stop=([list(s) for s in snap['stop']]
                  if snap.get('stop') else None),
            priority=int(snap.get('priority') or 0),
            output=list(snap['output']),
            submit_time=clock.now())
        # The first token happened on the prefill worker; set the
        # timestamp so per-token bookkeeping (and the slot engine's
        # readback guard) treats the slot as live. The serve layer
        # skips TTFT observation for handoff continuations.
        req.first_token_time = req.submit_time
        req._enq_out = len(req.output)
        if self.telemetry_enabled:
            # A handoff continuation JOINS the fleet-wide trace the
            # prefill worker started (the /kv/ingest hop carries
            # X-Skytpu-Trace; the server parks it in snap['trace']).
            ctx = snap.get('trace') or {}
            req.trace = tracing.RequestTrace(
                self._next_id, trace_id=ctx.get('trace_id'),
                parent_span=ctx.get('parent_span'))
            req.trace.begin('decode', handoff=True,
                            context_tokens=len(req.prompt)
                            + len(req.output))
        self._next_id += 1
        return req

    def ingest_kv_snapshot(self, snap: Dict[str, Any]) -> int:
        """Land a handoff: validate, seat the request in a free slot
        with its KV rows written at the exact original bytes, and
        return the new request id. Raises ``ValueError`` for
        malformed/mismatched handoffs (permanent) and
        :class:`HandoffCapacityError` when no slot or KV capacity is
        free (retryable — the router picks another decode worker)."""
        self._validate_ingest(snap)
        slot = next((s for s in range(self.max_batch)
                     if self._slots[s] is None), None)
        if slot is None:
            raise HandoffCapacityError('no free decode slot')
        req = self._ingest_request(snap)
        self._land_kv_rows(slot, req, snap)
        ctx = req.prompt + req.output
        self._slots[slot] = req
        self._slot_len[slot] = int(snap['n_rows'])
        # Current token = the last generated one; decode resumes on
        # the very next horizon without a host round trip.
        slot_d, tok_d = device_upload(
            (np.array([slot], np.int32),
             np.array([ctx[-1]], np.int32)))
        self._tok_dev = self._merge_tokens_drop(self._tok_dev, slot_d,
                                                tok_d)
        self._meta_dirty = True
        return req.request_id

    def _land_kv_rows(self, slot: int, req: Request,
                      snap: Dict[str, Any]) -> None:
        """Engine-specific: write the snapshot's rows into this slot's
        cache storage (raises ``HandoffCapacityError`` on pool
        pressure)."""
        raise NotImplementedError

    def get_finished(self, request_id: int) -> Optional[Request]:
        return self._finished.get(request_id)

    def pop_finished(self, request_id: int) -> Optional[Request]:
        """Consume a finished request, evicting it from the finished
        table. Long-lived servers MUST use this (or evict otherwise):
        the table grows without bound under steady traffic."""
        return self._finished.pop(request_id, None)

    # -------------------------------------------------------- internals
    def _free_slot(self, slot: int) -> None:
        self._slots[slot] = None
        self._slot_len[slot] = 0
        self._meta_dirty = True      # async engines re-upload slot meta

    def _maybe_finish(self, slot: int, token: int) -> bool:
        return self._finish_req(slot, self._slots[slot], token)

    def _finish_req(self, slot: int, req, token: int) -> bool:
        """Request-scoped finish check. Distinct from _maybe_finish so
        the paged engine's EARLY-RECYCLED tenancies (slot already freed
        or re-assigned, tail tokens still surfacing through the async
        pipeline) can finish their request without touching whoever
        holds the slot now — it is only freed when ``req`` still owns
        it."""
        # Stop sequences first: a stop completing exactly on the
        # max_new_tokens/max_seq boundary must still be trimmed.
        done = False
        if req.stop:
            for seq in req.stop:
                if (len(req.output) >= len(seq)
                        and req.output[-len(seq):] == seq):
                    del req.output[-len(seq):]
                    req.stop_hit = True
                    done = True
                    break
        done = (done or len(req.output) >= req.max_new_tokens
                or (req.eos_id is not None and token == req.eos_id)
                or len(req.prompt) + len(req.output) >= self.max_seq)
        if done:
            req.finish_time = clock.now()
            self._finished[req.request_id] = req
            self._release_adapter(req)
            self._trace_finish(req, stop_hit=req.stop_hit)
            if self._slots[slot] is req:
                self._free_slot(slot)
        return done

    def _release_adapter(self, req) -> None:
        """Drop this request's adapter-bank pin, exactly once per
        request lifetime (finish, cancel, and NaN eviction all call
        this; the flag makes overlapping exit paths safe)."""
        if req.adapter is None or req._adapter_released:
            return
        req._adapter_released = True
        registry = getattr(self, 'adapters', None)
        if registry is not None:
            registry.release(req.adapter)


def _slot_spec_verify(params, big_cache, tokens, proposals, n_prop,
                      temps, topks, topps, active, rng, *, cfg,
                      attn_impl, kv_bucket, max_seq, k, sample,
                      mlora_idx=None, vocab_mask=None):
    """One speculative verify round over the slot cache — the traced
    body shared by the single-round jit (``_get_spec_verify``) and the
    fused in-scan rounds (``_get_spec_fused``): one forward over the
    k+1 positions [t0, d1..dk] per slot, device acceptance, and a
    MASKED sentinel scatter of the accepted rows. Returns
    ``(commit, n_commit, new_tok, new_cache)``."""
    from skypilot_tpu.inference import speculative
    b = tokens.shape[0]
    len0 = big_cache.length
    # Length-aware cache read, same policy as decode_horizon: slice
    # only when it at least halves the stream (the sliced prefix
    # materializes as a program temp).
    ck = big_cache.k[:, :, :kv_bucket]
    cv = big_cache.v[:, :, :kv_bucket]
    if big_cache.quantized:
        cache_kv = (ck, cv, big_cache.k_scale[:, :, :kv_bucket],
                    big_cache.v_scale[:, :, :kv_bucket])
    else:
        cache_kv = (ck, cv)
    seq = jnp.concatenate([tokens[:, None], proposals], axis=1)
    logits, rows = llama.prefill_rows(
        params, seq, jnp.full((b,), k + 1, jnp.int32), cfg,
        attn_impl=attn_impl,
        quantize_rows=('int4' if big_cache.packed
                       else big_cache.quantized),
        cache_kv=cache_kv, cache_len=len0, all_logits=True,
        mlora_idx=mlora_idx)
    # Grammar masks constrain verification too — the [n, k+1, vocab]
    # logits mask broadcasts over the k+1 verify positions, so a
    # proposal outside the grammar is rejected exactly like any other
    # mismatching draft.
    logits = llama.apply_vocab_mask(logits, vocab_mask)
    commit, n_commit = speculative.verify_tokens(
        logits, proposals, n_prop, rng, temps, topks, topps,
        sample=sample)
    n_commit = jnp.where(active, n_commit, 0)
    # Masked commit: rows past each slot's accepted count (and every
    # row of inactive slots) scatter to the max_seq sentinel and drop.
    pos = len0[:, None] + jnp.arange(k + 1)[None, :]
    pos = jnp.where(jnp.arange(k + 1)[None, :]
                    < n_commit[:, None], pos, max_seq)
    slots = jnp.arange(b)
    length = len0 + n_commit

    def scatter(c, r):
        return c.at[:, slots[:, None], pos].set(
            r.astype(c.dtype), mode='drop')

    if big_cache.quantized:
        kq, vq, ks, vs = rows
        new_cache = llama.KVCache(
            k=scatter(big_cache.k, kq),
            v=scatter(big_cache.v, vq), length=length,
            k_scale=scatter(big_cache.k_scale, ks),
            v_scale=scatter(big_cache.v_scale, vs))
    else:
        k_rows, v_rows = rows
        new_cache = llama.KVCache(
            k=scatter(big_cache.k, k_rows),
            v=scatter(big_cache.v, v_rows), length=length)
    # Next round's t0 = the last committed token per slot.
    nxt = jnp.take_along_axis(
        commit, jnp.maximum(n_commit - 1, 0)[:, None],
        axis=1)[:, 0]
    new_tok = jnp.where(active, nxt, tokens)
    return commit, n_commit, new_tok, new_cache


class InferenceEngine(SpeculativeMixin, _EngineBase):
    """Slot-cache engine core: callers drive ``step()``; the serve layer
    wraps it in an HTTP loop. Decode/prefill calls dispatch through the
    async pipeline (``_EngineBase.step``): results are read back one
    call behind the enqueue, so per-call dispatch latency overlaps
    device compute and short fused horizons stop paying a round trip
    each. ``speculate_k > 0`` switches decode to the speculative
    propose→verify→commit loop (``inference/speculative.py``): up to
    k+1 tokens per slot per weight-stream pass."""

    def __init__(self, cfg: ModelConfig, params: Optional[Any] = None,
                 *, max_batch: int = 8, max_seq: int = 1024,
                 mesh: Optional[Any] = None, rng_seed: int = 0,
                 attn_impl: str = 'auto',
                 quantize: Optional[str] = None,
                 kv_cache_dtype: Optional[str] = None,
                 donate_params: bool = False,
                 prefill_w8a8: bool = False,
                 prefill_chunk_tokens: Optional[int] = 256,
                 decode_priority_ratio: Optional[float] = None,
                 decode_steps_per_call: Optional[int] = None,
                 speculate_k: int = 0,
                 adapter_slots: int = 0,
                 adapter_dir: Optional[str] = None,
                 adapter_rank: int = 8,
                 adapter_targets: Optional[Any] = None,
                 telemetry: bool = True):
        self._init_telemetry(telemetry)
        self.max_batch = max_batch
        self.max_seq = max_seq
        self.mesh = mesh
        self.attn_impl = attn_impl
        # Multi-step on-device decode (see _EngineBase): pin every
        # decode call at exactly k fused steps.
        self.decode_steps_per_call = self._validate_decode_steps(
            decode_steps_per_call)
        # Opt-in: quantize prefill activations to int8 (2x MXU rate on
        # the compute-bound prefill; decode unaffected). Off by default
        # — W8A8 adds activation quantization noise to the KV rows.
        self.prefill_w8a8 = prefill_w8a8
        # Chunked prefill (on by default): prompts prefill in
        # ``prefill_chunk_tokens``-sized chunks interleaved with decode
        # horizons, bounding how long running requests stall behind a
        # long prompt (the monolithic admit measured 5.5 s median burst
        # TTFT — head-of-line blocking, BENCH_r05). 0/None falls back
        # to monolithic whole-prompt admission waves (bench baseline).
        # ``decode_priority_ratio`` splits the interleaved token budget
        # (see _EngineBase._interleave_horizon); None = 0.5.
        chunk = prefill_chunk_tokens or 0
        self.chunk = _bucket_len(chunk, minimum=8) if chunk else 0
        self.chunked = self.chunk > 0
        self.decode_priority_ratio = decode_priority_ratio
        self._rng = jax.random.PRNGKey(rng_seed)

        cfg, self.params, quantize = prepare_params(
            cfg, params, quantize=quantize, mesh=mesh,
            donate_params=donate_params)
        self.cfg = cfg
        # Actual PER-DEVICE stored parameter bytes (int8 leaves count
        # 1B/elem; sharded leaves count their local shard) — sizes the
        # decode-horizon ring cap against the true per-chip weight
        # stream: under tp both the weight stream and the ring rows
        # split, so the cap stays put instead of drifting with mesh
        # shape.
        from skypilot_tpu.models import quantization
        self._param_bytes = quantization.per_device_bytes(self.params)

        # KV storage dtype is its OWN knob (decoupled from the weight
        # quantize mode; None follows it for backward compatibility):
        # the cache's quantized flag drives every downstream write site
        # (prefill scatter, chunk prefill, spec verify, decode merge)
        # and the fused-dequant attention reads.
        self.kv_cache_dtype = resolve_kv_cache_dtype(kv_cache_dtype,
                                                     quantize)
        self.cache = llama.KVCache.create(
            cfg, batch=max_batch, max_seq=max_seq,
            kv_dtype=self.kv_cache_dtype)
        # Pre-partitioned cache + pinned output shardings: the cache is
        # device_put ONCE with its logical-axis shardings, and every
        # jitted step that returns it pins the SAME tree as its
        # out_shardings — each program's output layout IS the next
        # program's input layout (the pjit in/out_axis_resources-
        # matching discipline), so steady state never inserts a
        # resharding collective between steps. None (meshless) skips
        # the machinery entirely.
        self._cache_sh = None
        if mesh is not None:
            self._cache_sh = mesh_lib.tree_shardings(
                llama.cache_logical_axes(quantized=self.cache.quantized),
                mesh, shapes=self.cache)
            self.cache = jax.device_put(self.cache, self._cache_sh)

        # slot bookkeeping (host side); device cache.length is
        # authoritative for attention masking.
        self._init_slots(max_batch)
        # Multi-tenant adapter bank (adapter_slots > 0): installs the
        # stacked multi-LoRA bank into params['layers']['mlora'] BEFORE
        # the decode programs trace, so every program below carries the
        # batched gather matmul. adapter_slots=0 leaves the params tree
        # — and every traced program — byte-identical to before.
        self.adapters = None
        if adapter_slots > 0:
            from skypilot_tpu.inference import adapters as adapters_lib
            self.adapters = adapters_lib.AdapterRegistry(
                self, slots=adapter_slots, rank=adapter_rank,
                adapter_dir=adapter_dir, targets=adapter_targets)
        self._decode_fn = self._build_decode()
        self._prefill_fns: Dict[int, Any] = {}
        # Chunked-prefill scheduler state: slot -> prompt tokens
        # prefilled so far. A slot in this dict is assigned but not yet
        # decodable; the scheduling loop interleaves its remaining
        # chunks with decode horizons.
        self._prefill_off: Dict[int, int] = {}
        self._chunk_prefill_fns: Dict[Tuple, Any] = {}
        # Max mid-prefill slots per chunk batch (padded to a compiled
        # n bucket); the per-call stacked-rows budget shrinks it
        # further when the gathered-cache bucket is wide.
        self._prefill_n_max = self._PREFILL_N_BUCKETS[-1]
        # Fixed-shape first-token merge (completing chunk rows):
        # padding entries scatter to the out-of-range sentinel
        # max_batch and are dropped.
        self._merge_tokens_drop = jax.jit(
            lambda tok, slots, vals: tok.at[slots].set(vals,
                                                       mode='drop'))
        # KV handoff programs (disaggregated serving): export gathers
        # keyed by context bucket, ingest scatters keyed by row bucket.
        self._export_fns: Dict[int, Any] = {}
        self._ingest_fns: Dict[int, Any] = {}
        # Speculative decoding (0 = off): n-gram propose + batched
        # verify instead of the fused decode horizon.
        self._init_spec(speculate_k)

    @classmethod
    def from_pretrained(cls, path: str, *, dtype: Any = None,
                        **kwargs) -> 'InferenceEngine':
        """Build an engine from an HF checkpoint directory
        (``config.json`` + safetensors; see ``models/weights.py``).
        Pass ``quantize='int8'`` for int8 serving (weights AND KV
        cache)."""
        import jax.numpy as jnp
        from skypilot_tpu.models import weights
        # Quantize host-side during load: only int8 codes + scales ever
        # reach the device (a 7B bf16 tree would not leave room on a
        # 16 GB chip for the quantization pass).
        cfg, params = weights.load_checkpoint(
            path, dtype=dtype if dtype is not None else jnp.bfloat16,
            quantize=kwargs.get('quantize'))
        # The freshly loaded tree has no other owner: let quantization
        # free bf16 buffers in place if it ever runs on-device.
        kwargs.setdefault('donate_params', True)
        return cls(cfg, params, **kwargs)

    def kv_pool_stats(self) -> Dict[str, Any]:
        """KV capacity/pressure in TOKENS — the schema the telemetry
        gauges and bench share with the paged engine. The slot cache's
        capacity is the static ``max_batch x max_seq`` reservation;
        "used" counts live context rows, and preemptions are always 0
        (every admitted request owns its full reservation)."""
        cap = self.max_batch * self.max_seq
        used = int(self._slot_len.sum())
        return {
            'kv_cache_dtype': self.kv_cache_dtype,
            'pool_token_capacity': cap,
            'tokens_used': used,
            'tokens_free': cap - used,
            'preemptions': int(self.preemptions),
            'kv_token_bytes': kv_token_bytes(self.cfg,
                                             self.kv_cache_dtype),
            # Bytes ONE device stores per token (kv heads shard over
            # tp) — the per-shard HBM view; token counts above stay
            # GLOBAL (a token is a token however many chips hold it).
            'kv_token_bytes_per_shard': kv_token_bytes(
                self.cfg, self.kv_cache_dtype, mesh=self.mesh),
            'kv_shards': kv_shard_degree(self.cfg, self.mesh),
        }

    # -------------------------------------------------- KV handoff
    def _get_export(self, bucket: int):
        """Compiled context-row gather for one slot (handoff export):
        [L, bucket, hkv, d] rows (+ scales) straight off the slot
        cache, in the STORED dtype — int8 codes and fp32 scales come
        out exactly as resident, never dequantized."""
        if bucket in self._export_fns:
            return self._export_fns[bucket]
        quantized = self.cache.quantized

        @jax.jit
        def export(cache, slot):
            k = cache.k[:, slot, :bucket]
            v = cache.v[:, slot, :bucket]
            if quantized:
                return (k, v, cache.k_scale[:, slot, :bucket],
                        cache.v_scale[:, slot, :bucket])
            return k, v

        self._export_fns[bucket] = export
        return export

    def _gather_kv_rows(self, slot: int, n_rows: int):
        bucket = min(_bucket_len(max(1, n_rows)), self.max_seq)
        slot_d = device_upload(np.array(slot, np.int32))
        out = self._get_export(bucket)(self.cache, slot_d)
        # Sanctioned d2h: the handoff export IS a host readback by
        # design (the rows leave this process on the wire).
        host = host_sync(out)
        if self.cache.quantized:
            k, v, ks, vs = host
            return (k[:, :n_rows], v[:, :n_rows],
                    ks[:, :n_rows, :, 0], vs[:, :n_rows, :, 0])
        k, v = host
        return k[:, :n_rows], v[:, :n_rows], None, None

    def _get_ingest(self, nb: int):
        """Compiled handoff scatter: land [L, 1, nb, hkv, d] rows (+
        scales) into one slot's reservation at positions [0, valid),
        padding rows dropping at the max_seq sentinel."""
        if nb in self._ingest_fns:
            return self._ingest_fns[nb]
        quantized = self.cache.quantized
        max_seq = self.max_seq

        def _scatter(c, r, slots_arr, pos):
            return c.at[:, slots_arr[:, None], pos].set(
                r.astype(c.dtype), mode='drop')

        if quantized:
            @functools.partial(jax.jit, donate_argnums=(0,),
                               **self._step_out_shardings(0))
            def ingest(cache, kq, ks, vq, vs, slots_arr, valid):
                pos = jnp.arange(nb)[None, :]
                pos = jnp.where(pos < valid[:, None], pos, max_seq)
                length = cache.length.at[slots_arr].set(valid,
                                                        mode='drop')
                return llama.KVCache(
                    k=_scatter(cache.k, kq, slots_arr, pos),
                    v=_scatter(cache.v, vq, slots_arr, pos),
                    length=length,
                    k_scale=_scatter(cache.k_scale, ks, slots_arr, pos),
                    v_scale=_scatter(cache.v_scale, vs, slots_arr, pos))
        else:
            @functools.partial(jax.jit, donate_argnums=(0,),
                               **self._step_out_shardings(0))
            def ingest(cache, kr, vr, slots_arr, valid):
                pos = jnp.arange(nb)[None, :]
                pos = jnp.where(pos < valid[:, None], pos, max_seq)
                length = cache.length.at[slots_arr].set(valid,
                                                        mode='drop')
                return llama.KVCache(
                    k=_scatter(cache.k, kr, slots_arr, pos),
                    v=_scatter(cache.v, vr, slots_arr, pos),
                    length=length)

        self._ingest_fns[nb] = ingest
        return ingest

    def _land_kv_rows(self, slot: int, req: Request,
                      snap: Dict[str, Any]) -> None:
        cfg = self.cfg
        n_rows = int(snap['n_rows'])
        nb = min(_bucket_len(max(1, n_rows)), self.max_seq)

        def pad(arr, tail):
            out = np.zeros((cfg.n_layers, 1, nb, cfg.n_kv_heads)
                           + tail, dtype=arr.dtype)
            out[:, 0, :n_rows] = arr.reshape(
                (cfg.n_layers, n_rows, cfg.n_kv_heads) + tail)
            return out

        slots_arr = np.array([slot], np.int32)
        valid = np.array([n_rows], np.int32)
        ingest = self._get_ingest(nb)
        code_d = (cfg.head_dim // 2 if self.cache.packed
                  else cfg.head_dim)
        if self.cache.quantized:
            (kq, ks, vq, vs, slots_d, valid_d) = device_upload(
                (pad(snap['k'], (code_d,)),
                 pad(snap['k_scale'], (1,)),
                 pad(snap['v'], (code_d,)),
                 pad(snap['v_scale'], (1,)), slots_arr, valid))
            self.cache = ingest(self.cache, kq, ks, vq, vs, slots_d,
                                valid_d)
        else:
            kr, vr, slots_d, valid_d = device_upload(
                (pad(snap['k'], (cfg.head_dim,)),
                 pad(snap['v'], (cfg.head_dim,)), slots_arr, valid))
            self.cache = ingest(self.cache, kr, vr, slots_d, valid_d)

    # ------------------------------------------------------------------
    # Compiled steps
    # ------------------------------------------------------------------
    def _build_decode(self):
        """Multi-step decode: ``horizon`` steps fused into one program per
        host sync (llama.decode_horizon's ring-buffer loop). Decode through
        the PJRT tunnel costs ~100ms per host round trip; fusing N steps
        amortizes it, the same trick a production engine uses to hide
        dispatch latency. ``sample`` is STATIC: the all-greedy program
        skips the top-k/temperature machinery entirely (a full-vocab sort
        per step otherwise)."""
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(1,),
                           static_argnames=('horizon', 'sample',
                                            'kv_bucket'),
                           **self._step_out_shardings(1))
        def decode_steps(params, cache, tokens, rng, temps, topks, topps,
                         active, adp, vmask, horizon, sample, kv_bucket):
            if sample:
                def sample_fn(logits, step_rng):
                    return sample_tokens(logits, step_rng, temps, topks,
                                         topps)
                rngs = jax.random.split(rng, horizon)
            else:
                sample_fn, rngs = None, None
            toks, cache = llama.decode_horizon(
                params, cache, tokens, cfg, horizon=horizon,
                sample_fn=sample_fn, rngs=rngs, kv_bucket=kv_bucket,
                mlora_idx=adp, vocab_mask=vmask)
            # inactive slots don't advance their cache length
            new_len = jnp.where(active, cache.length,
                                cache.length - horizon)
            cache = cache._replace(length=new_len)
            return toks, cache                        # [slots, horizon]

        return decode_steps

    def _get_prefill(self, bucket: int, n: int):
        """Batched prefill: n prompts (padded to one bucket) in one device
        call that computes KV, scatters it into the requested slots of the
        big cache, and returns the first sampled token per prompt. One host
        round trip per admit cycle instead of three per request.

        Rides ``llama.prefill_rows``: plain causal attention over the
        bucket (flash kernel on TPU — the old forward-with-scratch-cache
        path read a bucket of zero cache rows per layer and never hit
        flash), rows quantized inside the layer scan for int8 caches
        (halves the stacked-rows transient -> doubles the admission
        wave), and last-position-only unembed."""
        key = (bucket, n)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        cfg, attn_impl = self.cfg, self.attn_impl
        w8a8 = self.prefill_w8a8

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._step_out_shardings(1))
        def prefill(params, big_cache, tokens, true_lens, slots,
                    adp, vmask):
            """tokens [n, bucket]; true_lens [n]; slots [n] target rows."""
            last, rows = llama.prefill_rows(
                params, tokens, true_lens, cfg, attn_impl=attn_impl,
                quantize_rows=('int4' if big_cache.packed
                               else big_cache.quantized), w8a8=w8a8,
                mlora_idx=adp)
            last = llama.apply_vocab_mask(last, vmask)
            next_tokens = llama.mask_nonfinite_tokens(
                last, jnp.argmax(last, -1).astype(jnp.int32))
            # Scatter KV rows + lengths into the slot cache.
            length = big_cache.length.at[slots].set(true_lens)
            if big_cache.quantized:
                kq, vq, ks, vs = rows
                return next_tokens, llama.KVCache(
                    k=big_cache.k.at[:, slots, :bucket].set(kq),
                    v=big_cache.v.at[:, slots, :bucket].set(vq),
                    length=length,
                    k_scale=big_cache.k_scale.at[:, slots, :bucket].set(ks),
                    v_scale=big_cache.v_scale.at[:, slots, :bucket].set(vs))
            k_rows, v_rows = rows
            ck = big_cache.k.at[:, slots, :bucket].set(
                k_rows.astype(big_cache.k.dtype))
            cv = big_cache.v.at[:, slots, :bucket].set(
                v_rows.astype(big_cache.v.dtype))
            return next_tokens, llama.KVCache(k=ck, v=cv, length=length)

        self._prefill_fns[key] = prefill
        return prefill

    # ------------------------------------------------------------------
    _PREFILL_N_BUCKETS = (1, 2, 4, 8, 16, 32)

    # Under saturation, admissions batch into waves of at least this
    # many slots: a prefill call's cost is dominated by its fixed part
    # at small n (measured 7B: n=2 ~120 ms vs n=8 ~260 ms — 60 vs 32 ms
    # per request), so admitting every freed slot immediately spends
    # ~2x the device time on prefill for the same arrivals.
    _ADMIT_WAVE_MIN = 4

    def _admit(self) -> List[Tuple[int, int, bool]]:
        """Admission dispatch. Chunked (default): assign free slots
        immediately and run at most ONE prefill chunk batch before
        decode resumes — the scheduling loop (``step``) interleaves the
        remaining chunks with decode horizons. Monolithic
        (``prefill_chunk_tokens=0``): the historical whole-prompt
        admission wave. Both ALWAYS return [] — prefill results ride
        the async pipeline and their first-token events surface in
        ``_process_one`` up to ``_PIPELINE_DEPTH`` calls later."""
        if not self.chunked:
            return self._admit_monolithic()
        self._assign_slots()
        events = self._prefill_chunk_batch()
        # Burst exception (mirrors the paged engine): while the
        # DECODING population is under a quarter of the batch (cold
        # start / arrival burst), the one-chunk-per-step TPOT bound
        # protects almost nobody — run chunk batches back to back so
        # the first slots start decoding sooner.
        while (self._prefill_off
               and self.num_active - len(self._prefill_off)
               < self.max_batch // 4):
            events += self._prefill_chunk_batch()
        return events

    def _assign_slots(self) -> None:
        """Reserve free slots for queued requests with a zero prefill
        cursor; chunks stream in via _prefill_chunk_batch."""
        for slot in range(self.max_batch):
            if self._slots[slot] is not None:
                continue
            req = self._queue_pop()
            if req is None:
                return
            self._slots[slot] = req
            self._slot_len[slot] = 0
            self._prefill_off[slot] = 0
            self._trace_sched(req)

    def _free_slot(self, slot: int) -> None:
        self._prefill_off.pop(slot, None)      # cancel mid-prefill
        super()._free_slot(slot)

    def _slot_remaining_prefill(self, slot: int) -> int:
        off = self._prefill_off.get(slot)
        if off is None:
            return 0
        return max(0, len(self._slots[slot].prompt) - off)

    def _prefill_chunk_batch(self) -> List[Tuple[int, int, bool]]:
        """One fixed-size prefill chunk across up to a compiled
        n-bucket of mid-prefill slots, attending the slots' EXISTING
        cache rows (nonzero cache offset) and scattering the new rows
        at each slot's cursor. Completing rows sample their first token
        ON DEVICE (per-request params) and merge it into the device
        token vector before this returns, so they decode on the very
        next horizon; the first-token EVENT surfaces via _process_one.
        ALWAYS returns []."""
        pending = sorted(self._prefill_off)
        if not pending:
            return []
        # Per-DEVICE token cost: the stacked chunk transient shards
        # its kv-head dim over tp, so a tp=2 engine admits twice the
        # wave within the same per-chip scratch budget.
        scratch_tok = kv_token_bytes(self.cfg, self.kv_cache_dtype,
                                     mesh=self.mesh)

        def shapes(batch):
            # Chunk width: the full chunk, or a smaller bucket when
            # every pending piece is short (prompt tails) — bounded
            # compiled-program count, half/quarter the FLOPs.
            rest_max = max(len(self._slots[s].prompt)
                           - self._prefill_off[s] for s in batch)
            chunk_w = min(self.chunk,
                          _bucket_len(rest_max,
                                      minimum=min(64, self.chunk)))
            # Cache-read bucket: covers every batch row's cursor (rows
            # past each cursor are masked); 0 when no row has context
            # yet — that variant runs plain causal attention
            # (flash-eligible), exactly the monolithic first-chunk
            # math.
            start_max = int(max(self._slot_len[s] for s in batch))
            kv_bucket = (0 if start_max == 0
                         else min(_bucket_len(start_max), self.max_seq))
            return chunk_w, kv_bucket

        batch = pending[:self._prefill_n_max]
        chunk_w, kv_bucket = shapes(batch)
        # The chunk program's transient is the stacked [L, n, chunk_w]
        # new rows PLUS the gathered [L, n, kv_bucket] cache copy —
        # cap n to the same scratch budget as the monolithic wave.
        fit = int(0.75e9) // max(1, (chunk_w + kv_bucket) * scratch_tok)
        cap = 1
        for b in self._PREFILL_N_BUCKETS:
            if b <= fit:
                cap = b
        if len(batch) > cap:
            batch = batch[:cap]
            chunk_w, kv_bucket = shapes(batch)
        n = next(b for b in self._PREFILL_N_BUCKETS if b >= len(batch))

        tokens = np.zeros((n, chunk_w), np.int32)
        starts = np.zeros(n, np.int32)
        valid = np.zeros(n, np.int32)
        want = np.full(n, -1, np.int32)
        # Padding rows carry the out-of-range slot sentinel: their
        # writes (rows, lengths, token merge) all drop.
        slots_arr = np.full(n, self.max_batch, np.int32)
        temps = np.zeros(n, np.float32)
        topks = np.zeros(n, np.int32)
        topps = np.ones(n, np.float32)
        adp_h = (np.full(n, -1, np.int32)
                 if self.adapters is not None else None)
        vm_h = (np.ones((n, self.cfg.vocab_size), bool)
                if self._vmask_any else None)
        for i, slot in enumerate(batch):
            req = self._slots[slot]
            off = self._prefill_off[slot]
            piece = req.prompt[off:off + chunk_w]
            tokens[i, :len(piece)] = piece
            starts[i] = self._slot_len[slot]
            valid[i] = len(piece)
            if off + len(piece) == len(req.prompt):
                want[i] = len(piece) - 1
            slots_arr[i] = slot
            temps[i] = req.temperature
            topks[i] = req.top_k or 0
            topps[i] = req.top_p
            if adp_h is not None:
                adp_h[i] = req._adapter_slot
            if vm_h is not None and req._vocab_mask is not None:
                vm_h[i] = req._vocab_mask
        # Sampling variant only when a COMPLETING row needs it (the
        # full-vocab sort costs hundreds of ms on TPU; mid-prompt
        # chunks and greedy completions must not pay it).
        sample = any(self._slots[s].temperature > 0
                     for i, s in enumerate(batch) if want[i] >= 0)
        self._rng, prng = jax.random.split(self._rng)
        # ONE batched host->device transfer for every host-built
        # operand (each separate jnp.asarray is its own dispatch round
        # trip through a remote tunnel).
        extras = tuple(x for x in (adp_h, vm_h) if x is not None)
        uploaded = device_upload(
            (tokens, starts, valid, want, slots_arr, temps, topks,
             topps) + extras)
        (tokens_d, starts_d, valid_d, want_d, slots_d, temps_d,
         topks_d, topps_d) = uploaded[:8]
        rest = list(uploaded[8:])
        adp_d = rest.pop(0) if adp_h is not None else None
        vm_d = rest.pop(0) if vm_h is not None else None
        prefill = self._get_chunk_prefill(n, chunk_w, kv_bucket, sample)
        chunk_t0 = clock.monotonic()
        with self._prof.phase('prefill_chunk'), \
                self._prof.jit_key('chunk_prefill',
                                   (n, chunk_w, kv_bucket, sample)):
            first, self.cache = prefill(
                self.params, self.cache, tokens_d, starts_d, valid_d,
                want_d, slots_d, adp_d, vm_d, temps_d, topks_d,
                topps_d, prng)
        chunk_t1 = clock.monotonic()
        for i, slot in enumerate(batch):
            r = self._slots[slot]
            if r.trace is not None:
                r.trace.add('prefill_chunk', chunk_t0, chunk_t1,
                            offset=self._prefill_off[slot],
                            tokens=int(valid[i]))
        # Async: host bookkeeping advances NOW (device writes are
        # program-ordered); completing slots' sampled tokens merge into
        # the device token vector immediately so they decode on the
        # next horizon.
        done_rows: List[Tuple[int, int]] = []    # (row i, slot)
        for i, slot in enumerate(batch):
            self._slot_len[slot] += int(valid[i])
            self._prefill_off[slot] += int(valid[i])
            if want[i] < 0:
                continue                         # more chunks to go
            del self._prefill_off[slot]
            done_rows.append((i, slot))
        if done_rows:
            rows_p = np.zeros(n, np.int32)
            slots_p = np.full(n, self.max_batch, np.int32)
            for j, (i, slot) in enumerate(done_rows):
                rows_p[j], slots_p[j] = i, slot
            rows_d, sl_d = device_upload((rows_p, slots_p))
            self._tok_dev = self._merge_tokens_drop(
                self._tok_dev, sl_d, jnp.take(first, rows_d))
            self._meta_dirty = True              # slots become decodable
            self._pending.append({'kind': 'prefill', 'toks': first,
                                  'batch': [(slot, self._slots[slot], i)
                                            for i, slot in done_rows]})
        return []

    def _get_chunk_prefill(self, n: int, chunk_w: int, kv_bucket: int,
                           sample: bool):
        """Compiled chunk-prefill program: gather the batch slots' first
        ``kv_bucket`` cache rows (0 = no cache read — plain causal,
        flash-eligible), run the chunk through prefill_rows at each
        row's offset, scatter the new rows back at the cursors
        (mode='drop': positions past ``valid`` or ``max_seq`` and the
        padding sentinel slot all discard instead of clamp-corrupting
        the cache tail), and sample each completing row's next token."""
        key = (n, chunk_w, kv_bucket, sample)
        if key in self._chunk_prefill_fns:
            return self._chunk_prefill_fns[key]
        cfg, attn_impl = self.cfg, self.attn_impl
        w8a8 = self.prefill_w8a8
        max_seq = self.max_seq

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._step_out_shardings(1))
        def prefill(params, big_cache, tokens, starts, valid, want_idx,
                    slots, adp, vmask, temps, topks, topps, rng):
            if kv_bucket:
                ck = big_cache.k[:, slots, :kv_bucket]
                cv = big_cache.v[:, slots, :kv_bucket]
                if big_cache.quantized:
                    cache_kv = (ck, cv,
                                big_cache.k_scale[:, slots, :kv_bucket],
                                big_cache.v_scale[:, slots, :kv_bucket])
                else:
                    cache_kv = (ck, cv)
            else:
                cache_kv = None
            last_idx = jnp.clip(want_idx, 0, chunk_w - 1)
            last, rows = llama.prefill_rows(
                params, tokens, last_idx + 1, cfg, attn_impl=attn_impl,
                quantize_rows=('int4' if big_cache.packed
                               else big_cache.quantized), w8a8=w8a8,
                cache_kv=cache_kv,
                cache_len=starts if kv_bucket else None,
                mlora_idx=adp)
            # Completing rows' first sampled token honors the grammar.
            last = llama.apply_vocab_mask(last, vmask)
            if sample:
                first = sample_tokens(last, rng, temps, topks, topps)
            else:
                first = jnp.argmax(last, -1).astype(jnp.int32)
            # NaN guard on completing rows (llama.mask_nonfinite_tokens
            # — the host evicts the poisoned request at readback).
            first = llama.mask_nonfinite_tokens(last, first)
            pos = starts[:, None] + jnp.arange(chunk_w)[None, :]
            pos = jnp.where(jnp.arange(chunk_w)[None, :] < valid[:, None],
                            pos, max_seq)        # invalid rows drop
            length = big_cache.length.at[slots].set(starts + valid,
                                                    mode='drop')

            def scatter(c, r):
                return c.at[:, slots[:, None], pos].set(
                    r.astype(c.dtype), mode='drop')

            if big_cache.quantized:
                kq, vq, ks, vs = rows
                new_cache = llama.KVCache(
                    k=scatter(big_cache.k, kq),
                    v=scatter(big_cache.v, vq), length=length,
                    k_scale=scatter(big_cache.k_scale, ks),
                    v_scale=scatter(big_cache.v_scale, vs))
            else:
                k_rows, v_rows = rows
                new_cache = llama.KVCache(k=scatter(big_cache.k, k_rows),
                                          v=scatter(big_cache.v, v_rows),
                                          length=length)
            return first, new_cache

        self._chunk_prefill_fns[key] = prefill
        return prefill

    # ------------------------------------------------------- speculative
    def _get_spec_verify(self, sample: bool, kv_bucket: int):
        """Compiled speculative verify: one forward over the k+1
        positions [t0, d1..dk] per slot against the slots' existing
        cache rows (the nonzero-cache-offset prefill path), acceptance
        on device, and a MASKED scatter of the accepted rows — per-slot
        variable acceptance never changes a shape, so the jit key is
        exactly (k, sample, kv_bucket)."""
        key = (self.speculate_k, sample, kv_bucket)
        if key in self._spec_verify_fns:
            return self._spec_verify_fns[key]
        cfg, attn_impl = self.cfg, self.attn_impl
        k = self.speculate_k
        max_seq = self.max_seq

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._step_out_shardings(3))
        def verify(params, big_cache, tokens, proposals, n_prop, temps,
                   topks, topps, active, adp, vmask, rng):
            return _slot_spec_verify(
                params, big_cache, tokens, proposals, n_prop, temps,
                topks, topps, active, rng, cfg=cfg,
                attn_impl=attn_impl, kv_bucket=kv_bucket,
                max_seq=max_seq, k=k, sample=sample,
                mlora_idx=adp, vocab_mask=vmask)

        self._spec_verify_fns[key] = verify
        return verify

    def _get_spec_fused(self, sample: bool, kv_bucket: int,
                        rounds: int):
        """Compiled in-scan speculative rounds: ``rounds`` x (device
        n-gram propose → verify forward → masked commit) fused into ONE
        program via lax.scan. The verify body is exactly
        ``_slot_spec_verify`` (greedy byte-identity inherited), the
        proposer reads a gather-carried right-aligned history window,
        and the ``rem`` budget carry reproduces the host budget cap so
        commits never overshoot ``max_new_tokens`` or the sequence
        capacity. jit key: (k, sample, kv_bucket, rounds)."""
        key = ('fused', self.speculate_k, sample, kv_bucket, rounds)
        if key in self._spec_verify_fns:
            return self._spec_verify_fns[key]
        from skypilot_tpu.inference import speculative
        cfg, attn_impl = self.cfg, self.attn_impl
        k = self.speculate_k
        max_seq = self.max_seq
        max_ngram = self.spec_max_ngram
        H = self.spec_hist_window

        @functools.partial(jax.jit, donate_argnums=(1,),
                           **self._step_out_shardings(4))
        def fused(params, big_cache, tokens, hist, rem, temps, topks,
                  topps, active, adp, vmask, rngs):
            def round_body(carry, rng):
                cache, tok, hist, rem = carry
                prop, n_prop = speculative.ngram_propose_device(
                    hist, k, max_ngram=max_ngram)
                # Budget carry: at most ``rem`` tokens may still commit
                # (n_commit <= n_prop + 1) — _spec_build_proposals's
                # cap, applied round by round on device.
                n_prop = jnp.minimum(n_prop, jnp.maximum(rem - 1, 0))
                act = active & (rem >= 1)
                commit, n_commit, new_tok, new_cache = \
                    _slot_spec_verify(
                        params, cache, tok, prop, n_prop, temps,
                        topks, topps, act, rng, cfg=cfg,
                        attn_impl=attn_impl, kv_bucket=kv_bucket,
                        max_seq=max_seq, k=k, sample=sample,
                        mlora_idx=adp, vocab_mask=vmask)
                # History carry: append the commit row and re-right-
                # align (shift left by n_commit; uncommitted positions
                # land past the window and are never gathered).
                combined = jnp.concatenate([hist, commit], axis=1)
                gidx = (jnp.arange(H, dtype=jnp.int32)[None, :]
                        + n_commit[:, None])
                new_hist = jnp.take_along_axis(combined, gidx, axis=1)
                return ((new_cache, new_tok, new_hist,
                         rem - n_commit),
                        (commit, n_commit, n_prop))

            (big_cache, tokens, hist, rem), stacked = jax.lax.scan(
                round_body, (big_cache, tokens, hist, rem), rngs)
            commits, n_commits, n_props = stacked
            return commits, n_commits, n_props, tokens, big_cache

        self._spec_verify_fns[key] = fused
        return fused

    def _spec_verify_call(self, ready, proposals, n_prop):
        temps_d, topks_d, topps_d, active_d, sample = \
            self._slot_meta(ready)
        k = self.speculate_k
        max_live = int(max(self._slot_len[s]
                           for s in range(self.max_batch)
                           if self._slots[s] is not None))
        kv_bucket = min(self.max_seq, _bucket_len(max_live + k + 1))
        if kv_bucket > self.max_seq // 2:
            kv_bucket = self.max_seq
        self._rng, rng = jax.random.split(self._rng)
        prop_d, n_prop_d = device_upload((proposals, n_prop))
        verify = self._get_spec_verify(sample, kv_bucket)
        with self._prof.jit_key('spec_verify',
                                (self.speculate_k, sample, kv_bucket)):
            commit, n_commit, self._tok_dev, self.cache = verify(
                self.params, self.cache, self._tok_dev, prop_d, n_prop_d,
                temps_d, topks_d, topps_d, active_d, self._adp_dev,
                self._vmask_dev, rng)
        return commit, n_commit

    def _spec_fused_call(self, ready, rounds):
        """Dispatch ``rounds`` fused propose→verify→commit rounds in one
        jitted call (``_spec_step_fused``). The kv bucket covers the
        worst-case growth ``rounds * (k + 1)`` so every in-scan round
        reads a long-enough cache slice."""
        temps_d, topks_d, topps_d, active_d, sample = \
            self._slot_meta(ready)
        k = self.speculate_k
        max_live = int(max(self._slot_len[s]
                           for s in range(self.max_batch)
                           if self._slots[s] is not None))
        kv_bucket = min(self.max_seq,
                        _bucket_len(max_live + rounds * (k + 1)))
        if kv_bucket > self.max_seq // 2:
            kv_bucket = self.max_seq
        hist, rem = self._spec_hist_state(ready)
        keys = jax.random.split(self._rng, rounds + 1)
        self._rng = keys[0]
        hist_d, rem_d = device_upload((hist, rem))
        fused = self._get_spec_fused(sample, kv_bucket, rounds)
        with self._prof.jit_key('spec_fused',
                                (self.speculate_k, sample, kv_bucket,
                                 rounds)):
            commits, n_commits, n_props, self._tok_dev, self.cache = \
                fused(self.params, self.cache, self._tok_dev, hist_d,
                      rem_d, temps_d, topks_d, topps_d, active_d,
                      self._adp_dev, self._vmask_dev, keys[1:])
        return commits, n_commits, n_props

    def step(self, horizon: int = 1) -> List[Tuple[int, int, bool]]:
        """Chunked scheduling loop: admit (one chunk batch max), then
        enqueue decode through the async pipeline. While prompts are
        mid-prefill the decode horizon is capped by the
        ``decode_priority_ratio`` token budget so the next chunk runs
        within a bounded number of decode steps; while the queue is
        non-empty a medium cap keeps freed slots noticed promptly.
        Monolithic mode keeps _EngineBase.step semantics unchanged.
        ``speculate_k > 0`` replaces the fused decode horizon with one
        synchronous propose→verify→commit round per step (admission —
        chunked or monolithic — is unchanged); adding
        ``decode_steps_per_call > 1`` fuses that many rounds into one
        dispatch instead (in-scan speculative verify)."""
        if not self.chunked and not self.speculate_k:
            return super().step(horizon)
        events: List[Tuple[int, int, bool]] = []
        with self._prof.phase('readback'):
            while len(self._pending) >= self._PIPELINE_DEPTH:
                events.extend(self._process_one())
        with self._prof.phase('admit'):
            events.extend(self._admit())
        if self.speculate_k:
            if (self.decode_steps_per_call or 0) > 1:
                events.extend(self._spec_step_fused())
            else:
                events.extend(self._spec_step())
            return events
        if self.decode_steps_per_call:
            # Multi-step pin: exactly k fused steps per call — the
            # dispatch-amortization knob wins over the interleave /
            # queue-pressure shrinks (capacity caps still apply in
            # _enqueue_decode).
            horizon = self.decode_steps_per_call
        elif self._prefill_off:
            horizon = min(horizon, self._interleave_horizon())
        elif self._queue:
            horizon = min(horizon, 32)
        with self._prof.phase('decode_enqueue'):
            enqueued = self._enqueue_decode(horizon)
        if not enqueued and self._pending:
            with self._prof.phase('readback'):
                events.extend(self._process_one())
        return events

    def _admit_monolithic(self) -> List[Tuple[int, int, bool]]:
        """Whole-prompt admission waves (``prefill_chunk_tokens=0`` —
        the pre-chunking baseline, kept for bench comparison)."""
        free = [s for s in range(self.max_batch) if self._slots[s] is None]
        wave_min = min(self._ADMIT_WAVE_MIN, self.max_batch)
        if (0 < len(free) < wave_min and len(free) < self.max_batch
                and len(self._queue) > len(free) + wave_min):
            # Saturated (queue outruns capacity) with slots still
            # decoding: hold admission until a fuller wave accumulates.
            # Freed slots arrive within ~a call horizon, so the TTFT
            # cost is bounded; when the queue is short (latency regime)
            # or every slot is free (nothing to wait for) admission is
            # immediate.
            return []
        batch: List[Tuple[int, Request]] = []
        for slot in free:
            req = self._queue_pop()
            if req is None:
                break
            batch.append((slot, req))
        if not batch:
            return []
        # Cap the wave: by the largest compiled bucket, AND by the
        # prefill stacked-rows transient — the batched prefill stacks
        # [L, n, bucket] KV rows across the layer scan, and at n=32 x
        # bucket=256 on a 7B the bf16 stack is 2 GB x2, which pushed the
        # compile past HBM with the slot cache + weights resident. int8
        # caches quantize the rows INSIDE the scan (prefill_rows), so
        # their stack is half the width and the wave twice as deep. The
        # overflow requeues at the FRONT (keeps FIFO) for the next step.
        bucket = min(_bucket_len(max(len(r.prompt) for _, r in batch)),
                     self.max_seq)
        scratch_tok = kv_token_bytes(self.cfg, self.kv_cache_dtype,
                                     mesh=self.mesh)
        fit = int(0.75e9) // max(1, bucket * scratch_tok)
        cap = 1
        for b in self._PREFILL_N_BUCKETS:     # largest PADDED n that fits
            if b <= fit:
                cap = b
        if len(batch) > cap:
            self._requeue_front([req for _, req in batch[cap:]])
            batch = batch[:cap]
            bucket = min(_bucket_len(max(len(r.prompt)
                                         for _, r in batch)),
                         self.max_seq)
        # Pad request count to a compiled bucket (extra rows re-prefill the
        # first request into its own slot — harmless duplicate writes).
        n = 1
        for b in self._PREFILL_N_BUCKETS:
            if b >= len(batch):
                n = b
                break
        else:
            n = self._PREFILL_N_BUCKETS[-1]
        prefill = self._get_prefill(bucket, n)

        tokens = np.zeros((n, bucket), np.int32)
        true_lens = np.zeros(n, np.int32)
        slots = np.zeros(n, np.int32)
        adp_h = (np.full(n, -1, np.int32)
                 if self.adapters is not None else None)
        vm_h = (np.ones((n, self.cfg.vocab_size), bool)
                if self._vmask_any else None)
        for i in range(n):
            slot, req = batch[min(i, len(batch) - 1)]
            tokens[i, :len(req.prompt)] = req.prompt
            true_lens[i] = len(req.prompt)
            slots[i] = slot
            if adp_h is not None:
                adp_h[i] = req._adapter_slot
            if vm_h is not None and req._vocab_mask is not None:
                vm_h[i] = req._vocab_mask
        adp_d = jnp.asarray(adp_h) if adp_h is not None else None
        vm_d = jnp.asarray(vm_h) if vm_h is not None else None
        with self._prof.phase('prefill_chunk'), \
                self._prof.jit_key('prefill', (bucket, n)):
            next_tokens, self.cache = prefill(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(true_lens), jnp.asarray(slots),
                adp_d, vm_d)
        # Async: reserve the slots NOW (so the next admission wave and
        # _enqueue_decode see them taken) but defer the token readback —
        # the prefill result rides the pipeline and its events surface
        # in _process_one. The device token vector picks up the prefill
        # tokens without a host trip.
        slots_used = np.array([s for s, _ in batch], np.int32)
        self._tok_dev = self._merge_tokens(
            self._tok_dev, jnp.asarray(slots_used),
            next_tokens[:len(batch)])
        for slot, req in batch:
            self._slots[slot] = req
            self._slot_len[slot] = len(req.prompt)
            self._trace_sched(req)
        self._meta_dirty = True
        self._pending.append({'kind': 'prefill', 'toks': next_tokens,
                              'batch': [(slot, req, i) for i, (slot, req)
                                        in enumerate(batch)]})
        return []

    _HORIZON_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

    def _enqueue_decode(self, horizon: int = 1) -> bool:
        """Enqueue one fused-horizon decode call fed entirely by
        device-resident state (tokens from the previous call's last
        column, the chained cache). Returns False when nothing could be
        enqueued. The host reads the result back in _process_one, up to
        _PIPELINE_DEPTH calls later. Mid-prefill slots (chunked
        admission cursors still advancing) are masked inactive: their
        cache lengths are mid-prompt and their token-vector entries
        stale until the completing chunk merges the first token."""
        ready = self._decode_ready()
        active = np.array([r is not None for r in ready])
        if not active.any():
            return False
        # Cap the horizon by remaining KV capacity (+1 for the token
        # written during the step) — counting the steps already IN
        # FLIGHT, whose device-side lengths have advanced past the host
        # view. The max runs over EVERY occupied slot, mid-prefill ones
        # included: the horizon's ring merge writes (masked-off garbage)
        # rows at each slot's device length, and dynamic_update_slice
        # CLAMPS — a merge pushed past max_seq on a nearly-full
        # mid-prefill slot would slide back over its real prompt rows.
        max_live = int(max(self._slot_len[s]
                           for s in range(self.max_batch)
                           if self._slots[s] is not None))
        cap = int(self.max_seq - 1 - max_live - self._inflight_steps)
        if cap < 1:
            return False
        horizon = max(1, min(horizon, cap))
        # Each fused step re-reads the whole [L, b, horizon] ring of rows
        # produced this horizon; past ~15% of the weight-read traffic the
        # ring dominates the HBM budget and longer horizons backfire
        # (measured: 1B model, b=64 — horizon 128 halves throughput vs 64).
        # The ring rides MODEL dtype (it only quantizes on the merge into
        # an int8 cache), so its rows are costed at cfg.dtype — costing
        # them at the pool's int8 width (round-4 bug) both understated
        # the re-read traffic and allowed rings that blew the HBM budget.
        ring_cap = _ring_horizon_cap(self.cfg, self.max_batch,
                                     self._param_bytes, self.mesh)
        horizon = min(horizon, ring_cap)
        if self.decode_steps_per_call is None:
            for b in reversed(self._HORIZON_BUCKETS):
                if b <= horizon:
                    horizon = b
                    break
        # else: multi-step pin — run EXACTLY k (capacity-clamped above)
        # so the jit key stays (k, sample, kv_bucket) and the audit's
        # one-dispatch-per-k-tokens contract holds.

        temps_d, topks_d, topps_d, active_d, sample = \
            self._slot_meta(ready)
        # Length-aware KV reads: attention streams only the first
        # kv_bucket cache rows (decode is HBM-bound on this read). The
        # bucket must cover every live context through this horizon
        # (in-flight steps included); power-of-two-ish rounding bounds
        # compiled-program count.
        kv_bucket = min(self.max_seq,
                        _bucket_len(max_live + self._inflight_steps +
                                    horizon))
        self._rng, rng = jax.random.split(self._rng)
        # Per-substep attribution: one dispatch covers ``horizon``
        # decode substeps (the multi-step amortization the profiler's
        # per_substep_ms split makes visible).
        self._prof.note_substeps('decode_enqueue', horizon)
        t0 = clock.monotonic()
        with self._prof.jit_key('decode', (horizon, sample, kv_bucket)):
            toks, self.cache = self._decode_fn(
                self.params, self.cache, self._tok_dev, rng,
                temps_d, topks_d, topps_d, active_d, self._adp_dev,
                self._vmask_dev, horizon, sample, kv_bucket)
        live = int(sum(self._slot_len[s] + self._inflight_steps
                       for s in range(self.max_batch)
                       if ready[s] is not None))
        self._note_decode_step(live, horizon, clock.monotonic() - t0)
        self._tok_dev = toks[:, -1]
        self._inflight_steps += horizon
        self._pending.append({'kind': 'decode', 'toks': toks,
                              'horizon': horizon,
                              'snapshot': ready})
        return True

    def _process_one(self) -> List[Tuple[int, int, bool]]:
        """Sync the oldest in-flight call and turn it into events. A
        request that finished (or was cancelled) after the call was
        enqueued produced garbage rows on the device — skipped here;
        its cache rows sit past the corrected length and the slot's
        next prefill overwrites them."""
        entry = self._pending.popleft()
        # THE sanctioned device->host readback of the async pipeline:
        # everything else in the step loop must stay device-side (the
        # jaxpr audit gates on it).
        toks = host_sync(entry['toks'])
        events: List[Tuple[int, int, bool]] = []
        now = clock.now()
        if entry['kind'] == 'prefill':
            for slot, req, row in entry['batch']:
                if req.finish_time is not None:       # cancelled in flight
                    continue
                token = int(toks[row])
                if token < 0:
                    # Non-finite sentinel: the prompt blew up in
                    # prefill — evict just this request.
                    events.append(self._evict_nonfinite(slot, req))
                    continue
                req.first_token_time = now
                if req.trace is not None:
                    req.trace.end('prefill')
                    req.trace.begin('decode')
                req.output.append(token)
                finished = self._finish_req(slot, req, token)
                events.append((req.request_id, token, finished))
            return events
        self._inflight_steps -= entry['horizon']
        for slot, req in enumerate(entry['snapshot']):
            if req is None or req.finish_time is not None:
                continue
            if req.first_token_time is None:
                # Prefill result still queued behind this decode —
                # cannot happen (FIFO pipeline), but guard anyway.
                continue
            for i in range(entry['horizon']):
                token = int(toks[slot, i])
                if token < 0:
                    # Non-finite sentinel: this slot's logits row went
                    # NaN/Inf mid-horizon. Evict exactly this request
                    # (its remaining horizon tokens are garbage by
                    # construction); every other slot's tokens land
                    # normally.
                    events.append(self._evict_nonfinite(slot, req))
                    break
                req.output.append(token)
                self._slot_len[slot] += 1
                finished = self._maybe_finish(slot, token)
                events.append((req.request_id, token, finished))
                if finished:
                    break
        return events


def sample_tokens(logits: jax.Array, step_rng: jax.Array,
                  temps: jax.Array, topks: jax.Array,
                  topps: jax.Array,
                  vocab_mask: Optional[jax.Array] = None) -> jax.Array:
    """Per-slot next-token sampling, shared by the slot and paged
    engines' fused decode: optional grammar vocab mask, then
    temperature scaling, then top-k and nucleus (top-p) filtering
    (``llama.filtered_logits`` — one descending sort of the scaled
    logits, also the distribution speculative verify rejection-samples
    against), then categorical draw. Rows with temp <= 0 take the
    greedy argmax; top-k <= 0 and top-p >= 1 disable their filters.
    The mask applies BEFORE the greedy argmax too — a constrained
    greedy request picks the best ALLOWED token."""
    logits = llama.apply_vocab_mask(logits, vocab_mask)
    next_greedy = jnp.argmax(logits, -1).astype(jnp.int32)
    masked = llama.filtered_logits(logits, temps, topks, topps)
    sampled = jax.random.categorical(step_rng, masked).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, next_greedy)
