"""Task: a unit of work = run + setup + resources + files + env.

Functional parity with reference ``sky/task.py`` (``Task`` at
``sky/task.py:171``, ``from_yaml_config`` at ``:347``). TPU-first differences:

- ``num_nodes`` means *CPU VM count* for CPU clusters and *slice count*
  for TPU tasks (a multi-slice DCN job when > 1). Per-slice host count
  always comes from the slice topology (``Resources.tpu.num_hosts``) —
  the slice IS the gang.
- Env interpolation supports ``$VAR``/``${VAR}`` from ``envs`` at YAML load.
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Union

import yaml

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib

_VALID_NAME_RE = re.compile(r'^[a-zA-Z0-9][a-zA-Z0-9._-]*$')

CommandOrGenerator = Union[None, str, Callable[[int, List[str]], Optional[str]]]


class Task:
    """A coarse-grained unit of work submitted to the framework."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: CommandOrGenerator = None,
        envs: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        file_mounts: Optional[Dict[str, str]] = None,
        storage_mounts: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        if name is not None and not _VALID_NAME_RE.match(name):
            raise exceptions.InvalidTaskError(f'Invalid task name: {name!r}')
        self.setup = setup
        self.run = run
        self.envs = dict(envs) if envs else {}
        self.workdir = workdir
        self.num_nodes = int(num_nodes) if num_nodes else 1
        # dst path on cluster -> src (local path or bucket URI)
        self.file_mounts: Dict[str, str] = dict(file_mounts) if file_mounts else {}
        # dst path -> storage config dict (resolved to Storage objects lazily
        # to keep the spec layer import-light)
        self.storage_mounts: Dict[str, Any] = (
            dict(storage_mounts) if storage_mounts else {})
        self._resources: List[resources_lib.Resources] = [
            resources_lib.Resources()]
        self._resources_ordered = False
        # Managed-jobs fields
        self.max_restarts_on_errors = 0
        # Optimizer outputs / estimates
        self._best_resources: Optional[resources_lib.Resources] = None
        self.estimated_time_hours: float = 1.0
        self.estimated_outputs_gb: float = 0.0
        # Service spec (sky serve), parsed from the YAML 'service' section.
        self.service: Optional[Any] = None
        # DAG wiring (populated by Dag)
        self._dag = None

    # ---------------- resources ----------------
    @property
    def resources(self) -> List[resources_lib.Resources]:
        return list(self._resources)

    @property
    def resources_ordered(self) -> bool:
        """True when the candidate list order is a strict user preference."""
        return self._resources_ordered

    def set_resources(
        self,
        resources: Union[resources_lib.Resources,
                         List[resources_lib.Resources]],
        ordered: bool = False,
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = [resources]
        if not resources:
            raise exceptions.InvalidTaskError('Empty resources list.')
        self._resources = list(resources)
        self._resources_ordered = ordered
        self._validate_topology()
        return self

    @property
    def best_resources(self) -> resources_lib.Resources:
        """The optimizer's pick, falling back to the first candidate."""
        if self._best_resources is not None:
            return self._best_resources
        return self._resources[0]

    def set_best_resources(self,
                           resources: resources_lib.Resources) -> None:
        self._best_resources = resources

    def _validate_topology(self) -> None:
        # For TPU tasks, per-slice host count comes from the slice
        # topology; num_nodes > 1 requests a MULTI-SLICE job (num_nodes
        # slices joined over DCN — the SKYTPU_SLICE_ID/NUM_SLICES env
        # contract).
        if self.num_nodes < 1:
            raise exceptions.InvalidTaskError(
                f'num_nodes must be >= 1, got {self.num_nodes}')

    def num_hosts(self, resources: Optional[resources_lib.Resources] = None
                  ) -> int:
        """Hosts the run command executes on, for the chosen resources.
        TPU: hosts-per-slice x num_nodes (slices)."""
        res = resources or self.best_resources
        if res.is_tpu:
            return res.tpu.num_hosts * self.num_nodes
        return self.num_nodes

    # ---------------- env ----------------
    def update_envs(self, envs: Dict[str, str]) -> 'Task':
        self.envs.update(envs)
        return self

    # ---------------- YAML ----------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Task':
        from skypilot_tpu.utils import schemas
        config = dict(config or {})
        schemas.validate(config, schemas.TASK_SCHEMA, 'task')
        envs = config.get('envs') or {}
        if not isinstance(envs, dict):
            raise exceptions.InvalidTaskError('envs must be a mapping.')
        envs = {str(k): '' if v is None else str(v) for k, v in envs.items()}
        config = _interpolate_envs(config, envs)

        file_mounts = {}
        storage_mounts = {}
        for dst, src in (config.get('file_mounts') or {}).items():
            if isinstance(src, dict):
                storage_mounts[dst] = src
            else:
                file_mounts[dst] = src

        task = cls(
            name=config.get('name'),
            setup=config.get('setup'),
            run=config.get('run'),
            envs=envs,
            workdir=config.get('workdir'),
            num_nodes=config.get('num_nodes'),
            file_mounts=file_mounts,
            storage_mounts=storage_mounts,
        )
        res_cfg = config.get('resources')
        ordered = bool(res_cfg) and 'ordered' in res_cfg
        task.set_resources(
            resources_lib.Resources.from_yaml_config_list(res_cfg),
            ordered=ordered)
        # 'service' section is parsed by serve layer; keep it attached.
        task.service = config.get('service')
        return task

    @classmethod
    def from_yaml(cls, path: str) -> 'Task':
        with open(os.path.expanduser(path), 'r', encoding='utf-8') as f:
            config = yaml.safe_load(f)
        if config is None:
            config = {}
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f'YAML at {path} must be a mapping, got {type(config)}')
        return cls.from_yaml_config(config)

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        if self.name:
            cfg['name'] = self.name
        if self.workdir:
            cfg['workdir'] = self.workdir
        if self.num_nodes != 1:
            cfg['num_nodes'] = self.num_nodes
        if len(self._resources) == 1:
            res_cfg = self._resources[0].to_yaml_config()
        else:
            key = 'ordered' if self._resources_ordered else 'any_of'
            res_cfg = {key: [r.to_yaml_config() for r in self._resources]}
        if res_cfg:
            cfg['resources'] = res_cfg
        if self.envs:
            cfg['envs'] = dict(self.envs)
        mounts: Dict[str, Any] = {}
        mounts.update(self.file_mounts)
        mounts.update(self.storage_mounts)
        if mounts:
            cfg['file_mounts'] = mounts
        if self.setup:
            cfg['setup'] = self.setup
        if self.run is not None and isinstance(self.run, str):
            cfg['run'] = self.run
        if getattr(self, 'service', None):
            cfg['service'] = self.service
        return cfg

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_yaml_config(), sort_keys=False)

    # ---------------- DAG sugar ----------------
    def __rshift__(self, other: 'Task') -> 'Task':
        """``a >> b``: add edge a->b in the ambient DAG context.

        Reference: ``sky/task.py:1186``.
        """
        from skypilot_tpu import dag as dag_lib
        dag_lib._current_dag_add_edge(self, other)
        return other

    def __repr__(self) -> str:
        name = self.name or '<unnamed>'
        return f'Task({name}, resources={self._resources[0]!r})'


_ENV_RE = re.compile(r'\$(\w+)|\$\{(\w+)\}')


def _interpolate_envs(obj: Any, envs: Dict[str, str]) -> Any:
    """Substitute $VAR / ${VAR} from envs in all string values except run/setup
    scripts (those get the env injected at execution time instead)."""
    def sub(s: str) -> str:
        def repl(m: re.Match) -> str:
            key = m.group(1) or m.group(2)
            return envs.get(key, m.group(0))
        return _ENV_RE.sub(repl, s)

    def walk(o: Any, key_path: tuple) -> Any:
        if isinstance(o, dict):
            return {k: walk(v, key_path + (k,)) for k, v in o.items()}
        if isinstance(o, list):
            return [walk(v, key_path) for v in o]
        if isinstance(o, str) and key_path and key_path[0] not in (
                'run', 'setup', 'envs'):
            return sub(o)
        return o

    return walk(obj, ())
