"""Resources: an immutable request/filter for compute.

Functional parity with reference ``sky/resources.py`` (class ``Resources``,
``sky/resources.py:31``), re-designed TPU-first:

- ``accelerators: tpu-v5e-16`` resolves to a :class:`TpuTopology` — hosts per
  slice and chips per host are first-class (the reference bolts this on via
  ``num_ips_per_node``).
- ``accelerator_args`` carries TPU runtime knobs (``runtime_version``,
  ``reserved``, ``best_effort`` queueing) like the reference's
  ``tpu_vm``/``runtime_version`` args (``sky/resources.py:545``). There is no
  ``tpu_vm: False`` legacy path: TPU-VM is the only architecture.
- Multiple candidates are an ordered list on the Task (``any_of`` /
  ``ordered``), matching reference semantics for failover preference.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Set, Union

from skypilot_tpu import accelerators as accel_lib
from skypilot_tpu import exceptions

_DEFAULT_DISK_SIZE_GB = 256


class Resources:
    """An immutable compute requirement.

    Examples:
        Resources(accelerators='tpu-v5e-8')
        Resources(cloud='gcp', accelerators={'A100': 8}, use_spot=True)
        Resources(cpus='8+', memory='32+')
    """

    # Version for pickled handles shipped to controllers (reference:
    # ``Resources._VERSION = 20``, sky/resources.py:47).
    _VERSION = 1

    def __init__(
        self,
        cloud: Optional[str] = None,
        instance_type: Optional[str] = None,
        accelerators: Union[None, str, Dict[str, int]] = None,
        accelerator_args: Optional[Dict[str, Any]] = None,
        cpus: Union[None, int, float, str] = None,
        memory: Union[None, int, float, str] = None,
        use_spot: Optional[bool] = None,
        spot_recovery: Optional[str] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        image_id: Optional[str] = None,
        disk_size: Optional[int] = None,
        disk_tier: Optional[str] = None,
        ports: Optional[List[Union[int, str]]] = None,
        labels: Optional[Dict[str, str]] = None,
        job_recovery: Optional[str] = None,
    ):
        self._cloud = cloud.lower() if cloud else None
        self._instance_type = instance_type
        self._use_spot_specified = use_spot is not None
        self._use_spot = bool(use_spot) if use_spot is not None else False
        if isinstance(job_recovery, dict):
            job_recovery = job_recovery.get('strategy')
        self._spot_recovery = (spot_recovery or job_recovery or None)
        if self._spot_recovery is not None:
            self._spot_recovery = str(self._spot_recovery).upper()
        self._region = region
        self._zone = zone
        self._image_id = image_id
        self._disk_size = int(disk_size) if disk_size else _DEFAULT_DISK_SIZE_GB
        self._disk_tier = disk_tier
        self._ports = [str(p) for p in ports] if ports else None
        self._labels = dict(labels) if labels else None
        self._accelerator_args: Optional[Dict[str, Any]] = (
            dict(accelerator_args) if accelerator_args else None)

        self._set_cpus(cpus)
        self._set_memory(memory)
        self._set_accelerators(accelerators)

    # ---------------- parsing helpers ----------------
    def _set_cpus(self, cpus: Union[None, int, float, str]) -> None:
        # '8' exact, '8+' at-least. Stored as (count, is_at_least).
        self._cpus: Optional[float] = None
        self._cpus_at_least = False
        if cpus is None:
            return
        s = str(cpus)
        if s.endswith('+'):
            self._cpus_at_least = True
            s = s[:-1]
        try:
            self._cpus = float(s)
        except ValueError:
            raise exceptions.InvalidResourcesError(
                f'Invalid cpus spec: {cpus!r}') from None
        if self._cpus <= 0:
            raise exceptions.InvalidResourcesError(
                f'cpus must be positive: {cpus!r}')

    def _set_memory(self, memory: Union[None, int, float, str]) -> None:
        self._memory: Optional[float] = None
        self._memory_at_least = False
        if memory is None:
            return
        s = str(memory)
        if s.endswith('+'):
            self._memory_at_least = True
            s = s[:-1]
        try:
            self._memory = float(s)
        except ValueError:
            raise exceptions.InvalidResourcesError(
                f'Invalid memory spec: {memory!r}') from None
        if self._memory <= 0:
            raise exceptions.InvalidResourcesError(
                f'memory must be positive: {memory!r}')

    def _set_accelerators(
            self, accelerators: Union[None, str, Dict[str, int]]) -> None:
        """Normalize to {name: count}; resolve TPU topology.

        Reference: ``sky/resources.py:545`` ``_set_accelerators``.
        """
        self._accelerators: Optional[Dict[str, int]] = None
        self._tpu: Optional[accel_lib.TpuTopology] = None
        if accelerators is None:
            return
        if isinstance(accelerators, str):
            if ':' in accelerators:
                name, _, cnt = accelerators.partition(':')
                try:
                    accelerators = {name: int(cnt)}
                except ValueError:
                    raise exceptions.InvalidResourcesError(
                        f'Invalid accelerator count in {name}:{cnt!r}'
                    ) from None
            else:
                accelerators = {accelerators: 1}
        if len(accelerators) != 1:
            raise exceptions.InvalidResourcesError(
                'Exactly one accelerator type may be requested, got: '
                f'{accelerators}')
        name, count = next(iter(accelerators.items()))
        name = accel_lib.canonicalize_accelerator_name(name)
        if accel_lib.is_tpu(name):
            self._tpu = accel_lib.parse_tpu(name)
            # For TPUs the count suffix already encodes the slice size.
            if count not in (1, self._tpu.num_chips):
                raise exceptions.InvalidResourcesError(
                    f'TPU slice {name!r} already encodes its size; got '
                    f'conflicting count {count}.')
            self._accelerators = {self._tpu.name: 1}
            if self._cloud is None:
                self._cloud = 'gcp'
            elif self._cloud not in ('gcp', 'kubernetes', 'local'):
                # 'kubernetes' = GKE TPU node pools; 'local' simulates
                # slice topology for hermetic tests.
                raise exceptions.InvalidResourcesError(
                    f'TPUs are only available on GCP or Kubernetes, got '
                    f'cloud={self._cloud!r}')
        else:
            self._accelerators = {name: int(count)}

    # ---------------- properties ----------------
    @property
    def cloud(self) -> Optional[str]:
        return self._cloud

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def accelerators(self) -> Optional[Dict[str, int]]:
        return dict(self._accelerators) if self._accelerators else None

    @property
    def accelerator_args(self) -> Optional[Dict[str, Any]]:
        return dict(self._accelerator_args) if self._accelerator_args else None

    @property
    def tpu(self) -> Optional[accel_lib.TpuTopology]:
        """Resolved TPU topology, or None for CPU/GPU requests."""
        return self._tpu

    @property
    def is_tpu(self) -> bool:
        return self._tpu is not None

    @property
    def tpu_runtime_version(self) -> Optional[str]:
        if not self.is_tpu:
            return None
        args = self._accelerator_args or {}
        return args.get('runtime_version',
                        self._tpu.gen.default_runtime_version)

    @property
    def cpus(self) -> Optional[str]:
        if self._cpus is None:
            return None
        return f'{self._cpus:g}' + ('+' if self._cpus_at_least else '')

    @property
    def memory(self) -> Optional[str]:
        if self._memory is None:
            return None
        return f'{self._memory:g}' + ('+' if self._memory_at_least else '')

    @property
    def use_spot(self) -> bool:
        return self._use_spot

    @property
    def use_spot_specified(self) -> bool:
        return self._use_spot_specified

    @property
    def spot_recovery(self) -> Optional[str]:
        return self._spot_recovery

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    @property
    def disk_size(self) -> int:
        return self._disk_size

    @property
    def disk_tier(self) -> Optional[str]:
        return self._disk_tier

    @property
    def ports(self) -> Optional[List[str]]:
        return list(self._ports) if self._ports else None

    @property
    def labels(self) -> Optional[Dict[str, str]]:
        return dict(self._labels) if self._labels else None

    # ---------------- behaviors ----------------
    def copy(self, **override) -> 'Resources':
        """Return a copy with fields overridden (reference ``copy()``)."""
        fields: Dict[str, Any] = dict(
            cloud=self._cloud,
            instance_type=self._instance_type,
            accelerators=self.accelerators,
            accelerator_args=self.accelerator_args,
            cpus=self.cpus,
            memory=self.memory,
            use_spot=self._use_spot if self._use_spot_specified else None,
            spot_recovery=self._spot_recovery,
            region=self._region,
            zone=self._zone,
            image_id=self._image_id,
            disk_size=self._disk_size,
            disk_tier=self._disk_tier,
            ports=self.ports,
            labels=self.labels,
        )
        fields.update(override)
        return Resources(**fields)

    def less_demanding_than(self, other: 'Resources') -> bool:
        """True if ``other`` can serve a request for ``self``.

        Used for cluster reuse checks (``sky exec`` on an existing cluster).
        """
        if self._cloud is not None and self._cloud != other._cloud:
            return False
        if self._region is not None and self._region != other._region:
            return False
        if self._zone is not None and self._zone != other._zone:
            return False
        if self._use_spot_specified and self._use_spot != other._use_spot:
            return False
        if self._accelerators is not None:
            if other._accelerators is None:
                return False
            for name, cnt in self._accelerators.items():
                if other._accelerators.get(name, 0) < cnt:
                    return False
        if self._instance_type is not None and (
                self._instance_type != other._instance_type):
            return False
        # '8' (exact) only matches exactly-8; '8+' (at-least) matches >= 8.
        if self._cpus is not None:
            if other._cpus is None:
                return False
            if self._cpus_at_least:
                if other._cpus < self._cpus:
                    return False
            elif other._cpus != self._cpus:
                return False
        if self._memory is not None:
            if other._memory is None:
                return False
            if self._memory_at_least:
                if other._memory < self._memory:
                    return False
            elif other._memory != self._memory:
                return False
        if self._disk_size > other._disk_size:
            return False
        return True

    def get_required_chips(self) -> int:
        return self._tpu.num_chips if self._tpu else 0

    # ---------------- serialization ----------------
    @classmethod
    def from_yaml_config(cls, config: Optional[Dict[str, Any]]) -> 'Resources':
        if config is None:
            return cls()
        config = dict(config)
        if 'any_of' in config or 'ordered' in config:
            raise exceptions.InvalidResourcesError(
                'Multi-candidate resources (any_of/ordered) must be parsed '
                'with Resources.from_yaml_config_list().')
        known = {
            'cloud', 'instance_type', 'accelerators', 'accelerator_args',
            'cpus', 'memory', 'use_spot', 'spot_recovery', 'job_recovery',
            'region', 'zone', 'image_id', 'disk_size', 'disk_tier', 'ports',
            'labels',
        }
        unknown = set(config) - known
        if unknown:
            raise exceptions.InvalidResourcesError(
                f'Unknown resources fields: {sorted(unknown)}')
        return cls(**{k: v for k, v in config.items() if k in known})

    @classmethod
    def from_yaml_config_list(
            cls, config: Optional[Dict[str, Any]]) -> List['Resources']:
        """Expand ``any_of``/``ordered`` into an ordered candidate list.

        Reference semantics: ``ordered`` preserves user preference order for
        failover; ``any_of`` means cost-optimal order (optimizer sorts).
        """
        if config is None:
            return [cls()]
        for key in ('any_of', 'ordered'):
            if key in config:
                base = {k: v for k, v in config.items()
                        if k not in ('any_of', 'ordered')}
                out = []
                for sub in config[key]:
                    merged = dict(base)
                    merged.update(sub)
                    out.append(cls.from_yaml_config(merged))
                return out
        return [cls.from_yaml_config(config)]

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        if self._cloud:
            cfg['cloud'] = self._cloud
        if self._instance_type:
            cfg['instance_type'] = self._instance_type
        if self._accelerators:
            name, cnt = next(iter(self._accelerators.items()))
            cfg['accelerators'] = name if cnt == 1 else f'{name}:{cnt}'
        if self._accelerator_args:
            cfg['accelerator_args'] = dict(self._accelerator_args)
        if self.cpus:
            cfg['cpus'] = self.cpus
        if self.memory:
            cfg['memory'] = self.memory
        if self._use_spot_specified:
            cfg['use_spot'] = self._use_spot
        if self._spot_recovery:
            cfg['spot_recovery'] = self._spot_recovery
        if self._region:
            cfg['region'] = self._region
        if self._zone:
            cfg['zone'] = self._zone
        if self._image_id:
            cfg['image_id'] = self._image_id
        if self._disk_size != _DEFAULT_DISK_SIZE_GB:
            cfg['disk_size'] = self._disk_size
        if self._disk_tier:
            cfg['disk_tier'] = self._disk_tier
        if self._ports:
            cfg['ports'] = list(self._ports)
        if self._labels:
            cfg['labels'] = dict(self._labels)
        return cfg

    # ---------------- dunder ----------------
    def __repr__(self) -> str:
        parts = []
        if self._cloud:
            parts.append(self._cloud)
        if self._instance_type:
            parts.append(self._instance_type)
        if self._accelerators:
            name, cnt = next(iter(self._accelerators.items()))
            parts.append(name if cnt == 1 else f'{name}:{cnt}')
        if self._use_spot:
            parts.append('[spot]')
        if self._region:
            parts.append(f'region={self._region}')
        if self._zone:
            parts.append(f'zone={self._zone}')
        if not parts:
            parts.append('default')
        return f'Resources({", ".join(parts)})'

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        import json
        return hash(json.dumps(self.to_yaml_config(), sort_keys=True))
