"""Module-level callback API mirroring the reference's ``sky_callback``
package (``init`` / ``step_begin`` / ``step_end`` / ``step`` context
manager), plus a HuggingFace Trainer adapter. Apps that are NOT built on
the in-tree Trainer instrument their loop with these so ``skytpu bench``
can read step timing."""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional

from skypilot_tpu.callbacks.base import TimerCallback

_timer: Optional[TimerCallback] = None
_step = 0


def init(log_dir: Optional[str] = None, write_every: int = 10) -> None:
    global _timer, _step
    _timer = TimerCallback(log_dir=log_dir, write_every=write_every)
    _step = 0


def _ensure() -> TimerCallback:
    global _timer
    if _timer is None:
        init()
    return _timer


def step_begin() -> None:
    _ensure().on_step_begin(_step)


def step_end(metrics: Optional[Dict[str, Any]] = None) -> None:
    global _step
    _ensure().on_step_end(_step, metrics)
    _step += 1


@contextlib.contextmanager
def step(metrics: Optional[Dict[str, Any]] = None):
    step_begin()
    try:
        yield
    finally:
        step_end(metrics)


def write_summary() -> Optional[str]:
    if _timer is None:
        return None
    return _timer.write_summary()


def hf_trainer_callback(log_dir: Optional[str] = None):
    """A ``transformers.TrainerCallback`` forwarding step events (the
    reference ships an equivalent HF integration in sky-callback)."""
    from transformers import TrainerCallback

    timer = TimerCallback(log_dir=log_dir)

    class SkyTpuHFCallback(TrainerCallback):
        # transformers only delivers metrics via on_log (on_step_end
        # carries none); keep the latest logs and attach them to steps.
        _latest_logs: Dict[str, Any] = {}

        def on_log(self, args, state, control, logs=None, **kwargs):
            if logs:
                self._latest_logs = dict(logs)

        def on_step_begin(self, args, state, control, **kwargs):
            timer.on_step_begin(state.global_step)

        def on_step_end(self, args, state, control, **kwargs):
            timer.on_step_end(state.global_step, self._latest_logs)

        def on_train_end(self, args, state, control, **kwargs):
            timer.on_train_end()

    return SkyTpuHFCallback()
