"""Training callbacks: step instrumentation the benchmark subsystem (and
users) consume.

Role of the reference's ``sky-callback`` package (a pip-installable
shim apps call per step so ``sky bench`` can estimate time/cost): here
the in-tree Trainer owns the loop, so callbacks are first-class — a
``CallbackList`` gets on_step_begin/end and writes a summary file
(`benchmark_summary.json`) that ``skypilot_tpu.benchmark`` reads, the
same contract the reference's callback uploads to the benchmark bucket.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

SUMMARY_FILE = 'benchmark_summary.json'


class BaseCallback:

    def on_step_begin(self, step: int) -> None:
        pass

    def on_step_end(self, step: int, metrics: Optional[Dict[str, Any]]
                    ) -> None:
        pass

    def on_train_end(self) -> None:
        pass


class TimerCallback(BaseCallback):
    """Records per-step wall time; writes a rolling summary JSON with
    total steps, mean step seconds, and estimated steps/sec."""

    def __init__(self, log_dir: Optional[str] = None,
                 write_every: int = 10):
        self.log_dir = log_dir or os.environ.get('SKYTPU_BENCHMARK_DIR',
                                                 '.')
        self.write_every = write_every
        self._t0: Optional[float] = None
        self._first_step_time: Optional[float] = None
        self._steps = 0
        self._total = 0.0
        self._last_metrics: Dict[str, Any] = {}

    def on_step_begin(self, step: int) -> None:
        self._t0 = time.time()
        if self._first_step_time is None:
            self._first_step_time = self._t0

    def on_step_end(self, step: int, metrics: Optional[Dict[str, Any]]
                    ) -> None:
        if self._t0 is None:
            return
        self._steps += 1
        self._total += time.time() - self._t0
        if metrics:
            self._last_metrics = {
                k: float(v) for k, v in metrics.items()
                if isinstance(v, (int, float)) or hasattr(v, 'item')}
        if self._steps % self.write_every == 0:
            self.write_summary()

    def on_train_end(self) -> None:
        self.write_summary()

    def summary(self) -> Dict[str, Any]:
        mean = self._total / self._steps if self._steps else 0.0
        return {
            'num_steps': self._steps,
            'mean_step_seconds': mean,
            'steps_per_second': 1.0 / mean if mean else 0.0,
            'started_at': self._first_step_time,
            'last_metrics': self._last_metrics,
        }

    def write_summary(self) -> str:
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, SUMMARY_FILE)
        with open(path, 'w', encoding='utf-8') as f:
            json.dump(self.summary(), f, indent=1)
        return path


class CallbackList:

    def __init__(self, callbacks: Optional[List[BaseCallback]] = None):
        self.callbacks = list(callbacks or [])

    def on_step_begin(self, step: int) -> None:
        for cb in self.callbacks:
            cb.on_step_begin(step)

    def on_step_end(self, step: int,
                    metrics: Optional[Dict[str, Any]] = None) -> None:
        for cb in self.callbacks:
            cb.on_step_end(step, metrics)

    def on_train_end(self) -> None:
        for cb in self.callbacks:
            cb.on_train_end()
