"""Training callbacks (reference ``sky-callback``): step timing the
benchmark subsystem and users consume."""
from skypilot_tpu.callbacks.base import (BaseCallback, CallbackList,
                                         TimerCallback)

__all__ = ['BaseCallback', 'CallbackList', 'TimerCallback']
