"""Job driver: runs one job's command on EVERY host of the slice.

The TPU replacement for the reference's Ray-task-per-node driver program
(``RayCodeGen`` ``sky/backends/cloud_vm_ray_backend.py:220`` +
``_execute_task_n_nodes`` ``:5061``): multi-controller JAX means every host
runs the same program, so the driver is just a parallel fan-out over the
slice's hosts with the rank/coordinator env contract
(:mod:`skypilot_tpu.agent.constants`) exported per rank.

Spawned detached by the FIFO scheduler; exits after writing the terminal
job status and kicking the scheduler.
"""
from __future__ import annotations

import json
import os
import shlex
import sys
from typing import Dict

from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.utils import subprocess_utils


def _load_cluster_info() -> provision_common.ClusterInfo:
    with open(constants.cluster_info_path(), encoding='utf-8') as f:
        return provision_common.ClusterInfo.from_dict(json.load(f))


def build_rank_env(cluster_info: provision_common.ClusterInfo,
                   rank: int, job_id: int) -> Dict[str, str]:
    """The per-host env contract (gang/rank + jax.distributed bootstrap).

    Multi-slice: SKYTPU_SLICE_ID/NUM_SLICES come from the cluster
    topology (each provisioned TPU node/queued-resource is one slice);
    the jax.distributed coordinator is global rank 0's host, so one
    coordinator spans all slices and the DCN mesh axis works."""
    ips = cluster_info.worker_ips()
    head_ip = cluster_info.head_host().internal_ip
    # Lookup by rank, not position: a gapped host list (partial failure)
    # must fail loudly, not hand out another host's slice id.
    slice_id = {h.rank: h for h in cluster_info.hosts}[rank].slice_id
    return {
        constants.ENV_NODE_RANK: str(rank),
        constants.ENV_NODE_IPS: '\n'.join(ips),
        constants.ENV_NUM_NODES: str(len(ips)),
        constants.ENV_NUM_CHIPS_PER_NODE: str(cluster_info.chips_per_host),
        constants.ENV_COORDINATOR_ADDRESS:
            f'{head_ip}:{constants.JAX_COORDINATOR_PORT}',
        constants.ENV_JOB_ID: str(job_id),
        constants.ENV_CLUSTER_NAME: cluster_info.cluster_name,
        constants.ENV_SLICE_ID: str(slice_id),
        constants.ENV_NUM_SLICES: str(cluster_info.num_slices),
    }


def run_job(job_id: int) -> int:
    job = job_lib.get_job(job_id)
    if job is None:
        print(f'driver: job {job_id} not found', file=sys.stderr)
        return 1
    spec = job['spec'] or {}
    cluster_info = _load_cluster_info()
    runners = provision_common.get_command_runners(cluster_info)
    log_dir = constants.job_log_dir(job['run_timestamp'])
    os.makedirs(log_dir, exist_ok=True)

    job_lib.set_status(job_id, job_lib.JobStatus.RUNNING)

    run_cmd = spec.get('run') or ''
    user_env = {str(k): str(v) for k, v in (spec.get('env') or {}).items()}
    workdir = spec.get('workdir_target')  # remote cwd, e.g. ~/sky_workdir

    def run_one(rank_runner) -> int:
        rank, runner = rank_runner
        env = build_rank_env(cluster_info, rank, job_id)
        env.update(user_env)
        if not spec.get('control_plane'):
            # Only data-plane (user) jobs get the accelerator-runtime
            # env back; controller/LB service processes must not
            # initialize the TPU runtime or claim the chip.
            constants.restore_accelerator_env(env)
        log_path = os.path.join(log_dir,
                                constants.RANK_LOG_FMT.format(rank=rank))
        cmd = run_cmd
        if workdir:
            # Quote the path but keep ~ expandable by the remote shell
            # (shlex.quote('~/x') would suppress tilde expansion).
            if workdir.startswith('~/'):
                quoted = '"$HOME"/' + shlex.quote(workdir[2:])
            else:
                quoted = shlex.quote(workdir)
            cmd = f'cd {quoted} && {cmd}'
        docker_image = spec.get('docker_image')
        if docker_image:
            # Containerized run (image_id: docker:<image>); privileged
            # so the container sees the TPU devices.
            from skypilot_tpu.utils import docker_utils
            cmd = docker_utils.wrap_in_docker(cmd, docker_image, env)
        rc = runner.run(cmd, env=env, log_path=log_path)
        return rc if isinstance(rc, int) else rc[0]

    if run_cmd.strip():
        rcs = subprocess_utils.run_in_parallel(
            run_one, list(enumerate(runners)),
            num_threads=len(runners))
    else:
        rcs = [0]

    failed = [rc for rc in rcs if rc != 0]
    status = (job_lib.JobStatus.SUCCEEDED if not failed
              else job_lib.JobStatus.FAILED)
    job_lib.set_status(job_id, status)
    job_lib.schedule_step()
    return 0 if not failed else 1


def main() -> None:
    job_id = int(sys.argv[1])
    sys.exit(run_job(job_id))


if __name__ == '__main__':
    main()
