"""Log capture and tailing on the head host.

Role of reference ``sky/skylet/log_lib.py`` (``run_with_log`` ``:138``,
``tail_logs`` ``:386``). Per-job logs live under
``$SKYTPU_AGENT_DIR/logs/<run_timestamp>/rank-<i>.log`` — one file per
slice host, mirroring the reference's per-rank naming.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, Iterator, List, Optional

from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib


def run_with_log(cmd: List[str],
                 log_path: str,
                 *,
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 stream_logs: bool = False,
                 shell: bool = False) -> int:
    """Run cmd, teeing combined stdout/stderr to log_path. Returns rc."""
    os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
    with open(log_path, 'ab') as log_file:
        proc = subprocess.Popen(cmd, shell=shell, env=env, cwd=cwd,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)
        assert proc.stdout is not None
        for line in iter(proc.stdout.readline, b''):
            log_file.write(line)
            log_file.flush()
            if stream_logs:
                sys.stdout.buffer.write(line)
                sys.stdout.flush()
        proc.wait()
    return proc.returncode


def _job_log_paths(run_timestamp: str) -> List[str]:
    log_dir = constants.job_log_dir(run_timestamp)
    if not os.path.isdir(log_dir):
        return []
    return sorted(
        os.path.join(log_dir, f) for f in os.listdir(log_dir)
        if f.startswith('rank-'))


def read_job_logs(job_id: int, tail: int = 0) -> str:
    """Concatenated per-rank logs (rank-prefixed when multi-host)."""
    job = job_lib.get_job(job_id)
    if job is None:
        return f'Job {job_id} not found.\n'
    paths = _job_log_paths(job['run_timestamp'])
    chunks = []
    multi = len(paths) > 1
    for path in paths:
        rank = os.path.basename(path)[len('rank-'):-len('.log')]
        try:
            with open(path, encoding='utf-8', errors='replace') as f:
                lines = f.readlines()
        except FileNotFoundError:
            continue
        if tail:
            lines = lines[-tail:]
        prefix = f'({rank}) ' if multi else ''
        chunks.extend(prefix + line for line in lines)
    return ''.join(chunks)


def tail_job_logs(job_id: int, *, follow: bool = True,
                  poll_interval: float = 0.2) -> Iterator[str]:
    """Yield log lines; with follow, keep yielding until the job reaches a
    terminal state and files stop growing."""
    job = job_lib.get_job(job_id)
    if job is None:
        yield f'Job {job_id} not found.\n'
        return
    run_timestamp = job['run_timestamp']
    offsets: Dict[str, int] = {}
    # Wait for the driver to create the log dir (job may still be PENDING).
    while True:
        paths = _job_log_paths(run_timestamp)
        new_output = False
        for path in paths:
            rank = os.path.basename(path)[len('rank-'):-len('.log')]
            prefix = f'({rank}) ' if len(paths) > 1 else ''
            try:
                with open(path, encoding='utf-8', errors='replace') as f:
                    f.seek(offsets.get(path, 0))
                    chunk = f.read()
                    offsets[path] = f.tell()
            except FileNotFoundError:
                continue
            if chunk:
                new_output = True
                for line in chunk.splitlines(keepends=True):
                    yield prefix + line
        status = job_lib.get_status(job_id)
        if not follow:
            return
        if status is not None and status.is_terminal() and not new_output:
            return
        time.sleep(poll_interval)
