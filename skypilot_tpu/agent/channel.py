"""Persistent agent-RPC channels.

One-shot RPCs pay a remote interpreter start per call (over SSH that is
hundreds of ms; the reference pays the same per codegen-exec,
``sky/skylet/job_lib.py:930``). A channel starts ``python -m
<module> --serve`` on the head ONCE per client session and pipes
line-delimited JSON over its stdin/stdout — status/queue/logs/cancel
sequences then cost one round trip each instead of one interpreter
start each.

Failure model (the channel is an optimization, never a new failure
mode):

- Startup failure (old runtime without ``--serve``, agent not yet
  synced): raises ``ChannelError(sent=False)``; the caller falls back
  to the one-shot exec AND the key is negative-cached for a cooldown so
  every later call doesn't pay failed spawns first.
- Failure BEFORE the request was written: safe to re-establish and
  retry — nothing executed remotely.
- Failure AFTER the request was written (EOF mid-response, read
  timeout): NO retry and NO fallback — the op may have executed, and
  blindly re-sending would double-submit writes like ``queue_job``.
  The error surfaces to the caller (``sent=True``).
- Reads ride a reader thread + queue, so every wait is bounded by
  ``request_timeout`` — a wedged remote handler cannot hold the
  channel lock forever.
"""
from __future__ import annotations

import atexit
import json
import queue as queue_mod
import shlex
import threading
import time
from typing import Dict, Optional, Tuple

from skypilot_tpu import tpu_logging
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.agent import rpc as agent_rpc

logger = tpu_logging.init_logger(__name__)

# How long a failed-to-start channel key stays disabled (fall straight
# to one-shot execs) before the channel is attempted again.
_DISABLE_COOLDOWN_S = 120.0


class ChannelError(Exception):
    """The channel could not serve the request.

    ``sent`` is True when the request MAY have reached the remote
    handler — the caller must not re-execute non-idempotent ops."""

    def __init__(self, msg: str, *, sent: bool):
        super().__init__(msg)
        self.sent = sent


class RpcChannel:
    """One persistent ``--serve`` interpreter on a node."""

    def __init__(self, runner, module: str,
                 request_timeout: float = 120.0):
        self._runner = runner
        self._module = module
        self._timeout = request_timeout
        self._proc = None
        self._lines: 'queue_mod.Queue[Optional[str]]' = queue_mod.Queue()
        self._lock = threading.Lock()

    def _start(self) -> None:
        cmd = (f'{agent_constants.control_plane_env_prefix()}'
               f'{shlex.quote(self._runner.remote_python)} '
               f'-m {self._module} --serve')
        self._proc = self._runner.popen_interactive(cmd)
        self._lines = queue_mod.Queue()
        stdout = self._proc.stdout

        def reader(q: 'queue_mod.Queue[Optional[str]]') -> None:
            # Dedicated reader: readline() has no timeout, so waits
            # happen on the queue (bounded) instead of the pipe.
            for line in iter(stdout.readline, ''):
                q.put(line)
            q.put(None)                      # EOF marker

        threading.Thread(target=reader, args=(self._lines,),
                         daemon=True).start()
        # Wait for the ready banner so a failed spawn (e.g. a head
        # running an older runtime whose rpc has no --serve) surfaces
        # here as sent=False, never as a confusing mid-request EOF.
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                line = self._lines.get(timeout=1.0)
            except queue_mod.Empty:
                continue
            if line is None:
                raise ChannelError(
                    f'channel to {self._runner.node_id} died during '
                    f'startup (rc={self._proc.poll()})', sent=False)
            if line.strip() == agent_rpc.READY_LINE:
                return
        raise ChannelError('channel startup: no ready banner',
                           sent=False)

    def _ensure(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            return
        self.close()
        self._start()

    def _roundtrip(self, request: Dict) -> Dict:
        try:
            self._proc.stdin.write(json.dumps(request) + '\n')
            self._proc.stdin.flush()
        except (OSError, ValueError) as e:
            # Write failed outright — remote never saw the request.
            raise ChannelError(f'channel write failed: {e}',
                               sent=False) from e
        deadline = time.time() + self._timeout
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                raise ChannelError(
                    f'channel request timed out after '
                    f'{self._timeout}s', sent=True)
            try:
                line = self._lines.get(timeout=min(remaining, 5.0))
            except queue_mod.Empty:
                continue
            if line is None:
                raise ChannelError('channel EOF mid-request', sent=True)
            if line.startswith(agent_rpc.PAYLOAD_PREFIX):
                return json.loads(line[len(agent_rpc.PAYLOAD_PREFIX):])

    def request(self, request: Dict) -> Dict:
        """One RPC round trip. Re-establishes and retries only when the
        request provably never reached the remote (sent=False);
        anything after the write surfaces as ChannelError(sent=True) —
        the caller decides what re-execution means for the op."""
        with self._lock:
            for attempt in (0, 1):
                try:
                    self._ensure()
                    return self._roundtrip(request)
                except ChannelError as e:
                    if e.sent:
                        self.close()
                        raise
                    self.close()
                    if attempt == 1:
                        raise
                    logger.debug(f'RPC channel retry to '
                                 f'{self._runner.node_id}: {e}')
                except (OSError, ValueError,
                        NotImplementedError) as e:
                    self.close()
                    if attempt == 1:
                        raise ChannelError(str(e), sent=False) from e
                    logger.debug(f'RPC channel retry to '
                                 f'{self._runner.node_id}: {e}')

    def close(self) -> None:
        proc, self._proc = self._proc, None
        if proc is None:
            return
        try:
            if proc.stdin:
                proc.stdin.close()
            proc.terminate()
            proc.wait(timeout=2)
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'channel close: terminate failed '
                         f'({type(e).__name__}: {e}); killing')
            try:
                proc.kill()
            except Exception as e2:  # pylint: disable=broad-except
                logger.debug(f'channel close: kill failed too '
                             f'({type(e2).__name__}: {e2})')


_channels: Dict[Tuple, RpcChannel] = {}
_disabled_until: Dict[Tuple, float] = {}
_registry_lock = threading.Lock()


def channel_for(runner, module: str) -> Optional[RpcChannel]:
    """The cached channel for (node, module); None when the runner has
    no interactive transport or the key is in its failure cooldown."""
    try:
        key = runner.channel_key + (module,)
    except (AttributeError, NotImplementedError):
        return None
    with _registry_lock:
        if _disabled_until.get(key, 0) > time.time():
            return None
        ch = _channels.get(key)
        if ch is None:
            ch = RpcChannel(runner, module)
            _channels[key] = ch
        return ch


def disable(runner, module: str,
            cooldown: float = _DISABLE_COOLDOWN_S) -> None:
    """Negative-cache a channel key after a startup failure: later
    calls go straight to the one-shot exec instead of paying failed
    channel spawns first (e.g. a head running an older runtime)."""
    try:
        key = runner.channel_key + (module,)
    except (AttributeError, NotImplementedError):
        return
    with _registry_lock:
        _disabled_until[key] = time.time() + cooldown
        ch = _channels.pop(key, None)
    if ch is not None:
        ch.close()


def close_all() -> None:
    with _registry_lock:
        chans = list(_channels.values())
        _channels.clear()
        _disabled_until.clear()
    for ch in chans:
        ch.close()


atexit.register(close_all)
