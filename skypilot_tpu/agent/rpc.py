"""Agent RPC: a small JSON-over-stdio protocol the client drives via the
command runner.

This replaces the reference's "codegen RPC" (generating Python source and
exec-ing it on the head, e.g. ``JobLibCodeGen`` ``sky/skylet/job_lib.py:930``)
with a fixed command surface: the client runs
``python -m skypilot_tpu.agent.rpc '<json-request>'`` on the head and parses
the single JSON response line after :data:`PAYLOAD_PREFIX`. The ``tail`` op
instead streams raw log lines (the client passes the stream through).

Ops: queue_job, job_status, job_table, cancel, cancel_all, logs, tail,
set_autostop, autostop_config, is_idle, agent_health.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict

from skypilot_tpu.agent import autostop_lib
from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib
from skypilot_tpu.agent import log_lib
from skypilot_tpu.utils import subprocess_utils

PAYLOAD_PREFIX = 'SKYTPU_RPC_PAYLOAD:'


def _ok(**kwargs) -> Dict[str, Any]:
    return {'ok': True, **kwargs}


def _job_record_to_json(job: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(job)
    out['status'] = job['status'].value
    return out


def handle(request: Dict[str, Any]) -> Dict[str, Any]:
    op = request.get('op')
    if op == 'batch':
        # N ops in ONE ssh/python round trip: against a real cluster
        # every RPC costs a remote interpreter start (~100s of ms), so
        # status paths batch their reads (reference ops pay the same
        # per-codegen-exec cost; ``sky/benchmarks`` discussions).
        results = []
        for sub in request.get('requests', []):
            try:
                if sub.get('op') == 'batch':
                    raise ValueError('nested batch ops are not allowed')
                results.append(handle(sub))
            except Exception as e:  # pylint: disable=broad-except
                results.append({'ok': False,
                                'error': f'{type(e).__name__}: {e}'})
        return _ok(results=results)
    if op == 'queue_job':
        job_id = job_lib.add_job(
            name=request.get('name') or 'task',
            username=request.get('username') or 'unknown',
            run_timestamp=request['run_timestamp'],
            resources_str=request.get('resources') or '',
            spec=request['spec'])
        job_lib.schedule_step()
        return _ok(job_id=job_id)
    if op == 'job_status':
        status = job_lib.get_status(int(request['job_id']))
        return _ok(status=status.value if status else None)
    if op == 'job_table':
        jobs = [_job_record_to_json(j) for j in job_lib.get_jobs()]
        return _ok(jobs=jobs)
    if op == 'cancel':
        cancelled = job_lib.cancel_job(int(request['job_id']))
        return _ok(cancelled=cancelled)
    if op == 'cancel_all':
        return _ok(cancelled=job_lib.cancel_all())
    if op == 'logs':
        text = log_lib.read_job_logs(int(request['job_id']),
                                     tail=int(request.get('tail', 0)))
        return _ok(logs=text)
    if op == 'set_autostop':
        autostop_lib.set_autostop(int(request['idle_minutes']),
                                  bool(request.get('to_down', False)))
        return _ok()
    if op == 'autostop_config':
        cfg = autostop_lib.get_autostop_config()
        return _ok(idle_minutes=cfg.idle_minutes, to_down=cfg.to_down)
    if op == 'is_idle':
        return _ok(idle=job_lib.is_cluster_idle())
    if op == 'agent_health':
        pid = None
        try:
            with open(constants.agentd_pid_path(), encoding='utf-8') as f:
                pid = int(f.read().strip())
        except (FileNotFoundError, ValueError):
            pass
        alive = subprocess_utils.pid_is_alive(pid)
        runtime_version = None
        try:
            vpath = os.path.expanduser('~/.skytpu_runtime/version')
            with open(vpath, encoding='utf-8') as f:
                runtime_version = f.read().strip()
        except FileNotFoundError:
            pass
        return _ok(agentd_alive=alive, agentd_pid=pid,
                   runtime_version=runtime_version,
                   num_nonterminal_jobs=len(job_lib.get_jobs(
                       [job_lib.JobStatus.PENDING, job_lib.JobStatus.STARTING,
                        job_lib.JobStatus.RUNNING])))
    raise ValueError(f'Unknown RPC op: {op!r}')


READY_LINE = 'SKYTPU_RPC_READY'


def serve(handle_fn=None) -> None:
    """Persistent stdio server: one JSON request per stdin line, one
    PAYLOAD line per response. A single remote interpreter then serves
    every status/logs/cancel call of a client session — the per-op
    interpreter start (~100s of ms over SSH, the reference's
    per-codegen-exec cost) is paid once. EOF on stdin ends the loop
    (the channel dies with the client). Streaming ops (``tail``) are
    refused — they own stdout and ride the one-shot path."""
    handle_fn = handle_fn or handle
    print(READY_LINE, flush=True)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            request = json.loads(line)
            if request.get('op') == 'tail':
                raise ValueError('streaming op "tail" cannot ride the '
                                 'persistent channel')
            response = handle_fn(request)
        except Exception as e:  # pylint: disable=broad-except
            response = {'ok': False, 'error': f'{type(e).__name__}: {e}'}
        print(PAYLOAD_PREFIX + json.dumps(response), flush=True)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == '--serve':
        serve()
        return
    raw = sys.argv[1] if len(sys.argv) > 1 else sys.stdin.read()
    request = json.loads(raw)
    if request.get('op') == 'tail':
        # Streaming op: raw lines straight to stdout, no JSON envelope.
        for line in log_lib.tail_job_logs(
                int(request['job_id']),
                follow=bool(request.get('follow', True))):
            sys.stdout.write(line)
            sys.stdout.flush()
        status = job_lib.get_status(int(request['job_id']))
        if status is not None:
            print(f'\n[job {request["job_id"]}] {status.value}')
        return
    try:
        response = handle(request)
    except Exception as e:  # pylint: disable=broad-except
        response = {'ok': False, 'error': f'{type(e).__name__}: {e}'}
    print(PAYLOAD_PREFIX + json.dumps(response))


if __name__ == '__main__':
    main()
