"""Agent-side paths and the per-host environment contract.

The env contract is the TPU equivalent of the reference's
``SKYPILOT_NODE_RANK/NODE_IPS/NUM_NODES/NUM_GPUS_PER_NODE`` exports
(``sky/backends/cloud_vm_ray_backend.py:519-536``,
``sky/skylet/constants.py:296-299``) plus the jax.distributed bootstrap:
every host of the slice runs the same program with these set.
"""
from __future__ import annotations

import os

# ---- env contract exported to every rank of a job ----
ENV_NODE_RANK = 'SKYTPU_NODE_RANK'
ENV_NODE_IPS = 'SKYTPU_NODE_IPS'            # newline-separated, rank order
ENV_NUM_NODES = 'SKYTPU_NUM_NODES'
ENV_NUM_CHIPS_PER_NODE = 'SKYTPU_NUM_CHIPS_PER_NODE'
ENV_COORDINATOR_ADDRESS = 'SKYTPU_COORDINATOR_ADDRESS'  # head_ip:port
ENV_JOB_ID = 'SKYTPU_JOB_ID'
ENV_CLUSTER_NAME = 'SKYTPU_CLUSTER_NAME'
ENV_TASK_ID = 'SKYTPU_TASK_ID'
# Multi-slice (DCN) contract: which slice this host belongs to and how many.
ENV_SLICE_ID = 'SKYTPU_SLICE_ID'
ENV_NUM_SLICES = 'SKYTPU_NUM_SLICES'

JAX_COORDINATOR_PORT = 8476

# Where a task's workdir lands on every cluster host — shared by the
# backend's direct sync, the controller-side file-mount translation, and
# the driver's cwd decision.
WORKDIR_TARGET = '~/sky_workdir'

# ---- control-plane vs data-plane environment ----
# Accelerator-runtime env vars that control-plane processes (agentd, RPC
# subprocesses, job drivers) must NOT see: site hooks key off them to
# import jax and initialize the TPU PJRT runtime, which costs seconds of
# startup per process and can claim the chip. Control-plane commands run
# with these cleared and stashed under SKYTPU_SAVED_<var>; the job driver
# restores them into the *user job's* env (the job is the data plane — it
# does need the chip).
ENV_SAVED_PREFIX = 'SKYTPU_SAVED_'
ACCELERATOR_RUNTIME_ENV_VARS = ('PALLAS_AXON_POOL_IPS',)


def control_plane_env_prefix() -> str:
    """Shell prefix clearing accelerator-runtime env for one command,
    stashing original values for the driver to restore into user jobs."""
    parts = []
    for var in ACCELERATOR_RUNTIME_ENV_VARS:
        parts.append(f'{ENV_SAVED_PREFIX}{var}="${{{var}-}}"')
        parts.append(f'{var}=')
    return ' '.join(parts) + ' '


def restore_accelerator_env(env: dict) -> None:
    """Give a user job back the accelerator-runtime vars the control
    plane stashed (no-op if nothing was stashed or the var is live)."""
    for var in ACCELERATOR_RUNTIME_ENV_VARS:
        saved = os.environ.get(ENV_SAVED_PREFIX + var)
        if saved and not os.environ.get(var) and var not in env:
            env[var] = saved

# ---- agent filesystem layout (under $SKYTPU_AGENT_DIR) ----


def agent_dir() -> str:
    d = os.environ.get('SKYTPU_AGENT_DIR',
                       os.path.expanduser('~/.skytpu_agent'))
    os.makedirs(d, exist_ok=True)
    return d


def jobs_db_path() -> str:
    return os.path.join(agent_dir(), 'jobs.db')


def logs_dir() -> str:
    d = os.path.join(agent_dir(), 'logs')
    os.makedirs(d, exist_ok=True)
    return d


def job_log_dir(run_timestamp: str) -> str:
    return os.path.join(logs_dir(), run_timestamp)


def cluster_info_path() -> str:
    return os.path.join(agent_dir(), 'cluster_info.json')


def autostop_config_path() -> str:
    return os.path.join(agent_dir(), 'autostop.json')


def agentd_pid_path() -> str:
    return os.path.join(agent_dir(), 'agentd.pid')


def agentd_log_path() -> str:
    return os.path.join(agent_dir(), 'agentd.log')


def agentd_heartbeat_path() -> str:
    return os.path.join(agent_dir(), 'agentd.heartbeat')


# Agent daemon tick, seconds (reference skylet ticks every 20s,
# ``sky/skylet/skylet.py:17-33``). Env-overridable so tests run fast.
def agent_tick_seconds() -> float:
    return float(os.environ.get('SKYTPU_AGENT_TICK', '20'))


SETUP_LOG = 'setup.log'
RANK_LOG_FMT = 'rank-{rank}.log'   # per-host job output
DRIVER_LOG = 'driver.log'
