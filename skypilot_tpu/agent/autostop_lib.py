"""Autostop bookkeeping on the head host.

Role of reference ``sky/skylet/autostop_lib.py`` (config + last-active
tracking; ``AutostopCodeGen`` ``:105`` becomes an RPC op here). The agentd
AutostopEvent consumes this and tears the cluster down via the provision
API from the head (reference ``sky/skylet/events.py:93``).
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib


@dataclasses.dataclass
class AutostopConfig:
    idle_minutes: int = -1          # -1 = disabled
    to_down: bool = False           # terminate instead of stop
    set_at: float = 0.0

    @property
    def enabled(self) -> bool:
        return self.idle_minutes >= 0


def get_autostop_config() -> AutostopConfig:
    path = constants.autostop_config_path()
    if not os.path.exists(path):
        return AutostopConfig()
    with open(path, encoding='utf-8') as f:
        d = json.load(f)
    return AutostopConfig(**d)


def set_autostop(idle_minutes: int, to_down: bool = False) -> None:
    cfg = AutostopConfig(idle_minutes=idle_minutes, to_down=to_down,
                         set_at=time.time())
    with open(constants.autostop_config_path(), 'w', encoding='utf-8') as f:
        json.dump(dataclasses.asdict(cfg), f)


def idle_seconds() -> Optional[float]:
    """Seconds since the cluster went idle; None while busy."""
    if not job_lib.is_cluster_idle():
        return None
    cfg = get_autostop_config()
    anchor = max(job_lib.last_activity_time(), cfg.set_at)
    if anchor <= 0:
        anchor = cfg.set_at or time.time()
    return time.time() - anchor


def should_autostop() -> bool:
    cfg = get_autostop_config()
    if not cfg.enabled:
        return False
    idle = idle_seconds()
    return idle is not None and idle >= cfg.idle_minutes * 60
