"""Per-cluster job table + FIFO scheduler (runs on the head host).

Role of reference ``sky/skylet/job_lib.py`` (``JobStatus`` ``:118``,
``FIFOScheduler`` ``:194,266``, ``update_job_status`` ``:555``). TPU-first
simplification: a slice is exclusively owned by one program at a time, so
the scheduler runs jobs strictly serially (the reference's resource-slot
logic degenerates to FIFO-of-one on TPUs anyway).

The driver for a scheduled job is ``python -m skypilot_tpu.agent.driver``
launched as a detached daemon; its pid is recorded for liveness-based
status reconciliation (dead driver + non-terminal status = FAILED_DRIVER).
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import sys
import time
from typing import Any, Dict, List, Optional

import filelock

from skypilot_tpu.agent import constants
from skypilot_tpu.utils import subprocess_utils


class JobStatus(enum.Enum):
    """Job lifecycle. Terminal: SUCCEEDED / FAILED / FAILED_DRIVER /
    FAILED_SETUP / CANCELLED."""
    INIT = 'INIT'
    PENDING = 'PENDING'
    STARTING = 'STARTING'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_DRIVER = 'FAILED_DRIVER'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    @classmethod
    def nonterminal_values(cls) -> List[str]:
        return [s.value for s in cls if not s.is_terminal()]


_TERMINAL = {JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.FAILED_SETUP,
             JobStatus.FAILED_DRIVER, JobStatus.CANCELLED}


def _conn() -> sqlite3.Connection:
    path = constants.jobs_db_path()
    conn = sqlite3.connect(path, timeout=10)
    conn.execute("""\
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            username TEXT,
            submitted_at REAL,
            status TEXT,
            run_timestamp TEXT,
            start_at REAL,
            end_at REAL,
            resources TEXT,
            driver_pid INTEGER,
            spec TEXT)""")
    conn.commit()
    return conn


def _scheduler_lock() -> filelock.FileLock:
    return filelock.FileLock(
        os.path.join(constants.agent_dir(), '.scheduler.lock'))


# ------------------------------------------------------------------ CRUD
def add_job(name: str, username: str, run_timestamp: str,
            resources_str: str, spec: Dict[str, Any]) -> int:
    """Queue a job (status PENDING); returns job_id."""
    conn = _conn()
    with conn:
        cur = conn.execute(
            'INSERT INTO jobs (name, username, submitted_at, status, '
            'run_timestamp, resources, spec) VALUES (?,?,?,?,?,?,?)',
            (name, username, time.time(), JobStatus.PENDING.value,
             run_timestamp, resources_str, json.dumps(spec)))
        job_id = cur.lastrowid
    conn.close()
    return int(job_id)


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    conn = _conn()
    row = conn.execute(
        'SELECT job_id, name, username, submitted_at, status, '
        'run_timestamp, start_at, end_at, resources, driver_pid, spec '
        'FROM jobs WHERE job_id=?', (job_id,)).fetchone()
    conn.close()
    return _row_to_record(row) if row else None


def _row_to_record(row) -> Dict[str, Any]:
    return {
        'job_id': row[0], 'name': row[1], 'username': row[2],
        'submitted_at': row[3], 'status': JobStatus(row[4]),
        'run_timestamp': row[5], 'start_at': row[6], 'end_at': row[7],
        'resources': row[8], 'driver_pid': row[9],
        'spec': json.loads(row[10]) if row[10] else None,
    }


def get_jobs(statuses: Optional[List[JobStatus]] = None
             ) -> List[Dict[str, Any]]:
    conn = _conn()
    q = ('SELECT job_id, name, username, submitted_at, status, '
         'run_timestamp, start_at, end_at, resources, driver_pid, spec '
         'FROM jobs')
    args: tuple = ()
    if statuses:
        q += (' WHERE status IN (' +
              ','.join('?' * len(statuses)) + ')')
        args = tuple(s.value for s in statuses)
    q += ' ORDER BY job_id DESC'
    rows = conn.execute(q, args).fetchall()
    conn.close()
    return [_row_to_record(r) for r in rows]


def set_status(job_id: int, status: JobStatus,
               driver_pid: Optional[int] = None) -> None:
    conn = _conn()
    now = time.time()
    with conn:
        sets = ['status=?']
        args: List[Any] = [status.value]
        if status == JobStatus.RUNNING:
            sets.append('start_at=COALESCE(start_at, ?)')
            args.append(now)
        if status.is_terminal():
            sets.append('end_at=COALESCE(end_at, ?)')
            args.append(now)
        if driver_pid is not None:
            sets.append('driver_pid=?')
            args.append(driver_pid)
        args.append(job_id)
        conn.execute(f'UPDATE jobs SET {", ".join(sets)} WHERE job_id=?',
                     args)
    conn.close()


def get_status(job_id: int) -> Optional[JobStatus]:
    record = get_job(job_id)
    return record['status'] if record else None


# ------------------------------------------------------------- scheduler
def _cluster_is_exclusive() -> bool:
    """TPU slices are exclusively owned by one program at a time (the
    chips are); CPU clusters (e.g. the jobs/serve controllers) multiplex
    jobs — the TPU-first degeneration of the reference's resource-slot
    scheduler (``sky/skylet/job_lib.py:194``)."""
    try:
        with open(constants.cluster_info_path(), encoding='utf-8') as f:
            info = json.load(f)
        return int(info.get('chips_per_host') or 0) > 0
    except (FileNotFoundError, ValueError, json.JSONDecodeError):
        return True


def _max_parallel_jobs() -> int:
    if _cluster_is_exclusive():
        return 1
    return int(os.environ.get('SKYTPU_AGENT_MAX_PARALLEL_JOBS', '16'))


def schedule_step() -> None:
    """Launch PENDING jobs' drivers as detached processes, oldest first,
    up to the cluster's concurrency (1 on TPU slices — strict FIFO;
    reference ``FIFOScheduler.schedule_step`` ``sky/skylet/job_lib.py:266``)."""
    with _scheduler_lock():
        slots = _max_parallel_jobs() - len(
            get_jobs([JobStatus.STARTING, JobStatus.RUNNING,
                      JobStatus.INIT]))
        if slots <= 0:
            return
        pending = get_jobs([JobStatus.PENDING])
        # ORDER BY job_id DESC -> iterate reversed for oldest-first.
        for job in list(reversed(pending))[:slots]:
            job_id = job['job_id']
            log_dir = constants.job_log_dir(job['run_timestamp'])
            os.makedirs(log_dir, exist_ok=True)
            pid = subprocess_utils.launch_daemon(
                [sys.executable, '-m', 'skypilot_tpu.agent.driver',
                 str(job_id)],
                log_path=os.path.join(log_dir, constants.DRIVER_LOG),
                env=dict(os.environ))
            set_status(job_id, JobStatus.STARTING, driver_pid=pid)


def update_status() -> None:
    """Reconcile: a dead driver with a non-terminal job means the driver
    crashed (reference ``update_job_status`` pid-liveness logic)."""
    for job in get_jobs([JobStatus.STARTING, JobStatus.RUNNING]):
        pid = job['driver_pid']
        if not subprocess_utils.pid_is_alive(pid):
            # Re-read under the truth that drivers set terminal status
            # right before exiting — avoid racing a normal exit.
            current = get_status(job['job_id'])
            if current is not None and not current.is_terminal():
                set_status(job['job_id'], JobStatus.FAILED_DRIVER)


def cancel_job(job_id: int) -> bool:
    """Kill the driver tree (drivers own the whole remote process group)."""
    job = get_job(job_id)
    if job is None:
        return False
    if job['status'].is_terminal():
        return False
    if job['driver_pid']:
        subprocess_utils.kill_process_tree(job['driver_pid'])
    set_status(job_id, JobStatus.CANCELLED)
    schedule_step()
    return True


def cancel_all() -> List[int]:
    cancelled = []
    for job in get_jobs():
        if not job['status'].is_terminal():
            if cancel_job(job['job_id']):
                cancelled.append(job['job_id'])
    return cancelled


def is_cluster_idle() -> bool:
    """No non-terminal jobs (autostop predicate,
    reference ``job_lib.is_cluster_idle`` ``sky/skylet/job_lib.py:717``)."""
    return not get_jobs([JobStatus.INIT, JobStatus.PENDING,
                         JobStatus.STARTING, JobStatus.RUNNING])


def last_activity_time() -> float:
    """Most recent of: any job's end/start/submit time; 0 if no jobs."""
    latest = 0.0
    for job in get_jobs():
        for key in ('submitted_at', 'start_at', 'end_at'):
            v = job[key]
            if v:
                latest = max(latest, v)
    return latest


def format_job_table(jobs: List[Dict[str, Any]]) -> str:
    header = f'{"ID":<4}{"NAME":<16}{"SUBMITTED":<20}{"STATUS":<14}'
    lines = [header]
    for j in jobs:
        sub = time.strftime('%Y-%m-%d %H:%M:%S',
                            time.localtime(j['submitted_at']))
        lines.append(
            f'{j["job_id"]:<4}{(j["name"] or "-")[:15]:<16}{sub:<20}'
            f'{j["status"].value:<14}')
    return '\n'.join(lines)
