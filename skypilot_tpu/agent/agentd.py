"""The head-host agent daemon (skylet equivalent).

Role of reference ``sky/skylet/skylet.py:17-33`` + ``events.py``: a tick
loop running periodic events — job scheduling, status reconciliation, and
autostop. Started detached by the provisioner's post-setup; the pidfile +
heartbeat let the client check agent liveness cheaply.
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

from skypilot_tpu.agent import autostop_lib
from skypilot_tpu.agent import constants
from skypilot_tpu.agent import job_lib


class Event:
    interval_seconds: float = 20.0

    def __init__(self) -> None:
        self._last = 0.0

    def maybe_run(self, now: float) -> None:
        # Fast test ticks shorten every event's period too.
        interval = min(self.interval_seconds, constants.agent_tick_seconds())
        if now - self._last >= interval:
            self._last = now
            try:
                self.run()
            except Exception:  # pylint: disable=broad-except
                traceback.print_exc()

    def run(self) -> None:
        raise NotImplementedError


class JobSchedulerEvent(Event):
    """Reconcile job statuses and schedule the next queued job."""
    interval_seconds = 0.0          # every tick

    def run(self) -> None:
        job_lib.update_status()
        job_lib.schedule_step()


class AutostopEvent(Event):
    """Stop/terminate the cluster when idle past the threshold
    (reference ``sky/skylet/events.py:93``)."""
    interval_seconds = 5.0

    def run(self) -> None:
        if not autostop_lib.should_autostop():
            return
        cfg = autostop_lib.get_autostop_config()
        with open(constants.cluster_info_path(), encoding='utf-8') as f:
            info = json.load(f)
        provider = info['provider_name']
        cluster_name = info['cluster_name']
        region = info['region']
        print(f'[agentd] autostop: cluster idle >= {cfg.idle_minutes}m, '
              f'{"terminating" if cfg.to_down else "stopping"} '
              f'{cluster_name}', flush=True)
        from skypilot_tpu import provision
        # Disable autostop BEFORE acting: stop_instances kills this very
        # process tree, and a stale autostop.json on the persisted node
        # would re-stop the cluster right after a restart. Re-arm if the
        # cloud call fails so a transient error doesn't permanently
        # disable autostop on an idle (billing) cluster.
        autostop_lib.set_autostop(-1)
        try:
            if cfg.to_down:
                provision.terminate_instances(provider, region,
                                              cluster_name)
            else:
                provision.stop_instances(provider, region, cluster_name)
        except Exception:
            autostop_lib.set_autostop(cfg.idle_minutes, cfg.to_down)
            raise


def main() -> None:
    agent_dir = constants.agent_dir()
    with open(constants.agentd_pid_path(), 'w', encoding='utf-8') as f:
        f.write(str(os.getpid()))
    print(f'[agentd] started in {agent_dir} (pid {os.getpid()})',
          flush=True)
    events = [JobSchedulerEvent(), AutostopEvent()]
    tick = constants.agent_tick_seconds()
    info_missing_ticks = 0
    while True:
        now = time.time()
        # cluster_info.json is rsynced before agentd starts; if it stays
        # gone the cluster was torn down under us (teardown can miss an
        # agentd whose pidfile it never saw) — exit instead of ticking
        # forever against a deleted directory, which agent_dir()'s
        # makedirs would otherwise silently recreate.
        if os.path.exists(constants.cluster_info_path()):
            info_missing_ticks = 0
        else:
            info_missing_ticks += 1
            if info_missing_ticks >= 3:
                print('[agentd] cluster_info.json gone; cluster torn down '
                      '— exiting.', flush=True)
                return
        for event in events:
            event.maybe_run(now)
        with open(constants.agentd_heartbeat_path(), 'w',
                  encoding='utf-8') as f:
            f.write(str(now))
        time.sleep(tick)


if __name__ == '__main__':
    try:
        main()
    except KeyboardInterrupt:
        sys.exit(0)
