"""Logging setup (role of reference ``sky/sky_logging.py``).

Env-tunable:
- ``SKYTPU_DEBUG=1``    -> DEBUG level + timestamps.
- ``SKYTPU_MINIMIZE_LOGGING=1`` -> WARNING level (controllers set this).
"""
from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading

_FORMAT = '%(levelname).1s %(asctime)s %(name)s:%(lineno)d] %(message)s'
_SIMPLE_FORMAT = '%(message)s'
_DATE_FORMAT = '%m-%d %H:%M:%S'

_lock = threading.Lock()
_initialized = False


def _debug_enabled() -> bool:
    return os.environ.get('SKYTPU_DEBUG', '0') == '1'


def _minimize() -> bool:
    return os.environ.get('SKYTPU_MINIMIZE_LOGGING', '0') == '1'


def _root() -> logging.Logger:
    return logging.getLogger('skytpu')


def _setup() -> None:
    global _initialized
    with _lock:
        if _initialized:
            return
        root = _root()
        root.propagate = False
        handler = logging.StreamHandler(sys.stdout)
        if _debug_enabled():
            root.setLevel(logging.DEBUG)
            handler.setFormatter(logging.Formatter(_FORMAT, _DATE_FORMAT))
        else:
            root.setLevel(logging.WARNING if _minimize() else logging.INFO)
            handler.setFormatter(logging.Formatter(_SIMPLE_FORMAT))
        root.addHandler(handler)
        _initialized = True


def init_logger(name: str) -> logging.Logger:
    _setup()
    if name.startswith('skypilot_tpu'):
        name = 'skytpu' + name[len('skypilot_tpu'):]
    elif not name.startswith('skytpu'):
        name = f'skytpu.{name}'
    return logging.getLogger(name)


@contextlib.contextmanager
def silent():
    """Temporarily raise the level to ERROR (quiet internal launches)."""
    root = _root()
    prev = root.level
    root.setLevel(logging.ERROR)
    try:
        yield
    finally:
        root.setLevel(prev)


def is_silent() -> bool:
    return _root().level >= logging.ERROR
