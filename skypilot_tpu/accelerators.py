"""Accelerator registry with first-class TPU slice topology.

The reference treats a TPU pod slice as one "node" with many IPs
(``num_ips_per_node`` hack at ``sky/backends/cloud_vm_ray_backend.py:2550``)
and keeps TPU-type knowledge scattered across ``sky/clouds/utils/gcp_utils.py``
and catalog CSVs. Here slice topology (generation, chip count, hosts,
chips/host, ICI layout) is a first-class, parsed object that every layer —
optimizer, provisioner, backend, trainer — shares.

Naming convention (same strings SkyPilot's catalog uses):
  ``tpu-v4-8``     -> v4,  8 TensorCores  = 4 chips, 1 host
  ``tpu-v5litepod-8`` / ``tpu-v5e-8`` -> v5e, 8 chips, 1 host
  ``tpu-v5p-16``   -> v5p, 16 cores = 8 chips, 2 hosts
  ``tpu-v6e-16``   -> v6e, 16 chips, 2 hosts
Generations v2/v3/v4/v5p name slices by TensorCore count; v5e/v6e by chips.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional, Tuple

from skypilot_tpu import exceptions


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    """Static facts about one TPU generation."""
    name: str                      # 'v5e'
    names_by_cores: bool           # True: v2/v3/v4/v5p; False: v5e/v6e
    cores_per_chip: int
    chips_per_host: int
    peak_bf16_tflops: float        # per chip
    hbm_gb_per_chip: float
    hbm_bw_gbps: float             # per chip
    default_runtime_version: str
    # max chips in a single slice (pod size)
    max_chips: int
    # GCE machine-type prefix used by the TPU-VM API for this gen
    accelerator_api_type: str      # value for acceleratorType, e.g. 'v5litepod'


TPU_GENERATIONS: Dict[str, TpuGeneration] = {
    'v2': TpuGeneration('v2', True, 2, 4, 23.0, 8.0, 300.0,
                        'tpu-vm-base', 512, 'v2'),
    'v3': TpuGeneration('v3', True, 2, 4, 61.0, 16.0, 450.0,
                        'tpu-vm-base', 2048, 'v3'),
    'v4': TpuGeneration('v4', True, 2, 4, 137.5, 32.0, 615.0,
                        'tpu-vm-v4-base', 8192, 'v4'),
    'v5e': TpuGeneration('v5e', False, 1, 8, 197.0, 16.0, 819.0,
                         'v2-alpha-tpuv5-lite', 256, 'v5litepod'),
    'v5p': TpuGeneration('v5p', True, 2, 4, 459.0, 95.0, 2765.0,
                         'v2-alpha-tpuv5', 17920, 'v5p'),
    'v6e': TpuGeneration('v6e', False, 1, 8, 918.0, 32.0, 1640.0,
                         'v2-alpha-tpuv6e', 256, 'v6e'),
}

# Aliases accepted in user YAML.
_GEN_ALIASES = {
    'v5litepod': 'v5e',
    'v5lite': 'v5e',
    'v5-lite': 'v5e',
    'v6litepod': 'v6e',
}

_TPU_RE = re.compile(r'^tpu[-_]?(v[0-9]+[a-z]*?(?:litepod|lite|p|e)?)[-_]([0-9]+)$',
                     re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class TpuTopology:
    """A fully-resolved TPU slice shape.

    ``num_hosts`` is first-class: the backend runs the user program on every
    host; the trainer builds its mesh from ``num_chips``.
    """
    generation: str          # 'v5e'
    num_chips: int
    num_hosts: int
    chips_per_host: int
    num_cores: int
    name: str                # canonical 'tpu-v5e-8'

    @property
    def gen(self) -> TpuGeneration:
        return TPU_GENERATIONS[self.generation]

    @property
    def is_pod(self) -> bool:
        return self.num_hosts > 1

    @property
    def peak_bf16_tflops(self) -> float:
        return self.gen.peak_bf16_tflops * self.num_chips

    @property
    def hbm_gb(self) -> float:
        return self.gen.hbm_gb_per_chip * self.num_chips

    @property
    def accelerator_type(self) -> str:
        """String for the TPU API ``acceleratorType`` field, e.g. 'v5litepod-8'."""
        gen = self.gen
        count = self.num_cores if gen.names_by_cores else self.num_chips
        return f'{gen.accelerator_api_type}-{count}'

    def mesh_shape_2d(self) -> Tuple[int, int]:
        """A (rows, cols) factorization of num_chips close to square.

        Used for default ICI mesh layout hints; XLA handles the physical
        mapping, we only need a logical factorization.
        """
        n = self.num_chips
        best = (1, n)
        r = 1
        while r * r <= n:
            if n % r == 0:
                best = (r, n // r)
            r += 1
        return best

    def __str__(self) -> str:
        return self.name


def is_tpu(accelerator_name: Optional[str]) -> bool:
    """Mirrors reference ``sky/clouds/utils/gcp_utils.py:29`` predicates."""
    if accelerator_name is None:
        return False
    return accelerator_name.lower().startswith('tpu')


def parse_tpu(accelerator_name: str) -> TpuTopology:
    """Parse 'tpu-v5e-8' / 'tpu-v5litepod-16' / 'tpu-v4-32' into a topology."""
    m = _TPU_RE.match(accelerator_name.strip())
    if not m:
        raise exceptions.InvalidResourcesError(
            f'Cannot parse TPU accelerator name {accelerator_name!r}. '
            f"Expected e.g. 'tpu-v5e-8', 'tpu-v4-8', 'tpu-v5p-16'.")
    gen_raw = m.group(1).lower()
    count = int(m.group(2))
    gen_name = _GEN_ALIASES.get(gen_raw, gen_raw)
    if gen_name not in TPU_GENERATIONS:
        raise exceptions.InvalidResourcesError(
            f'Unknown TPU generation {gen_raw!r} in {accelerator_name!r}. '
            f'Known: {sorted(TPU_GENERATIONS)}')
    gen = TPU_GENERATIONS[gen_name]
    if count <= 0:
        raise exceptions.InvalidResourcesError(
            f'TPU count must be positive: {accelerator_name!r}')
    if gen.names_by_cores:
        if count % gen.cores_per_chip != 0:
            raise exceptions.InvalidResourcesError(
                f'{accelerator_name!r}: {gen_name} slice sizes count '
                f'TensorCores and must be a multiple of {gen.cores_per_chip}.')
        num_cores = count
        num_chips = count // gen.cores_per_chip
    else:
        num_chips = count
        num_cores = count * gen.cores_per_chip
    if num_chips > gen.max_chips:
        raise exceptions.InvalidResourcesError(
            f'{accelerator_name!r} exceeds the max pod size for {gen_name} '
            f'({gen.max_chips} chips).')
    # Valid slice shapes: sub-host slices must evenly divide a host; pod
    # slices must be whole hosts (otherwise num_hosts would be inconsistent
    # and the backend would under-provision the gang).
    if num_chips < gen.chips_per_host:
        if gen.chips_per_host % num_chips != 0:
            raise exceptions.InvalidResourcesError(
                f'{accelerator_name!r}: sub-host slice size must divide '
                f'{gen.chips_per_host} chips/host.')
    elif num_chips % gen.chips_per_host != 0:
        raise exceptions.InvalidResourcesError(
            f'{accelerator_name!r}: slice must be a whole number of hosts '
            f'({gen.chips_per_host} chips/host for {gen_name}).')
    # Hosts: full hosts for slices >= one host; sub-host slices (e.g.
    # v5e-1, v5e-4) run on one shared host.
    num_hosts = max(1, num_chips // gen.chips_per_host)
    chips_per_host = min(num_chips, gen.chips_per_host)
    canonical = f'tpu-{gen_name}-{count}'
    return TpuTopology(generation=gen_name, num_chips=num_chips,
                       num_hosts=num_hosts, chips_per_host=chips_per_host,
                       num_cores=num_cores, name=canonical)


# --- GPU registry (for optimizer comparisons; reference: accelerator_registry)
_CANONICAL_GPUS = {
    'a100': 'A100', 'a100-80gb': 'A100-80GB', 'h100': 'H100',
    'v100': 'V100', 't4': 'T4', 'l4': 'L4', 'p4': 'P4', 'k80': 'K80',
    'a10g': 'A10G', 'l40s': 'L40S',
}


def canonicalize_accelerator_name(name: str) -> str:
    """Canonical accelerator name: TPUs get canonical slice names, GPUs a
    fixed capitalization. Unknown names pass through unchanged (catalog will
    reject them at feasibility time), mirroring the reference's permissive
    registry (``sky/utils/accelerator_registry.py``)."""
    if is_tpu(name):
        return parse_tpu(name).name
    return _CANONICAL_GPUS.get(name.lower(), name)
