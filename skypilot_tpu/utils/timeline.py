"""Timeline profiling: chrome://tracing event capture for control-plane
operations.

Role of reference ``sky/utils/timeline.py`` (Event context manager +
``@event`` decorator, JSON trace written per run): instrument the slow
stages (optimize, provision, setup, sync, exec) so "why was my launch
slow" is answerable from a trace. Enabled by pointing
``SKYTPU_TIMELINE_FILE`` at a path; events are buffered in-process and
flushed atexit (and on save()).
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_events: List[Dict[str, Any]] = []
_lock = threading.Lock()
_registered = False


def enabled() -> bool:
    return bool(os.environ.get('SKYTPU_TIMELINE_FILE'))


class Event:
    """``with Event('provision'):`` records a complete (ph=X) slice."""

    def __init__(self, name: str, **args: Any):
        self.name = name
        self.args = args
        self._t0 = 0.0

    def __enter__(self) -> 'Event':
        self._t0 = time.time()
        return self

    def __exit__(self, *exc) -> None:
        if not enabled():
            return
        global _registered
        ev = {
            'name': self.name,
            'ph': 'X',                            # complete event
            'ts': self._t0 * 1e6,                 # microseconds
            'dur': (time.time() - self._t0) * 1e6,
            'pid': os.getpid(),
            'tid': threading.get_ident() % 10000,
        }
        if self.args:
            ev['args'] = {k: str(v) for k, v in self.args.items()}
        with _lock:
            _events.append(ev)
            if not _registered:
                atexit.register(save)
                _registered = True


def event(name_or_fn=None):
    """Decorator: ``@timeline.event`` or ``@timeline.event('name')``."""

    def deco(fn: Callable, name: Optional[str] = None):
        label = name or f'{fn.__module__}.{fn.__qualname__}'

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with Event(label):
                return fn(*a, **kw)
        return wrapper

    if callable(name_or_fn):
        return deco(name_or_fn)
    return lambda fn: deco(fn, name_or_fn)


def write_trace(path: str, events: List[Dict[str, Any]]) -> str:
    """THE chrome-trace writer: dump ``events`` (chrome trace-event
    dicts) as a ``chrome://tracing``-loadable JSON file. Shared by
    :func:`save` (control-plane events) and
    ``skypilot_tpu.telemetry.tracing.export_chrome_trace``
    (per-request engine timelines)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    return path


def save(path: Optional[str] = None) -> Optional[str]:
    """Write buffered events as a Chrome trace; returns the path."""
    path = path or os.environ.get('SKYTPU_TIMELINE_FILE')
    if not path:
        return None
    with _lock:
        events = list(_events)
    if not events:
        return None
    return write_trace(path, events)


def clear() -> None:
    with _lock:
        _events.clear()
