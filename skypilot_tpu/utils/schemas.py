"""JSON schemas for task YAML / resources / config validation.

Functional parity with reference ``sky/utils/schemas.py`` (987 LoC of JSON
schema). We validate with ``jsonschema`` at YAML load; the dataclasses also
validate, so the schema focuses on early, readable errors.
"""
from __future__ import annotations

from typing import Any, Dict

import jsonschema

from skypilot_tpu import exceptions


def _resources_fields() -> Dict[str, Any]:
    return {
        'cloud': {'type': 'string'},
        'instance_type': {'type': 'string'},
        'accelerators': {
            'anyOf': [{'type': 'string'},
                      {'type': 'object',
                       'additionalProperties': {'type': 'integer'}}]
        },
        'accelerator_args': {'type': 'object'},
        'cpus': {'anyOf': [{'type': 'string'}, {'type': 'number'}]},
        'memory': {'anyOf': [{'type': 'string'}, {'type': 'number'}]},
        'use_spot': {'type': 'boolean'},
        'spot_recovery': {'type': 'string'},
        'job_recovery': {'anyOf': [{'type': 'string'}, {'type': 'object'}]},
        'region': {'type': 'string'},
        'zone': {'type': 'string'},
        'image_id': {'type': 'string'},
        'disk_size': {'type': 'integer'},
        'disk_tier': {'type': 'string',
                      'enum': ['low', 'medium', 'high', 'best']},
        'ports': {'type': 'array',
                  'items': {'anyOf': [{'type': 'integer'},
                                      {'type': 'string'}]}},
        'labels': {'type': 'object',
                   'additionalProperties': {'type': 'string'}},
    }


RESOURCES_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        **_resources_fields(),
        'any_of': {'type': 'array',
                   'items': {'type': 'object',
                             'properties': _resources_fields(),
                             'additionalProperties': False}},
        'ordered': {'type': 'array',
                    'items': {'type': 'object',
                              'properties': _resources_fields(),
                              'additionalProperties': False}},
    },
}

STORAGE_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'name': {'type': 'string'},
        'source': {'anyOf': [{'type': 'string'},
                             {'type': 'array', 'items': {'type': 'string'}}]},
        'store': {'type': 'string', 'enum': ['gcs', 's3', 'r2', 'azure']},
        'mode': {'type': 'string', 'enum': ['MOUNT', 'COPY',
                                            'mount', 'copy']},
        'persistent': {'type': 'boolean'},
    },
}

SERVICE_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'required': ['readiness_probe'],
    'properties': {
        'readiness_probe': {
            'anyOf': [
                {'type': 'string'},
                {'type': 'object',
                 'additionalProperties': False,
                 'properties': {
                     'path': {'type': 'string'},
                     'initial_delay_seconds': {'type': 'number'},
                     'timeout_seconds': {'type': 'number'},
                     'post_data': {'anyOf': [{'type': 'string'},
                                             {'type': 'object'}]},
                 }},
            ]
        },
        'replica_policy': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'min_replicas': {'type': 'integer', 'minimum': 0},
                'max_replicas': {'type': 'integer', 'minimum': 0},
                'target_qps_per_replica': {'type': 'number'},
                'upscale_delay_seconds': {'type': 'number'},
                'downscale_delay_seconds': {'type': 'number'},
                'base_ondemand_fallback_replicas': {'type': 'integer'},
                'dynamic_ondemand_fallback': {'type': 'boolean'},
                # Forecast-aware autoscaling (serve/forecaster.py):
                # pre-scale ahead of ramps by the learned provisioning
                # lead time. `forecast: true` takes the defaults; the
                # object form tunes the forecaster.
                'forecast': {
                    'anyOf': [
                        {'type': 'boolean'},
                        {'type': 'object',
                         'additionalProperties': False,
                         'properties': {
                             'bucket_seconds': {'type': 'number'},
                             'season_seconds': {'type': 'number'},
                             'horizon_seconds': {'type': 'number'},
                         }},
                    ]
                },
            },
        },
        'replicas': {'type': 'integer', 'minimum': 0},
        'port': {'type': 'integer', 'minimum': 1, 'maximum': 65535},
        'load_balancing_policy': {
            'type': 'string',
            'enum': ['round_robin', 'least_load', 'queue_depth',
                     'phase_aware'],
        },
        'tls': {
            'type': 'object',
            'additionalProperties': False,
            'required': ['certfile', 'keyfile'],
            'properties': {
                'certfile': {'type': 'string'},
                'keyfile': {'type': 'string'},
            },
        },
        # Disaggregated prefill/decode serving: dedicate this many
        # replicas to each phase (prefill workers hand finished KV to
        # decode workers over /kv/ingest; remaining replicas stay
        # colocated). Roles reach replicas as SKYTPU_ROLE launch env;
        # pair with load_balancing_policy: phase_aware.
        'disaggregation': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'prefill_replicas': {'type': 'integer', 'minimum': 0},
                'decode_replicas': {'type': 'integer', 'minimum': 0},
            },
        },
        # Multi-chip replica parallelism: adaptive picks (tp, dp) per
        # model size and SLO tier (serve/placement.py); fixed pins an
        # explicit shape. Exported to replicas as SKYTPU_TP/SKYTPU_DP.
        'parallelism': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'policy': {'type': 'string',
                           'enum': ['adaptive', 'fixed']},
                'chips_per_replica': {'type': 'integer', 'minimum': 1},
                'slo_tier': {'type': 'string',
                             'enum': ['latency', 'throughput']},
                'model': {'type': 'string'},
                'quantize': {'type': 'string', 'enum': ['int8']},
                'hbm_per_chip_gb': {'type': 'number'},
                'tp': {'type': 'integer', 'minimum': 1},
                'dp': {'type': 'integer', 'minimum': 1},
                # Multi-host gang serving: processes per replica. The
                # replica becomes a gang that launches, drains,
                # checkpoints, and dies together (serve/gang.py);
                # rank 0 is its one routable endpoint.
                'hosts': {'type': 'integer', 'minimum': 1},
            },
        },
        # Per-tier service-level objectives: tier name -> objectives.
        # The controller's fleet aggregator evaluates 5m/1h burn rates
        # against these (telemetry/fleet.py) and exports
        # Multi-tenant LoRA serving (``adapters:`` block,
        # inference/adapters.py): every replica carries a
        # device-resident adapter bank of ``slots`` rows at ``rank``,
        # loading named adapters on demand from ``dir`` (LRU evict
        # under pressure). Requests pick an adapter by name; slots
        # re-upload bank rows, never recompile.
        'adapters': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'slots': {'type': 'integer', 'minimum': 1},
                'dir': {'type': 'string'},
                'rank': {'type': 'integer', 'minimum': 1},
            },
        },
        # skytpu_slo_burn_rate{tier,window} / skytpu_slo_attainment.
        'slos': {
            'type': 'object',
            'additionalProperties': {
                'type': 'object',
                'additionalProperties': False,
                'properties': {
                    'ttft_ms': {'type': 'number',
                                'exclusiveMinimum': 0},
                    'tpot_ms': {'type': 'number',
                                'exclusiveMinimum': 0},
                    'shed_rate': {'type': 'number',
                                  'exclusiveMinimum': 0,
                                  'maximum': 1},
                    'target': {'type': 'number',
                               'exclusiveMinimum': 0,
                               'exclusiveMaximum': 1},
                },
            },
        },
    },
}

TASK_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'name': {'type': 'string'},
        'workdir': {'type': 'string'},
        'num_nodes': {'type': 'integer', 'minimum': 1},
        'resources': RESOURCES_SCHEMA,
        'envs': {'type': 'object'},
        'file_mounts': {
            'type': 'object',
            'additionalProperties': {
                'anyOf': [{'type': 'string'}, STORAGE_SCHEMA]
            },
        },
        'setup': {'type': 'string'},
        'run': {'type': 'string'},
        'service': SERVICE_SCHEMA,
    },
}

CONFIG_SCHEMA: Dict[str, Any] = {
    'type': 'object',
    'additionalProperties': False,
    'properties': {
        'jobs': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'controller': {
                    'type': 'object',
                    'properties': {'resources': RESOURCES_SCHEMA},
                },
            },
        },
        'serve': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'controller': {
                    'type': 'object',
                    'properties': {'resources': RESOURCES_SCHEMA},
                },
            },
        },
        'gcp': {
            'type': 'object',
            'additionalProperties': False,
            'properties': {
                'project_id': {'type': 'string'},
                'vpc_name': {'type': 'string'},
                'use_internal_ips': {'type': 'boolean'},
                'ssh_proxy_command': {'type': 'string'},
                'labels': {'type': 'object'},
                'reserved': {'type': 'boolean'},
                'queued_resource_timeout_seconds': {'type': 'number'},
            },
        },
        'local': {'type': 'object'},
        'admin_policy': {'type': 'string'},
        'allowed_clouds': {'type': 'array', 'items': {'type': 'string'}},
    },
}


# Compiled-validator cache keyed by schema object identity (the
# schemas in this module are module-level constants, so identity is
# stable). ``jsonschema.validate()`` re-checks the SCHEMA itself and
# rebuilds the validator on every call — ~150 ms per task config,
# paid on every launch; a 1000-replica scale-up spent 80+ seconds in
# it. Building the validator once drops a validate() to ~1 ms.
_VALIDATOR_CACHE: Dict[int, Any] = {}


def _validator_for(schema: Dict[str, Any]):
    key = id(schema)
    validator = _VALIDATOR_CACHE.get(key)
    if validator is None:
        cls = jsonschema.validators.validator_for(schema)
        cls.check_schema(schema)
        validator = cls(schema)
        _VALIDATOR_CACHE[key] = validator
    return validator


def validate(config: Dict[str, Any], schema: Dict[str, Any],
             what: str = 'task') -> None:
    error = jsonschema.exceptions.best_match(
        _validator_for(schema).iter_errors(config))
    if error is not None:
        path = '.'.join(str(p) for p in error.absolute_path) or '<root>'
        raise exceptions.InvalidTaskError(
            f'Invalid {what} YAML at {path}: {error.message}') from None
