"""Containerized-run helper for ``image_id: docker:<image>`` tasks.

One wrap implementation shared by the driver (run command) and the
backend (setup command) so both phases execute in the SAME image with
the same mounts — the reference's docker runtime runs setup inside the
container too (``sky/backends/local_docker_backend.py``).
"""
from __future__ import annotations

import shlex
from typing import Dict, Optional

DOCKER_PREFIX = 'docker:'


def docker_image_of(image_id: Optional[str]) -> Optional[str]:
    """The container image when ``image_id`` selects the docker runtime,
    else None (a VM image or unset)."""
    if image_id and image_id.startswith(DOCKER_PREFIX):
        return image_id[len(DOCKER_PREFIX):]
    return None


def wrap_in_docker(cmd: str, image: str, env: Dict[str, str],
                   privileged: bool = True) -> str:
    """Wrap ``cmd`` to run inside ``image`` on the host.

    - ``--privileged``: Cloud TPU containers need the accelerator
      devices (/dev/accel*, vfio); control-plane wraps may pass False.
    - host network + $HOME bind-mounted and exported so synced files,
      the workdir cd, and the rank/coordinator env contract behave the
      same as a bare-host run.
    """
    env_flags = ' '.join(f'-e {shlex.quote(k)}' for k in env)
    priv = '--privileged ' if privileged else ''
    return (f'docker run --rm --net=host {priv}{env_flags} '
            f'-e HOME="$HOME" '
            f'-v "$HOME":"$HOME" -w "$HOME" '
            f'{shlex.quote(image)} '
            f'bash -c {shlex.quote(cmd)}')
