"""The sanctioned device->host synchronization points.

Every device->host readback in the compute layer (``inference/``,
``models/``, ``ops/``, ``train/``) goes through :func:`host_sync` (or
:func:`host_block` when only a completion barrier is needed, not a
copy). Two reasons:

- **Auditability**: ``graftcheck``'s AST lint (rule GC202) flags any
  other host-sync spelling (bare ``np.asarray``, ``.item()``,
  ``jax.device_get``, implicit ``float()``) in compute files, and the
  runtime jaxpr auditor (``skypilot_tpu.analysis.jaxpr_audit``) counts
  transfers made outside these helpers as violations — an accidental
  sync inside the decode hot loop becomes a failing test, not a silent
  5.5s TTFT regression.
- **Explicitness**: a call spelled ``host_sync(x)`` tells the reader
  the host is about to stall on device completion (100 ms+ through a
  remote PJRT tunnel); ``np.asarray(x)`` says nothing.

The helpers are dependency-light: jax is imported lazily so the
orchestration layer can import ``skypilot_tpu.utils`` without the
compute extra installed.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any

# Audit hook: the jaxpr auditor installs a recorder here while it
# drives an engine step; ``host_sync``/``host_block`` announce
# themselves so the interceptor can tell a sanctioned readback from an
# accidental one. Thread-local because the serve layer runs engines
# from a dedicated engine thread while tests drive audits from another.
_tls = threading.local()


@contextlib.contextmanager
def _sanctioned():
    prev = getattr(_tls, 'sanctioned', 0)
    _tls.sanctioned = prev + 1
    try:
        yield
    finally:
        _tls.sanctioned = prev


def in_sanctioned_sync() -> bool:
    """True while the current thread is inside host_sync/host_block —
    the jaxpr auditor's transfer interceptor checks this."""
    return getattr(_tls, 'sanctioned', 0) > 0


def host_sync(tree: Any) -> Any:
    """Copy a device array (or pytree of them) to host numpy, blocking
    until the device values are ready.

    This is THE device->host readback point for the compute layer: the
    engines' lagged async-pipeline readback, trainer metrics, and
    checkpoint saves all come through here. Keeping the spelling unique
    lets graftcheck prove the decode hot loop performs no OTHER host
    transfers."""
    with _sanctioned():
        try:
            import jax
        except ImportError:           # host-only tree (tests, tooling)
            import numpy as np
            if isinstance(tree, dict):
                return {k: np.asarray(v) for k, v in tree.items()}
            if isinstance(tree, (list, tuple)):
                return type(tree)(np.asarray(v) for v in tree)
            return np.asarray(tree)
        return jax.device_get(tree)


def host_scalars(tree: Any) -> Any:
    """host_sync + unwrap: every 0-d array in ``tree`` becomes a plain
    Python scalar (the trainer's metrics-logging path — ``float(v)`` on
    a device value is the implicit-sync spelling GC202 bans)."""
    tree = host_sync(tree)

    def scalar(x):
        return x.item() if hasattr(x, 'item') and getattr(
            x, 'ndim', None) == 0 else x
    try:
        import jax
        return jax.tree.map(scalar, tree)
    except ImportError:
        if isinstance(tree, dict):
            return {k: scalar(v) for k, v in tree.items()}
        return scalar(tree)


def host_block(tree: Any) -> Any:
    """Block until every array in ``tree`` has been computed, WITHOUT
    copying it to host (the donation barrier in quantize_params, bench
    timing fences). Returns ``tree``."""
    with _sanctioned():
        import jax
        return jax.block_until_ready(tree)


def device_upload(tree: Any, sharding: Any = None) -> Any:
    """The sanctioned host->device upload for compute-layer step paths.

    Thin wrapper over ``jax.device_put`` whose NAME carries the
    contract: the operands are freshly built HOST arrays (numpy) —
    never committed device arrays — so the call is a pure h2d copy and
    can NEVER trigger an implicit cross-mesh reshard of device state.
    graftcheck GC113 bans bare ``jax.device_put`` inside ``inference/``
    step functions; placement (construction-time sharding of params and
    caches) stays on ``jax.device_put`` in the sanctioned helpers
    (``prepare_params``, engine ``__init__``).

    ``sharding`` (optional) pre-partitions the upload — matching the
    consuming program's ``in_shardings`` so steady state never inserts
    a resharding collective between upload and use."""
    import jax
    if sharding is None:
        return jax.device_put(tree)
    return jax.device_put(tree, sharding)
