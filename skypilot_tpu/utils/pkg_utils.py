"""Runtime package shipping: hash-addressed source archive synced to
cluster hosts so the remote agent runs the SAME code as the client.

Role of reference ``sky/backends/wheel_utils.py`` (``build_sky_wheel``
``:140``: build a wheel locally, hash-addressed, rsync to clusters so the
remote skylet matches the client). TPU-first simplification: Python can
import straight from a zip (zipimport), so the artifact is a source zip
of ``skypilot_tpu`` put on every host's PYTHONPATH via ``~/.bashrc`` —
no pip install on the host, and a content-hash version marker lets the
bootstrap detect skew and restart the agent with the new code.

The local provisioner skips all of this (LocalProcessRunner already
injects the repo into PYTHONPATH).
"""
from __future__ import annotations

import hashlib
import os
import zipfile
from typing import Tuple

import filelock

_REMOTE_DIR = '~/.skytpu_runtime'
_REMOTE_ZIP = f'{_REMOTE_DIR}/skypilot_tpu.zip'
_SHIP_EXTENSIONS = ('.py', '.csv', '.json')


def _package_root() -> str:
    import skypilot_tpu
    return os.path.dirname(os.path.abspath(skypilot_tpu.__file__))


def _iter_package_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        # Sorted: the content hash must not depend on filesystem
        # directory order, or identical code hashes differently across
        # client machines and spuriously restarts remote agents.
        dirnames[:] = sorted(d for d in dirnames if d != '__pycache__')
        for fname in sorted(filenames):
            if fname.endswith(_SHIP_EXTENSIONS):
                full = os.path.join(dirpath, fname)
                rel = os.path.join('skypilot_tpu',
                                   os.path.relpath(full, root))
                yield full, rel


def package_hash() -> str:
    """Content hash over every shipped file (path + bytes)."""
    h = hashlib.sha256()
    root = _package_root()
    for full, rel in _iter_package_files(root):
        h.update(rel.encode())
        with open(full, 'rb') as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def build_package() -> Tuple[str, str]:
    """Build (or reuse) the hash-addressed source zip.

    Returns (zip_path, content_hash)."""
    digest = package_hash()
    out_dir = os.path.expanduser(
        os.environ.get('SKYTPU_WHEEL_DIR', '~/.skytpu/wheels'))
    os.makedirs(out_dir, exist_ok=True)
    zip_path = os.path.join(out_dir, f'skypilot_tpu-{digest}.zip')
    lock = filelock.FileLock(zip_path + '.lock')
    with lock:
        if os.path.exists(zip_path):
            return zip_path, digest
        tmp = zip_path + '.tmp'
        root = _package_root()
        with zipfile.ZipFile(tmp, 'w', zipfile.ZIP_DEFLATED) as zf:
            for full, rel in _iter_package_files(root):
                zf.write(full, rel)
        os.replace(tmp, zip_path)
    return zip_path, digest


# Prefix the SSH runner applies to EVERY remote command: correctness
# does not depend on shell init files (stock images' ~/.bashrc returns
# early for non-interactive shells, so an appended export there would
# never run). Harmless when the zip is absent.
RUNTIME_PYTHONPATH_PREFIX = (
    'export PYTHONPATH="$HOME/.skytpu_runtime/skypilot_tpu.zip'
    ':${PYTHONPATH:-}"; ')


def remote_setup_command(digest: str) -> str:
    """Shell snippet run on each host AFTER the zip is rsynced to
    ``{_REMOTE_ZIP}``: records the version (skew kills the running
    agentd so the bootstrap restarts it on the new code) and adds the
    PYTHONPATH export to ~/.profile for interactive debugging — the
    load-bearing path is RUNTIME_PYTHONPATH_PREFIX in the SSH runner."""
    return (
        f'mkdir -p {_REMOTE_DIR}; '
        'grep -q skytpu_runtime ~/.profile 2>/dev/null || '
        f'echo \'export PYTHONPATH="$HOME/.skytpu_runtime/'
        f'skypilot_tpu.zip:$PYTHONPATH"\' >> ~/.profile; '
        f'if [ -f {_REMOTE_DIR}/version ] && '
        f'[ "$(cat {_REMOTE_DIR}/version)" != "{digest}" ] && '
        '[ -f ~/.skytpu_agent/agentd.pid ]; then '
        'p="$(cat ~/.skytpu_agent/agentd.pid)"; '
        'kill "$p" 2>/dev/null || true; '
        # Wait for the old agent to actually exit: the restart snippet
        # checks liveness via the pid file, and a still-dying agent
        # would read as "already running" — leaving NO agent after it
        # exits.
        'for _ in $(seq 50); do '
        'kill -0 "$p" 2>/dev/null || break; sleep 0.2; done; '
        'fi; '
        f'echo "{digest}" > {_REMOTE_DIR}/version'
    )


def remote_zip_path() -> str:
    return _REMOTE_ZIP
