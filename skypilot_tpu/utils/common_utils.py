"""Small shared helpers (role of reference ``sky/utils/common_utils.py``)."""
from __future__ import annotations

import functools
import getpass
import hashlib
import json
import os
import re
import socket
import time
import uuid
from typing import Any, Dict, Optional

_USER_HASH_FILE = None  # resolved lazily against the state dir
_USER_HASH_LENGTH = 8

_CLUSTER_NAME_RE = re.compile(r'^[a-z]([-a-z0-9]{0,62}[a-z0-9])?$')


def state_dir() -> str:
    """Client-side state directory (SQLite DB, keys, generated files)."""
    d = os.environ.get('SKYTPU_STATE_DIR',
                       os.path.expanduser('~/.skytpu'))
    os.makedirs(d, exist_ok=True)
    return d


def get_user_hash() -> str:
    """Stable per-user hash; persisted so controllers can impersonate the
    submitting user (reference: ``common_utils.get_user_hash``)."""
    env = os.environ.get('SKYTPU_USER_ID')
    if env:
        return env
    path = os.path.join(state_dir(), 'user_hash')
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            h = f.read().strip()
        if h:
            return h
    h = hashlib.md5(
        f'{getpass.getuser()}+{uuid.getnode()}'.encode()).hexdigest()
    h = h[:_USER_HASH_LENGTH]
    with open(path, 'w', encoding='utf-8') as f:
        f.write(h)
    return h


def get_cleaned_username() -> str:
    try:
        return re.sub(r'[^a-z0-9-]', '-', getpass.getuser().lower())
    except (OSError, KeyError):   # no passwd entry / env in containers
        return 'unknown'


def check_cluster_name_is_valid(name: Optional[str]) -> None:
    if name is None:
        return
    if not _CLUSTER_NAME_RE.match(name):
        raise ValueError(
            f'Cluster name {name!r} is invalid: must match '
            f'{_CLUSTER_NAME_RE.pattern} (lowercase RFC1035, <=64 chars).')


def generate_cluster_name(prefix: str = 'sky') -> str:
    return f'{prefix}-{get_cleaned_username()}-{uuid.uuid4().hex[:4]}'


def make_run_timestamp() -> str:
    # time.strftime has no %f; append microseconds by hand so two
    # submissions in the same second get distinct log dirs.
    now = time.time()
    micros = int((now % 1) * 1e6)
    return ('sky-' + time.strftime('%Y-%m-%d-%H-%M-%S',
                                   time.localtime(now)) + f'-{micros:06d}')


def read_last_n_lines(path: str, n: int) -> str:
    try:
        with open(path, 'r', encoding='utf-8', errors='replace') as f:
            return ''.join(f.readlines()[-n:])
    except FileNotFoundError:
        return ''


def dump_json(value: Any) -> str:
    return json.dumps(value, sort_keys=True, separators=(',', ':'))


def load_json(s: Optional[str]) -> Any:
    if not s:
        return None
    return json.loads(s)


def find_free_port(start: int = 10000, exclude=()) -> int:
    """Find a free TCP port on localhost (local provisioner, serve LB).

    ``exclude``: ports already allocated but possibly not yet bound
    (e.g. recorded in a state DB for a process that starts later) —
    a bind test alone cannot see those."""
    for port in range(start, start + 2000):
        if port in exclude:
            continue
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            try:
                s.bind(('127.0.0.1', port))
                return port
            except OSError:
                continue
    raise RuntimeError('No free port found')


def retry(n: int = 3, delay: float = 1.0, backoff: float = 2.0,
          exceptions=(Exception,)):
    """Retry decorator with exponential backoff."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            d = delay
            for i in range(n):
                try:
                    return fn(*args, **kwargs)
                except exceptions:
                    if i == n - 1:
                        raise
                    time.sleep(d)
                    d *= backoff
        return wrapper
    return deco


def format_float(x: float, precision: int = 2) -> str:
    if x >= 1000:
        return f'{x:,.0f}'
    return f'{x:.{precision}f}'


def fields_to_dict(obj: Any, fields) -> Dict[str, Any]:
    return {f: getattr(obj, f) for f in fields}
