"""Subprocess helpers: parallel map, process-tree kill.

Role of reference ``sky/utils/subprocess_utils.py`` +
``sky/skylet/subprocess_daemon.py`` (orphan reaping is handled by the agent
driver holding the process group instead of a separate daemon).
"""
from __future__ import annotations

import concurrent.futures
import os
import signal
import subprocess
import time
from typing import Any, Callable, List, Optional, Sequence

import psutil


def get_parallel_threads(requested: Optional[int] = None) -> int:
    cpu = os.cpu_count() or 4
    n = requested if requested is not None else max(4, cpu - 1)
    return max(1, n)


def run_in_parallel(fn: Callable, args: Sequence[Any],
                    num_threads: Optional[int] = None) -> List[Any]:
    """Map fn over args with a thread pool; preserves order, propagates the
    first exception."""
    args = list(args)
    if not args:
        return []
    if len(args) == 1:
        return [fn(args[0])]
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=get_parallel_threads(num_threads)) as pool:
        return list(pool.map(fn, args))


def kill_process_tree(pid: int, include_parent: bool = True,
                      sig: int = signal.SIGTERM,
                      timeout: float = 5.0) -> None:
    """TERM then KILL the whole tree rooted at pid."""
    try:
        parent = psutil.Process(pid)
    except psutil.NoSuchProcess:
        return
    procs = parent.children(recursive=True)
    if include_parent:
        procs.append(parent)
    for p in procs:
        try:
            p.send_signal(sig)
        except psutil.NoSuchProcess:
            pass
    _, alive = psutil.wait_procs(procs, timeout=timeout)
    for p in alive:
        try:
            p.kill()
        except psutil.NoSuchProcess:
            pass


def kill_children_processes(parent_pid: Optional[int] = None) -> None:
    kill_process_tree(parent_pid or os.getpid(), include_parent=False)


def pid_is_alive(pid: Optional[int]) -> bool:
    if pid is None or pid <= 0:
        return False
    try:
        proc = psutil.Process(pid)
        return proc.status() != psutil.STATUS_ZOMBIE
    except psutil.NoSuchProcess:
        return False


def launch_daemon(cmd: List[str], log_path: str,
                  env: Optional[dict] = None,
                  cwd: Optional[str] = None) -> int:
    """Start a detached daemon process (own session), stdout+stderr to
    log_path. Returns pid."""
    os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
    with open(log_path, 'ab') as log:
        proc = subprocess.Popen(
            cmd,
            stdout=log,
            stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,
            env=env,
            cwd=cwd,
        )
    return proc.pid


def wait_for(predicate: Callable[[], bool], timeout: float,
             interval: float = 0.1) -> bool:
    """Poll predicate until true or timeout. Returns final value."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()
