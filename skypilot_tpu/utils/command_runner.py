"""Command runners: uniform run/rsync over local processes or SSH.

Role of reference ``sky/utils/command_runner.py:168`` (``SSHCommandRunner``
``:426``). Two implementations:

- :class:`LocalProcessRunner` — a "node" is a directory on this machine
  (HOME is pointed there), used by the local provisioner so the whole
  orchestration stack runs hermetically in tests and on dev boxes.
- :class:`SSHCommandRunner` — OpenSSH with ControlMaster multiplexing +
  rsync, used for real TPU-VM hosts.
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
import sys
import tempfile
import threading
from typing import Dict, List, Optional, Tuple, Union

from skypilot_tpu import exceptions
from skypilot_tpu import tpu_logging

logger = tpu_logging.init_logger(__name__)

RunResult = Union[int, Tuple[int, str, str]]


def _env_prefix(env: Optional[Dict[str, str]]) -> str:
    if not env:
        return ''
    exports = ' && '.join(
        f'export {k}={shlex.quote(str(v))}' for k, v in env.items())
    return exports + ' && '


class CommandRunner:
    """Abstract runner for one node."""

    # Interpreter to use for skypilot_tpu commands ON the node. Local
    # nodes share this process's interpreter; remote hosts must not see
    # the client's sys.executable (venv paths don't exist there).
    remote_python: str = 'python3'

    def __init__(self, node_id: str):
        self.node_id = node_id

    def run(self,
            cmd: str,
            *,
            env: Optional[Dict[str, str]] = None,
            log_path: str = os.devnull,
            stream_logs: bool = False,
            require_outputs: bool = False,
            cwd: Optional[str] = None,
            timeout: Optional[float] = None) -> RunResult:
        raise NotImplementedError

    def rsync(self, source: str, target: str, *, up: bool) -> None:
        """Sync source->target. ``up=True``: local source to node target."""
        raise NotImplementedError

    def popen_interactive(self, cmd: str) -> 'subprocess.Popen':
        """Start ``cmd`` on the node with stdin/stdout attached as text
        pipes (stderr discarded) — the transport for persistent RPC
        channels (``agent/channel.py``)."""
        raise NotImplementedError

    @property
    def channel_key(self) -> tuple:
        """Identity for caching persistent channels per node."""
        return (type(self).__name__, self.node_id)

    def check_run(self, cmd: str, **kwargs) -> str:
        """Run; raise CommandError on failure; return stdout."""
        rc, stdout, stderr = self.run(cmd, require_outputs=True, **kwargs)
        if rc != 0:
            raise exceptions.CommandError(rc, cmd, stderr[-2000:])
        return stdout

    @staticmethod
    def _popen(args: List[str], *, shell: bool, env, cwd, log_path: str,
               stream_logs: bool, require_outputs: bool,
               timeout: Optional[float]) -> RunResult:
        """Run, teeing output to the log file (and stdout when
        ``stream_logs``) line-by-line as it is produced — tail/follow
        consumers must see output while the command is still running."""
        os.makedirs(os.path.dirname(os.path.abspath(log_path)) or '.',
                    exist_ok=True)
        chunks: Dict[str, List[str]] = {'out': [], 'err': []}
        with open(log_path, 'a', encoding='utf-8') as log_file:
            proc = subprocess.Popen(
                args, shell=shell, env=env, cwd=cwd,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            write_lock = threading.Lock()

            def pump(stream, key: str) -> None:
                for line in iter(stream.readline, ''):
                    with write_lock:
                        chunks[key].append(line)
                        try:
                            log_file.write(line)
                            log_file.flush()
                        except ValueError:
                            # A backgrounded grandchild can hold the pipe
                            # open past join(timeout); the log file is
                            # closed by then.
                            pass
                    if stream_logs:
                        print(line, end='', flush=True)
                stream.close()

            pumps = [
                threading.Thread(target=pump, args=(proc.stdout, 'out'),
                                 daemon=True),
                threading.Thread(target=pump, args=(proc.stderr, 'err'),
                                 daemon=True),
            ]
            for t in pumps:
                t.start()
            timed_out = False
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                timed_out = True
                proc.kill()
                proc.wait()
            for t in pumps:
                t.join(timeout=5)
            with write_lock:
                out = ''.join(chunks['out'])
                err = ''.join(chunks['err'])
        if timed_out:
            return (124, out, err + '\n[timeout]') if require_outputs \
                else 124
        rc = proc.returncode
        if require_outputs:
            return rc, out, err
        return rc


class LocalProcessRunner(CommandRunner):
    """Runs commands as local subprocesses with HOME pointed at the node
    dir, so per-node files (``~/.skytpu_agent``, workdir, logs) are
    isolated exactly like distinct VMs."""

    remote_python = sys.executable

    def __init__(self, node_id: str, node_dir: str):
        super().__init__(node_id)
        self.node_dir = os.path.abspath(node_dir)
        os.makedirs(self.node_dir, exist_ok=True)

    def _node_env(self, extra: Optional[Dict[str, str]]) -> Dict[str, str]:
        env = dict(os.environ)
        env['HOME'] = self.node_dir
        env['SKYTPU_AGENT_DIR'] = os.path.join(self.node_dir, '.skytpu_agent')
        # The "VM" must see the same skypilot_tpu package as the client
        # (real hosts get it via the runtime sync; local nodes via path).
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        prev_pp = env.get('PYTHONPATH', '')
        if repo_root not in prev_pp.split(os.pathsep):
            env['PYTHONPATH'] = (f'{repo_root}{os.pathsep}{prev_pp}'
                                 if prev_pp else repo_root)
        if extra:
            env.update({k: str(v) for k, v in extra.items()})
        return env

    def run(self, cmd, *, env=None, log_path=os.devnull, stream_logs=False,
            require_outputs=False, cwd=None, timeout=None) -> RunResult:
        full_env = self._node_env(env)
        return self._popen(
            ['bash', '-c', cmd], shell=False, env=full_env,
            cwd=cwd or self.node_dir, log_path=log_path,
            stream_logs=stream_logs, require_outputs=require_outputs,
            timeout=timeout)

    def popen_interactive(self, cmd: str) -> 'subprocess.Popen':
        return subprocess.Popen(
            ['bash', '-c', cmd], env=self._node_env(None),
            cwd=self.node_dir, text=True, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)

    @property
    def channel_key(self) -> tuple:
        return (type(self).__name__, self.node_id, self.node_dir)

    def rsync(self, source: str, target: str, *, up: bool) -> None:
        if up:
            src = os.path.expanduser(source)
            dst = target
            if dst.startswith('~'):
                dst = os.path.join(self.node_dir, dst.lstrip('~/'))
        else:
            src = source
            if src.startswith('~'):
                src = os.path.join(self.node_dir, src.lstrip('~/'))
            src = os.path.expanduser(src)
            dst = os.path.expanduser(target)
        dst = os.path.abspath(dst)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True, symlinks=True)
        elif os.path.exists(src):
            shutil.copy2(src, dst)
        else:
            raise exceptions.CommandError(1, f'rsync {source}',
                                          f'source not found: {src}')


class SSHCommandRunner(CommandRunner):
    """OpenSSH runner with connection multiplexing (ControlMaster), the
    same transport strategy as the reference (``command_runner.py:426``)."""

    def __init__(self,
                 ip: str,
                 ssh_user: str,
                 ssh_private_key: str,
                 *,
                 port: int = 22,
                 ssh_proxy_command: Optional[str] = None,
                 node_id: Optional[str] = None):
        super().__init__(node_id or ip)
        self.ip = ip
        self.port = port
        self.ssh_user = ssh_user
        self.ssh_private_key = os.path.expanduser(ssh_private_key)
        self.ssh_proxy_command = ssh_proxy_command
        self._control_dir = os.path.join(
            tempfile.gettempdir(), f'skytpu-ssh-{os.getuid()}')
        os.makedirs(self._control_dir, exist_ok=True)

    def _ssh_options(self) -> List[str]:
        opts = [
            '-i', self.ssh_private_key,
            '-o', 'StrictHostKeyChecking=no',
            '-o', 'UserKnownHostsFile=/dev/null',
            '-o', 'IdentitiesOnly=yes',
            '-o', 'GlobalKnownHostsFile=/dev/null',
            '-o', 'ConnectTimeout=30',
            '-o', 'ServerAliveInterval=5',
            '-o', 'ServerAliveCountMax=3',
            '-o', f'ControlPath={self._control_dir}/%C',
            '-o', 'ControlMaster=auto',
            '-o', 'ControlPersist=120s',
            '-o', 'LogLevel=ERROR',
            '-p', str(self.port),
        ]
        if self.ssh_proxy_command:
            opts += ['-o', f'ProxyCommand={self.ssh_proxy_command}']
        return opts

    def ssh_base_command(self) -> List[str]:
        return ['ssh'] + self._ssh_options() + [
            f'{self.ssh_user}@{self.ip}']

    def run(self, cmd, *, env=None, log_path=os.devnull, stream_logs=False,
            require_outputs=False, cwd=None, timeout=None) -> RunResult:
        # Every remote command sees the shipped runtime zip on
        # PYTHONPATH explicitly — shell init files can't be relied on
        # from non-interactive login shells (see pkg_utils).
        from skypilot_tpu.utils import pkg_utils
        remote_cmd = (pkg_utils.RUNTIME_PYTHONPATH_PREFIX +
                      _env_prefix(env) + cmd)
        if cwd:
            remote_cmd = f'cd {shlex.quote(cwd)} && {remote_cmd}'
        args = self.ssh_base_command() + [
            f'bash --login -c {shlex.quote(remote_cmd)}']
        return self._popen(
            args, shell=False, env=None, cwd=None, log_path=log_path,
            stream_logs=stream_logs, require_outputs=require_outputs,
            timeout=timeout)

    def popen_interactive(self, cmd: str) -> 'subprocess.Popen':
        from skypilot_tpu.utils import pkg_utils
        remote_cmd = pkg_utils.RUNTIME_PYTHONPATH_PREFIX + cmd
        args = self.ssh_base_command() + [
            f'bash --login -c {shlex.quote(remote_cmd)}']
        return subprocess.Popen(
            args, text=True, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)

    @property
    def channel_key(self) -> tuple:
        return (type(self).__name__, self.ip, self.port, self.ssh_user)

    def rsync(self, source: str, target: str, *, up: bool) -> None:
        ssh_cmd = ' '.join(['ssh'] + [shlex.quote(o)
                                      for o in self._ssh_options()])
        rsync_cmd = [
            'rsync', '-a', '--delete-missing-args',
            '--exclude', '.git',
            '-e', ssh_cmd,
        ]
        remote = f'{self.ssh_user}@{self.ip}:{target}'
        if up:
            rsync_cmd += [os.path.expanduser(source), remote]
        else:
            rsync_cmd += [remote, os.path.expanduser(target)]
        proc = subprocess.run(rsync_cmd, capture_output=True, text=True,
                              check=False)
        if proc.returncode != 0:
            raise exceptions.CommandError(
                proc.returncode, ' '.join(rsync_cmd), proc.stderr[-2000:])


class KubernetesPodRunner(CommandRunner):
    """Runs commands in a pod via ``kubectl exec`` (role of the
    reference's ``KubernetesCommandRunner``, ``command_runner.py:685``);
    file sync is a tar pipe through exec (kubectl cp needs tar in the
    image anyway, and a pipe preserves the rsync-like semantics)."""

    def __init__(self, pod_name: str, namespace: str = 'default',
                 context: Optional[str] = None):
        super().__init__(pod_name)
        self.pod_name = pod_name
        self.namespace = namespace
        self.context = context

    def _kubectl(self) -> List[str]:
        args = ['kubectl', '--namespace', self.namespace]
        if self.context:
            args += ['--context', self.context]
        return args

    def run(self, cmd, *, env=None, log_path=os.devnull, stream_logs=False,
            require_outputs=False, cwd=None, timeout=None) -> RunResult:
        from skypilot_tpu.utils import pkg_utils
        remote_cmd = (pkg_utils.RUNTIME_PYTHONPATH_PREFIX +
                      _env_prefix(env) + cmd)
        if cwd:
            remote_cmd = f'cd {shlex.quote(cwd)} && {remote_cmd}'
        args = self._kubectl() + [
            'exec', self.pod_name, '--',
            'sh', '-c', remote_cmd]
        return self._popen(
            args, shell=False, env=None, cwd=None, log_path=log_path,
            stream_logs=stream_logs, require_outputs=require_outputs,
            timeout=timeout)

    def popen_interactive(self, cmd: str) -> 'subprocess.Popen':
        from skypilot_tpu.utils import pkg_utils
        remote_cmd = pkg_utils.RUNTIME_PYTHONPATH_PREFIX + cmd
        args = self._kubectl() + [
            'exec', '-i', self.pod_name, '--', 'sh', '-c', remote_cmd]
        return subprocess.Popen(
            args, text=True, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)

    @property
    def channel_key(self) -> tuple:
        return (type(self).__name__, self.pod_name, self.namespace,
                self.context)

    @staticmethod
    def _remote_path(p: str) -> str:
        """Quote a remote path but keep a leading ~ expandable by the
        pod's shell (plain shlex.quote would suppress it)."""
        if p.startswith('~/'):
            return '"$HOME"/' + shlex.quote(p[2:])
        return shlex.quote(p)

    def rsync(self, source: str, target: str, *, up: bool) -> None:
        if up:
            src = os.path.expanduser(source)
            if os.path.isfile(src):
                # Single file -> exact target path (runtime setup pushes
                # cluster_info.json / the pkg zip this way).
                with open(src, 'rb') as f:
                    data = f.read()
                qt = self._remote_path(target)
                sink = self._kubectl() + [
                    'exec', '-i', self.pod_name, '--', 'sh', '-c',
                    f'mkdir -p $(dirname {qt}) && cat > {qt}']
                proc = subprocess.run(sink, input=data,
                                      capture_output=True)
                if proc.returncode != 0:
                    raise exceptions.CommandError(
                        proc.returncode, f'pod rsync up {source}',
                        proc.stderr.decode(errors="replace")[-2000:])
                return
            # rsync trailing-slash semantics: 'src/' ships contents into
            # target; 'src' ships the directory itself under target.
            if source.endswith('/'):
                tar_dir, tar_what = src, '.'
            else:
                tar_dir = os.path.dirname(src.rstrip('/')) or '.'
                tar_what = os.path.basename(src.rstrip('/'))
            tar_make = subprocess.Popen(
                ['tar', '-C', tar_dir, '--exclude', '.git', '-cf', '-',
                 tar_what],
                stdout=subprocess.PIPE)
            qt = self._remote_path(target)
            untar = self._kubectl() + [
                'exec', '-i', self.pod_name, '--', 'sh', '-c',
                f'mkdir -p {qt} && tar -C {qt} -xf -']
            proc = subprocess.run(untar, stdin=tar_make.stdout,
                                  capture_output=True, text=True)
            tar_make.wait()
            if proc.returncode != 0 or tar_make.returncode != 0:
                raise exceptions.CommandError(
                    proc.returncode or tar_make.returncode,
                    f'pod rsync up {source}', proc.stderr[-2000:])
        else:
            os.makedirs(os.path.expanduser(target), exist_ok=True)
            tar_out = self._kubectl() + [
                'exec', self.pod_name, '--', 'sh', '-c',
                f'tar -C {self._remote_path(source)} -cf - .']
            make = subprocess.Popen(tar_out, stdout=subprocess.PIPE)
            proc = subprocess.run(
                ['tar', '-C', os.path.expanduser(target), '-xf', '-'],
                stdin=make.stdout, capture_output=True, text=True)
            make.wait()
            if proc.returncode != 0 or make.returncode != 0:
                raise exceptions.CommandError(
                    proc.returncode or make.returncode,
                    f'pod rsync down {source}', proc.stderr[-2000:])
