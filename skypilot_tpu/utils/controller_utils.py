"""Controller-side task translation: local files -> store buckets.

Role of reference ``sky/utils/controller_utils.py:663``
(``maybe_translate_local_file_mounts_and_sync_up``): a managed job's
controller may run on a DIFFERENT machine than the client, so a task
whose ``workdir``/``file_mounts`` reference client-local paths cannot be
launched there. Before submission, upload those paths to a store bucket
and rewrite the task to download from the bucket URI instead.

Store choice mirrors the task's cloud: GCS for gcp/kubernetes tasks,
the LOCAL store (a directory pretending to be a bucket, shared-
filesystem) for local tasks — overridable via config
``jobs.bucket`` (e.g. ``gs://my-bucket``).
"""
from __future__ import annotations

import os
from typing import Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import tpu_logging
from skypilot_tpu.dag import Dag
from skypilot_tpu.task import Task

logger = tpu_logging.init_logger(__name__)

from skypilot_tpu.agent.constants import WORKDIR_TARGET  # noqa: E402


def _store_for(task: Task, name: str):
    from skypilot_tpu.data import storage as storage_lib
    from skypilot_tpu.utils import common_utils
    bucket_cfg: Optional[str] = config_lib.get_nested(('jobs', 'bucket'),
                                                      None)
    if bucket_cfg:
        store_type = storage_lib.StoreType.from_uri(bucket_cfg)
        bucket = bucket_cfg.split('://', 1)[1].rstrip('/')
        return storage_lib.make_store(store_type, f'{bucket}/{name}')
    cloud = None
    for res in task.resources:
        if res.cloud:
            cloud = res.cloud
            break
    if cloud in (None, 'gcp', 'kubernetes'):
        # GCS bucket names are GLOBAL: include the user hash so every
        # user/project gets a creatable bucket (reference does the same,
        # 'skypilot-filemounts-{user}-{hash}').
        bucket = f'skytpu-filemounts-{common_utils.get_user_hash()}'
        return storage_lib.make_store(storage_lib.StoreType.GCS,
                                      f'{bucket}/{name}')
    return storage_lib.make_store(storage_lib.StoreType.LOCAL, name)


def translate_local_file_mounts(dag: Dag, job_name: str,
                                run_id: str) -> bool:
    """Rewrite every task in ``dag`` so it carries no client-local
    paths: upload workdir/file_mounts to a bucket, point the task at the
    bucket URIs. Returns True if anything was translated."""
    from skypilot_tpu import global_state

    def _upload(store, source: str) -> None:
        store.source = os.path.expanduser(source)
        store.ensure_bucket()
        store.upload()
        # Register so `skytpu storage ls/delete` sees and can clean up
        # translation buckets (they are per-run; nothing auto-deletes
        # them — the user's checkpoint-bucket lifecycle applies).
        global_state.add_or_update_storage(
            store.name,
            {'name': store.name, 'source': source,
             'stores': [store.store_type.value], 'mode': 'COPY',
             'persistent': False},
            global_state.StorageStatus.READY)

    translated = False
    for ti, task in enumerate(dag.topological_order()):
        base = f'{job_name}-{run_id}-{ti}'
        if task.workdir:
            store = _store_for(task, f'{base}-workdir')
            _upload(store, task.workdir)
            task.workdir = None
            task.file_mounts = dict(task.file_mounts or {})
            task.file_mounts[WORKDIR_TARGET] = store.uri()
            translated = True
            logger.info(f'Translated workdir -> {store.uri()}')
        local_mounts = {
            dst: src for dst, src in (task.file_mounts or {}).items()
            if '://' not in src}
        for i, (dst, src) in enumerate(sorted(local_mounts.items())):
            store = _store_for(task, f'{base}-mount{i}')
            _upload(store, src)
            task.file_mounts[dst] = store.uri()
            translated = True
            logger.info(f'Translated file_mount {src} -> {store.uri()}')
    return translated
