"""graftcheck part B: runtime jaxpr + host-transfer auditor.

Proves, at runtime, the invariants the slot/paged engines' performance
depends on (SageServe/ThunderServe-class serving wins hinge on a
sync-free, recompile-stable steady-state loop — PAPERS.md):

1. **Host-transfer freedom** — while an engine steps in steady state,
   no device value is read back to host except through the sanctioned
   :func:`skypilot_tpu.utils.host.host_sync` helper (the async
   pipeline's lagged readback). jax's native ``transfer_guard`` is a
   no-op on the zero-copy CPU backend CI runs on, so the interceptor
   patches the actual Python sync entry points instead
   (``ArrayImpl.__float__/__int__/__bool__/.item()/.tolist()``,
   ``jax.device_get``, ``np.asarray``/``np.array``) — backend
   independent by construction.
2. **Recompile stability** — the decode (and chunked-prefill) jit
   caches do not grow across repeated same-shaped calls; the observed
   static keys (horizon, sample, kv_bucket) that form the recompile
   key are reported.
3. **Jaxpr hygiene** — the traced decode/prefill/forward jaxprs
   contain no host-callback primitives and no unexpected wide-dtype
   promotions (anything promoting to float64 on a TPU program is a
   bug); donation misses surface as captured compile warnings.

Pre-existing violations live in the same baseline mechanism as the AST
lint (the pytest gate hard-fails on new ones).
"""
from __future__ import annotations

import contextlib
import dataclasses
import traceback
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

_CALLBACK_PRIMS = {'pure_callback', 'io_callback', 'debug_callback',
                   'callback', 'outside_call', 'host_callback_call'}


@dataclasses.dataclass
class TransferEvent:
    kind: str          # '__float__' | 'item' | 'np.asarray' | ...
    sanctioned: bool   # made inside host_sync()/host_block()
    where: str         # innermost skypilot_tpu frame 'file:line (fn)'

    def __str__(self):
        tag = 'sanctioned' if self.sanctioned else 'UNSANCTIONED'
        return f'[{tag}] {self.kind} at {self.where}'


@dataclasses.dataclass
class AuditReport:
    name: str
    transfers: List[TransferEvent] = dataclasses.field(
        default_factory=list)
    compile_counts: Dict[str, Tuple[int, int]] = dataclasses.field(
        default_factory=dict)           # label -> (before, after)
    static_keys: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)           # observed decode static args
    callback_prims: List[str] = dataclasses.field(default_factory=list)
    promotions: List[str] = dataclasses.field(default_factory=list)
    f64_promotions: List[str] = dataclasses.field(default_factory=list)
    donation_warnings: List[str] = dataclasses.field(
        default_factory=list)
    # Collective-instruction census of the steady-state decode chain's
    # compiled HLO (mesh presets only): program label -> {op: count}.
    # The zero-resharding contract: no all-to-all / collective-permute
    # anywhere, and all-gathers bounded by the KNOWN decode set (the
    # tp-sharded argmax's tiny top-candidate gathers) — a pool- or
    # activation-shaped gather appearing here means a step's output
    # sharding stopped matching the next step's input sharding.
    collectives: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    allowed_all_gathers: int = 2
    # Per-label overrides: the dp>1 merge all-gathers ring-rows INSIDE
    # its shard_map body by design (dp pool replicas must not diverge
    # — see merge_rows_into_pool), so gang-shaped presets budget that
    # label explicitly instead of loosening the decode gate.
    allowed_all_gathers_by_label: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    # Static per-dispatch cost model (analysis/costmodel.py): label ->
    # DispatchCost priced from the captured steady-state arg structs.
    # ``byte_budget`` is the preset's declared per-class read ceiling
    # (costmodel.BYTE_BUDGETS via run_preset); exceeding it fails ok()
    # with per-eqn byte attribution, same as a recompile would.
    preset: str = ''
    dispatch_costs: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    byte_budget: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    cost_error: str = ''

    @property
    def unsanctioned_transfers(self) -> List[TransferEvent]:
        return [t for t in self.transfers if not t.sanctioned]

    @property
    def recompiles(self) -> Dict[str, int]:
        return {k: after - before
                for k, (before, after) in self.compile_counts.items()}

    def collective_violations(self) -> List[str]:
        out = []
        for label, counts in self.collectives.items():
            for op in ('all-to-all', 'collective-permute'):
                if counts.get(op, 0):
                    out.append(f'{label}: {counts[op]} {op}')
            gathers = counts.get('all-gather', 0)
            allowed = self.allowed_all_gathers_by_label.get(
                label, self.allowed_all_gathers)
            if gathers > allowed:
                out.append(f'{label}: {gathers} all-gather(s) > '
                           f'{allowed} known')
        return out

    def byte_budget_violations(self) -> List[str]:
        """The byte-budget gate: only armed when a budget is declared
        for this preset. A declared budget with NO captured costs is a
        loud failure (the capture path regressed), never a silent
        pass."""
        if not self.byte_budget:
            return []
        if self.cost_error:
            return ['byte budget declared but the cost model failed: '
                    f'{self.cost_error}']
        if not self.dispatch_costs:
            return ['byte budget declared but no dispatch costs were '
                    'captured (decode never fired through the shim?)']
        from skypilot_tpu.analysis import costmodel
        return costmodel.check_budget(self.dispatch_costs,
                                      self.byte_budget)

    def ok(self) -> bool:
        return (not self.unsanctioned_transfers
                and not any(self.recompiles.values())
                and not self.callback_prims
                and not self.f64_promotions
                and not self.collective_violations()
                and not self.byte_budget_violations())

    def format(self) -> str:
        lines = [f'jaxpr audit: {self.name} — '
                 f'{"OK" if self.ok() else "VIOLATIONS"}']
        lines.append(f'  host transfers: {len(self.transfers)} total, '
                     f'{len(self.unsanctioned_transfers)} unsanctioned')
        for t in self.unsanctioned_transfers:
            lines.append(f'    {t}')
        for label, (before, after) in self.compile_counts.items():
            lines.append(f'  compile cache [{label}]: {before} -> '
                         f'{after} ({after - before} recompiles in '
                         'steady state)')
        if self.static_keys:
            keys = sorted({tuple(sorted(k.items()))
                           for k in self.static_keys})
            lines.append(f'  recompile key (observed static args): '
                         f'{[dict(k) for k in keys]}')
        if self.callback_prims:
            lines.append(f'  host-callback primitives: '
                         f'{self.callback_prims}')
        if self.promotions:
            lines.append(f'  dtype promotions: {self.promotions[:8]}'
                         + (' ...' if len(self.promotions) > 8 else ''))
        if self.f64_promotions:
            lines.append(f'  float64 promotions (BUG on TPU): '
                         f'{self.f64_promotions}')
        if self.donation_warnings:
            lines.append(f'  donation misses: {self.donation_warnings}')
        for label, counts in self.collectives.items():
            lines.append(f'  collectives [{label}]: '
                         f'{dict(sorted(counts.items())) or "none"}')
        for v in self.collective_violations():
            lines.append(f'  RESHARDING COLLECTIVE: {v}')
        for label, cost in self.dispatch_costs.items():
            lines.append(f'  cost [{label}]: {cost.read_total:,} B '
                         f'read, {cost.written_total:,} B written, '
                         f'{cost.flops:,} FLOPs')
        if self.cost_error:
            lines.append(f'  cost model error: {self.cost_error}')
        for v in self.byte_budget_violations():
            lines.append(f'  BYTE BUDGET: {v}')
        return '\n'.join(lines)

    def to_json(self) -> Dict[str, Any]:
        """Machine-readable report (the ``graftcheck --json`` schema;
        see docs/analysis.md)."""
        return {
            'name': self.name,
            'preset': self.preset,
            'ok': self.ok(),
            'transfers': {
                'total': len(self.transfers),
                'unsanctioned': [str(t) for t in
                                 self.unsanctioned_transfers],
            },
            'recompiles': dict(self.recompiles),
            'static_keys': self.static_keys,
            'callback_prims': list(self.callback_prims),
            'f64_promotions': list(self.f64_promotions),
            'collectives': {k: dict(v)
                            for k, v in self.collectives.items()},
            'collective_violations': self.collective_violations(),
            'dispatch_costs': {k: c.to_json()
                               for k, c in self.dispatch_costs.items()},
            'byte_budget': self.byte_budget,
            'byte_budget_violations': self.byte_budget_violations(),
            'cost_error': self.cost_error,
        }


# ------------------------------------------------------------------ intercept
def _caller_frame() -> str:
    """Innermost stack frame inside skypilot_tpu but outside this
    module / the host helper — where the sync was requested."""
    for frame in reversed(traceback.extract_stack(limit=40)):
        fn = frame.filename.replace('\\', '/')
        if ('skypilot_tpu' in fn and 'analysis/jaxpr_audit' not in fn
                and 'utils/host' not in fn):
            short = fn.split('skypilot_tpu/', 1)[-1]
            return f'{short}:{frame.lineno} ({frame.name})'
    return '<outside skypilot_tpu>'


@contextlib.contextmanager
def intercept_host_transfers(events: List[TransferEvent]):
    """Record every device->host materialization made while active.

    Patches the Python-level sync entry points on jax's ArrayImpl plus
    the module-level ``jax.device_get`` / ``np.asarray`` / ``np.array``
    names. Re-entrant internal calls (device_get materializes via
    ``_value``) are collapsed to one event via a depth guard. Events
    made inside host_sync()/host_block() are marked sanctioned."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from skypilot_tpu.utils import host as host_lib

    array_t = type(jnp.zeros((), jnp.int32))
    depth = [0]

    def record(kind: str) -> None:
        if depth[0] == 0:
            events.append(TransferEvent(
                kind=kind, sanctioned=host_lib.in_sanctioned_sync(),
                where=_caller_frame()))

    def wrap_method(name: str):
        orig = getattr(array_t, name)

        def patched(self, *args, **kwargs):
            record(name)
            depth[0] += 1
            try:
                return orig(self, *args, **kwargs)
            finally:
                depth[0] -= 1
        return orig, patched

    def wrap_module(mod, name: str, kind: str, check_first_arg: bool):
        orig = getattr(mod, name)

        def patched(*args, **kwargs):
            is_dev = bool(args) and isinstance(args[0], array_t)
            if not check_first_arg or is_dev:
                record(kind)
            depth[0] += 1
            try:
                return orig(*args, **kwargs)
            finally:
                depth[0] -= 1
        return orig, patched

    method_names = ['__array__', '__float__', '__int__', '__bool__',
                    '__index__', 'item', 'tolist']
    saved_methods = {}
    for name in method_names:
        try:
            orig, patched = wrap_method(name)
            setattr(array_t, name, patched)
            saved_methods[name] = orig
        except (AttributeError, TypeError):
            continue
    saved_mods = []
    for mod, name, kind, chk in [
            (jax, 'device_get', 'jax.device_get', False),
            (np, 'asarray', 'np.asarray', True),
            (np, 'array', 'np.array', True)]:
        try:
            orig, patched = wrap_module(mod, name, kind, chk)
            setattr(mod, name, patched)
            saved_mods.append((mod, name, orig))
        except (AttributeError, TypeError):
            continue
    try:
        yield events
    finally:
        for name, orig in saved_methods.items():
            setattr(array_t, name, orig)
        for mod, name, orig in saved_mods:
            setattr(mod, name, orig)


# ------------------------------------------------------------------- jaxpr
def walk_jaxpr(jaxpr) -> Tuple[List[str], List[str]]:
    """Recursively walk a (closed) jaxpr: returns (callback primitive
    names, dtype-promotion descriptions from convert_element_type eqns
    that WIDEN the element type)."""
    import numpy as np
    callbacks: List[str] = []
    promotions: List[str] = []

    def visit(jx) -> None:
        jx = getattr(jx, 'jaxpr', jx)
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in _CALLBACK_PRIMS:
                callbacks.append(name)
            if name == 'convert_element_type' and eqn.invars:
                src = getattr(eqn.invars[0].aval, 'dtype', None)
                dst = eqn.params.get('new_dtype')
                if (src is not None and dst is not None
                        and np.dtype(dst).itemsize
                        > np.dtype(src).itemsize):
                    promotions.append(f'{src} -> {np.dtype(dst).name}')
            for param in eqn.params.values():
                for sub in (param if isinstance(param, (list, tuple))
                            else [param]):
                    if hasattr(sub, 'eqns') or hasattr(sub, 'jaxpr'):
                        visit(sub)
    visit(jaxpr)
    return callbacks, promotions


def check_donation(jit_fn, *args, **kwargs) -> List[str]:
    """Compile ``jit_fn`` for the given arguments, capturing
    donation-miss warnings ('Some donated buffers were not usable',
    'buffer donations ... ignored')."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        jit_fn.lower(*args, **kwargs).compile()
    return [str(w.message) for w in caught
            if 'donat' in str(w.message).lower()]


def _cache_size(fn) -> int:
    getter = getattr(fn, '_cache_size', None)
    if getter is None:
        return -1
    try:
        return int(getter())
    except (TypeError, ValueError):    # jax-internal API drift
        return -1


def _jit_fns(fn) -> List[Any]:
    """The jitted function(s) behind ``fn``: itself if jitted, else any
    jitted functions captured in its closure (the paged engine's decode
    is a plain wrapper enqueueing two jitted programs)."""
    if hasattr(fn, '_cache_size'):
        return [fn]
    out = []
    for cell in getattr(fn, '__closure__', None) or ():
        obj = cell.cell_contents
        if hasattr(obj, '_cache_size'):
            out.append(obj)
    return out


# ------------------------------------------------------------------ presets
def _tiny_engine(kind: str, chunked: bool, speculate_k: int = 0,
                 telemetry: bool = True,
                 kv_cache_dtype: Optional[str] = None,
                 mesh_tp: int = 0, mesh_dp: int = 0,
                 quantize: Optional[str] = None,
                 decode_steps_per_call: Optional[int] = None,
                 decode_impl: Optional[str] = None,
                 adapter_slots: int = 0, adapter_rank: int = 8):
    from skypilot_tpu.models import configs
    cfg = configs.get_config('tiny')
    chunk = 16 if chunked else 0
    extra: Dict[str, Any] = {}
    if quantize is not None:
        extra['quantize'] = quantize
    if adapter_slots:
        extra['adapter_slots'] = adapter_slots
        extra['adapter_rank'] = adapter_rank
    if decode_steps_per_call is not None:
        extra['decode_steps_per_call'] = decode_steps_per_call
    if decode_impl is not None:
        # Paged-only knob ('gather' | 'pallas' | 'cross_layer'); the
        # slot engine rejects it, so only paged presets may set it.
        extra['decode_impl'] = decode_impl
    if mesh_tp and mesh_tp > 1:
        import jax

        from skypilot_tpu.parallel import mesh as mesh_lib
        need = mesh_tp * max(1, mesh_dp)
        if jax.device_count() < need:
            # LOUD: a single-device environment must fail the preset
            # with the fix in the message, not silently audit tp=1.
            raise RuntimeError(
                f'mesh preset needs {need} devices but only '
                f'{jax.device_count()} visible; run under '
                f'XLA_FLAGS=--xla_force_host_platform_device_count='
                f'{need} JAX_PLATFORMS=cpu (the graftcheck CLI '
                'does this re-exec automatically)')
        extra['mesh'] = mesh_lib.serving_mesh(tp=mesh_tp,
                                              dp=max(1, mesh_dp))
        extra['attn_impl'] = 'xla'
    if kind == 'paged':
        from skypilot_tpu.inference.paged import PagedInferenceEngine
        return PagedInferenceEngine(cfg, max_batch=4, max_seq=128,
                                    prefill_chunk_tokens=chunk or None,
                                    speculate_k=speculate_k,
                                    kv_cache_dtype=kv_cache_dtype,
                                    telemetry=telemetry, **extra)
    from skypilot_tpu.inference.engine import InferenceEngine
    return InferenceEngine(cfg, max_batch=4, max_seq=128,
                           prefill_chunk_tokens=chunk,
                           speculate_k=speculate_k,
                           kv_cache_dtype=kv_cache_dtype,
                           telemetry=telemetry, **extra)


def _drive(engine, prompts: List[List[int]], max_new: int = 8) -> None:
    for p in prompts:
        engine.add_request(list(p), max_new_tokens=max_new)
    engine.run_to_completion(horizon=8)


def _record_static_keys(engine, report: AuditReport,
                        capture: Optional[Dict[str, Any]] = None):
    """Shim the engine's decode fn to log the static args of each call
    — the (horizon, sample[, kv_bucket]) tuple IS the recompile key the
    scheduler must keep stable. The slot engine's decode takes
    (..., horizon, sample, kv_bucket); the paged engine's
    (..., horizon, sample) — both pass them as trailing positionals.
    ``capture`` (optional dict) additionally records each call's full
    argument avals+shardings — what the mesh presets re-lower the
    steady-state decode chain from for the collective census."""
    inner = engine._decode_fn
    names = (('horizon', 'sample')
             if type(engine).__name__.startswith('Paged')
             else ('horizon', 'sample', 'kv_bucket'))

    def shim(*args, **kwargs):
        key = {k: kwargs[k] for k in names if k in kwargs}
        missing = [k for k in names if k not in key]
        if missing:
            tail = args[len(args) - len(missing):]
            key.update(dict(zip(missing, tail)))
        report.static_keys.append(key)
        if capture is not None:
            capture['args'] = _arg_structs(args)
        return inner(*args, **kwargs)

    engine._decode_fn = shim
    return inner


def _capture_spec_args(engine, capture: Dict[str, Any]) -> None:
    """Shim the spec jit getters so the verify/fused dispatch's args
    are captured for pricing: spec steady state never touches
    ``_decode_fn``, so the decode shim alone would leave speculative
    presets without dispatch costs. The spec jits take all-array args
    (sample/kv_bucket are baked into the closure), so the capture is
    (arg structs, jit fn) — directly traceable."""
    for getter_name, label in (('_get_spec_verify', 'spec_verify'),
                               ('_get_spec_fused', 'spec_fused')):
        getter = getattr(engine, getter_name, None)
        if getter is None:
            continue

        def shim(*gargs, _getter=getter, _label=label, **gkw):
            fn = _getter(*gargs, **gkw)

            def wrapped(*args, **kwargs):
                capture[_label] = (_arg_structs(args), fn)
                return fn(*args, **kwargs)
            return wrapped

        setattr(engine, getter_name, shim)


def _capture_decode_args(engine, capture: Dict[str, Any]):
    """Minimal capture shim (no static-key recording) for audits that
    track dispatch counts through other entry points."""
    inner = engine._decode_fn

    def shim(*args, **kwargs):
        capture['args'] = _arg_structs(args)
        return inner(*args, **kwargs)

    engine._decode_fn = shim
    return inner


def _attach_costs(report: AuditReport, engine, inner,
                  capture: Dict[str, Any]) -> None:
    """Price the captured steady-state dispatches with the static cost
    model. Failures land in ``cost_error`` — fatal only for presets
    that declare a byte budget (see byte_budget_violations)."""
    try:
        from skypilot_tpu.analysis import costmodel
        report.dispatch_costs = costmodel.engine_dispatch_costs(
            engine, _jit_fns(inner), capture.get('args'))
        for label in ('spec_verify', 'spec_fused'):
            got = capture.get(label)
            if got is None:
                continue
            sargs, sfn = got
            classes = engine.decode_operand_classes(sargs)
            report.dispatch_costs[label] = costmodel.trace_dispatch(
                sfn, sargs, classes, label=label)
    except Exception as e:  # pragma: no cover - trace-shape drift
        report.cost_error = f'{type(e).__name__}: {e}'


def _arg_structs(args):
    """args -> ShapeDtypeStructs carrying mesh shardings. Committed
    NamedSharding args (params, cache, the pinned ring) keep their
    sharding; per-call host uploads (single-device placed) become
    unspecified, exactly how the real call presents them to jit.
    Structs, not arrays: donated buffers in ``args`` are dead by the
    time the census lowers from them."""
    import jax
    from jax.sharding import NamedSharding

    def struct(a):
        if isinstance(a, jax.Array):
            sh = (a.sharding if isinstance(a.sharding, NamedSharding)
                  else None)
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
        return a

    return jax.tree.map(struct, args)


_COLLECTIVE_RE = None


def _count_collectives(hlo_text: str) -> Dict[str, int]:
    """Instruction-level census of communication ops in compiled HLO
    (matches the op at its defining instruction only, so async
    start/done pairs and textual mentions don't double-count)."""
    global _COLLECTIVE_RE
    import collections
    import re
    if _COLLECTIVE_RE is None:
        _COLLECTIVE_RE = re.compile(
            r'= \S+ (all-reduce|all-gather|all-to-all'
            r'|collective-permute|reduce-scatter)(?:-start)?\(')
    return dict(collections.Counter(
        m.group(1) for m in _COLLECTIVE_RE.finditer(hlo_text)))


def _decode_chain_collectives(engine, inner, captured
                              ) -> Dict[str, Dict[str, int]]:
    """Compile-and-census the steady-state decode chain from the last
    captured call's arg structs: the slot engine's fused decode is one
    jitted program; the paged engine's chain is (decode_steps, merge)
    behind a plain wrapper — the merge's ring operands are
    reconstructed at the pinned ``_ring_sh`` sharding (decode's output
    sharding IS merge's input sharding — the contract under test)."""
    import jax
    args = captured.get('args')
    if args is None:
        return {}
    out: Dict[str, Dict[str, int]] = {}
    for fn in _jit_fns(inner):
        try:
            txt = fn.lower(*args).compile().as_text()
            out['decode'] = _count_collectives(txt)
            continue
        except TypeError:
            pass        # the paged merge: different signature
        cache, table, lengths, active = args[1], args[2], args[4], args[9]
        # args[10]/args[11] are the adapter indices / vocab mask; the
        # merge consumes neither.
        horizon = args[12]
        cfg = engine.cfg
        ring = jax.ShapeDtypeStruct(
            (cfg.n_layers, engine.max_batch, horizon, cfg.n_kv_heads,
             cfg.head_dim), cfg.dtype,
            sharding=getattr(engine, '_ring_sh', None))
        txt = fn.lower(cache, ring, ring, table, lengths,
                       active).compile().as_text()
        out['merge'] = _count_collectives(txt)
    return out


def audit_engine(kind: str = 'slot', chunked: bool = True,
                 rounds: int = 2, speculate_k: int = 0,
                 kv_cache_dtype: Optional[str] = None,
                 mesh_tp: int = 0, mesh_dp: int = 0,
                 warmup_rounds: int = 1,
                 merge_all_gathers: int = 0,
                 quantize: Optional[str] = None,
                 decode_impl: Optional[str] = None) -> AuditReport:
    """Build a tiny engine, run one warmup wave (compiles allowed),
    then audit ``rounds`` identical same-shaped waves: every compile
    and every unsanctioned host transfer in those waves is a violation.

    ``kind``: 'slot' | 'paged'. ``chunked``: prompts longer than one
    chunk so the chunked-prefill path (cursor chunks + completing
    chunk) is exercised, not just monolithic admission.
    ``speculate_k > 0`` drives the speculative propose→verify→commit
    steady state on REPETITIVE prompts (so proposals actually fire and
    acceptance varies per slot): the verify jit cache must stay bounded
    by the observed (k, sample, kv_bucket) key set, and the only host
    readback per round is the sanctioned commit sync.

    ``mesh_tp >= 2`` audits the SHARDED serving path on a (tp,) CPU
    mesh (forced host platform device count): the same transfer/
    recompile gates, plus a collective census of the compiled decode
    chain — no all-to-all / collective-permute, and no all-gathers
    beyond the known decode set (the tp-sharded argmax's tiny top-
    candidate gathers). This is the zero-resharding contract: every
    step's pinned output shardings ARE the next step's input
    shardings, so a fat gather here means the chain broke."""
    spec_tag = f' + speculate_k={speculate_k}' if speculate_k else ''
    kv_tag = (f' + kv_cache_dtype={kv_cache_dtype}'
              if kv_cache_dtype else '')
    q_tag = f' + quantize={quantize}' if quantize else ''
    tp_tag = f' + tp={mesh_tp}' if mesh_tp else ''
    tp_tag += f' x dp={mesh_dp}' if mesh_dp else ''
    impl_tag = f' + decode_impl={decode_impl}' if decode_impl else ''
    report = AuditReport(
        name=f'{kind} engine '
             f'({"chunked prefill + " if chunked else ""}decode'
             f'{spec_tag}{kv_tag}{q_tag}{tp_tag}{impl_tag})')
    engine = _tiny_engine(kind, chunked, speculate_k,
                          kv_cache_dtype=kv_cache_dtype,
                          mesh_tp=mesh_tp, mesh_dp=mesh_dp,
                          quantize=quantize, decode_impl=decode_impl)
    if speculate_k:
        # Repetitive prompts: the n-gram proposer matches, acceptance
        # is nonzero AND per-slot variable — the masked-commit shapes
        # are what must stay recompile-free.
        prompts = [[1, 2, 3, 4] * 7, [5, 6] * 11, [7, 8, 9] * 7]
    else:
        prompts = [[1, 2, 3] * 9, [4, 5] * 10, [7] * 21]  # >1 chunk
    for _ in range(max(1, warmup_rounds)):              # warmup: compiles
        _drive(engine, prompts)
    capture: Dict[str, Any] = {}
    inner = _record_static_keys(engine, report, capture)
    if speculate_k:
        _capture_spec_args(engine, capture)
    decode_jits = _jit_fns(inner)
    labels = {'decode': lambda: (sum(_cache_size(f)
                                     for f in decode_jits)
                                 if decode_jits else -1)}
    chunk_fns = getattr(engine, '_chunk_prefill_fns', None)
    if chunk_fns is not None:
        labels['chunk_prefill'] = lambda: len(chunk_fns)
    prefill_fns = getattr(engine, '_prefill_fns', None)
    if prefill_fns is not None:
        labels['prefill'] = lambda: len(prefill_fns)
    spec_fns = getattr(engine, '_spec_verify_fns', None)
    if spec_fns is not None and speculate_k:
        # The verify program cache is keyed (k, sample, kv_bucket) —
        # steady state must never grow it (per-slot acceptance rides
        # masked commits, not fresh shapes).
        labels['spec_verify'] = lambda: len(spec_fns)
    before = {k: get() for k, get in labels.items()}
    with intercept_host_transfers(report.transfers):
        for _ in range(rounds):
            _drive(engine, prompts)        # identical shapes: no compiles
    engine._decode_fn = inner
    if spec_fns is not None and speculate_k:
        names = ('k', 'sample',
                 'P' if kind == 'paged' else 'kv_bucket')
        report.static_keys.extend(
            dict(zip(names, key)) for key in sorted(spec_fns))
    report.compile_counts = {
        k: (before[k], get()) for k, get in labels.items()}
    if mesh_tp:
        report.collectives = _decode_chain_collectives(
            engine, inner, capture)
        if merge_all_gathers:
            report.allowed_all_gathers_by_label['merge'] = \
                merge_all_gathers
    _attach_costs(report, engine, inner, capture)
    # Jaxpr of the fused decode step itself (the hot program).
    try:
        import jax
        from skypilot_tpu.models import llama
        cfg = engine.cfg
        if kind == 'slot':
            jx = jax.make_jaxpr(
                lambda p, c, t: llama.decode_horizon(
                    p, c, t, cfg, horizon=4, kv_bucket=64))(
                        engine.params, engine.cache, engine._tok_dev)
            report.callback_prims, report.promotions = walk_jaxpr(jx)
            report.f64_promotions = [
                p for p in report.promotions if 'float64' in p]
    except Exception as e:  # pragma: no cover - trace-shape drift
        report.promotions.append(f'<jaxpr trace failed: {e}>')
    return report


def audit_multistep(k: int = 4,
                    quantize: Optional[str] = None) -> AuditReport:
    """Multi-step on-device decode (``decode_steps_per_call=k``): the
    dispatch-amortization contract, audited.

    A paged engine with the knob pinned serves EQUAL-shape budget-bound
    requests (no eos/stop — early-free keeps every slot in lockstep),
    with ``max_new_tokens = 2k + 1``: one first token from prefill plus
    exactly ``2k`` decode tokens. Steady state must show, per round:

    - exactly TWO decode dispatches — ONE jitted call per k tokens
      (the whole point of the knob; a partial-k call or an extra
      tail dispatch fails the count);
    - every dispatch's static horizon == k (the jit key stays
      (k, sample, P) — a drifting horizon would both recompile and
      break the amortization claim);
    - the usual gates: zero unsanctioned d2h, zero steady-state
      recompiles."""
    q_tag = f', quantize={quantize}' if quantize else ''
    report = AuditReport(
        name=f'multi-step decode (decode_steps_per_call={k}{q_tag})')
    engine = _tiny_engine('paged', chunked=True,
                          quantize=quantize, decode_steps_per_call=k)
    prompts = [[3 + i, 5, 7, 9, 2, 4, 6, 8, 1, 3, 5, 7]
               for i in range(4)]               # equal shapes: lockstep
    max_new = 2 * k + 1

    def one_round() -> None:
        for p in prompts:
            engine.add_request(list(p), max_new_tokens=max_new)
        # Caller horizon 1: the KNOB must fuse k, not the caller.
        engine.run_to_completion(horizon=1)

    one_round()                                   # warmup: compiles
    capture: Dict[str, Any] = {}
    inner = _record_static_keys(engine, report, capture)
    decode_jits = _jit_fns(inner)
    labels = {'decode': lambda: (sum(_cache_size(f)
                                     for f in decode_jits)
                                 if decode_jits else -1),
              'prefill': lambda: len(engine._prefill_fns)}
    before = {name: get() for name, get in labels.items()}
    rounds = 2
    with intercept_host_transfers(report.transfers):
        for _ in range(rounds):
            one_round()
    engine._decode_fn = inner
    report.compile_counts = {
        name: (before[name], get()) for name, get in labels.items()}
    _attach_costs(report, engine, inner, capture)
    # ONE dispatch per k tokens: 2k decode tokens/round at lockstep =
    # exactly 2 dispatches/round. Recorded as an (expected, actual)
    # compile_counts pair so a mismatch fails ok() like a recompile.
    report.compile_counts['decode dispatches (ONE per '
                          f'{k} tokens)'] = (
        rounds * 2, len(report.static_keys))
    bad_h = [key for key in report.static_keys
             if key.get('horizon') != k]
    report.compile_counts['dispatches at horizon != k'] = (
        0, len(bad_h))
    return report


def audit_spec_multistep(k: int = 4, steps: int = 3) -> AuditReport:
    """In-scan speculative verify (``speculate_k`` x
    ``decode_steps_per_call``): the COMPOSED amortization contract.

    When both knobs are set, ``steps`` propose→verify→commit rounds
    fuse into ONE jitted dispatch (a lax.scan with the device n-gram
    proposer); greedy decode is byte-identical to the single-round
    path, so per-round commit counts — and therefore the number of
    verify rounds a wave needs — match a reference single-round
    engine exactly. Steady state must show:

    - fused dispatches == ceil(single-round verify dispatches /
      ``steps``) per wave: ONE dispatch per ``steps`` verify rounds,
      with no partial-round or tail dispatches beyond the final
      ceil;
    - ZERO single-round fallback dispatches (the pool reservation in
      ``_spec_can_fuse`` must hold at this scale — a fallback means
      the fusion silently degraded);
    - every fused jit key pins rounds == ``steps`` (a drifting rounds
      count would recompile AND break the amortization claim);
    - the usual gates: zero unsanctioned d2h (the stacked-commit
      host_sync is the ONE sanctioned readback per dispatch), zero
      steady-state growth of the spec program cache."""
    report = AuditReport(
        name=f'in-scan speculative verify (speculate_k={k} x '
             f'decode_steps_per_call={steps})')
    # Repetitive prompts so the n-gram proposer fires and acceptance
    # varies per slot (same shapes as the spec presets).
    prompts = [[1, 2, 3, 4] * 7, [5, 6] * 11, [7, 8, 9] * 7]
    max_new = 12

    def one_wave(engine) -> None:
        for p in prompts:
            engine.add_request(list(p), max_new_tokens=max_new)
        # Caller horizon 1: the KNOB must fuse the rounds, not the
        # caller's horizon loop.
        engine.run_to_completion(horizon=1)

    def count_calls(engine, name: str, counter: List[int]):
        orig = getattr(engine, name)

        def counting(*args, **kwargs):
            counter[0] += 1
            return orig(*args, **kwargs)
        setattr(engine, name, counting)

    # Reference: identical wave on a single-round verify engine — its
    # dispatch count is the ground truth the fusion must divide.
    ref = _tiny_engine('paged', chunked=True, speculate_k=k)
    single = [0]
    count_calls(ref, '_spec_verify_call', single)
    one_wave(ref)                                 # warmup: compiles
    single[0] = 0
    one_wave(ref)                                 # counted wave

    engine = _tiny_engine('paged', chunked=True, speculate_k=k,
                          decode_steps_per_call=steps)
    fused, fallback = [0], [0]
    count_calls(engine, '_spec_fused_call', fused)
    count_calls(engine, '_spec_verify_call', fallback)
    one_wave(engine)                              # warmup: compiles
    capture: Dict[str, Any] = {}
    inner = _capture_decode_args(engine, capture)
    _capture_spec_args(engine, capture)
    spec_fns = engine._spec_verify_fns
    before = len(spec_fns)
    fused[0] = fallback[0] = 0
    rounds = 2
    with intercept_host_transfers(report.transfers):
        for _ in range(rounds):
            one_wave(engine)
    engine._decode_fn = inner
    _attach_costs(report, engine, inner, capture)
    per_wave = -(-single[0] // steps)             # ceil
    report.compile_counts = {
        'spec program cache': (before, len(spec_fns)),
        f'fused dispatches (ONE per {steps} verify rounds; '
        f'{single[0]} single-round rounds/wave)': (
            rounds * per_wave, fused[0]),
        'single-round fallback dispatches': (0, fallback[0]),
    }
    names = ('mode', 'k', 'sample', 'P', 'rounds')
    report.static_keys.extend(
        dict(zip(names, key)) for key in sorted(spec_fns)
        if isinstance(key, tuple) and key and key[0] == 'fused')
    bad_r = [key for key in report.static_keys
             if key.get('rounds') != steps]
    report.compile_counts['fused keys at rounds != steps'] = (
        0, len(bad_r))
    return report


def audit_adapters(kind: str = 'paged') -> AuditReport:
    """Batched multi-LoRA decode under adapter-bank churn.

    A tiny engine with a 2-slot adapter bank serves waves where two
    slots decode under DIFFERENT adapters and one decodes the base
    model (zero-adapter row) — the gathered bank matmul rides inside
    the same fused programs. Between audited waves the wave's adapter
    pair rotates through four registered adapters, so every audited
    wave LRU-evicts both bank rows and loads two fresh ones. Steady
    state must show:

    - zero unsanctioned d2h and zero jit-cache growth across the
      churn waves: load/evict re-uploads bank rows (donated
      ``set_bank_row`` updates), it NEVER recompiles — the bank lives
      in params, so the (horizon, sample[, bucket]) jit key does not
      grow an adapter dimension;
    - the expected load/evict counts actually happened (2 loads + 2
      evictions per audited wave) — a silent cache hit would mean the
      churn, and therefore the gate, never ran;
    - the armed byte budget (costmodel BYTE_BUDGETS['adapters']): the
      decode dispatch's ``adapter_bank``-class HBM reads stay at
      bank-rows-touched bytes — the gather interpreter bills rows
      actually gathered, so a regression that reads the whole bank
      (or dequants it into activations) trips the ceiling."""
    import numpy as np

    from skypilot_tpu.models import multilora
    report = AuditReport(
        name=f'{kind} engine (chunked prefill + decode + multi-LoRA '
             f'bank churn, 2 slots x 4 adapters)')
    engine = _tiny_engine(kind, chunked=True,
                          adapter_slots=2, adapter_rank=4)
    cfg = engine.cfg
    rng = np.random.default_rng(0)
    names = [f'ad{i}' for i in range(4)]
    for i, name in enumerate(names):
        tree = {}
        for t in multilora.default_targets(cfg):
            a_shape, b_shape = multilora.target_shapes(cfg, t, 4)
            tree[t] = {
                'a': rng.normal(0, 0.02, (cfg.n_layers,) + a_shape
                                ).astype(np.float32),
                'b': rng.normal(0, 0.02, (cfg.n_layers,) + b_shape
                                ).astype(np.float32)}
        engine.adapters.register(name, tree, scale=1.0 + i)
    prompts = [[1, 2, 3] * 9, [4, 5] * 10, [7] * 21]    # >1 chunk

    def wave(pair) -> None:
        # Two adapter rows + one base row per wave: the zero-adapter
        # slot rides the SAME gathered dispatch (where-select row).
        for p, adapter in zip(prompts, (pair[0], pair[1], None)):
            engine.add_request(list(p), max_new_tokens=8,
                               adapter=adapter)
        engine.run_to_completion(horizon=8)

    wave(names[0:2])           # warmup: compiles (incl. set_bank_row)
    wave(names[2:4])           # warmup: the evict/re-upload path
    capture: Dict[str, Any] = {}
    inner = _record_static_keys(engine, report, capture)
    decode_jits = _jit_fns(inner)
    labels = {'decode': lambda: (sum(_cache_size(f)
                                     for f in decode_jits)
                                 if decode_jits else -1),
              'prefill': lambda: len(engine._prefill_fns)}
    chunk_fns = getattr(engine, '_chunk_prefill_fns', None)
    if chunk_fns is not None:
        labels['chunk_prefill'] = lambda: len(chunk_fns)
    before = {k: get() for k, get in labels.items()}
    reg = engine.adapters
    loads0, evicts0 = reg.loads_total, reg.evictions_total
    rounds = 2
    with intercept_host_transfers(report.transfers):
        for i in range(rounds):
            # Rotate the pair: every audited wave evicts both rows.
            wave(names[0:2] if i % 2 == 0 else names[2:4])
    engine._decode_fn = inner
    report.compile_counts = {
        k: (before[k], get()) for k, get in labels.items()}
    report.compile_counts['adapter loads per churn wave (x2)'] = (
        rounds * 2, reg.loads_total - loads0)
    report.compile_counts['adapter evictions per churn wave (x2)'] = (
        rounds * 2, reg.evictions_total - evicts0)
    _attach_costs(report, engine, inner, capture)
    return report


def audit_llama_forward() -> AuditReport:
    """Static jaxpr audit of the llama training/prefill forward."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import configs, llama
    report = AuditReport(name='llama forward (jaxpr)')
    cfg = configs.get_config('tiny')
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    jx = jax.make_jaxpr(
        lambda p, t: llama.forward(p, t, cfg))(params, tokens)
    report.callback_prims, report.promotions = walk_jaxpr(jx)
    report.f64_promotions = [p for p in report.promotions
                             if 'float64' in p]
    try:
        from skypilot_tpu.analysis import costmodel
        classes = jax.tree.leaves(costmodel.classify_params(params))
        classes.append(costmodel.TABLE)           # the token ids
        report.dispatch_costs['forward'] = \
            costmodel.analyze_closed_jaxpr(jx, classes,
                                           label='forward')
    except Exception as e:  # pragma: no cover - trace-shape drift
        report.cost_error = f'{type(e).__name__}: {e}'
    return report


def audit_disagg() -> AuditReport:
    """Disaggregated prefill→decode handoff steady state (int8 wire).

    Two tiny paged engines play prefill worker and decode worker:
    each round the prefill engine admits + chunk-prefills a fixed
    prompt set, exports every request's KV snapshot at its first
    token (the sanctioned ``host_sync`` readback — the rows LEAVE the
    process by design), the wire codec round-trips it, and the decode
    engine ingests + decodes to completion. After a warmup round the
    audited rounds must show:

    - the DECODE worker compiles **zero prefill programs** — phase
      isolation is real, not just routing (its only programs are the
      ingest merge and the decode chain);
    - ingest causes **zero extra recompiles** (the ingest fn cache and
      decode jit caches stay at their warmup size) and **zero
      unsanctioned d2h transfers**;
    - the prefill worker's export adds no unsanctioned transfers
      either (every readback rides ``host_sync``)."""
    from skypilot_tpu.inference import kv_transfer
    report = AuditReport(
        name='disagg prefill→decode handoff (paged, int8 wire)')
    prefill = _tiny_engine('paged', chunked=True,
                           kv_cache_dtype='int8')
    decode = _tiny_engine('paged', chunked=True, kv_cache_dtype='int8')
    prompts = [[1, 2, 3] * 9, [4, 5] * 10, [7] * 21]

    def one_round() -> None:
        rids = [prefill.add_request(list(p), max_new_tokens=24,
                                    hold=True) for p in prompts]
        first: Dict[int, int] = {}
        waiting = set(rids)
        while waiting:
            for rid, token, _fin in prefill.step(horizon=4):
                if rid in waiting:
                    first[rid] = token
                    waiting.discard(rid)
        for rid in rids:
            snap, _events = prefill.export_kv_snapshot(rid)
            assert snap is not None, f'export failed for {rid}'
            prefill.cancel(rid)
            snap = kv_transfer.decode_handoff(
                kv_transfer.encode_handoff(snap))
            decode.ingest_kv_snapshot(snap)
        decode.run_to_completion(horizon=8)
        prefill.run_to_completion(horizon=8)

    one_round()                                   # warmup: compiles
    capture: Dict[str, Any] = {}
    inner = _capture_decode_args(decode, capture)
    decode_jits = _jit_fns(inner)
    labels = {
        'decode-worker decode': lambda: (sum(
            _cache_size(f) for f in decode_jits)
            if decode_jits else -1),
        'decode-worker ingest': lambda: len(decode._ingest_fns),
        # Phase isolation: the decode worker must never compile a
        # prefill program — not at warmup, not ever. Recorded with a
        # ZERO baseline so any prefill compile (warmup included)
        # fails ok() as cache growth.
        'decode-worker prefill programs (must stay 0)': lambda: len(
            decode._prefill_fns),
        'prefill-worker export': lambda: len(prefill._export_fns),
        'prefill-worker prefill': lambda: len(prefill._prefill_fns),
    }
    before = {k: get() for k, get in labels.items()}
    before['decode-worker prefill programs (must stay 0)'] = 0
    with intercept_host_transfers(report.transfers):
        for _ in range(2):
            one_round()
    decode._decode_fn = inner
    report.compile_counts = {
        k: (before[k], get()) for k, get in labels.items()}
    _attach_costs(report, decode, inner, capture)
    return report


def audit_telemetry_parity(kind: str = 'slot') -> AuditReport:
    """Prove telemetry is free at the device boundary: a
    telemetry-ENABLED engine run performs zero unsanctioned d2h
    transfers and compiles exactly the same set of programs as a
    telemetry-OFF run (all measurement is host-side around
    dispatches). Per-mode steady-state recompiles and the on-vs-off
    jit-cache-size comparison both land in ``compile_counts``, so a
    parity break fails ``ok()`` like any other recompile."""
    report = AuditReport(name=f'telemetry parity ({kind} engine)')
    prompts = [[1, 2, 3] * 9, [4, 5] * 10, [7] * 21]

    def cache_total(engine) -> int:
        total = 0
        for attr in ('_prefill_fns', '_chunk_prefill_fns',
                     '_spec_verify_fns'):
            fns = getattr(engine, attr, None)
            if fns is not None:
                total += len(fns)
        decode_jits = _jit_fns(engine._decode_fn)
        total += sum(max(0, _cache_size(f)) for f in decode_jits)
        return total

    totals: Dict[bool, int] = {}
    for mode in (False, True):
        engine = _tiny_engine(kind, chunked=True, telemetry=mode)
        _drive(engine, prompts)                   # warmup: compiles
        before = cache_total(engine)
        label = 'telemetry-on' if mode else 'telemetry-off'
        if mode:
            # Transfers recorded only for the telemetry-ON run: the
            # claim under test is that telemetry adds none.
            capture: Dict[str, Any] = {}
            inner = _capture_decode_args(engine, capture)
            with intercept_host_transfers(report.transfers):
                for _ in range(2):
                    _drive(engine, prompts)
            engine._decode_fn = inner
            _attach_costs(report, engine, inner, capture)
        else:
            for _ in range(2):
                _drive(engine, prompts)
        report.compile_counts[f'steady-state [{label}]'] = (
            before, cache_total(engine))
        totals[mode] = cache_total(engine)
    report.compile_counts['jit cache size (off vs on)'] = (
        totals[False], totals[True])
    return report


def audit_digest_export() -> AuditReport:
    """Prefix-digest export on the probe path, audited.

    ``hot_prefix_digest()`` ships the hottest prefix chains to the LB
    on every ``/metrics`` scrape (prefix-affinity routing). The
    contract that makes that free: the digest is built from the
    host-side heat tracker ONLY — no allocator matching, no device
    gather. Steady state with a scrape after EVERY wave (far hotter
    than the real ~1 Hz probe cadence) must show zero unsanctioned
    d2h transfers and zero jit-cache growth, and every scrape must
    return entries (the chains the waves registered) — an empty
    export means the heat tracker regressed, recorded as a
    compile-count mismatch so it fails ``ok()`` loudly."""
    report = AuditReport(
        name='hot-prefix digest export (paged probe path)')
    engine = _tiny_engine('paged', chunked=True)
    prompts = [[1, 2, 3] * 9, [4, 5] * 10, [7] * 21]  # >= 1 full page
    _drive(engine, prompts)                       # warmup: compiles
    capture: Dict[str, Any] = {}
    inner = _record_static_keys(engine, report, capture)
    decode_jits = _jit_fns(inner)
    labels = {'decode': lambda: (sum(_cache_size(f)
                                     for f in decode_jits)
                                 if decode_jits else -1),
              'prefill': lambda: len(engine._prefill_fns)}
    before = {k: get() for k, get in labels.items()}
    rounds = 2
    scrapes: List[List[Dict[str, Any]]] = []
    with intercept_host_transfers(report.transfers):
        for _ in range(rounds):
            _drive(engine, prompts)
            scrapes.append(engine.hot_prefix_digest())
    engine._decode_fn = inner
    report.compile_counts = {
        k: (before[k], get()) for k, get in labels.items()}
    report.compile_counts['scrapes returning entries'] = (
        rounds, sum(1 for d in scrapes if d))
    _attach_costs(report, engine, inner, capture)
    return report


def audit_fleet_obs() -> AuditReport:
    """Fleet observability scrape path, audited.

    The fleet plane (telemetry/fleet.py) merges per-replica registry
    exports and completed-trace summaries on the controller and
    evaluates SLO burn rates — all of it host-side bookkeeping. The
    contract: a FULL fleet scrape after EVERY wave (registry
    ``export_wire()`` + trace-buffer drain + ``FleetAggregator``
    ingest + burn evaluation + a prometheus render, far hotter than
    the real probe cadence) adds zero unsanctioned d2h transfers and
    zero jit-cache growth to the engine hot loop. Every scrape must
    also land series in the aggregator and drain at least one
    completed trace — an empty scrape means the registry or the
    trace-buffer wiring regressed, recorded as a compile-count
    mismatch so it fails ``ok()`` loudly."""
    from skypilot_tpu.telemetry import clock as clock_lib
    from skypilot_tpu.telemetry import fleet as fleet_lib
    from skypilot_tpu.telemetry import registry as registry_lib
    from skypilot_tpu.telemetry import tracing
    report = AuditReport(
        name='fleet observability scrape (registry+trace -> aggregator)')
    engine = _tiny_engine('paged', chunked=True, telemetry=True)
    prompts = [[1, 2, 3] * 9, [4, 5] * 10, [7] * 21]
    _drive(engine, prompts)                       # warmup: compiles
    capture: Dict[str, Any] = {}
    inner = _record_static_keys(engine, report, capture)
    decode_jits = _jit_fns(inner)
    labels = {'decode': lambda: (sum(_cache_size(f)
                                     for f in decode_jits)
                                 if decode_jits else -1),
              'prefill': lambda: len(engine._prefill_fns)}
    before = {k: get() for k, get in labels.items()}
    agg = fleet_lib.FleetAggregator(
        clock=clock_lib.now,
        slos=[fleet_lib.TierSLO(tier='latency', ttft_ms=2000.0,
                                target=0.99)])
    reg = registry_lib.get_registry()
    buf = tracing.get_trace_buffer()
    cursor = len(buf.snapshot())    # other presets' traces: skip them
    rounds = 2
    good_scrapes = 0
    with intercept_host_transfers(report.transfers):
        for _ in range(rounds):
            _drive(engine, prompts)
            cursor, traces = buf.summaries_since(cursor)
            wire = reg.export_wire()
            agg.ingest('audit-replica', {
                'clock': {'wall': clock_lib.now()},
                'registry': wire, 'traces': traces})
            rendered = agg.render_prometheus()
            if wire and traces and rendered:
                good_scrapes += 1
    engine._decode_fn = inner
    report.compile_counts = {
        k: (before[k], get()) for k, get in labels.items()}
    report.compile_counts['scrapes ingesting series+traces'] = (
        rounds, good_scrapes)
    report.compile_counts['aggregator sources'] = (
        1, agg.source_count())
    _attach_costs(report, engine, inner, capture)
    return report


PRESETS: Dict[str, Callable[[], AuditReport]] = {
    'slot': lambda: audit_engine('slot', chunked=True),
    'slot-monolithic': lambda: audit_engine('slot', chunked=False),
    'paged': lambda: audit_engine('paged', chunked=True),
    'slot-spec': lambda: audit_engine('slot', chunked=True,
                                      speculate_k=4),
    'paged-spec': lambda: audit_engine('paged', chunked=True,
                                       speculate_k=4),
    'telemetry': audit_telemetry_parity,
    'telemetry-paged': lambda: audit_telemetry_parity('paged'),
    # int8 KV over bf16 weights — the DECOUPLED kv_cache_dtype path no
    # other preset drives (the coupled int8+int8 case rides the bench):
    # quantize-on-write in every scan + fused-dequant reads must add
    # zero d2h transfers and zero steady-state jit-cache growth.
    'kv-int8': lambda: audit_engine('paged', chunked=True,
                                    kv_cache_dtype='int8'),
    'kv-int8-slot': lambda: audit_engine('slot', chunked=True,
                                         kv_cache_dtype='int8'),
    # int4 KV codes (packed nibble rows + absmax/7 scales): quantize-
    # on-write and fused in-kernel dequant reads must add zero d2h and
    # zero steady-state jit-cache growth — halving KV bytes must not
    # buy a single host round-trip.
    'kv-int4': lambda: audit_engine('paged', chunked=True,
                                    kv_cache_dtype='int4'),
    'kv-int4-slot': lambda: audit_engine('slot', chunked=True,
                                         kv_cache_dtype='int4'),
    # Cross-layer fused decode attention: the per-layer ring+current-
    # token merge folded into the kernel's final grid step. Same hot-
    # loop gates as 'paged' — the fusion must be free at the dispatch
    # boundary.
    'fused-attn': lambda: audit_engine('paged', chunked=True,
                                       decode_impl='cross_layer'),
    # Sharded serving path (tp=2 CPU mesh): chunked prefill + decode +
    # ring merge over the head-sharded pool — zero steady-state
    # recompiles, zero unsanctioned d2h, and no resharding collectives
    # (no all-to-all; all-gathers bounded by the known sharded-argmax
    # pair). Needs >= 2 devices — the graftcheck CLI re-execs under a
    # forced host platform device count when short.
    'paged-tp': lambda: audit_engine('paged', chunked=True, mesh_tp=2),
    # Gang-shaped mesh: (tp=2, dp=2) over 4 devices stands in for a
    # 2-process gang x 2 chips/process — on a pod the dp axis crosses
    # process boundaries, and the compiled HLO (and therefore this
    # collective census) is identical whether the devices are local or
    # remote: no all-to-all/collective-permute, no fat all-gathers in
    # the decode chain, merge collective-free ACROSS the process axis.
    # warmup_rounds=2: the dp-sharded pool crosses one page-table
    # bucket after its first full wave (cold-start shape, not a
    # steady-state leak — the cache is flat from the second wave on);
    # merge_all_gathers budgets the IN-BODY ring-row gathers the dp>1
    # shard_map merge performs by design (dp pool replicas must not
    # diverge).
    'paged-gang': lambda: audit_engine('paged', chunked=True,
                                       mesh_tp=2, mesh_dp=2,
                                       warmup_rounds=2,
                                       merge_all_gathers=6),
    'paged-tp-int8': lambda: audit_engine('paged', chunked=True,
                                          mesh_tp=2,
                                          kv_cache_dtype='int8'),
    # Disaggregated prefill→decode handoff: the decode worker's steady
    # state compiles ZERO prefill programs, and ingest adds zero
    # recompiles / unsanctioned d2h (int8 KV rides the wire codec).
    'disagg': audit_disagg,
    # int4 fused-dequant weights (packed codes + int8 KV via auto):
    # the unpack-inside-qeinsum path must add zero d2h transfers and
    # zero steady-state jit-cache growth on both engines' hot loops.
    'int4': lambda: audit_engine('paged', chunked=True,
                                 quantize='int4'),
    'int4-slot': lambda: audit_engine('slot', chunked=True,
                                      quantize='int4'),
    # Multi-step on-device decode: exactly ONE dispatch per k tokens,
    # every dispatch at static horizon k, zero recompiles/d2h.
    'multistep': audit_multistep,
    'int4-multistep': lambda: audit_multistep(quantize='int4'),
    # In-scan speculative verify: speculate_k x decode_steps_per_call
    # compose into ONE dispatch per `steps` verify rounds, pinned
    # against a single-round reference engine's dispatch count.
    'spec-multistep': audit_spec_multistep,
    # Batched multi-LoRA bank churn: loads/evicts between waves
    # re-upload bank rows with zero recompiles and zero unsanctioned
    # d2h; the gather matmul bills bank-rows-touched bytes (armed
    # byte budget on the adapter_bank class).
    'adapters': audit_adapters,
    'adapters-slot': lambda: audit_adapters('slot'),
    # Prefix-digest export on the LB probe path: a hot_prefix_digest()
    # scrape after every wave adds zero unsanctioned d2h and zero
    # jit-cache growth (host-side heat tracker only), and every scrape
    # returns entries.
    'digest': audit_digest_export,
    # Fleet observability plane: a full controller-style scrape
    # (registry export + trace drain + aggregator ingest + SLO burn
    # eval + prometheus render) after every wave adds zero
    # unsanctioned d2h and zero jit-cache growth, and every scrape
    # lands series AND completed traces in the aggregator.
    'fleet-obs': audit_fleet_obs,
    'llama': audit_llama_forward,
}

# Presets that need a multi-device backend: preset -> device count.
# The CLI (and any other single-device driver) re-execs these under
# XLA_FLAGS=--xla_force_host_platform_device_count=<n>.
MULTI_DEVICE_PRESETS: Dict[str, int] = {
    'paged-tp': 2,
    'paged-tp-int8': 2,
    'paged-gang': 4,
}

DEFAULT_PRESETS: List[str] = [
    'slot', 'paged', 'slot-spec', 'paged-spec', 'telemetry',
    'kv-int8', 'kv-int8-slot', 'kv-int4', 'kv-int4-slot',
    'fused-attn', 'paged-tp', 'paged-tp-int8',
    'paged-gang', 'disagg', 'int4', 'multistep', 'int4-multistep',
    'spec-multistep', 'adapters', 'adapters-slot', 'digest',
    'fleet-obs', 'llama']


def run_preset(name: str) -> AuditReport:
    """Run one preset and arm its declared byte budget (the gate):
    presets listed in costmodel.BYTE_BUDGETS fail ok() when a captured
    dispatch's per-class HBM reads exceed the declared ceiling."""
    report = PRESETS[name]()
    report.preset = name
    try:
        from skypilot_tpu.analysis import costmodel
        report.byte_budget = costmodel.budget_for(name) or {}
    except Exception as e:  # pragma: no cover - import drift
        report.cost_error = report.cost_error or \
            f'{type(e).__name__}: {e}'
    return report


def run_presets(names: Optional[List[str]] = None) -> List[AuditReport]:
    names = names or list(DEFAULT_PRESETS)
    return [run_preset(n) for n in names]
