"""graftcheck part A: repo-specific AST lint rules.

The reference SkyPilot is ~94k LoC of lock-and-thread Python whose
concurrency discipline lives in reviewers' heads; this module turns the
discipline this repo actually relies on into machine-checked rules.
Two families:

Concurrency / control-plane hygiene (GC1xx):

- **GC101 unlocked-state-write** — an attribute that is written under a
  class's threading lock somewhere is part of that lock's protected
  state; writing it without the lock elsewhere is a race.
- **GC102 blocking-under-lock** — ``time.sleep``, socket/HTTP I/O,
  subprocess waits, unbounded ``.wait()/.get()/.join()``, and (under a
  *threading* lock) sqlite-backed state-module or cluster-RPC calls
  stall every thread contending for the lock. Locks whose name marks
  them as DB-serialization locks (``db_lock``, ``_state_lock``,
  ``_scheduler_lock``, ``FileLock``) are exempt from the state-module
  check only — serializing DB access is their entire job.
- **GC103 rpc-no-timeout** — ``urlopen``/``create_connection`` without
  a timeout turns a wedged peer into a wedged controller.
- **GC104 bare-except** — ``except:`` catches ``KeyboardInterrupt`` and
  ``SystemExit``; never acceptable.
- **GC105 swallowed-except** — ``except Exception`` whose body neither
  logs, raises, nor does any real work erases the only evidence of a
  failure. (Narrow exception types may be silently dropped; broad ones
  may not.)
- **GC107 handler-no-timeout** — an ``http.server`` request handler
  without a ``timeout`` class attribute lets one slow-loris client pin
  a server thread forever.
- **GC108 proposer-under-lock** — speculative-decoding proposer host
  work (``prepare_proposals``/``ngram_propose`` — per-slot numpy n-gram
  matching) invoked while holding a lock serializes every HTTP handler
  behind proposer CPU time; the serve loop runs it before locking.
- **GC109 adhoc-timing** — ``time.time()`` / ``perf_counter()`` /
  ``monotonic()`` calls in the ``inference/`` hot paths outside the
  telemetry helpers. Timing on the engine hot path must route through
  ``skypilot_tpu.telemetry`` (``clock`` / the step-phase profiler) so
  overhead is accounted, phases land in the registry, and a stray
  timing pair around a jitted dispatch can't masquerade as device
  time (inside jit bodies GC201 already fires; this rule covers the
  host side).
- **GC111 sync-engine-call-in-coroutine** — synchronous engine-path
  calls (``step``/``submit``/``add_request``/``run_to_completion``)
  or unbounded blocking waits (argless ``.get()``/``.wait()``/
  ``.join()`` with no timeout) inside an ``async def`` in ``serve/``.
  One such call freezes the whole event loop — every concurrent
  stream stalls behind one engine step. Coroutines must consume
  through the async adapters (``Outbox.aget``) or hand blocking work
  to a thread (``await loop.run_in_executor(...)``).
- **GC112 fixed-sleep-retry** — ``time.sleep`` with a loop-invariant
  delay inside a ``while``/``for`` loop in ``serve/`` or ``jobs/``.
  A fleet of controllers/retriers sleeping the same fixed interval
  produces synchronized retry storms (every replica relaunches
  against the same exhausted quota at the same instant) and
  lockstep DB/RPC polling. Retry/poll loops must back off (reassign
  the delay inside the loop), jitter (draw from ``random``), or wait
  on an ``Event`` with a timeout. The delay counts as dynamic when
  its expression contains a ``random``-module/RNG call or any name
  reassigned within the loop.
- **GC114 wide-float-kv-on-wire** — ``.astype`` to a wide float dtype
  (bfloat16/float16/float32/float64) or any ``dequant*`` call inside a
  KV transfer path (``inference/kv_transfer.py``, ``serve/disagg.py``).
  Disaggregated handoffs move int8 KV as codes + absmax scales in the
  STORED dtype; the wire codec never converts — widening KV for the
  wire doubles handoff bytes and silently defeats the whole
  disaggregation economics.
- **GC115 wallclock-in-scaling-path** — a direct ``time.time()`` /
  ``time.monotonic()`` call anywhere in ``serve/autoscalers.py`` or
  ``serve/forecaster.py``. Scaling and forecast decisions are
  clock-injectable (the ``now`` parameter / constructor ``clock=``)
  so tests replay recorded traces to identical decisions; one raw
  wall-clock read re-introduces nondeterminism invisibly. Referencing
  ``time.time`` as an injectable default argument is the mechanism
  itself and stays legal — only *calls* are flagged.
- **GC117 wallclock-in-simulator** — any ``time.time()`` /
  ``time.monotonic()`` / ``time.sleep()`` (and *_ns/perf_counter
  variants) call anywhere under ``serve/sim/``. The fleet simulator's
  one time axis is the virtual clock (``EventLoop.now`` /
  ``EventLoop.sleep``); a single wall-clock read or real sleep makes
  same-seed runs diverge and silently breaks the byte-identical
  event-log replay contract.
- **GC118 unknown-fault-site** — a ``faults.fire('<site>')`` call
  whose site string literal is not in the central site registry
  (``serve/faults.py FAULT_SITES``). A typo'd site parses fine, counts
  nothing, and SILENTLY never fires — the chaos test then passes
  because no fault was injected, which is the exact false confidence
  the fault subsystem exists to kill. Applies under ``serve/``
  (every injector hook lives there).
- **GC123 untraced-outbound-http** — a body-carrying
  ``urllib.request.Request``/``urlopen`` under ``serve/`` outside the
  trace-propagating helper (``serve/wire.py``). Every outbound hop
  that carries a request body (LB dispatch, KV ingest, gang sync,
  idempotency handoff) must ride the wire helpers so the
  ``X-Skytpu-Trace`` header survives the hop; read-only GETs and
  liveness probes (scope name mentions ``probe``) are exempt.

TPU hot-path hygiene (GC2xx), applied to the compute layer
(``inference/``, ``models/``, ``ops/``, ``train/``):

- **GC201 impure-jit** — impure or host-synchronizing calls inside a
  ``@jax.jit`` body (``time.time``, ``print``, ``np.*``, ``.item()``,
  ``float()`` on a traced value) either fail at trace time or bake a
  constant into the compiled program.
- **GC110 unscaled-int8-kv-write** — ``.astype(jnp.int8)`` in the
  compute layer outside the quantization helpers
  (``models/quantization.py``, ``quantize_*`` functions). Symmetric
  int8 KV is (codes, absmax/127 scales) pairs written through
  ``llama.quantize_kv_rows``; a bare astype silently truncates to
  ±1-integer range and drops the scale — garbage KV that still
  type-checks. (Classed with the 1xx rules because it polices a
  repo-wide write discipline, not a jaxpr property.)
- **GC119 bare-int4-bit-twiddling** — ``.astype(int4/uint4)`` or a
  hand-rolled nibble op (``<< 4`` / ``>> 4`` / ``& 0xF``) in the
  compute layer outside ``models/quantization.py``. Packed int4 has
  exactly ONE layout contract (pack axis = last contracted, low
  nibble first, sign-extended codes, absmax/7 scales) defined next to
  ``pack_int4``/``unpack_int4``/``qeinsum``; a local re-implementation
  that disagrees on any of those produces numerically-wrong weights
  that still type-check.
- **GC120 unjournaled-lifecycle-write** — a replica-row / journal /
  controller-note mutation (``serve_state`` spelling or the
  ``ControlPlaneEnv`` seam) in ``serve/replica_managers.py`` /
  ``serve/controller.py`` outside the journaled persist helpers
  (``_persist`` / ``_untrack`` / ``_journal_start`` /
  ``_journal_finish`` / ``_put_note`` / ``_del_note`` /
  ``_persist_autoscaler_state``). Restart reconciliation replays the
  journal; a write it didn't see is state it cannot rebuild.
- **GC121 per-layer-pool-read** — a per-layer pool slice
  (``lax.dynamic_index_in_dim`` over a ``[L, ...]`` KV pool, or a
  scalar layer subscript) or a ``_gather_layer`` call inside a
  decode-scoped function in ``inference/``. The paged decode path is
  KV-bandwidth-bound: slicing the stacked pool makes XLA materialize
  that layer's whole pool as a fresh operand, and gather-per-layer
  materializes a full KV copy per layer per step — exactly the
  traffic the paged-attention kernels (scalar-prefetch layer index,
  cross-layer fused variant) exist to avoid. Decode code hands the
  FULL stacked pool to the kernels; prefill/verify-shaped functions
  (compute-bound, need contiguous rows) are exempt.
- **GC122 unbounded-lb-map-growth** — a growth mutation on a
  ``self.*`` container (``self.x[k] = v``, ``.append``, ``.add``,
  ``.setdefault``, ``.update``, ...) in
  ``serve/load_balancing_policies.py`` outside the
  :class:`BoundedStore` helper. LB policies run for months and see
  millions of sessions/replicas churn through; a raw per-key insert
  on a policy attribute is a slow memory leak with no eviction and no
  telemetry. Every runtime table goes through ``BoundedStore``
  (TTL + LRU cap, evictions counted loudly); wholesale reassignment
  (``self.x = dict(...)``) stays legal — it replaces, never grows.
- **GC202 host-sync** — device->host readbacks outside the sanctioned
  :func:`skypilot_tpu.utils.host.host_sync` helper (bare
  ``np.asarray(x)``, ``.item()``, ``jax.device_get``,
  ``block_until_ready``, ``float(x)``). One accidental sync in the
  decode loop costs a dispatch round trip (~100 ms through a remote
  PJRT tunnel) *per step*. ``np.asarray(x, dtype)`` — the explicit
  host-side conversion idiom — is allowed; the bare one-argument form
  is the classic accidental-sync spelling.

Suppression: ``# graftcheck: disable=GC102`` (comma-list or ``all``)
on the offending line, or a checked-in baseline (``graftcheck.baseline``)
of fingerprints for pre-existing violations — new ones hard-fail.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

RULES: Dict[str, str] = {
    'GC101': 'unlocked-state-write: attribute guarded by a lock '
             'elsewhere is written without holding it',
    'GC102': 'blocking-under-lock: blocking call (sleep / socket / '
             'subprocess / sqlite state / cluster RPC / unbounded wait) '
             'while holding a lock',
    'GC103': 'rpc-no-timeout: network call with no timeout',
    'GC104': 'bare-except: except: catches KeyboardInterrupt/SystemExit',
    'GC105': 'swallowed-except: broad except whose body neither logs, '
             'raises, nor acts',
    'GC107': 'handler-no-timeout: http.server handler class without a '
             'timeout attribute (slow-loris pins a thread)',
    'GC108': 'proposer-under-lock: speculative-proposer host work '
             '(n-gram matching) invoked while holding a lock — call '
             'prepare_proposals() BEFORE taking the engine lock',
    'GC109': 'adhoc-timing: wall-clock/perf-counter call in an '
             'inference hot path — use skypilot_tpu.telemetry '
             '(clock / step-phase profiler) instead',
    'GC110': 'unscaled-int8-kv-write: .astype(jnp.int8) outside the '
             'quantization helpers — int8 KV/weight writes must go '
             'through quantize_kv_rows/models.quantization (codes + '
             'scales); a bare astype drops the scale',
    'GC111': 'sync-engine-call-in-coroutine: synchronous engine call '
             '(step/submit/add_request/...) or unbounded blocking '
             'wait inside an async def in serve/ freezes the event '
             'loop — use the async adapters (Outbox.aget) or '
             'await loop.run_in_executor(...)',
    'GC112': 'fixed-sleep-retry: time.sleep with a loop-invariant '
             'delay inside a retry/poll loop in serve/ or jobs/ — '
             'add exponential backoff and/or jitter, or wait on an '
             'Event with a timeout (fixed sleeps synchronize retry '
             'storms across the fleet)',
    'GC113': 'device-put-in-step-path: jax.device_put inside an '
             'inference/ step function — an implicit cross-mesh '
             'reshard of committed device state silently inserts '
             'collectives (or a full device round trip) into the hot '
             'loop. Host->device uploads of freshly built numpy '
             'operands go through utils.host.device_upload; placement '
             '(construction-time sharding) belongs in prepare_params '
             'or engine __init__',
    'GC114': 'wide-float-kv-on-wire: bf16/float32 conversion (or a '
             'dequantize call) on a KV transfer path — int8 KV must '
             'stay int8 codes + scales end to end (the wire codec '
             'helpers in inference/kv_transfer.py are the sanctioned '
             'spelling); dequantizing for the wire doubles handoff '
             'bytes and silently defeats the disaggregation win',
    'GC115': 'wallclock-in-scaling-path: direct time.time()/'
             'time.monotonic() call inside serve/autoscalers.py or '
             'serve/forecaster.py — scaling/forecast decisions must '
             'read the injected clock (the `now` parameter / '
             'self._clock) so recorded traces replay to identical '
             'decisions under test; referencing time.time as an '
             'injectable default is fine, calling it is not',
    'GC116': 'unbounded-gang-join: a distributed join/barrier/wait in '
             'the gang layer (serve/gang.py) with no timeout — a rank '
             'that never comes up (or a dead coordinator) would hang '
             'the whole gang forever instead of failing it fast; '
             'every distributed join must carry a timeout (and '
             'jax.distributed.initialize an initialization_timeout)',
    'GC117': 'wallclock-in-simulator: time.time()/time.monotonic()/'
             'time.sleep() call under serve/sim/ — the fleet '
             'simulator runs on the virtual clock ONLY (EventLoop.now'
             '/EventLoop.sleep); one wall-clock read makes same-seed '
             'runs diverge and silently breaks the byte-identical '
             'event-log contract',
    'GC118': 'unknown-fault-site: .fire(<site>) with a site string '
             'not in the serve/faults.py FAULT_SITES registry — a '
             'typo\'d site silently never fires, so the chaos test '
             'passes WITHOUT injecting anything (register the site '
             'or fix the spelling)',
    'GC119': 'bare-int4-bit-twiddling: int4/uint4 astype or manual '
             'nibble packing (<<4 / >>4 / &0xF) in a compute dir '
             'outside models/quantization.py — the packed-nibble '
             'layout (pack axis, sign extension, scale grouping) is '
             'defined in exactly one place; hand-rolled twiddling '
             'silently diverges from it (use pack_int4/unpack_int4/'
             'qeinsum)',
    'GC120': 'unjournaled-lifecycle-write: a replica-row / journal / '
             'note mutation in serve/replica_managers.py or '
             'serve/controller.py outside the journaled persist '
             'helpers (_persist/_untrack/_journal_start/'
             '_journal_finish/_put_note/_del_note/'
             '_persist_autoscaler_state) — crash-safe restart '
             'reconciliation is only sound if the journal can never '
             'drift from what the state machines actually did',
    'GC121': 'per-layer-pool-read: per-layer KV-pool slice '
             '(dynamic_index_in_dim / scalar layer subscript) or '
             '_gather_layer call in a decode-scoped inference '
             'function — the paged decode read goes through the '
             'paged-attention kernels (scalar-prefetch layer index, '
             'or the cross-layer fused kernel), never a materialized '
             'per-layer pool copy; prefill/verify-shaped functions '
             'are exempt (compute-bound, need contiguous rows)',
    'GC122': 'unbounded-lb-map-growth: growth mutation on a self.* '
             'container (subscript-assign / append / add / setdefault '
             '/ update / ...) in serve/load_balancing_policies.py '
             'outside the BoundedStore helper — LB-policy tables see '
             'unbounded session/replica churn, so every runtime map '
             'goes through BoundedStore (TTL + LRU cap, evictions '
             'counted); wholesale reassignment stays legal',
    'GC123': 'untraced-outbound-http: body-carrying urllib '
             'Request/urlopen under serve/ outside serve/wire.py — a '
             'raw POST drops the X-Skytpu-Trace context at that hop '
             'and the assembled fleet trace gets a hole exactly where '
             'the cross-process leg happened; route body-carrying '
             'calls through the wire helpers (build_request / '
             'post_json / post_bytes). Read-only GETs and liveness '
             'probes are exempt',
    'GC201': 'impure-jit: impure or host-synchronizing call inside a '
             '@jax.jit body',
    'GC202': 'host-sync: device->host readback outside the '
             'host_sync()/host_block() helpers (compute layer only)',
}

# Directories (relative to the package root) where the GC2xx hot-path
# rules apply.
COMPUTE_DIRS = ('inference', 'models', 'ops', 'train')

# The sanctioned-sync helper module: GC202 does not apply to its own
# implementation.
HOST_HELPER_SUFFIX = 'utils/host.py'

# The sanctioned quantization module: GC110 does not apply to its own
# implementation (nor to any function whose name carries 'quantize' —
# llama.quantize_kv_rows is the KV write helper the rule points at).
QUANT_HELPER_SUFFIX = 'models/quantization.py'
# Spellings of the int8 dtype as an astype argument.
_INT8_DTYPES = {'jnp.int8', 'jax.numpy.int8', 'np.int8', 'numpy.int8'}

# --------------------------------------------------------------------- GC119
# int4 nibble spellings: 4-bit dtypes as astype/asarray args, plus the
# manual bit-twiddling shapes (shift-by-4 / low-nibble mask) that
# re-implement the packed layout by hand. The quantization module is
# the one sanctioned home of both (pack_int4 / unpack_int4 / qeinsum).
_INT4_DTYPES = {'jnp.int4', 'jax.numpy.int4', 'np.int4', 'numpy.int4',
                'jnp.uint4', 'jax.numpy.uint4', 'ml_dtypes.int4',
                'ml_dtypes.uint4'}
_INT4_DTYPE_STRINGS = {'int4', 'uint4'}
# Scope names whose functions ARE nibble helpers by construction
# (mirrors GC110's 'quantize' scope exemption).
_NIBBLE_SCOPE_MARKERS = ('quantize', 'pack_int4', 'unpack_int4')

# --------------------------------------------------------------------- GC121
# The paged decode hot path is KV-bandwidth-bound: a per-layer pool
# slice forces XLA to materialize that layer's whole pool as a fresh
# operand of the consumer, and a gather-per-layer materializes a full
# KV copy per layer per step. Decode-scoped functions in inference/
# hand the FULL stacked pool to the paged-attention kernels
# (ops/paged_attention.py: the layer rides scalar prefetch; the
# cross-layer variant runs every layer in one pallas_call). Exempt
# scopes are the prefill/verify-shaped functions (compute-bound — they
# legitimately materialize contiguous rows for cached_attention) and
# the gather helper's own body; the one legacy gather fallback inside
# paged_decode_horizon is suppressed inline, so any NEW site
# hard-fails.
_POOL_SLICE_FNS = {'lax.dynamic_index_in_dim',
                   'jax.lax.dynamic_index_in_dim',
                   'dynamic_index_in_dim'}
_GATHER_LAYER_FNS = {'_gather_layer', 'gather_layer'}
_POOL_SCALE_NAMES = {'k_scale', 'v_scale'}
_GC121_EXEMPT_SCOPE_MARKERS = ('prefill', 'verify', '_gather_layer')

# --------------------------------------------------------------------- GC114
# KV transfer paths: the disaggregated-serving wire codec and handoff
# plumbing. int8 KV rides the wire as codes + scales; ANY wide-float
# conversion (or dequantize call) here is a silent 2x on handoff
# bytes — the codec never changes dtype, so these files stay free of
# both spellings entirely.
TRANSFER_PATH_SUFFIXES = ('inference/kv_transfer.py', 'serve/disagg.py')
_WIDE_FLOAT_DTYPES = {
    'jnp.bfloat16', 'jax.numpy.bfloat16', 'jnp.float32',
    'jax.numpy.float32', 'jnp.float16', 'jax.numpy.float16',
    'np.float32', 'numpy.float32', 'np.float16', 'numpy.float16',
    'np.float64', 'numpy.float64', 'ml_dtypes.bfloat16',
}
_WIDE_FLOAT_NAMES = {'bfloat16', 'float16', 'float32', 'float64'}

_SUPPRESS_RE = re.compile(r'graftcheck:\s*disable=([A-Za-z0-9,\s]+)')

# --------------------------------------------------------------------- GC102
# Calls that block regardless of what lock is held.
_ALWAYS_BLOCKING = {
    'time.sleep', 'sleep',
    'urllib.request.urlopen', 'urlopen',
    'subprocess.run', 'subprocess.call', 'subprocess.check_call',
    'subprocess.check_output',
    'socket.create_connection',
}
# Methods that block regardless of arguments.
_BLOCKING_METHODS = {'recv', 'accept', 'communicate', 'serve_forever'}
# Methods that block *unboundedly* when called with no args and no
# timeout= (Event.wait, Queue.get, Thread.join, Popen.wait).
_UNBOUNDED_WAIT_METHODS = {'wait', 'get', 'join'}
# sqlite-backed state modules and cluster-RPC-grade modules: calling
# them under a *threading* lock stalls every contending thread behind
# disk/SSH latency. (Under a DB-named lock the sqlite calls are the
# point.)
_STATE_MODULES = {'state', 'serve_state', 'global_state', 'job_lib',
                  'agent_job_lib'}
_RPC_MODULES = {'core', 'execution', 'backend_utils', 'provisioner'}
# --------------------------------------------------------------------- GC108
# Speculative-proposer host entry points: O(history x max_ngram) numpy
# matching per slot. Under the serve layer's engine lock this work
# serializes every HTTP handler behind proposer CPU time — the serve
# loop must call prepare_proposals() BEFORE locking (the engine
# revalidates and recomputes stale entries inside step()).
_PROPOSER_HOST_FNS = {'prepare_proposals', 'ngram_propose'}

# --------------------------------------------------------------------- GC111
# Synchronous engine-path entry points banned inside serve/ coroutines:
# each one either drives the engine (step / run_to_completion), takes
# the scheduler/engine locks (submit / add_request / fill_engine /
# cancel-side pops), or runs proposer CPU work — all of it blocks the
# event loop for every concurrent stream. The directory the rule
# applies to:
SERVE_DIR = 'serve'
_ENGINE_SYNC_CALLS = {'step', 'submit', 'submit_stream', 'add_request',
                      'run_to_completion', 'fill_engine', 'pop_finished',
                      'prepare_proposals'}
# Argless no-timeout waits that park the event loop (Outbox.get /
# Event.wait / Queue.get / Thread.join). With a timeout they are still
# wrong in a coroutine, but bounded — the unbounded form is the
# deadlock-shaped one this rule hard-fails.
_ASYNC_BLOCKING_WAITS = {'get', 'wait', 'join'}

# --------------------------------------------------------------------- GC112
# Directories whose retry/poll loops must back off or jitter: the
# serve control plane (replica relaunch, drain/DB polls) and the jobs
# layer (status polls, recovery relaunches) both run MANY concurrent
# loops against shared, failure-correlated resources.
RETRYLOOP_DIRS = ('serve', 'jobs')
# RNG method spellings whose presence in a sleep delay expression
# marks it as jittered (module `random`, a Random instance, numpy).
_JITTER_METHODS = {'random', 'uniform', 'expovariate', 'gauss',
                   'betavariate', 'triangular', 'randint', 'randrange',
                   'choice', 'rand', 'random_sample'}

# --------------------------------------------------------------------- GC115
# Scaling-decision modules: every decision path is clock-injectable
# (`now` parameter / constructor `clock=`), so a direct wall-clock CALL
# anywhere in them silently breaks deterministic trace replay. Name
# *references* (`clock=time.time` default args) are the injection
# mechanism itself and stay legal.
SCALING_PATH_SUFFIXES = ('serve/autoscalers.py', 'serve/forecaster.py')
_SCALING_WALLCLOCK = {'time.time', 'time.monotonic'}
_SCALING_WALLCLOCK_BARE = {'monotonic'}   # from time import monotonic

# --------------------------------------------------------------------- GC116
# The gang layer: every distributed join — barrier waits, member
# joins, follower sync waits — must be BOUNDED, or one rank that never
# comes up hangs the whole gang (the exact half-alive failure mode
# gang-atomicity exists to kill). Argless no-timeout wait/join/get/
# barrier calls are flagged file-wide (not just under locks or in
# coroutines like GC102/GC111), and jax.distributed.initialize must
# carry initialization_timeout.
GANG_PATH_SUFFIXES = ('serve/gang.py',)
_GANG_JOIN_METHODS = {'wait', 'join', 'get', 'barrier'}

# --------------------------------------------------------------------- GC117
# The fleet simulator: deterministic virtual time ONLY. Any time.*
# call here (including sleep — virtual sleeps go through
# EventLoop.sleep / the env seam) desynchronizes same-seed replays.
# Name references (e.g. passing a clock callable) stay legal, as do
# method calls like loop.sleep(...) — only the time-module spellings
# are flagged.
SIM_PATH_MARKER = '/serve/sim/'
_SIM_WALLCLOCK = {'time.time', 'time.monotonic', 'time.sleep',
                  'time.perf_counter', 'time.perf_counter_ns',
                  'time.time_ns', 'time.monotonic_ns',
                  'time.process_time'}
# from-import spellings flagged bare (ambiguous ones like 'sleep' and
# 'time' are skipped — a sim module has no business importing them
# from time either, but the dotted form is the realistic miss).
_SIM_WALLCLOCK_BARE = {'monotonic', 'perf_counter', 'time_ns',
                       'monotonic_ns'}

# --------------------------------------------------------------------- GC120
# The controller failure domain's one invariant: every lifecycle-state
# mutation (replica rows, journal ops, controller notes — spelled as a
# direct serve_state call or through the env seam) in the manager/
# controller modules goes through the journaled persist helpers, so
# restart reconciliation replays EXACTLY what the state machines did.
# Reads (get_replicas / pending_ops / get_notes / load_replica_rows)
# are not gated; service-level rows (set_service_status / ...) belong
# to the service lifecycle, not the replica journal.
LIFECYCLE_PATH_SUFFIXES = ('serve/replica_managers.py',
                           'serve/controller.py')
_LIFECYCLE_MUTATORS = {'add_or_update_replica', 'set_replica_status',
                       'remove_replica', 'persist_replica',
                       'journal_op_start', 'journal_op_finish',
                       'put_note', 'del_note'}
_LIFECYCLE_HELPER_SCOPES = ('_persist', '_untrack', '_journal_start',
                            '_journal_finish', '_put_note',
                            '_del_note', '_persist_autoscaler_state')

# --------------------------------------------------------------------- GC122
# The LB-policy module's one sanctioned mutable map is BoundedStore
# (TTL + LRU cap, loud evictions). Any OTHER growth mutation on a
# ``self.*`` container there is a slow leak: policies are resident for
# months while sessions, request keys and replica URLs churn
# unboundedly beneath them. Wholesale reassignment (``self.x =
# dict(...)``) replaces rather than grows and stays legal, as do
# mutations of locals (per-call, garbage-collected).
LB_POLICY_PATH_SUFFIXES = ('serve/load_balancing_policies.py',)
_GC122_EXEMPT_SCOPE_MARKERS = ('BoundedStore',)
_GC122_GROW_METHODS = {'append', 'appendleft', 'add', 'setdefault',
                       'update', 'extend', 'insert'}

# --------------------------------------------------------------------- GC123
# The trace-propagating outbound-HTTP helper (serve/wire.py) stamps
# X-Skytpu-Trace on every body-carrying hop (dispatch, KV ingest,
# gang sync, idempotency handoff, controller nudges). A raw
# urllib Request/urlopen WITH a body under serve/ silently drops the
# trace context at that hop — the assembled fleet trace then has a
# hole exactly where the interesting cross-process leg happened.
# Read-only GETs (no body: metrics scrapes, checkpoint exports) and
# liveness probes carry no causal payload and stay on urllib.
WIRE_HELPER_SUFFIX = 'serve/wire.py'
_GC123_HTTP_CALLS = {'urllib.request.urlopen', 'urlopen',
                     'urllib.request.Request', 'request.Request'}
_GC123_EXEMPT_SCOPE_MARKERS = ('probe',)

# --------------------------------------------------------------------- GC118
# The central fault-site registry, resolved lazily (the faults module
# imports telemetry; pulling it at import time would make the linter's
# import graph heavier than it needs to be). Falls back to None when
# the serve package is unavailable (standalone lint runs) — the rule
# then skips rather than false-positives.
_FAULT_SITES_CACHE: Optional[frozenset] = None


def _known_fault_sites() -> Optional[frozenset]:
    global _FAULT_SITES_CACHE
    if _FAULT_SITES_CACHE is None:
        try:
            from skypilot_tpu.serve import faults as _faults
        except ImportError:
            return None      # standalone lint run: skip, don't guess
        _FAULT_SITES_CACHE = frozenset(_faults.FAULT_SITES)
    return _FAULT_SITES_CACHE


# --------------------------------------------------------------------- GC109
# Ad-hoc timing calls banned from inference/ hot paths: telemetry's
# clock/profiler are the sanctioned spellings there (GC201 covers the
# inside-jit case; this covers the host side of the engine loop).
_ADHOC_TIMING = {
    'time.time', 'time.monotonic', 'time.perf_counter',
    'time.perf_counter_ns', 'time.process_time', 'time.thread_time',
}
# from-import spellings (``from time import perf_counter``).
_ADHOC_TIMING_BARE = {'perf_counter', 'perf_counter_ns', 'monotonic',
                      'process_time', 'thread_time'}

# --------------------------------------------------------------------- GC201
_IMPURE_IN_JIT = {
    'time.time', 'time.sleep', 'time.monotonic', 'time.perf_counter',
    'print', 'open', 'input',
    'np.asarray', 'np.array', 'numpy.asarray', 'numpy.array',
    'jax.device_get', 'jax.block_until_ready',
}
_IMPURE_PREFIXES_IN_JIT = ('np.random.', 'numpy.random.', 'random.')

_LOCK_FACTORIES = {'threading.Lock', 'threading.RLock',
                   'threading.Condition', 'Lock', 'RLock', 'Condition'}
_DB_LOCK_MARKERS = ('db_lock', 'state_lock', 'scheduler_lock', 'filelock')


@dataclasses.dataclass
class Violation:
    rule: str
    path: str               # repo-relative path
    line: int
    col: int
    func: str               # enclosing scope qualname ('' = module)
    message: str
    source: str             # stripped source line

    @property
    def fingerprint(self) -> str:
        """Stable identity for the baseline: deliberately excludes the
        line number so unrelated edits above a known violation don't
        invalidate the suppression."""
        return f'{self.path}::{self.rule}::{self.func}::{self.source}'

    def format(self) -> str:
        return (f'{self.path}:{self.line}:{self.col}: {self.rule} '
                f'{self.message}\n    {self.source}')


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x`` (through one Subscript level: ``self.x[k]``)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == 'self'):
        return node.attr
    return None


def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == 'timeout' for kw in call.keywords)


def _lock_category(item: ast.AST, lock_attrs: Set[str],
                   db_locals: Optional[Set[str]] = None) -> Optional[str]:
    """Classify a with-item expression: None (not a lock), 'thread'
    (in-process mutual exclusion), or 'db' (a lock whose purpose is
    serializing DB/file access — sqlite calls under it are exempt).
    ``db_locals`` are local names known to hold file locks
    (``x = filelock.FileLock(...)``)."""
    expr = item
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = _dotted(expr)
    if name is None:
        return None
    low = name.lower()
    attr = _self_attr(expr)
    if any(m in low for m in _DB_LOCK_MARKERS):
        return 'db'
    if db_locals and isinstance(expr, ast.Name) and expr.id in db_locals:
        return 'db'
    if attr is not None and attr in lock_attrs:
        return 'thread'
    if 'lock' in low.rsplit('.', 1)[-1]:
        return 'thread'
    return None


class _ClassPrepass(ast.NodeVisitor):
    """First pass over a ClassDef: find lock attributes and the set of
    self-attributes ever written while holding one (the lock's
    protected state)."""

    def __init__(self):
        self.lock_attrs: Set[str] = set()
        self.guarded_attrs: Set[str] = set()
        self._lock_depth = 0
        self._in_init = False

    def visit_FunctionDef(self, node):
        outer = self._in_init
        if node.name in ('__init__', '__new__'):
            self._in_init = True
        self.generic_visit(node)
        self._in_init = outer

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        value = node.value
        factory = None
        if isinstance(value, ast.Call):
            factory = _dotted(value.func)
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            if factory in _LOCK_FACTORIES:
                self.lock_attrs.add(attr)
            elif self._lock_depth and not self._in_init:
                self.guarded_attrs.add(attr)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        attr = _self_attr(node.target)
        if attr and self._lock_depth and not self._in_init:
            self.guarded_attrs.add(attr)
        self.generic_visit(node)

    def visit_With(self, node):
        held = any(_lock_category(i.context_expr, self.lock_attrs)
                   == 'thread' for i in node.items)
        self._lock_depth += 1 if held else 0
        self.generic_visit(node)
        self._lock_depth -= 1 if held else 0


class _Checker(ast.NodeVisitor):

    def __init__(self, rel: str, lines: List[str], is_compute: bool,
                 is_inference: bool = False,
                 is_quant_helper: bool = False,
                 is_serve: bool = False,
                 is_retryloop_dir: bool = False,
                 is_transfer_path: bool = False,
                 is_scaling_path: bool = False,
                 is_gang_path: bool = False,
                 is_sim_path: bool = False,
                 is_lifecycle_path: bool = False,
                 is_lb_policy_path: bool = False,
                 is_wire_helper: bool = False):
        self.rel = rel
        self.lines = lines
        self.is_compute = is_compute
        self.is_inference = is_inference
        self.is_quant_helper = is_quant_helper
        self.is_serve = is_serve
        self.is_retryloop_dir = is_retryloop_dir
        self.is_transfer_path = is_transfer_path
        self.is_scaling_path = is_scaling_path
        self.is_gang_path = is_gang_path
        self.is_sim_path = is_sim_path
        self.is_lifecycle_path = is_lifecycle_path
        self.is_lb_policy_path = is_lb_policy_path
        self.is_wire_helper = is_wire_helper
        self._flagged_sleeps: Set[int] = set()   # node ids (GC112 dedupe)
        # Aliased time-module spellings seen in this file:
        # ``import time as t`` -> {'t': 'time'};
        # ``from time import monotonic as mono`` -> {'mono':
        # 'time.monotonic'}. The timing rules (GC109/GC115/GC117)
        # canonicalize call names through this map so an alias can't
        # smuggle a wall-clock read past them.
        self._time_aliases: Dict[str, str] = {}
        self.violations: List[Violation] = []
        self._scope: List[str] = []
        self._class: List[Tuple[Set[str], Set[str]]] = []  # (locks, guarded)
        self._locks: List[str] = []     # categories of locks held
        self._db_locals: Set[str] = set()   # names bound to FileLocks
        self._jit_depth = 0
        self._in_init = 0
        # Innermost-function asyncness (a sync def nested inside an
        # async def runs off-loop when handed to an executor, so only
        # the IMMEDIATE enclosing function decides GC111).
        self._async_stack: List[bool] = []

    # ------------------------------------------------------------ helpers
    def _add(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, 'lineno', 1)
        src = (self.lines[line - 1].strip()
               if 0 < line <= len(self.lines) else '')
        self.violations.append(Violation(
            rule=rule, path=self.rel, line=line,
            col=getattr(node, 'col_offset', 0) + 1,
            func='.'.join(self._scope), message=message, source=src))

    @property
    def _lock_attrs(self) -> Set[str]:
        return self._class[-1][0] if self._class else set()

    @property
    def _guarded(self) -> Set[str]:
        return self._class[-1][1] if self._class else set()

    def _thread_lock_held(self) -> bool:
        return 'thread' in self._locks

    def _any_lock_held(self) -> bool:
        return bool(self._locks)

    # ------------------------------------------------------------- scopes
    def visit_ClassDef(self, node):
        pre = _ClassPrepass()
        pre.visit(node)
        self._class.append((pre.lock_attrs, pre.guarded_attrs))
        self._scope.append(node.name)
        self._check_handler_timeout(node)
        self.generic_visit(node)
        self._scope.pop()
        self._class.pop()

    def _check_handler_timeout(self, node: ast.ClassDef) -> None:
        bases = {(_dotted(b) or '').rsplit('.', 1)[-1]
                 for b in node.bases}
        if not bases & {'BaseHTTPRequestHandler', 'StreamRequestHandler',
                        'SimpleHTTPRequestHandler'}:
            return
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == 'timeout'
                    for t in stmt.targets):
                return
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and stmt.target.id == 'timeout'):
                return
        self._add('GC107', node,
                  f'{node.name} extends an http.server handler but sets '
                  'no `timeout` class attribute — a slow-loris client '
                  'pins one server thread forever')

    def _is_jit_decorated(self, node) -> bool:
        for dec in node.decorator_list:
            d = dec
            if isinstance(d, ast.Call):
                fname = _dotted(d.func)
                if fname in ('jax.jit', 'jit'):
                    return True
                if fname in ('functools.partial', 'partial') and d.args:
                    if _dotted(d.args[0]) in ('jax.jit', 'jit'):
                        return True
                continue
            if _dotted(d) in ('jax.jit', 'jit'):
                return True
        return False

    def _visit_func(self, node, is_async: bool):
        jit = self._is_jit_decorated(node)
        self._jit_depth += 1 if jit else 0
        self._in_init += 1 if node.name in ('__init__', '__new__') else 0
        self._scope.append(node.name)
        self._async_stack.append(is_async)
        self.generic_visit(node)
        self._async_stack.pop()
        self._scope.pop()
        self._in_init -= 1 if node.name in ('__init__', '__new__') else 0
        self._jit_depth -= 1 if jit else 0

    def visit_FunctionDef(self, node):
        self._visit_func(node, is_async=False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node, is_async=True)

    # ------------------------------------------------- time aliases
    def visit_Import(self, node):
        for alias in node.names:
            if alias.name == 'time' and alias.asname:
                self._time_aliases[alias.asname] = 'time'
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module == 'time' and not node.level:
            for alias in node.names:
                if alias.asname:
                    self._time_aliases[alias.asname] = \
                        f'time.{alias.name}'
        self.generic_visit(node)

    def _canon_time_name(self, name: str) -> str:
        """Canonical time.* spelling for an aliased call name:
        ``t.monotonic`` -> ``time.monotonic`` (import time as t),
        ``now`` -> ``time.time`` (from time import time as now).
        Unaliased names pass through untouched, so the bare-name
        fallbacks in the timing rules keep working."""
        if not name or not self._time_aliases:
            return name
        head, dot, rest = name.partition('.')
        target = self._time_aliases.get(head)
        if target is None:
            return name
        if dot:
            return f'time.{rest}' if target == 'time' else name
        return target

    @property
    def _in_async(self) -> bool:
        return bool(self._async_stack) and self._async_stack[-1]

    def visit_With(self, node):
        cats = [c for c in (_lock_category(i.context_expr,
                                           self._lock_attrs,
                                           self._db_locals)
                            for i in node.items) if c]
        self._locks.extend(cats)
        self.generic_visit(node)
        del self._locks[len(self._locks) - len(cats):]

    visit_AsyncWith = visit_With

    # ------------------------------------------------------------- GC112
    def visit_While(self, node):
        if self.is_retryloop_dir:
            self._check_fixed_sleep_loop(node)
        self.generic_visit(node)

    def visit_For(self, node):
        if self.is_retryloop_dir:
            self._check_fixed_sleep_loop(node)
        self.generic_visit(node)

    def _check_fixed_sleep_loop(self, loop) -> None:
        """GC112: a ``time.sleep`` whose delay never changes across
        iterations, inside a loop in serve//jobs/. The delay counts as
        dynamic when its expression draws from an RNG (jitter) or
        references a name reassigned inside the loop (backoff)."""
        assigned: Set[str] = set()
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            assigned.add(n.id)
            elif isinstance(sub, ast.AugAssign):
                for n in ast.walk(sub.target):
                    if isinstance(n, ast.Name):
                        assigned.add(n.id)
            elif isinstance(sub, ast.For):
                for n in ast.walk(sub.target):
                    if isinstance(n, ast.Name):
                        assigned.add(n.id)
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.Call) or id(sub) in \
                    self._flagged_sleeps:
                continue
            name = _dotted(sub.func)
            if name not in ('time.sleep', 'sleep') or not sub.args:
                continue
            if self._sleep_delay_is_fixed(sub.args[0], assigned):
                self._flagged_sleeps.add(id(sub))
                self._add('GC112', sub,
                          'fixed-delay sleep inside a retry/poll loop '
                          'synchronizes retry storms across the fleet '
                          '— add backoff (reassign the delay in the '
                          'loop) and/or jitter (multiply by a random '
                          'draw), or wait on an Event with a timeout')

    @staticmethod
    def _sleep_delay_is_fixed(arg: ast.AST, assigned: Set[str]) -> bool:
        """Loop-invariant delay heuristic: fixed unless the expression
        contains an RNG call, a name reassigned inside the loop, or an
        attribute/subscript/call read (unknown value — conservatively
        treated as dynamic to keep the rule low-noise)."""
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Call):
                cname = _dotted(sub.func) or ''
                leaf = cname.rsplit('.', 1)[-1]
                if (cname.split('.', 1)[0] == 'random'
                        or leaf in _JITTER_METHODS):
                    return False
                # Any other call: value unknown per-iteration — assume
                # dynamic (poll_interval()-style accessors).
                return False
            if isinstance(sub, ast.Name) and sub.id in assigned:
                return False
            if isinstance(sub, (ast.Attribute, ast.Subscript)):
                return False
        return True

    # ------------------------------------------------------------- GC101
    def _check_state_write(self, target: ast.AST, node: ast.AST) -> None:
        attr = _self_attr(target)
        if (attr and attr in self._guarded and attr not in self._lock_attrs
                and not self._in_init and not self._thread_lock_held()):
            self._add('GC101', node,
                      f'self.{attr} is written under a lock elsewhere in '
                      'this class but written here without it')

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call):
            factory = _dotted(node.value.func) or ''
            if factory.rsplit('.', 1)[-1] == 'FileLock':
                self._db_locals.update(
                    t.id for t in node.targets
                    if isinstance(t, ast.Name))
        for tgt in node.targets:
            self._check_state_write(tgt, node)
            if self.is_lb_policy_path:
                self._check_lb_map_growth_target(tgt, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_state_write(node.target, node)
        if self.is_lb_policy_path:
            self._check_lb_map_growth_target(node.target, node)
        self.generic_visit(node)

    # ----------------------------------------------------------- excepts
    def visit_ExceptHandler(self, node):
        if node.type is None:
            if not self._reraises(node):
                self._add('GC104', node,
                          'bare `except:` (catches KeyboardInterrupt / '
                          'SystemExit); catch Exception or narrower')
        elif self._is_broad(node.type) and self._is_swallowed(node):
            self._add('GC105', node,
                      'broad except swallows the failure silently — log '
                      'it, re-raise, or narrow the exception type')
        self.generic_visit(node)

    @staticmethod
    def _is_broad(type_node: ast.AST) -> bool:
        names = ([_dotted(e) for e in type_node.elts]
                 if isinstance(type_node, ast.Tuple)
                 else [_dotted(type_node)])
        return any(n in ('Exception', 'BaseException') for n in names)

    @staticmethod
    def _reraises(node: ast.ExceptHandler) -> bool:
        return any(isinstance(n, ast.Raise)
                   for n in ast.walk(node))  # type: ignore[arg-type]

    @staticmethod
    def _is_swallowed(node: ast.ExceptHandler) -> bool:
        """True when the handler body does nothing observable: no call
        (logging or otherwise), no raise, no assignment — just
        pass/continue/constant-return."""
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Call, ast.Raise, ast.Assign,
                                    ast.AugAssign, ast.Yield,
                                    ast.YieldFrom)):
                    return False
        return True

    # -------------------------------------------------------------- calls
    def visit_Call(self, node):
        name = _dotted(node.func) or ''
        method = (node.func.attr
                  if isinstance(node.func, ast.Attribute) else '')
        self._check_timeouts(node, name)
        if self.is_compute:
            # Applies inside jit bodies too — int8 KV writes live in
            # the jitted prefill/decode scans.
            self._check_int8_write(node, method)
            self._check_int4_write(node, method)
        if self.is_inference:
            self._check_device_put(node, name)
            self._check_pool_slice_call(node, name)
        if self.is_transfer_path:
            self._check_wire_dtype(node, name, method)
        if self.is_scaling_path:
            self._check_scaling_clock(node, name)
        if self.is_sim_path:
            self._check_sim_wallclock(node, name)
        if self.is_gang_path:
            self._check_gang_join(node, name, method)
        if self.is_serve and method == 'fire':
            self._check_fault_site(node)
        if self.is_serve and not self.is_wire_helper:
            self._check_untraced_http(node, name)
        if self.is_lifecycle_path:
            self._check_lifecycle_write(node, name, method)
        if self.is_lb_policy_path:
            self._check_lb_map_growth_call(node, method)
        if self.is_serve and self._in_async:
            self._check_async_engine_call(node, name, method)
        if self._any_lock_held():
            self._check_blocking_under_lock(node, name, method)
        if self._jit_depth:
            self._check_jit_purity(node, name, method)
        elif self.is_compute:
            self._check_host_sync(node, name, method)
            if self.is_inference:
                self._check_adhoc_timing(node, name)
        self.generic_visit(node)

    # Functions where jax.device_put IS the sanctioned spelling:
    # construction-time placement of params and caches (runs once, off
    # the step path). Everything else in inference/ uses
    # utils.host.device_upload (h2d-only by contract) — or is a bug.
    _PLACEMENT_FUNCS = ('prepare_params', '__init__', 'from_pretrained')

    def _check_device_put(self, node: ast.Call, name: str) -> None:
        """GC113: bare ``jax.device_put`` in an inference/ step path.
        On a committed (mesh-sharded) array device_put is an implicit
        RESHARD — a collective (or full host round trip) the zero-
        resharding steady-state contract bans; on host operands it is
        an upload that must use the auditable ``device_upload``
        spelling instead."""
        if name != 'jax.device_put':
            return
        if any(s in self._PLACEMENT_FUNCS for s in self._scope):
            return
        self._add('GC113', node,
                  'jax.device_put outside the sanctioned placement '
                  'helpers (prepare_params / __init__ / '
                  'from_pretrained) — use utils.host.device_upload '
                  'for per-step host uploads; resharding committed '
                  'state in the step path is banned')

    # ------------------------------------------------------------- GC121
    @staticmethod
    def _is_pool_named(node: ast.AST) -> bool:
        """A KV pool (or its scale pool) by naming convention: the last
        identifier segment mentions 'pool' (pool_k / ks_pool /
        cache.pool_v) or is a scale-pool field (cache.k_scale)."""
        dotted = _dotted(node)
        if not dotted:
            return False
        seg = dotted.rsplit('.', 1)[-1]
        return 'pool' in seg or seg in _POOL_SCALE_NAMES

    def _gc121_applies(self) -> bool:
        """GC121 polices DECODE-scoped inference functions only:
        prefill/verify-shaped scopes legitimately materialize
        contiguous rows (compute-bound), and the gather helper is the
        one sanctioned materializer."""
        if any(m in s for s in self._scope
               for m in _GC121_EXEMPT_SCOPE_MARKERS):
            return False
        return any('decode' in s for s in self._scope)

    def _check_pool_slice_call(self, node: ast.Call, name: str) -> None:
        """GC121 (call half): ``lax.dynamic_index_in_dim(pool, li)``
        or ``_gather_layer(...)`` in a decode scope — a materialized
        per-layer pool read on the KV-bandwidth-bound path."""
        if not self._gc121_applies():
            return
        short = name.rsplit('.', 1)[-1]
        if (name in _POOL_SLICE_FNS and node.args
                and self._is_pool_named(node.args[0])):
            self._add('GC121', node,
                      'per-layer pool slice on the paged decode path '
                      '— dynamic_index_in_dim materializes a copy of '
                      'the layer\'s whole pool per step; hand the '
                      'FULL stacked pool to the paged-attention '
                      'kernels (layer via scalar prefetch, or the '
                      'cross-layer fused kernel)')
        elif short in _GATHER_LAYER_FNS:
            self._add('GC121', node,
                      'gather-per-layer on the paged decode path — '
                      '_gather_layer materializes a full KV copy per '
                      'layer per step; decode reads go through the '
                      'paged-attention kernels instead')

    def visit_Subscript(self, node):
        """GC121 (subscript half): a scalar layer subscript of a pool
        (``pool_k[li]`` / ``pool_k[0]`` / ``pool_k[li, ...]``) in a
        decode scope — the same materialized per-layer read as the
        dynamic_index_in_dim spelling."""
        if (self.is_inference and self._gc121_applies()
                and self._is_pool_named(node.value)):
            idx = node.slice
            if isinstance(idx, ast.Tuple) and idx.elts:
                idx = idx.elts[0]
            scalar = (isinstance(idx, ast.Name)
                      or (isinstance(idx, ast.Constant)
                          and isinstance(idx.value, int)))
            if scalar:
                self._add('GC121', node,
                          'scalar layer subscript of a KV pool on the '
                          'paged decode path — a materialized '
                          'per-layer pool read; hand the FULL stacked '
                          'pool to the paged-attention kernels')
        self.generic_visit(node)

    def _check_wire_dtype(self, node: ast.Call, name: str,
                          method: str) -> None:
        """GC114: wide-float conversion or dequantize call on a KV
        transfer path. The wire codec moves KV in its STORED dtype —
        int8 codes + fp32 scales stay exactly as resident — so a
        ``.astype(bfloat16/float32/...)`` (or anything spelled
        ``dequant*``) in these files means someone is widening KV for
        the wire: 2x the handoff bytes, silently."""
        leaf = (method or name.rsplit('.', 1)[-1]).lower()
        if 'dequant' in leaf:
            self._add('GC114', node,
                      f'{leaf}() on a KV transfer path — handoffs move '
                      'int8 KV as codes + scales (the kv_transfer wire '
                      'codec); dequantizing for the wire doubles the '
                      'bytes')
            return
        if method != 'astype' or not node.args:
            return
        arg = node.args[0]
        dtype = _dotted(arg)
        wide = (dtype in _WIDE_FLOAT_DTYPES
                or (isinstance(arg, ast.Constant)
                    and arg.value in _WIDE_FLOAT_NAMES))
        if wide:
            self._add('GC114', node,
                      '.astype(wide float) on a KV transfer path — '
                      'int8 KV must stay int8 codes + scales end to '
                      'end; serialize with the kv_transfer wire codec '
                      '(no dtype conversion)')

    def _check_int8_write(self, node: ast.Call, method: str) -> None:
        """GC110: ``x.astype(jnp.int8)`` / ``x.astype('int8')`` outside
        the quantization helpers. Exempt: the quantization module
        itself, and any enclosing function whose name carries
        'quantize' (``quantize_kv_rows``, ``_quantize_array``, ...) —
        those ARE the sanctioned spellings this rule routes writers
        to."""
        if (self.is_quant_helper or method != 'astype'
                or not node.args):
            return
        if any('quantize' in s for s in self._scope):
            return
        arg = node.args[0]
        dtype = _dotted(arg)
        is_int8 = (dtype in _INT8_DTYPES
                   or (isinstance(arg, ast.Constant)
                       and arg.value == 'int8'))
        if is_int8:
            self._add('GC110', node,
                      '.astype(int8) outside the quantization helpers '
                      'silently drops the scale — write int8 KV/weights '
                      'through llama.quantize_kv_rows / '
                      'models.quantization (codes + absmax scales)')

    def _check_int4_write(self, node: ast.Call, method: str) -> None:
        """GC119 (call half): ``x.astype(jnp.int4/uint4)`` — or the
        string spellings — outside the quantization module. A bare
        4-bit astype bypasses the one packed-nibble layout contract
        (pack axis, sign extension, scale grouping)."""
        if (self.is_quant_helper or method != 'astype'
                or not node.args):
            return
        if any(m in s for s in self._scope
               for m in _NIBBLE_SCOPE_MARKERS):
            return
        arg = node.args[0]
        dtype = _dotted(arg)
        is_int4 = (dtype in _INT4_DTYPES
                   or (isinstance(arg, ast.Constant)
                       and arg.value in _INT4_DTYPE_STRINGS))
        if is_int4:
            self._add('GC119', node,
                      '.astype(int4/uint4) outside the quantization '
                      'helpers — the packed-nibble layout is defined '
                      'once in models/quantization.py (pack_int4/'
                      'unpack_int4/qeinsum); a bare 4-bit cast '
                      'silently diverges from it')

    def visit_BinOp(self, node):
        """GC119 (operator half): manual nibble twiddling — ``<< 4`` /
        ``>> 4`` / ``& 0xF`` — in a compute dir outside the
        quantization module's sanctioned pack/unpack helpers."""
        if (self.is_compute and not self.is_quant_helper
                and not any(m in s for s in self._scope
                            for m in _NIBBLE_SCOPE_MARKERS)):
            nibble = (
                (isinstance(node.op, (ast.LShift, ast.RShift))
                 and isinstance(node.right, ast.Constant)
                 and node.right.value == 4)
                or (isinstance(node.op, ast.BitAnd)
                    and any(isinstance(s, ast.Constant)
                            and s.value == 0xF
                            for s in (node.left, node.right))))
            if nibble:
                self._add('GC119', node,
                          'manual nibble bit-twiddling (<<4 / >>4 / '
                          '&0xF) in a compute dir — int4 packing has '
                          'exactly one layout, defined in models/'
                          'quantization.py; use pack_int4/unpack_int4 '
                          '(or qeinsum for fused dequant)')
        self.generic_visit(node)

    def _check_async_engine_call(self, node: ast.Call, name: str,
                                 method: str) -> None:
        """GC111: a synchronous engine call or an unbounded blocking
        wait inside an ``async def`` in ``serve/`` parks the event
        loop — every concurrent stream stalls behind it."""
        target = method or name.rsplit('.', 1)[-1]
        if target in _ENGINE_SYNC_CALLS:
            self._add('GC111', node,
                      f'synchronous engine call {target}() inside an '
                      'async coroutine blocks the event loop for every '
                      'concurrent stream — await the async adapter '
                      '(Outbox.aget) or hand it to a thread via '
                      'await loop.run_in_executor(...)')
        elif (target in _ASYNC_BLOCKING_WAITS and not node.args
              and not _has_timeout(node)
              and not name.startswith('asyncio.')):
            self._add('GC111', node,
                      f'unbounded .{target}() inside an async '
                      'coroutine parks the event loop — await an '
                      'async primitive or run the wait in an executor')

    def _check_gang_join(self, node: ast.Call, name: str,
                         method: str) -> None:
        """GC116: an unbounded distributed join in the gang layer. A
        barrier/join/wait/get with neither a positional bound nor a
        ``timeout=`` hangs the whole gang on one dead rank; the gang
        contract is fail-fast (join timeout, heartbeat timeout), so
        every wait must carry one. ``jax.distributed.initialize`` must
        pass ``initialization_timeout`` for the same reason."""
        leaf = method or name.rsplit('.', 1)[-1]
        if name.endswith('distributed.initialize'):
            if not any(kw.arg == 'initialization_timeout'
                       for kw in node.keywords):
                self._add('GC116', node,
                          'jax.distributed.initialize without '
                          'initialization_timeout in the gang layer — '
                          'a member that never starts must fail the '
                          'gang, not hang its bootstrap forever')
            return
        if (leaf in _GANG_JOIN_METHODS and not node.args
                and not _has_timeout(node)):
            self._add('GC116', node,
                      f'unbounded .{leaf}() in the gang layer — a '
                      'distributed join with no timeout hangs the '
                      'whole gang on one dead rank; pass timeout= '
                      '(the gang contract is fail-fast)')

    def _check_scaling_clock(self, node: ast.Call, name: str) -> None:
        """GC115: a direct wall-clock CALL in a scaling-decision
        module. The autoscaler/forecaster decision paths take an
        explicit ``now`` or draw from the injected ``clock`` — a raw
        ``time.time()`` makes the decision unreplayable under test
        (and silently divergent between the test's synthetic trace and
        production)."""
        name = self._canon_time_name(name)
        if (name in _SCALING_WALLCLOCK
                or ('.' not in name and name in _SCALING_WALLCLOCK_BARE)):
            self._add('GC115', node,
                      f'{name}() inside a scaling decision path — use '
                      'the injected clock (the `now` parameter / '
                      'self._clock) so scaling logic stays '
                      'deterministic under test')

    def _check_fault_site(self, node: ast.Call) -> None:
        """GC118: every literal site string handed to ``.fire()``
        under ``serve/`` must exist in the central registry
        (``faults.FAULT_SITES``). A typo'd site is legal Python that
        counts invocations of a site NO RULE will ever name — the hook
        silently never fires and the chaos test it was written for
        passes vacuously. Non-literal sites (a loop over a site tuple,
        e.g. the simulator's storm sweep) are skipped — their tuples
        hold registry members the fixture tests pin."""
        site = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            site = node.args[0].value
        else:
            for kw in node.keywords:
                if kw.arg == 'site' and isinstance(kw.value,
                                                   ast.Constant) \
                        and isinstance(kw.value.value, str):
                    site = kw.value.value
        if site is None:
            return
        known = _known_fault_sites()
        if known is None or site in known:
            return
        self._add('GC118', node,
                  f'.fire({site!r}) names a site missing from '
                  'serve/faults.py FAULT_SITES — this hook will '
                  'SILENTLY never fire (no rule can ever match an '
                  'unregistered site); register the site or fix the '
                  'spelling')

    def _check_untraced_http(self, node: ast.Call, name: str) -> None:
        """GC123: a body-carrying ``urllib`` Request/urlopen under
        ``serve/`` outside the wire helper. The body is what makes it
        a causal hop (dispatch, ingest, sync, handoff) — exactly the
        hops whose missing ``X-Skytpu-Trace`` header leaves a hole in
        the assembled fleet trace. Liveness probes (scope mentions
        'probe') and body-less calls (metrics GETs, checkpoint
        exports) are exempt."""
        if name not in _GC123_HTTP_CALLS:
            return
        data: Optional[ast.AST] = None
        if len(node.args) >= 2:
            data = node.args[1]
        for kw in node.keywords:
            if kw.arg == 'data':
                data = kw.value
        if data is None or (isinstance(data, ast.Constant)
                            and data.value is None):
            return
        if any(m in s.lower() for s in self._scope
               for m in _GC123_EXEMPT_SCOPE_MARKERS):
            return
        short = name.rsplit('.', 1)[-1]
        self._add('GC123', node,
                  f'body-carrying {short}() under serve/ bypasses the '
                  'trace-propagating wire helper — the X-Skytpu-Trace '
                  'header is dropped at this hop and the assembled '
                  'fleet trace gets a hole here; use serve/wire.py '
                  '(build_request / post_json / post_bytes)')

    def _check_lifecycle_write(self, node: ast.Call, name: str,
                               method: str) -> None:
        """GC120: a lifecycle-state mutation (replica row / journal op
        / controller note — via ``serve_state.*`` or the env seam)
        outside the journaled persist helpers. A write the journal
        doesn't see is a write restart reconciliation can't replay —
        the exact drift the controller failure domain exists to
        kill."""
        leaf = method or name.rsplit('.', 1)[-1]
        if leaf not in _LIFECYCLE_MUTATORS:
            return
        if any(s in _LIFECYCLE_HELPER_SCOPES for s in self._scope):
            return
        self._add('GC120', node,
                  f'{leaf}() mutates lifecycle state outside the '
                  'journaled persist helpers '
                  f'({", ".join(_LIFECYCLE_HELPER_SCOPES)}) — route '
                  'the write through them so the journal can never '
                  'drift from the state machine (restart '
                  'reconciliation replays the journal)')

    def _gc122_exempt(self) -> bool:
        return any(m in s for s in self._scope
                   for m in _GC122_EXEMPT_SCOPE_MARKERS)

    def _check_lb_map_growth_target(self, target: ast.AST,
                                    node: ast.AST) -> None:
        """GC122 (stores): ``self.x[k] = v`` / ``self.x[k] += v`` in the
        LB-policy module grows a per-key table keyed by churning
        sessions/replicas — route it through BoundedStore (put/incr)
        so TTL + LRU bound it. Plain ``self.x = ...`` (wholesale
        reassignment) and mutations of locals stay legal."""
        if not isinstance(target, ast.Subscript):
            return
        attr = _self_attr(target)
        if attr is None or self._gc122_exempt():
            return
        self._add('GC122', node,
                  f'per-key write to self.{attr}[...] in the LB-policy '
                  'hot path — sessions and replica URLs churn '
                  'unboundedly, so runtime maps here must be a '
                  'BoundedStore (put/incr: TTL + LRU cap, evictions '
                  'counted), not a raw container')

    def _check_lb_map_growth_call(self, node: ast.Call,
                                  method: str) -> None:
        """GC122 (methods): a growth-method call (append/add/update/...)
        on a ``self.*`` container in the LB-policy module — same leak,
        spelled as a method."""
        if method not in _GC122_GROW_METHODS:
            return
        if not isinstance(node.func, ast.Attribute):
            return
        attr = _self_attr(node.func.value)
        if attr is None or self._gc122_exempt():
            return
        self._add('GC122', node,
                  f'self.{attr}.{method}(...) grows a container in the '
                  'LB-policy hot path — sessions and replica URLs '
                  'churn unboundedly, so runtime collections here '
                  'must go through BoundedStore (TTL + LRU cap, '
                  'evictions counted)')

    def _check_sim_wallclock(self, node: ast.Call, name: str) -> None:
        """GC117: a wall-clock read (or real sleep) inside the fleet
        simulator. The sim's one time axis is the virtual clock
        (``EventLoop.now``/``EventLoop.sleep``); a single ``time.*``
        call makes same-seed runs diverge — silently, since the run
        still *works*, it just stops being byte-replayable."""
        name = self._canon_time_name(name)
        if (name in _SIM_WALLCLOCK
                or ('.' not in name and name in _SIM_WALLCLOCK_BARE)):
            self._add('GC117', node,
                      f'{name}() inside serve/sim/ — the simulator '
                      'runs on the virtual clock only (EventLoop.now '
                      '/ EventLoop.sleep); a wall-clock read breaks '
                      'the byte-identical same-seed replay contract')

    def _check_adhoc_timing(self, node: ast.Call, name: str) -> None:
        name = self._canon_time_name(name)
        if (name in _ADHOC_TIMING
                or ('.' not in name and name in _ADHOC_TIMING_BARE)):
            self._add('GC109', node,
                      f'{name}() in an inference hot path — route '
                      'timing through skypilot_tpu.telemetry '
                      '(clock.now()/clock.monotonic() or the '
                      'step-phase profiler) so overhead is accounted '
                      'and the phase lands in the registry')

    def _check_timeouts(self, node: ast.Call, name: str) -> None:
        if name.rsplit('.', 1)[-1] == 'urlopen' and not _has_timeout(node):
            self._add('GC103', node,
                      'urlopen without timeout= — a wedged peer wedges '
                      'this thread (and any lock it holds) forever')
        elif (name.endswith('create_connection')
              and not _has_timeout(node) and len(node.args) < 2):
            self._add('GC103', node,
                      'socket.create_connection without a timeout')

    def _check_blocking_under_lock(self, node: ast.Call, name: str,
                                   method: str) -> None:
        if name.rsplit('.', 1)[-1] in _PROPOSER_HOST_FNS:
            self._add('GC108', node,
                      f'{name}() (speculative-proposer host work) while '
                      'holding a lock — run it before taking the '
                      'engine lock; the engine revalidates stale '
                      'proposals itself')
            return
        if name in _ALWAYS_BLOCKING:
            self._add('GC102', node,
                      f'{name}() while holding a lock stalls every '
                      'contending thread')
            return
        if method in _BLOCKING_METHODS:
            self._add('GC102', node,
                      f'.{method}() (blocking I/O) while holding a lock')
            return
        if (method in _UNBOUNDED_WAIT_METHODS and not node.args
                and not _has_timeout(node)):
            self._add('GC102', node,
                      f'unbounded .{method}() while holding a lock — '
                      'pass timeout= or move it outside the lock')
            return
        if self._thread_lock_held():
            root = name.split('.', 1)[0]
            if root in _STATE_MODULES and '.' in name:
                self._add('GC102', node,
                          f'sqlite-backed {name}() under a threading '
                          'lock — hoist the DB write out of the hot '
                          'lock (dedicated *_db_lock locks are exempt)')
            elif root in _RPC_MODULES and '.' in name:
                self._add('GC102', node,
                          f'cluster RPC {name}() under a threading lock')

    def _check_jit_purity(self, node: ast.Call, name: str,
                          method: str) -> None:
        if (name in _IMPURE_IN_JIT
                or any(name.startswith(p)
                       for p in _IMPURE_PREFIXES_IN_JIT)):
            self._add('GC201', node,
                      f'{name}() inside a @jax.jit body is impure or '
                      'host-synchronizing — it runs at trace time, not '
                      'per step')
        elif method in ('item', 'block_until_ready') and not node.args:
            self._add('GC201', node,
                      f'.{method}() on a traced value inside @jax.jit')
        elif (name in ('float', 'int', 'bool')
              and len(node.args) == 1
              and isinstance(node.args[0], (ast.Name, ast.Subscript))):
            self._add('GC201', node,
                      f'{name}() on a traced value inside @jax.jit '
                      'forces a concretization error or a baked-in '
                      'constant')

    def _check_host_sync(self, node: ast.Call, name: str,
                         method: str) -> None:
        if name in ('jax.device_get', 'jax.block_until_ready'):
            self._add('GC202', node,
                      f'{name}() outside host_sync()/host_block() — '
                      'route the readback through '
                      'skypilot_tpu.utils.host')
        elif method in ('item', 'block_until_ready') and not node.args:
            self._add('GC202', node,
                      f'.{method}() is an implicit device sync — use '
                      'host_sync()/host_block()')
        elif (name in ('np.asarray', 'numpy.asarray')
              and len(node.args) == 1 and not node.keywords):
            self._add('GC202', node,
                      'bare np.asarray(x) on a (possibly device) array '
                      'is the classic accidental sync — use host_sync() '
                      'for readbacks, or np.asarray(x, dtype) for '
                      'explicit host-side conversion')
        elif (name == 'float' and len(node.args) == 1
              and isinstance(node.args[0], (ast.Name, ast.Subscript))):
            self._add('GC202', node,
                      'float(x) implicitly syncs a device value — use '
                      'host_sync()')


def _line_suppressions(source: str) -> Dict[int, Set[str]]:
    """line -> set of rule ids disabled on that line ('all' disables
    everything)."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = {r.strip().upper() if r.strip().lower() != 'all'
                         else 'all' for r in m.group(1).split(',')}
                out.setdefault(tok.start[0], set()).update(
                    r for r in rules if r)
    except tokenize.TokenizeError:
        pass
    return out


def check_source(rel: str, source: str) -> List[Violation]:
    """Run every rule over one file's source; returns violations with
    line-level suppressions already applied."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation(rule='GC000', path=rel, line=e.lineno or 1,
                          col=e.offset or 1, func='',
                          message=f'syntax error: {e.msg}', source='')]
    norm = rel.replace('\\', '/')
    is_compute = (any(f'/{d}/' in f'/{norm}' for d in COMPUTE_DIRS)
                  and not norm.endswith(HOST_HELPER_SUFFIX))
    is_inference = is_compute and '/inference/' in f'/{norm}'
    checker = _Checker(norm, source.splitlines(), is_compute,
                       is_inference,
                       is_quant_helper=norm.endswith(
                           QUANT_HELPER_SUFFIX),
                       is_serve=f'/{SERVE_DIR}/' in f'/{norm}',
                       is_retryloop_dir=any(
                           f'/{d}/' in f'/{norm}'
                           for d in RETRYLOOP_DIRS),
                       is_transfer_path=norm.endswith(
                           TRANSFER_PATH_SUFFIXES),
                       is_scaling_path=norm.endswith(
                           SCALING_PATH_SUFFIXES),
                       is_gang_path=norm.endswith(GANG_PATH_SUFFIXES),
                       is_sim_path=SIM_PATH_MARKER in f'/{norm}',
                       is_lifecycle_path=norm.endswith(
                           LIFECYCLE_PATH_SUFFIXES),
                       is_lb_policy_path=norm.endswith(
                           LB_POLICY_PATH_SUFFIXES),
                       is_wire_helper=norm.endswith(
                           WIRE_HELPER_SUFFIX))
    checker.visit(tree)
    suppressed = _line_suppressions(source)
    out = []
    for v in checker.violations:
        rules_off = suppressed.get(v.line, set())
        if 'all' in rules_off or v.rule in rules_off:
            continue
        out.append(v)
    return out
