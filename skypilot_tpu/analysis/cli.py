"""The ``graftcheck`` console entry point.

Subcommands:

- ``graftcheck lint [paths...]`` (default when omitted) — AST lint;
  exit 1 on violations not covered by the baseline or inline
  suppressions. ``--update-baseline`` rewrites the baseline from the
  current violations (review before committing).
- ``graftcheck audit [--preset slot|slot-monolithic|paged|slot-spec|
  paged-spec|telemetry|telemetry-paged|kv-int8|kv-int8-slot|llama]`` —
  runtime jaxpr audit of the engines' hot loops, including the
  speculative propose→verify→commit steady state and the int8-KV
  (``kv_cache_dtype='int8'`` over bf16 weights) quantize-on-write path
  (requires jax); exit 1 on unsanctioned host transfers, steady-state
  recompiles, callback primitives, or float64 promotions.
- ``graftcheck rules`` — list the rule set.
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _cmd_lint(args: argparse.Namespace) -> int:
    from skypilot_tpu.analysis import lint
    baseline = lint.load_baseline(args.baseline)
    new, old = lint.lint_paths(args.paths or None, baseline=baseline)
    if args.update_baseline:
        path = lint.write_baseline(new + old, args.baseline)
        print(f'graftcheck: baseline with {len(new) + len(old)} '
              f'fingerprint(s) written to {path}')
        return 0
    for v in sorted(new, key=lambda v: (v.path, v.line)):
        print(v.format())
    stale = baseline - {v.fingerprint for v in old}
    if stale and args.verbose:
        print(f'note: {len(stale)} baseline entr(ies) no longer match '
              'any violation — prune with --update-baseline')
    print(f'graftcheck lint: {len(new)} violation(s), '
          f'{len(old)} baselined')
    return 1 if new else 0


def _cmd_audit(args: argparse.Namespace) -> int:
    from skypilot_tpu.analysis import jaxpr_audit
    try:
        import jax  # noqa: F401
    except ImportError:
        print('graftcheck audit requires jax (the compute extra)')
        return 2
    names = args.preset or list(jaxpr_audit.DEFAULT_PRESETS)
    # Multi-device presets (paged-tp*) need >= N devices; on a
    # single-device environment re-exec JUST those in a subprocess
    # with a forced virtual CPU device count (the env must be set
    # before jax initializes — this process's backend is already
    # pinned). Same bootstrap as __graft_entry__.dryrun_multichip.
    local = [n for n in names
             if jax.device_count()
             >= jaxpr_audit.MULTI_DEVICE_PRESETS.get(n, 1)]
    remote = [n for n in names if n not in local]
    rc = 0
    for rep in jaxpr_audit.run_presets(local) if local else []:
        print(rep.format())
        if not rep.ok():
            rc = 1
    if remote:
        import os
        import subprocess
        n_dev = max(jaxpr_audit.MULTI_DEVICE_PRESETS[n] for n in remote)
        env = dict(os.environ)
        env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') +
                            f' --xla_force_host_platform_device_count='
                            f'{n_dev}').strip()
        env['JAX_PLATFORMS'] = 'cpu'
        cmd = [sys.executable, '-m', 'skypilot_tpu.analysis.cli',
               'audit'] + [x for n in remote for x in ('--preset', n)]
        print(f'graftcheck audit: re-exec for {remote} on a '
              f'{n_dev}-device virtual CPU mesh')
        proc = subprocess.run(cmd, env=env)
        rc = rc or proc.returncode
    return rc


def _cmd_rules(_args: argparse.Namespace) -> int:
    from skypilot_tpu.analysis import rules as rules_lib
    for rule, desc in sorted(rules_lib.RULES.items()):
        print(f'{rule}  {desc}')
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog='graftcheck',
        description='skypilot-tpu static analysis + jaxpr audit')
    sub = parser.add_subparsers(dest='cmd')

    p_lint = sub.add_parser('lint', help='AST lint (GC1xx/GC2xx rules)')
    p_lint.add_argument('paths', nargs='*',
                        help='files/dirs (default: the whole package)')
    p_lint.add_argument('--baseline', default=None,
                        help='baseline file (default: '
                             'graftcheck.baseline at the repo root)')
    p_lint.add_argument('--update-baseline', action='store_true',
                        help='rewrite the baseline from current '
                             'violations')
    p_lint.add_argument('-v', '--verbose', action='store_true')

    p_audit = sub.add_parser('audit',
                             help='runtime jaxpr audit of engine hot '
                                  'loops (requires jax)')
    # Choices come from the preset registry (importable without jax)
    # so new presets are runnable from the CLI the day they land.
    from skypilot_tpu.analysis import jaxpr_audit
    p_audit.add_argument('--preset', action='append',
                         choices=sorted(jaxpr_audit.PRESETS),
                         help='repeatable; default: slot, paged, '
                              'slot-spec, paged-spec, telemetry, '
                              'kv-int8, kv-int8-slot, llama')

    sub.add_parser('rules', help='list the rule set')

    args = parser.parse_args(argv)
    if args.cmd == 'audit':
        return _cmd_audit(args)
    if args.cmd == 'rules':
        return _cmd_rules(args)
    if args.cmd is None:
        args = parser.parse_args(['lint'] + (argv or []))
    return _cmd_lint(args)


if __name__ == '__main__':
    sys.exit(main())
