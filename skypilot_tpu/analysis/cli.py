"""The ``graftcheck`` console entry point.

Subcommands:

- ``graftcheck lint [paths...]`` (default when omitted) — AST lint;
  exit 1 on violations not covered by the baseline or inline
  suppressions. ``--update-baseline`` rewrites the baseline from the
  current violations (review before committing).
- ``graftcheck audit [--preset slot|slot-monolithic|paged|slot-spec|
  paged-spec|telemetry|telemetry-paged|kv-int8|kv-int8-slot|llama]`` —
  runtime jaxpr audit of the engines' hot loops, including the
  speculative propose→verify→commit steady state and the int8-KV
  (``kv_cache_dtype='int8'`` over bf16 weights) quantize-on-write path
  (requires jax); exit 1 on unsanctioned host transfers, steady-state
  recompiles, callback primitives, float64 promotions, or byte-budget
  violations.
- ``graftcheck costmodel [--preset ...]`` — static per-dispatch cost
  attribution (HBM bytes by operand class, FLOPs, collectives) for a
  preset's captured steady-state dispatches, checked against the
  preset's declared byte budget.
- ``graftcheck rules`` — list the rule set.

``lint``, ``audit`` and ``costmodel`` all take ``--json`` for
machine-readable output (schema: docs/analysis.md#graftcheck-json).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def _violation_json(v) -> Dict[str, Any]:
    return {'rule': v.rule, 'path': v.path, 'line': v.line,
            'col': v.col, 'func': v.func, 'message': v.message,
            'source': v.source}


def _cmd_lint(args: argparse.Namespace) -> int:
    from skypilot_tpu.analysis import lint
    baseline = lint.load_baseline(args.baseline)
    new, old = lint.lint_paths(args.paths or None, baseline=baseline)
    if args.update_baseline:
        path = lint.write_baseline(new + old, args.baseline)
        print(f'graftcheck: baseline with {len(new) + len(old)} '
              f'fingerprint(s) written to {path}')
        return 0
    if getattr(args, 'json', False):
        print(json.dumps({
            'ok': not new,
            'violations': [_violation_json(v) for v in
                           sorted(new, key=lambda v: (v.path, v.line))],
            'baselined': len(old),
        }, indent=1, sort_keys=True))
        return 1 if new else 0
    for v in sorted(new, key=lambda v: (v.path, v.line)):
        print(v.format())
    stale = baseline - {v.fingerprint for v in old}
    if stale and args.verbose:
        print(f'note: {len(stale)} baseline entr(ies) no longer match '
              'any violation — prune with --update-baseline')
    print(f'graftcheck lint: {len(new)} violation(s), '
          f'{len(old)} baselined')
    return 1 if new else 0


def _split_presets(names: List[str]):
    """(local, remote) preset split: multi-device presets run in a
    re-exec'd subprocess with a forced virtual CPU device count when
    this process is short on devices (the env must be set before jax
    initializes — this process's backend is already pinned)."""
    import jax

    from skypilot_tpu.analysis import jaxpr_audit
    local = [n for n in names
             if jax.device_count()
             >= jaxpr_audit.MULTI_DEVICE_PRESETS.get(n, 1)]
    return local, [n for n in names if n not in local]


def _reexec(subcmd: str, remote: List[str],
            want_json: bool) -> 'subprocess.CompletedProcess':
    import os
    import subprocess

    from skypilot_tpu.analysis import jaxpr_audit
    n_dev = max(jaxpr_audit.MULTI_DEVICE_PRESETS[n] for n in remote)
    env = dict(os.environ)
    env['XLA_FLAGS'] = (env.get('XLA_FLAGS', '') +
                        f' --xla_force_host_platform_device_count='
                        f'{n_dev}').strip()
    env['JAX_PLATFORMS'] = 'cpu'
    cmd = [sys.executable, '-m', 'skypilot_tpu.analysis.cli',
           subcmd] + [x for n in remote for x in ('--preset', n)]
    if want_json:
        cmd.append('--json')
        return subprocess.run(cmd, env=env, capture_output=True,
                              text=True)
    print(f'graftcheck {subcmd}: re-exec for {remote} on a '
          f'{n_dev}-device virtual CPU mesh')
    return subprocess.run(cmd, env=env)


def _cmd_audit(args: argparse.Namespace) -> int:
    from skypilot_tpu.analysis import jaxpr_audit
    try:
        import jax  # noqa: F401
    except ImportError:
        print('graftcheck audit requires jax (the compute extra)')
        return 2
    want_json = getattr(args, 'json', False)
    names = args.preset or list(jaxpr_audit.DEFAULT_PRESETS)
    local, remote = _split_presets(names)
    rc = 0
    reports: List[Dict[str, Any]] = []
    for rep in jaxpr_audit.run_presets(local) if local else []:
        if want_json:
            reports.append(rep.to_json())
        else:
            print(rep.format())
        if not rep.ok():
            rc = 1
    if remote:
        proc = _reexec('audit', remote, want_json)
        rc = rc or proc.returncode
        if want_json:
            try:
                reports.extend(json.loads(proc.stdout)['reports'])
            except (json.JSONDecodeError, KeyError):
                reports.append({'name': f're-exec {remote}',
                                'ok': False,
                                'error': proc.stderr[-2000:]})
                rc = rc or 1
    if want_json:
        print(json.dumps({'ok': rc == 0, 'reports': reports},
                         indent=1, sort_keys=True))
    return rc


def _cmd_costmodel(args: argparse.Namespace) -> int:
    from skypilot_tpu.analysis import costmodel, jaxpr_audit
    try:
        import jax  # noqa: F401
    except ImportError:
        print('graftcheck costmodel requires jax (the compute extra)')
        return 2
    want_json = getattr(args, 'json', False)
    names = args.preset or list(jaxpr_audit.DEFAULT_PRESETS)
    local, remote = _split_presets(names)
    rc = 0
    presets: Dict[str, Any] = {}
    for name in local:
        costs, violations = costmodel.preset_costs(name)
        if violations:
            rc = 1
        if want_json:
            presets[name] = {
                'dispatches': {k: c.to_json()
                               for k, c in costs.items()},
                'byte_budget': costmodel.budget_for(name) or {},
                'violations': violations,
            }
            continue
        print(f'=== costmodel [{name}] ===')
        if not costs:
            print('  (no dispatch captured)')
        for _label, cost in sorted(costs.items()):
            print(cost.format_table())
        for v in violations:
            print(f'  BYTE BUDGET: {v}')
    if remote:
        proc = _reexec('costmodel', remote, want_json)
        rc = rc or proc.returncode
        if want_json:
            try:
                presets.update(json.loads(proc.stdout)['presets'])
            except (json.JSONDecodeError, KeyError):
                presets[f're-exec {remote}'] = {
                    'violations': [proc.stderr[-2000:]]}
                rc = rc or 1
    if want_json:
        print(json.dumps({'ok': rc == 0, 'presets': presets},
                         indent=1, sort_keys=True))
    return rc


def _cmd_rules(_args: argparse.Namespace) -> int:
    from skypilot_tpu.analysis import rules as rules_lib
    for rule, desc in sorted(rules_lib.RULES.items()):
        print(f'{rule}  {desc}')
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog='graftcheck',
        description='skypilot-tpu static analysis + jaxpr audit')
    sub = parser.add_subparsers(dest='cmd')

    p_lint = sub.add_parser('lint', help='AST lint (GC1xx/GC2xx rules)')
    p_lint.add_argument('paths', nargs='*',
                        help='files/dirs (default: the whole package)')
    p_lint.add_argument('--baseline', default=None,
                        help='baseline file (default: '
                             'graftcheck.baseline at the repo root)')
    p_lint.add_argument('--update-baseline', action='store_true',
                        help='rewrite the baseline from current '
                             'violations')
    p_lint.add_argument('--json', action='store_true',
                        help='machine-readable output')
    p_lint.add_argument('-v', '--verbose', action='store_true')

    p_audit = sub.add_parser('audit',
                             help='runtime jaxpr audit of engine hot '
                                  'loops (requires jax)')
    # Choices come from the preset registry (importable without jax)
    # so new presets are runnable from the CLI the day they land.
    from skypilot_tpu.analysis import jaxpr_audit
    p_audit.add_argument('--preset', action='append',
                         choices=sorted(jaxpr_audit.PRESETS),
                         help='repeatable; default: slot, paged, '
                              'slot-spec, paged-spec, telemetry, '
                              'kv-int8, kv-int8-slot, llama')
    p_audit.add_argument('--json', action='store_true',
                         help='machine-readable output')

    p_cost = sub.add_parser('costmodel',
                            help='static per-dispatch byte/FLOP/'
                                 'collective attribution (requires '
                                 'jax)')
    p_cost.add_argument('--preset', action='append',
                        choices=sorted(jaxpr_audit.PRESETS),
                        help='repeatable; default: all default audit '
                             'presets')
    p_cost.add_argument('--json', action='store_true',
                        help='machine-readable output')

    sub.add_parser('rules', help='list the rule set')

    args = parser.parse_args(argv)
    if args.cmd == 'audit':
        return _cmd_audit(args)
    if args.cmd == 'costmodel':
        return _cmd_costmodel(args)
    if args.cmd == 'rules':
        return _cmd_rules(args)
    if args.cmd is None:
        args = parser.parse_args(['lint'] + (argv or []))
    return _cmd_lint(args)


if __name__ == '__main__':
    sys.exit(main())
