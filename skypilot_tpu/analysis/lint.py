"""graftcheck lint driver: file discovery, baseline handling, reporting.

The baseline file (``graftcheck.baseline`` at the repo root) holds one
violation fingerprint per line for pre-existing violations that are
understood and deliberately retained; the pytest gate
(``tests/test_analysis.py``) fails on any violation NOT in the
baseline, so new violations cannot land while old ones cannot silently
multiply. Regenerate with ``graftcheck lint --update-baseline`` only
after reviewing each retained entry.
"""
from __future__ import annotations

import os
from typing import Iterable, List, Optional, Set, Tuple

from skypilot_tpu.analysis import rules as rules_lib

BASELINE_NAME = 'graftcheck.baseline'


def repo_root() -> str:
    """The directory containing the ``skypilot_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(repo_root(), BASELINE_NAME)


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(os.path.abspath(path))
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d not in ('__pycache__', '.git')]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith('.py'))
    return out


def load_baseline(path: Optional[str] = None) -> Set[str]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return set()
    with open(path, encoding='utf-8') as f:
        return {line.rstrip('\n') for line in f
                if line.strip() and not line.startswith('#')}


def write_baseline(violations: List[rules_lib.Violation],
                   path: Optional[str] = None) -> str:
    path = path or default_baseline_path()
    with open(path, 'w', encoding='utf-8') as f:
        f.write('# graftcheck baseline: reviewed pre-existing '
                'violations (one fingerprint per line).\n'
                '# Regenerate with `graftcheck lint --update-baseline` '
                'after reviewing each entry.\n')
        for fp in sorted({v.fingerprint for v in violations}):
            f.write(fp + '\n')
    return path


def lint_paths(paths: Optional[Iterable[str]] = None,
               baseline: Optional[Set[str]] = None,
               ) -> Tuple[List[rules_lib.Violation],
                          List[rules_lib.Violation]]:
    """Lint ``paths`` (default: the whole ``skypilot_tpu`` package).
    Returns (new_violations, baselined_violations)."""
    root = repo_root()
    if paths is None:
        paths = [os.path.join(root, 'skypilot_tpu')]
    if baseline is None:
        baseline = load_baseline()
    new: List[rules_lib.Violation] = []
    old: List[rules_lib.Violation] = []
    for fpath in iter_py_files(paths):
        rel = os.path.relpath(fpath, root).replace(os.sep, '/')
        try:
            with open(fpath, encoding='utf-8') as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        for v in rules_lib.check_source(rel, source):
            (old if v.fingerprint in baseline else new).append(v)
    return new, old
