"""graftcheck: repo-specific static analysis + runtime jaxpr audit.

Part A (``rules``/``lint``) is an AST lint over the package enforcing
the concurrency and TPU hot-path discipline the serving tier depends
on; part B (``jaxpr_audit``) traces the engines' decode/chunked-prefill
steps at runtime and proves them host-transfer-free and
recompile-stable. Both gate the tier-1 test suite via
``tests/test_analysis.py`` and run standalone as the ``graftcheck``
CLI. The lint half is stdlib-only; jax is required only for the audit.
"""
from skypilot_tpu.analysis.lint import (default_baseline_path,
                                        lint_paths, load_baseline,
                                        write_baseline)
from skypilot_tpu.analysis.rules import RULES, Violation, check_source

__all__ = [
    'RULES', 'Violation', 'check_source', 'lint_paths', 'load_baseline',
    'write_baseline', 'default_baseline_path',
]
