"""SSH auth: per-user keypair generation + cloud public-key injection.

Role of reference ``sky/authentication.py`` (``get_or_generate_keys``
``:106``, GCP project-metadata injection ``:148``): every cluster is
reachable with the user's skytpu keypair; the public key rides into the
VM/TPU-VM via cloud metadata at provision time.

Keys are ed25519, generated with the ``cryptography`` library (no
ssh-keygen dependency) under ``~/.skytpu/keys/`` with a filelock so
concurrent launches don't race.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Tuple

import filelock

_KEY_NAME = 'skytpu'


def keys_dir() -> str:
    d = os.environ.get('SKYTPU_KEYS_DIR',
                       os.path.expanduser('~/.skytpu/keys'))
    os.makedirs(d, exist_ok=True)
    return d


def private_key_path() -> str:
    return os.path.join(keys_dir(), f'{_KEY_NAME}.pem')


def public_key_path() -> str:
    return private_key_path() + '.pub'


def get_or_generate_keys() -> Tuple[str, str]:
    """Returns (private_key_path, public_key_path), generating once."""
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519

    priv, pub = private_key_path(), public_key_path()
    lock = filelock.FileLock(os.path.join(keys_dir(), '.keygen.lock'))
    with lock:
        if os.path.exists(priv) and os.path.exists(pub):
            return priv, pub
        if os.path.exists(priv):          # pub lost: rederive
            with open(priv, 'rb') as f:
                key = serialization.load_ssh_private_key(f.read(),
                                                         password=None)
        else:
            key = ed25519.Ed25519PrivateKey.generate()
            pem = key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.OpenSSH,
                serialization.NoEncryption())
            fd = os.open(priv, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                         0o600)
            with os.fdopen(fd, 'wb') as f:
                f.write(pem)
        pub_line = key.public_key().public_bytes(
            serialization.Encoding.OpenSSH,
            serialization.PublicFormat.OpenSSH).decode() + ' skytpu\n'
        with open(pub, 'w', encoding='utf-8') as f:
            f.write(pub_line)
        os.chmod(pub, 0o644)
        return priv, pub


def ssh_user() -> str:
    return os.environ.get('SKYTPU_SSH_USER', 'skytpu')


def gcp_metadata_entry() -> Dict[str, Any]:
    """The metadata item GCP node/instance bodies carry so the VM boots
    with our key authorized (reference injects into project metadata;
    per-instance metadata avoids needing project-level IAM)."""
    _, pub = get_or_generate_keys()
    with open(pub, encoding='utf-8') as f:
        pub_key = f.read().strip()
    return {'key': 'ssh-keys', 'value': f'{ssh_user()}:{pub_key}'}


def configure_node_body(body: Dict[str, Any],
                        kind: str = 'tpu_vm') -> Dict[str, Any]:
    """Attach the ssh public key to a TPU node / GCE instance create
    body (both use the ``metadata`` field, with different shapes)."""
    entry = gcp_metadata_entry()
    if kind == 'tpu_vm':
        md = dict(body.get('metadata') or {})
        md[entry['key']] = entry['value']
        body['metadata'] = md
    else:
        md = dict(body.get('metadata') or {'items': []})
        items = [i for i in md.get('items', [])
                 if i.get('key') != entry['key']]
        items.append(entry)
        md['items'] = items
        body['metadata'] = md
    return body
