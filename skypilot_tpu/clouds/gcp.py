"""GCP cloud: TPU slices (first-class), GPU VMs, CPU VMs.

Role of reference ``sky/clouds/gcp.py`` (feasibility ``:460-651``, TPU
specifics: stop unsupported for TPU pods ``:193-200``,
``need_cleanup_after_preemption_or_failure`` for TPU VMs ``:935-944``).
TPU-first redesign: a slice is one logical node with ``num_hosts`` hosts
(no ``num_ips_per_node`` hack); ``make_provision_config`` emits the
queued-resources/TPU-VM node config directly.
"""
from __future__ import annotations

import os
import subprocess
from typing import Iterator, List, Optional, Tuple, TYPE_CHECKING

from skypilot_tpu import catalog
from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.provision import common as provision_common

if TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

_DEFAULT_TPU_VM_IMAGE_CPUS = 8


@cloud_lib.register
class GCP(cloud_lib.Cloud):
    NAME = 'gcp'
    PROVISIONER = 'gcp'

    @classmethod
    def unsupported_features(cls):
        return {
            cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
                'disk tiers are not configurable for TPU VMs',
        }

    @classmethod
    def check_stop_supported(cls, resources: 'Resources'
                             ) -> Optional[str]:
        """TPU pods (multi-host slices) cannot be stopped, only deleted
        (reference ``sky/clouds/gcp.py:193-200``)."""
        if resources.is_tpu and resources.tpu.is_pod:
            return ('TPU pod slices do not support stop; use down '
                    '(terminate) instead.')
        return None

    # ------------------------------------------------ feasibility
    def get_feasible_launchable_resources(
            self, resources: 'Resources',
            num_nodes: int = 1) -> Tuple[List['Resources'], List[str]]:
        if resources.is_tpu:
            return self._feasible_tpu(resources)
        if resources.accelerators:
            return self._feasible_gpu(resources)
        return self._feasible_cpu(resources)

    def _feasible_tpu(self, resources: 'Resources'
                      ) -> Tuple[List['Resources'], List[str]]:
        tpu = resources.tpu
        entries = catalog.zones_for_accelerator(
            tpu.name, region=resources.region, cloud='gcp')
        if resources.zone is not None:
            entries = [e for e in entries if e.zone == resources.zone]
        if not entries:
            hints = [
                name for name in catalog.get_tpus()
                if name.startswith(f'tpu-{tpu.generation}')
            ]
            return [], hints[:8]
        # One candidate per region (zone chosen by the zone loop).
        seen_regions = set()
        out = []
        for e in entries:
            if e.region in seen_regions:
                continue
            seen_regions.add(e.region)
            out.append(resources.copy(
                instance_type=e.instance_type, region=e.region))
        return out, []

    def _feasible_gpu(self, resources: 'Resources'
                      ) -> Tuple[List['Resources'], List[str]]:
        (name, count), = resources.accelerators.items()
        matches = [
            e for e in catalog.get_catalog('gcp')
            if e.accelerator_name == name and e.accelerator_count == count
            and (resources.region is None or e.region == resources.region)
            and (resources.zone is None or e.zone == resources.zone)
        ]
        if not matches:
            hints = sorted({
                e.accelerator_name for e in catalog.get_catalog('gcp')
                if e.accelerator_name
                and name.lower().split('-')[0] in e.accelerator_name.lower()
            })
            return [], hints[:8]
        best_by_region = {}
        for e in matches:
            cur = best_by_region.get(e.region)
            if cur is None or e.price < cur.price:
                best_by_region[e.region] = e
        out = [
            resources.copy(instance_type=e.instance_type, region=e.region)
            for e in sorted(best_by_region.values(), key=lambda e: e.price)
        ]
        return out, []

    def _feasible_cpu(self, resources: 'Resources'
                      ) -> Tuple[List['Resources'], List[str]]:
        cpus = memory = None
        at_least = True
        if resources.cpus:
            at_least = resources.cpus.endswith('+')
            cpus = float(resources.cpus.rstrip('+'))
        if resources.memory:
            memory = float(resources.memory.rstrip('+'))
        if resources.instance_type:
            if not catalog.instance_type_exists(resources.instance_type):
                return [], []
            return [resources.copy()], []
        entry = catalog.get_instance_type_for_cpus(
            cpus, memory, at_least=at_least, region=resources.region)
        if entry is None:
            return [], []
        return [resources.copy(instance_type=entry.instance_type,
                               region=resources.region or entry.region)], []

    def zones_provision_loop(self, resources: 'Resources'
                             ) -> Iterator[cloud_lib.Zone]:
        if resources.zone is not None:
            yield cloud_lib.Zone(resources.zone,
                                 resources.region or 'unknown')
            return
        if resources.is_tpu:
            entries = catalog.zones_for_accelerator(
                resources.tpu.name, region=resources.region)
        elif resources.accelerators:
            (name, count), = resources.accelerators.items()
            entries = catalog.zones_for_accelerator(
                name, count=count, region=resources.region)
        else:
            entries = [e for e in catalog.get_catalog('gcp')
                       if e.instance_type == resources.instance_type
                       and (resources.region is None
                            or e.region == resources.region)]
        seen = set()
        for e in entries:
            if e.zone in seen:
                continue
            seen.add(e.zone)
            yield cloud_lib.Zone(e.zone, e.region)

    # ------------------------------------------------ pricing
    def instance_type_to_hourly_cost(self, resources: 'Resources',
                                     use_spot: bool) -> float:
        accel = None
        if resources.is_tpu:
            accel = resources.tpu.name
        elif resources.accelerators:
            accel, = resources.accelerators.keys()
        return catalog.get_hourly_cost(
            resources.instance_type, use_spot=use_spot,
            region=resources.region, accelerator_name=accel)

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # GCP inter-continent egress, $/GB (reference egress model,
        # ``sky/optimizer.py:77-106`` / ``sky/clouds/gcp.py``).
        if num_gigabytes <= 0:
            return 0.0
        return 0.12 * num_gigabytes

    # ------------------------------------------------ provisioning
    def make_provision_config(self, resources: 'Resources', num_nodes: int,
                              cluster_name: str
                              ) -> provision_common.ProvisionConfig:
        provider_config = {
            'project_id': config_lib.get_nested(('gcp', 'project_id')),
            'vpc_name': config_lib.get_nested(('gcp', 'vpc_name')),
        }
        accel_args = resources.accelerator_args or {}
        node_config = {
            'use_spot': resources.use_spot,
            'disk_size_gb': resources.disk_size,
            'labels': resources.labels or {},
        }
        if resources.is_tpu:
            tpu = resources.tpu
            node_config.update({
                'kind': 'tpu_vm',
                'accelerator': tpu.name,
                'accelerator_type': tpu.accelerator_type,
                'runtime_version': resources.tpu_runtime_version,
                'hosts_per_node': tpu.num_hosts,
                'chips_per_host': tpu.chips_per_host,
                'reserved': bool(accel_args.get(
                    'reserved',
                    config_lib.get_nested(('gcp', 'reserved'), False))),
                'best_effort': bool(accel_args.get('best_effort', False)),
            })
        else:
            # 'docker:<image>' is the CONTAINER runtime (the driver
            # wraps commands on the host); the VM boots the default
            # image in that case.
            vm_image = resources.image_id
            if vm_image and vm_image.startswith('docker:'):
                vm_image = None
            node_config.update({
                'kind': 'gce',
                'machine_type': resources.instance_type,
                'hosts_per_node': 1,
                'chips_per_host': 0,
                'image_id': vm_image,
            })
            if resources.accelerators:
                (name, count), = resources.accelerators.items()
                node_config['guest_accelerators'] = {name: count}
        return provision_common.ProvisionConfig(
            provider_config=provider_config,
            node_config=node_config,
            count=num_nodes,
            tags={'skytpu-cluster-name': cluster_name},
            ports_to_open=resources.ports or [])

    # ------------------------------------------------ credentials
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('GOOGLE_APPLICATION_CREDENTIALS'):
            return True, None
        try:
            proc = subprocess.run(
                ['gcloud', 'auth', 'list',
                 '--filter=status:ACTIVE', '--format=value(account)'],
                capture_output=True, text=True, timeout=10, check=False)
            if proc.returncode == 0 and proc.stdout.strip():
                return True, None
            return False, 'No active gcloud account; run `gcloud auth login`.'
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return False, ('gcloud CLI not found and '
                           'GOOGLE_APPLICATION_CREDENTIALS not set.')


def need_cleanup_after_preemption_or_failure(
        resources: 'Resources') -> bool:
    """Preempted TPU VMs leave debris that must be deleted explicitly
    (reference ``sky/clouds/gcp.py:935-944``)."""
    return resources.is_tpu
