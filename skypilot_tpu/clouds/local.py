"""Local cloud: hermetic dev/test substrate over the local provisioner.

The reference's closest analog is ``LocalDockerBackend``
(``sky/backends/local_docker_backend.py:47``), which bypasses the
optimizer; here local is a real Cloud so the ENTIRE pipeline (optimizer →
failover → provisioner → agent) runs hermetically. It is only feasible
when explicitly requested (``cloud: local``), so it never shadows real
clouds in optimization.

"TPU slices" on the local cloud simulate topology: a tpu-v5e-16 request
becomes 2 node dirs (hosts) with the full rank/coordinator env contract —
multi-host logic is exercised for real, compute is whatever the local
machine runs.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Tuple, TYPE_CHECKING

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.provision import common as provision_common

if TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

ZONES = ('local-a', 'local-b', 'local-c')
REGION = 'local'


@cloud_lib.register
class Local(cloud_lib.Cloud):
    NAME = 'local'
    PROVISIONER = 'local'

    @classmethod
    def unsupported_features(cls):
        return {
            cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
                'local clusters have no cloud firewall',
            cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
                'local disks are what they are',
        }

    def get_feasible_launchable_resources(
            self, resources: 'Resources',
            num_nodes: int = 1) -> Tuple[List['Resources'], List[str]]:
        # Only feasible when the user pinned cloud=local.
        if resources.cloud != 'local':
            return [], []
        return [resources.copy(instance_type='local',
                               region=REGION)], []

    def zones_provision_loop(self, resources: 'Resources'
                             ) -> Iterator[cloud_lib.Zone]:
        if resources.zone is not None:
            yield cloud_lib.Zone(resources.zone, REGION)
            return
        for z in ZONES:
            yield cloud_lib.Zone(z, REGION)

    def instance_type_to_hourly_cost(self, resources: 'Resources',
                                     use_spot: bool) -> float:
        del resources, use_spot
        return 0.0

    def make_provision_config(self, resources: 'Resources', num_nodes: int,
                              cluster_name: str
                              ) -> provision_common.ProvisionConfig:
        node_config = {
            'use_spot': resources.use_spot,
            'hosts_per_node': 1,
            'chips_per_host': 0,
        }
        if resources.is_tpu:
            tpu = resources.tpu
            node_config.update({
                'accelerator': tpu.name,
                'hosts_per_node': tpu.num_hosts,
                'chips_per_host': tpu.chips_per_host,
            })
        return provision_common.ProvisionConfig(
            provider_config={},
            node_config=node_config,
            count=num_nodes,
            tags={'skytpu-cluster-name': cluster_name})

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        return True, None
