"""Cloud abstraction: capability flags, feasibility, pricing, provisioning
config generation, credentials.

Role of reference ``sky/clouds/cloud.py:117`` (``Cloud`` ABC,
``CloudImplementationFeatures`` ``:29``,
``get_feasible_launchable_resources`` ``:372``,
``make_deploy_resources_variables`` ``:280`` — here
:meth:`make_provision_config`, emitting the provisioner's dataclass
directly instead of Jinja template vars).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Iterator, List, Optional, Tuple, TYPE_CHECKING

from skypilot_tpu.provision import common as provision_common

if TYPE_CHECKING:
    from skypilot_tpu.resources import Resources


class CloudImplementationFeatures(enum.Enum):
    """Capabilities a cloud may not support; requirement checks raise
    NotSupportedError early (reference ``sky/clouds/cloud.py:29``)."""
    STOP = 'stop'
    AUTOSTOP = 'autostop'
    SPOT_INSTANCE = 'spot_instance'
    MULTI_NODE = 'multi_node'
    STORAGE_MOUNTING = 'storage_mounting'
    OPEN_PORTS = 'open_ports'
    CUSTOM_DISK_TIER = 'custom_disk_tier'


@dataclasses.dataclass(frozen=True)
class Zone:
    name: str
    region: str


class Cloud:
    """Base class; subclasses register via :func:`register`."""

    NAME = 'abstract'
    # provision dispatch key (module under skypilot_tpu.provision.<name>)
    PROVISIONER = 'abstract'

    # ------------------------------------------------ capabilities
    @classmethod
    def check_stop_supported(cls, resources: 'Resources'
                             ) -> Optional[str]:
        """None if stop is supported for these resources, else the
        human-readable reason it is not."""
        del resources
        return None

    @classmethod
    def unsupported_features(cls) -> Dict[CloudImplementationFeatures, str]:
        """feature -> human reason, for features this cloud lacks."""
        return {}

    @classmethod
    def check_features(cls, requested: List[CloudImplementationFeatures]
                       ) -> Optional[str]:
        unsupported = cls.unsupported_features()
        for feature in requested:
            if feature in unsupported:
                return f'{cls.NAME}: {unsupported[feature]}'
        return None

    # ------------------------------------------------ feasibility
    def get_feasible_launchable_resources(
            self, resources: 'Resources',
            num_nodes: int = 1) -> Tuple[List['Resources'], List[str]]:
        """Concrete launchable candidates for a (possibly partial) request.

        Returns (candidates, fuzzy_hints). Each candidate has
        instance_type/region resolved (zone left open for the zone loop
        unless the user pinned one)."""
        raise NotImplementedError

    def zones_provision_loop(self, resources: 'Resources'
                             ) -> Iterator[Zone]:
        """Zones to attempt, cheapest/preferred first (reference
        ``_yield_zones``)."""
        raise NotImplementedError

    # ------------------------------------------------ pricing
    def instance_type_to_hourly_cost(self, resources: 'Resources',
                                     use_spot: bool) -> float:
        raise NotImplementedError

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0

    # ------------------------------------------------ provisioning
    def make_provision_config(self, resources: 'Resources', num_nodes: int,
                              cluster_name: str
                              ) -> provision_common.ProvisionConfig:
        """The deploy-variables step: Resources -> ProvisionConfig."""
        raise NotImplementedError

    # ------------------------------------------------ credentials
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        """(ok, reason-if-not)."""
        raise NotImplementedError

    def __repr__(self) -> str:
        return self.NAME


CLOUD_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    CLOUD_REGISTRY[cls.NAME.lower()] = cls
    return cls


def from_name(name: str) -> Cloud:
    key = name.lower()
    if key not in CLOUD_REGISTRY:
        raise ValueError(
            f'Unknown cloud {name!r}; known: {sorted(CLOUD_REGISTRY)}')
    return CLOUD_REGISTRY[key]()
