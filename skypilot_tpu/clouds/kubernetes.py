"""Kubernetes cloud: GKE TPU node pools (and plain CPU pods).

Role of reference ``sky/clouds/kubernetes.py`` (713 LoC). The cluster is
assumed to already exist (that's the k8s model — capacity lives in node
pools); feasibility is "the kubeconfig context is reachable", pricing is
zero (the nodes are already paid for), and the provisioner schedules
pods against GKE TPU node selectors
(``sky/provision/kubernetes/utils.py:340-390``).

Zones == kubeconfig contexts: ``resources.region='kubernetes'`` with
``zone=<context>`` pins a context; otherwise the current context is
used.

Image contract: the pod image (``resources.image_id``) must provide
``python3``, ``tar``, and — for multi-host jobs, where the head pod's
driver execs into worker pods — ``kubectl`` plus a service account
bound to a role allowing ``pods/exec`` in the namespace. Single-host
jobs need only python3 + tar.
"""
from __future__ import annotations

import os
from typing import Iterator, List, Optional, Tuple, TYPE_CHECKING

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.provision import common as provision_common

if TYPE_CHECKING:
    from skypilot_tpu.resources import Resources

REGION = 'kubernetes'


@cloud_lib.register
class Kubernetes(cloud_lib.Cloud):
    NAME = 'kubernetes'
    PROVISIONER = 'kubernetes'

    @classmethod
    def unsupported_features(cls):
        return {
            cloud_lib.CloudImplementationFeatures.STOP:
                'pods cannot be stopped, only terminated',
            cloud_lib.CloudImplementationFeatures.AUTOSTOP:
                'pods cannot be stopped, only terminated',
        }

    @classmethod
    def check_stop_supported(cls, resources: 'Resources'
                             ) -> Optional[str]:
        del resources
        return 'kubernetes pods cannot be stopped; use down instead.'

    # ------------------------------------------------ feasibility
    def get_feasible_launchable_resources(
            self, resources: 'Resources',
            num_nodes: int = 1) -> Tuple[List['Resources'], List[str]]:
        del num_nodes
        # No catalog: the node pools are user-provisioned. Any TPU or
        # CPU request is feasible iff the API is reachable (checked at
        # `skytpu check` time); GPU passthrough is not supported yet.
        if resources.accelerators and not resources.is_tpu:
            return [], ['kubernetes cloud currently supports TPU node '
                        'pools and CPU pods (no GPU passthrough)']
        return [resources.copy(region=REGION)], []

    def zones_provision_loop(self, resources: 'Resources'
                             ) -> Iterator[cloud_lib.Zone]:
        # Zone == kubeconfig context. Pinned zone, else current context.
        if resources.zone is not None:
            yield cloud_lib.Zone(resources.zone, REGION)
            return
        yield cloud_lib.Zone('default', REGION)

    # ------------------------------------------------ pricing
    def instance_type_to_hourly_cost(self, resources: 'Resources',
                                     use_spot: bool) -> float:
        del resources, use_spot
        return 0.0          # node pools are already paid for

    def get_egress_cost(self, num_gigabytes: float) -> float:
        del num_gigabytes
        return 0.0

    # ------------------------------------------------ provisioning
    def make_provision_config(self, resources: 'Resources', num_nodes: int,
                              cluster_name: str
                              ) -> provision_common.ProvisionConfig:
        from skypilot_tpu import config as config_lib
        from skypilot_tpu.utils import docker_utils
        # Pods ARE containers: 'docker:<image>' maps straight to the
        # pod image (no second docker layer inside the pod).
        image = (docker_utils.docker_image_of(resources.image_id)
                 or resources.image_id)
        node_config = {
            'use_spot': resources.use_spot,
            'hosts_per_node': 1,
            'chips_per_host': 0,
            'image': image,
        }
        if resources.is_tpu:
            tpu = resources.tpu
            node_config.update({
                'accelerator': tpu.name,
                'generation': tpu.generation,
                'num_chips': tpu.num_chips,
                'hosts_per_node': tpu.num_hosts,
                'chips_per_host': tpu.chips_per_host,
            })
        return provision_common.ProvisionConfig(
            provider_config={
                'namespace': config_lib.get_nested(
                    ('kubernetes', 'namespace'), 'default'),
            },
            node_config=node_config,
            count=num_nodes,
            tags={'skytpu-cluster-name': cluster_name})

    # ------------------------------------------------ credentials
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        kubeconfig = os.environ.get(
            'KUBECONFIG', os.path.expanduser('~/.kube/config'))
        if not os.path.exists(kubeconfig):
            return False, (f'no kubeconfig at {kubeconfig}; set '
                           'KUBECONFIG or create a cluster')
        from skypilot_tpu.provision.kubernetes import k8s_client
        return k8s_client.K8sClient().check_reachable()
