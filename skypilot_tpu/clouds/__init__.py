"""Cloud registry (role of reference ``sky/clouds/__init__.py`` +
``cloud_registry.py``). Importing this package registers all clouds."""
from skypilot_tpu.clouds.cloud import (CLOUD_REGISTRY, Cloud,
                                       CloudImplementationFeatures, Zone,
                                       from_name, register)
from skypilot_tpu.clouds.gcp import GCP
from skypilot_tpu.clouds.kubernetes import Kubernetes
from skypilot_tpu.clouds.local import Local

__all__ = [
    'CLOUD_REGISTRY', 'Cloud', 'CloudImplementationFeatures', 'GCP',
    'Kubernetes', 'Local', 'Zone', 'from_name', 'register',
]
