"""Cost/time optimizer: pick the best launchable resources per task.

Role of reference ``sky/optimizer.py`` (``optimize`` ``:110``,
``_fill_in_launchable_resources`` ``:1257``, chain DP ``:411``, egress
model ``:77-106``). Differences: chains use DP with egress edge costs;
general DAGs use per-task greedy (the reference's ILP needs pulp, and its
jobs pipelines only support chains anyway — ``sky/dag.py`` docstring).

The failover loop re-runs ``optimize`` with ``blocked_resources`` grown
from provisioning errors (reference ``provision_with_retries``
``sky/backends/cloud_vm_ray_backend.py:1979-2152``).
"""
from __future__ import annotations

import enum
from typing import Dict, Iterable, List, Optional, Tuple

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import tpu_logging
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

logger = tpu_logging.init_logger(__name__)


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


def _enabled_clouds() -> List[str]:
    enabled = global_state.get_enabled_clouds()
    if not enabled:
        # Local is always available; gcp joins after `check` caches it.
        enabled = ['local', 'gcp']
    return enabled


def resources_blocked(candidate: Resources,
                      blocked: Iterable[Resources]) -> bool:
    """True if any blocked entry covers the candidate: every field the
    blocked entry pins must match (unset fields are wildcards) — the
    blocklist semantics of the reference failover loop."""
    for b in blocked:
        if b.cloud is not None and b.cloud != candidate.cloud:
            continue
        if b.region is not None and b.region != candidate.region:
            continue
        if b.zone is not None and b.zone != candidate.zone:
            continue
        if (b.instance_type is not None
                and b.instance_type != candidate.instance_type):
            continue
        if b.accelerators is not None and (
                b.accelerators != candidate.accelerators):
            continue
        if b.use_spot_specified and b.use_spot != candidate.use_spot:
            continue
        return True
    return False


def fill_in_launchable_resources(
    task: Task,
    blocked_resources: Optional[Iterable[Resources]] = None,
) -> List[Tuple[Resources, float]]:
    """Enumerate concrete (resources, $/hr) candidates for a task across
    enabled clouds, cheapest first (stable for user-ordered lists)."""
    blocked = list(blocked_resources or [])
    enabled = _enabled_clouds()
    out: List[Tuple[Resources, float]] = []
    hints: List[str] = []
    for res in task.resources:
        target_clouds = ([res.cloud] if res.cloud is not None else
                         [c for c in enabled if c != 'local'])
        for cloud_name in target_clouds:
            if cloud_name not in enabled:
                raise exceptions.NoCloudAccessError(
                    f'Cloud {cloud_name!r} requested but not enabled. '
                    f"Run `skytpu check`. Enabled: {enabled}")
            cloud = clouds_lib.from_name(cloud_name)
            feasible, fuzzy = cloud.get_feasible_launchable_resources(
                res, num_nodes=task.num_nodes)
            hints.extend(fuzzy)
            for cand in feasible:
                if resources_blocked(cand, blocked):
                    continue
                cost = cloud.instance_type_to_hourly_cost(
                    cand, cand.use_spot) * task.num_nodes
                out.append((cand, cost))
    if task.resources_ordered:
        # Keep user preference order: candidates from earlier entries first.
        return out
    return sorted(out, key=lambda rc: rc[1])


def _estimate_cost(task: Task, resources_cost_per_hr: float,
                   minimize: OptimizeTarget) -> float:
    hours = max(task.estimated_time_hours, 1e-6)
    if minimize == OptimizeTarget.TIME:
        return hours
    return resources_cost_per_hr * hours


def optimize(dag: Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             blocked_resources: Optional[Iterable[Resources]] = None,
             quiet: bool = True) -> Dag:
    """Assign ``best_resources`` to every task of the dag.

    Chains get DP with egress edge costs; non-chains greedy per task.
    Raises ResourcesUnavailableError when a task has no candidates."""
    tasks = dag.topological_order()
    per_task: Dict[Task, List[Tuple[Resources, float]]] = {}
    for task in tasks:
        candidates = fill_in_launchable_resources(task, blocked_resources)
        if not candidates:
            raise exceptions.ResourcesUnavailableError(
                f'No launchable resources satisfy task {task.name!r} '
                f'request(s): {task.resources} '
                f'(blocked: {list(blocked_resources or [])})')
        per_task[task] = candidates

    if dag.is_chain() and len(tasks) > 1:
        _optimize_chain_dp(tasks, per_task, minimize)
    elif len(tasks) > 1 and dag.edges() and _have_scipy():
        # General DAG: exact ILP over placements + egress edges
        # (reference ``_optimize_by_ilp`` ``sky/optimizer.py:472``).
        _optimize_by_ilp(dag, tasks, per_task, minimize)
    else:
        for task in tasks:
            if task.resources_ordered:
                task.set_best_resources(per_task[task][0][0])
            else:
                best = min(per_task[task],
                           key=lambda rc: _estimate_cost(
                               task, rc[1], minimize))
                task.set_best_resources(best[0])

    if not quiet:
        print(format_plan(dag, per_task))
    return dag


def _egress_cost(src: Resources, dst: Resources, gigabytes: float) -> float:
    """Egress between consecutive chain tasks (reference
    ``sky/optimizer.py:77-106``): free within a cloud, billed across."""
    if gigabytes <= 0 or src.cloud == dst.cloud:
        return 0.0
    cloud = clouds_lib.from_name(src.cloud or 'gcp')
    return cloud.get_egress_cost(gigabytes)


def _optimize_chain_dp(tasks: List[Task],
                       per_task: Dict[Task, List[Tuple[Resources, float]]],
                       minimize: OptimizeTarget) -> None:
    """DP over the chain (reference ``_optimize_by_dp``
    ``sky/optimizer.py:411``)."""
    # dp[i][j] = min total cost ending with task i on candidate j
    dp: List[List[float]] = []
    parent: List[List[int]] = []
    first = per_task[tasks[0]]
    dp.append([_estimate_cost(tasks[0], c, minimize) for _, c in first])
    parent.append([-1] * len(first))
    for i in range(1, len(tasks)):
        prev_task, cur_task = tasks[i - 1], tasks[i]
        cur = per_task[cur_task]
        row: List[float] = []
        prow: List[int] = []
        for res, cost_hr in cur:
            best_val, best_j = float('inf'), -1
            for j, (pres, _) in enumerate(per_task[prev_task]):
                val = dp[i - 1][j] + _egress_cost(
                    pres, res, prev_task.estimated_outputs_gb)
                if val < best_val:
                    best_val, best_j = val, j
            row.append(best_val + _estimate_cost(cur_task, cost_hr,
                                                 minimize))
            prow.append(best_j)
        dp.append(row)
        parent.append(prow)
    # Backtrack.
    j = min(range(len(dp[-1])), key=lambda jj: dp[-1][jj])
    for i in range(len(tasks) - 1, -1, -1):
        tasks[i].set_best_resources(per_task[tasks[i]][j][0])
        j = parent[i][j]


def _have_scipy() -> bool:
    """The ILP needs scipy (HiGHS), which the base orchestration install
    does not require; general DAGs degrade to greedy without it."""
    try:
        import scipy.optimize  # noqa: F401 pylint: disable=unused-import
        return True
    except ImportError:
        logger.warning('scipy not installed; general-DAG placement falls '
                       'back to greedy per-task choice (no egress-aware '
                       'ILP). pip install scipy to enable it.')
        return False


def _optimize_by_ilp(dag: Dag, tasks: List[Task],
                     per_task: Dict[Task, List[Tuple[Resources, float]]],
                     minimize: OptimizeTarget) -> None:
    """Exact placement for general DAGs as a 0/1 ILP (reference
    ``_optimize_by_ilp`` ``sky/optimizer.py:472``, which uses pulp; here
    scipy's HiGHS MILP — already in the environment).

    Variables: x[i,j] = task i uses candidate j; for every dag edge
    (u, v) with egress, y[u,v,j,l] = (u on j) AND (v on l), linearized
    with the standard flow constraints  sum_l y[..] = x[u,j]  and
    sum_j y[..] = x[v,l].
    """
    import numpy as np
    from scipy import optimize as sciopt
    from scipy import sparse

    idx: Dict[Task, int] = {t: i for i, t in enumerate(tasks)}
    # Variable layout: all x's first, then y's per edge.
    x_off: List[int] = []
    n_vars = 0
    for t in tasks:
        x_off.append(n_vars)
        n_vars += len(per_task[t])
    costs: List[float] = []
    for t in tasks:
        costs.extend(_estimate_cost(t, c, minimize)
                     for _, c in per_task[t])

    edges = [(u, v) for (u, v) in dag.edges()
             if u.estimated_outputs_gb > 0]
    y_off: Dict[Tuple[int, int], int] = {}
    for (u, v) in edges:
        y_off[(idx[u], idx[v])] = n_vars
        for pres, _ in per_task[u]:
            for vres, _ in per_task[v]:
                costs.append(_egress_cost(pres, vres,
                                          u.estimated_outputs_gb))
                n_vars += 1

    rows, cols, vals = [], [], []
    rhs_lo, rhs_hi = [], []
    row = 0
    # One candidate per task.
    for i, t in enumerate(tasks):
        for j in range(len(per_task[t])):
            rows.append(row)
            cols.append(x_off[i] + j)
            vals.append(1.0)
        rhs_lo.append(1.0)
        rhs_hi.append(1.0)
        row += 1
    # Edge consistency.
    for (u, v) in edges:
        ui, vi = idx[u], idx[v]
        nu, nv = len(per_task[u]), len(per_task[v])
        base = y_off[(ui, vi)]
        for j in range(nu):       # sum_l y[j,l] - x[u,j] = 0
            for l in range(nv):
                rows.append(row)
                cols.append(base + j * nv + l)
                vals.append(1.0)
            rows.append(row)
            cols.append(x_off[ui] + j)
            vals.append(-1.0)
            rhs_lo.append(0.0)
            rhs_hi.append(0.0)
            row += 1
        for l in range(nv):       # sum_j y[j,l] - x[v,l] = 0
            for j in range(nu):
                rows.append(row)
                cols.append(base + j * nv + l)
                vals.append(1.0)
            rows.append(row)
            cols.append(x_off[vi] + l)
            vals.append(-1.0)
            rhs_lo.append(0.0)
            rhs_hi.append(0.0)
            row += 1

    a_mat = sparse.csr_matrix(
        (vals, (rows, cols)), shape=(row, n_vars))
    res = sciopt.milp(
        c=np.asarray(costs),
        constraints=sciopt.LinearConstraint(a_mat, rhs_lo, rhs_hi),
        integrality=np.ones(n_vars),
        bounds=sciopt.Bounds(0, 1))
    if not res.success:       # pragma: no cover — solver failure
        logger.warning(f'ILP failed ({res.message}); falling back to '
                       'greedy per-task placement')
        for t in tasks:
            best = min(per_task[t],
                       key=lambda rc: _estimate_cost(t, rc[1], minimize))
            t.set_best_resources(best[0])
        return
    for i, t in enumerate(tasks):
        j = int(np.argmax(res.x[x_off[i]:x_off[i] + len(per_task[t])]))
        t.set_best_resources(per_task[t][j][0])


def format_plan(dag: Dag,
                per_task: Optional[Dict[Task, List]] = None) -> str:
    """Human-readable optimization table (reference comparison table)."""
    lines = ['Optimizer plan:']
    header = (f'  {"TASK":<18}{"RESOURCES":<40}{"$/HR":<10}'
              f'{"EST. COST":<10}')
    lines.append(header)
    for task in dag.topological_order():
        res = task.best_resources
        try:
            cloud = clouds_lib.from_name(res.cloud or 'gcp')
            cost_hr = cloud.instance_type_to_hourly_cost(res, res.use_spot)
        except Exception:  # pylint: disable=broad-except
            cost_hr = 0.0
        est = cost_hr * task.estimated_time_hours * task.num_nodes
        lines.append(f'  {(task.name or "-")[:17]:<18}{str(res)[:39]:<40}'
                     f'{cost_hr:<10.2f}{est:<10.2f}')
    return '\n'.join(lines)
