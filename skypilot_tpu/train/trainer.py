"""pjit trainer: FSDP/TP/SP-sharded training step for the in-tree models.

Reference parity: the reference launches external trainers (HF+PyTorch/XLA at
``examples/tpu/v6e/train.py``, torchtune at ``llm/llama-3_1-finetuning``);
this module IS the trainer, built on the standard TPU recipe:

- One jitted train step: loss (fp32 logits CE) -> grad -> optax update,
  with in/out shardings derived from the model's logical axes, so FSDP is
  "params sharded over fsdp; XLA all-gathers per layer and reduce-scatters
  grads" — no wrapper classes.
- Per-layer rematerialization via the model's ``remat='block'`` policy.
- bf16 params/activations, fp32 optimizer moments (cast on update).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from skypilot_tpu.models import llama
from skypilot_tpu.models.configs import ModelConfig
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.utils.host import host_scalars


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip_norm: float = 1.0
    attn_impl: str = 'auto'
    moe_aux_weight: float = 0.01
    # Adam first-moment dtype: bfloat16 halves optimizer memory with
    # negligible quality impact (the noisy moment tolerates it; the
    # variance stays fp32) — lets ~1B-param models train on one 16GB chip.
    mu_dtype: str = 'float32'
    # LoRA weight decay (applied to adapter leaves when the model config
    # has lora_rank > 0; the frozen base takes no updates at all, so
    # tc.weight_decay never touches it). 0.0 is the standard choice.
    lora_weight_decay: float = 0.0


def make_optimizer(tc: TrainConfig,
                   weight_decay: Optional[float] = None
                   ) -> optax.GradientTransformation:
    # Clamp warmup below the step budget: optax requires positive decay
    # span (a short --steps run with the default warmup would crash).
    warmup = min(tc.warmup_steps, max(tc.total_steps - 1, 0))
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=tc.learning_rate,
        warmup_steps=warmup, decay_steps=tc.total_steps,
        end_value=tc.learning_rate * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip_norm),
        optax.adamw(schedule, b1=tc.b1, b2=tc.b2,
                    weight_decay=(tc.weight_decay if weight_decay is None
                                  else weight_decay),
                    mu_dtype=jnp.dtype(tc.mu_dtype)),
    )


def loss_fn(params, batch: Dict[str, jax.Array], cfg: ModelConfig,
            attn_impl: str = 'auto', moe_aux_weight: float = 0.01
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Causal LM loss (+ MoE load-balancing aux).

    batch: inputs [b,s], targets [b,s], mask [b,s]."""
    logits, _, aux = llama.forward(params, batch['inputs'], cfg,
                                   attn_impl=attn_impl, return_aux=True)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = batch['targets']
    token_ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    mask = batch.get('mask')
    if mask is None:
        mask = jnp.ones_like(tgt, jnp.float32)
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    ce = -(token_ll * mask).sum() / denom
    loss = ce + moe_aux_weight * aux
    metrics = {
        'loss': ce,
        'moe_aux_loss': aux,
        'tokens': mask.sum(),
        'accuracy': ((jnp.argmax(logits, -1) == tgt) * mask).sum() / denom,
    }
    return loss, metrics


class Trainer:
    """Owns the mesh, sharded state, and the compiled train step."""

    def __init__(self, cfg: ModelConfig,
                 mesh_spec: Optional[mesh_lib.MeshSpec] = None,
                 train_config: Optional[TrainConfig] = None,
                 mesh: Optional[Mesh] = None,
                 rules: Optional[mesh_lib.LogicalRules] = None):
        self.cfg = cfg
        self.tc = train_config or TrainConfig()
        if mesh is None:
            # Multi-host launched jobs: join the jax.distributed gang
            # BEFORE reading device_count, else each host builds a
            # disconnected local mesh. No-op outside a launched job.
            mesh_lib.initialize_distributed_from_env()
            # Default spec honors the launch env contract (multi-slice
            # jobs set SKYTPU_NUM_SLICES; standalone use sees 1 slice).
            spec = mesh_spec or mesh_lib.spec_from_env()
            mesh = mesh_lib.make_mesh(spec)
        self.mesh = mesh
        self.rules = rules or mesh_lib.DEFAULT_RULES
        # LoRA configs train ONLY the adapter subtree: grads, updates,
        # and optimizer moments are adapter-sized (the memory win that
        # makes fine-tuning a 7B on one chip possible); the base is
        # frozen bit-for-bit.
        self._lora = cfg.lora_enabled
        self.optimizer = make_optimizer(
            self.tc, weight_decay=(self.tc.lora_weight_decay
                                   if self._lora else None))

        self._params_shape = jax.eval_shape(
            functools.partial(llama.init_params, cfg=cfg),
            jax.random.PRNGKey(0))
        self.param_shardings = mesh_lib.tree_shardings(
            llama.param_logical_axes(cfg), mesh, self.rules,
            shapes=self._params_shape)
        if self._lora:
            self._trainable_shape = self._params_shape['layers']['lora']
            self._trainable_shardings = \
                self.param_shardings['layers']['lora']
        else:
            self._trainable_shape = self._params_shape
            self._trainable_shardings = self.param_shardings
        self.state_shardings = self._state_shardings()
        self.batch_sharding = mesh_lib.batch_sharding(mesh, self.rules)

        self._init_jit = jax.jit(
            self._init_fn, out_shardings=self.state_shardings)
        self._step_jit = jax.jit(
            self._step_fn,
            in_shardings=(self.state_shardings,
                          {'inputs': self.batch_sharding,
                           'targets': self.batch_sharding,
                           'mask': self.batch_sharding}),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,))

    # ---------------- sharding derivation ----------------
    def _state_shardings(self) -> TrainState:
        """Derive opt_state shardings: any subtree with the same structure
        as the TRAINABLE tree (full params, or the LoRA adapter subtree)
        gets that tree's shardings (adam mu/nu); everything else is
        replicated (scalars like count)."""
        trainable_shape = self._trainable_shape
        opt_shape = jax.eval_shape(self.optimizer.init, trainable_shape)
        trainable_treedef = jax.tree.structure(trainable_shape)
        replicated = NamedSharding(self.mesh, PartitionSpec())

        def map_opt(node):
            if jax.tree.structure(node) == trainable_treedef:
                return self._trainable_shardings
            return jax.tree.map(lambda _: replicated, node)

        opt_shardings = jax.tree.map(
            map_opt, opt_shape,
            is_leaf=lambda n: (jax.tree.structure(n) == trainable_treedef
                               if not isinstance(n, jax.ShapeDtypeStruct)
                               else True))
        return TrainState(step=replicated, params=self.param_shardings,
                          opt_state=opt_shardings)

    # ---------------- init / step ----------------
    def _init_fn(self, rng: jax.Array) -> TrainState:
        params = llama.init_params(rng, self.cfg)
        opt_state = self.optimizer.init(self._trainable(params))
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state)

    def _trainable(self, params):
        from skypilot_tpu.models import lora as lora_lib
        return lora_lib.split_lora(params) if self._lora else params

    def _step_fn(self, state: TrainState, batch) -> Tuple[TrainState, Dict]:
        if self._lora:
            from skypilot_tpu.models import lora as lora_lib

            def lora_loss(lora_tree, batch):
                return loss_fn(lora_lib.with_lora(state.params, lora_tree),
                               batch, self.cfg, self.tc.attn_impl,
                               self.tc.moe_aux_weight)

            trainable = lora_lib.split_lora(state.params)
            (_, metrics), grads = jax.value_and_grad(
                lora_loss, has_aux=True)(trainable, batch)
            updates, new_opt = self.optimizer.update(grads, state.opt_state,
                                                     trainable)
            new_params = lora_lib.with_lora(
                state.params, optax.apply_updates(trainable, updates))
        else:
            (_, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, batch, self.cfg,
                                       self.tc.attn_impl,
                                       self.tc.moe_aux_weight)
            updates, new_opt = self.optimizer.update(grads, state.opt_state,
                                                     state.params)
            new_params = optax.apply_updates(state.params, updates)
        metrics['grad_norm'] = optax.global_norm(grads)
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), metrics

    def init(self, rng: jax.Array) -> TrainState:
        with self.mesh:
            return self._init_jit(rng)

    def init_from_pretrained(self, path: str) -> TrainState:
        """Start training from an HF checkpoint (fine-tuning entry):
        params come from the checkpoint (sharded per the param rules),
        optimizer state is fresh. Under LoRA the checkpoint carries no
        adapters — fresh ones are initialized (delta starts at 0)."""
        from skypilot_tpu.models import weights
        params = weights.load_hf_params(path, self.cfg)
        if self._lora and 'lora' not in params['layers']:
            from skypilot_tpu.models import lora as lora_lib
            params = lora_lib.with_lora(
                params,
                lora_lib.init_lora_layers(jax.random.PRNGKey(0), self.cfg))
        params = jax.device_put(params, self.param_shardings)

        def init_opt(p):
            return TrainState(step=jnp.zeros((), jnp.int32), params=p,
                              opt_state=self.optimizer.init(
                                  self._trainable(p)))

        with self.mesh:
            return jax.jit(init_opt,
                           out_shardings=self.state_shardings)(params)

    def step(self, state: TrainState, batch: Dict[str, jax.Array]
             ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        if 'mask' not in batch:
            batch = dict(batch,
                         mask=jnp.ones_like(batch['targets'], jnp.float32))
        with self.mesh:
            return self._step_jit(state, batch)

    def fit(self, state: TrainState, data_iter, num_steps: int,
            callbacks=None) -> TrainState:
        """Drive ``num_steps`` steps with callback instrumentation
        (``skypilot_tpu.callbacks``) — the hook the benchmark subsystem
        reads step timing from."""
        from skypilot_tpu.callbacks.base import BaseCallback, CallbackList
        if isinstance(callbacks, CallbackList):
            cbs = callbacks
        elif isinstance(callbacks, BaseCallback):
            cbs = CallbackList([callbacks])
        else:
            cbs = CallbackList(callbacks)
        for _ in range(num_steps):
            batch = next(data_iter)
            step_no = int(state.step)
            cbs.on_step_begin(step_no)
            state, metrics = self.step(state, batch)
            # Block so the timer measures compute, not dispatch.
            metrics = host_scalars(metrics)
            cbs.on_step_end(step_no, metrics)
        cbs.on_train_end()
        return state

    # ---------------- checkpointing ----------------
    def save_checkpoint(self, path: str, state: TrainState) -> None:
        """Orbax checkpoint (async-capable); the managed-jobs recovery
        contract re-mounts the same bucket path and calls restore."""
        import orbax.checkpoint as ocp
        ckpt = ocp.StandardCheckpointer()
        ckpt.save(path, state, force=True)
        ckpt.wait_until_finished()

    def restore_checkpoint(self, path: str,
                           like: Optional[TrainState] = None) -> TrainState:
        import orbax.checkpoint as ocp
        ckpt = ocp.StandardCheckpointer()
        if like is None:
            like = jax.eval_shape(self._init_fn, jax.random.PRNGKey(0))
            like = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                                   sharding=sh),
                like, self.state_shardings)
        return ckpt.restore(path, like)

    # ---------------- LoRA adapter checkpoints ----------------
    def save_adapter(self, path: str, state: TrainState) -> None:
        """Adapter-only checkpoint: the LoRA subtree, megabytes instead
        of the base's gigabytes (the artifact a fine-tuning job ships)."""
        from skypilot_tpu.models import lora as lora_lib
        if not self._lora:
            raise ValueError('save_adapter requires a LoRA config '
                             '(cfg.lora_rank > 0)')
        import orbax.checkpoint as ocp
        ckpt = ocp.StandardCheckpointer()
        ckpt.save(path, lora_lib.split_lora(state.params), force=True)
        ckpt.wait_until_finished()
        # Sidecar metadata: rank is recoverable from the tree, but a
        # wrong lora_alpha at serve time would silently mis-scale the
        # fold — record the full adapter config so load can validate.
        import json
        with open(self._adapter_meta_path(path), 'w',
                  encoding='utf-8') as f:
            json.dump({'lora_rank': self.cfg.lora_rank,
                       'lora_alpha': self.cfg.lora_alpha,
                       'lora_targets': list(self.cfg.lora_targets)}, f)

    @staticmethod
    def _adapter_meta_path(path: str) -> str:
        return path.rstrip('/') + '.lora.json'

    def load_adapter(self, path: str, state: TrainState) -> TrainState:
        """Swap a saved adapter into an existing state (base unchanged);
        optimizer moments are NOT restored — use restore_checkpoint to
        resume training exactly."""
        from skypilot_tpu.models import lora as lora_lib
        if not self._lora:
            raise ValueError('load_adapter requires a LoRA config '
                             '(cfg.lora_rank > 0)')
        import json
        import os
        meta_path = self._adapter_meta_path(path)
        if os.path.exists(meta_path):
            with open(meta_path, encoding='utf-8') as f:
                meta = json.load(f)
            mine = {'lora_rank': self.cfg.lora_rank,
                    'lora_alpha': self.cfg.lora_alpha,
                    'lora_targets': list(self.cfg.lora_targets)}
            if meta != mine:
                raise ValueError(
                    f'adapter at {path} was trained with {meta}, but '
                    f'this trainer is configured with {mine}; a '
                    f'mismatched alpha/rank would silently mis-scale '
                    f'the fold')
        import orbax.checkpoint as ocp
        ckpt = ocp.StandardCheckpointer()
        like = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            self._trainable_shape, self._trainable_shardings)
        adapter = ckpt.restore(path, like)
        return state._replace(
            params=lora_lib.with_lora(state.params, adapter))
