"""``python -m skypilot_tpu.train`` — train a model on a text corpus.

The in-tree counterpart of the reference's training recipes (which shell
out to torchrun/HF scripts, e.g. ``llm/llama-3_1-finetuning/lora.yaml``):
one command that tokenizes/packs a corpus, builds the sharded trainer,
and runs with automatic checkpoint-resume — the managed-jobs recovery
contract (relaunch on a fresh cluster with the same mounted checkpoint
bucket resumes exactly where training stopped, SURVEY §5 checkpoint/
resume).

Example (and ``examples/train_llama_job.yaml``):

    python -m skypilot_tpu.train --model llama3-1b --data gs://bkt/corpus \
        --batch 8 --seq 2048 --steps 5000 --ckpt-dir /ckpt/llama \
        --save-every 500
"""
from __future__ import annotations

import argparse
import json
import os
import time

from skypilot_tpu.utils.host import host_scalars


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog='python -m skypilot_tpu.train')
    parser.add_argument('--model', default='tiny',
                        help='preset config name (models/configs.py)')
    parser.add_argument('--data', required=True,
                        help='corpus: text file/dir/glob or gs:// URI')
    parser.add_argument('--tokenizer', default=None,
                        help='HF tokenizer dir (default: byte tokenizer)')
    parser.add_argument('--batch', type=int, default=8,
                        help='per-host batch size')
    parser.add_argument('--seq', type=int, default=512)
    parser.add_argument('--steps', type=int, default=100,
                        help='total optimizer steps (training stops at '
                             'this step, including restored progress)')
    parser.add_argument('--lr', type=float, default=3e-4)
    parser.add_argument('--warmup-steps', type=int, default=100)
    parser.add_argument('--ckpt-dir', default=None,
                        help='checkpoint dir (orbax); auto-resumes if a '
                             'checkpoint exists — the managed-jobs '
                             'MOUNT-bucket recovery contract')
    parser.add_argument('--save-every', type=int, default=500)
    parser.add_argument('--from-pretrained', default=None,
                        help='HF checkpoint dir to fine-tune from')
    parser.add_argument('--lora-rank', type=int, default=0,
                        help='> 0 enables LoRA fine-tuning: the base is '
                             'frozen, only low-rank adapters train '
                             '(models/lora.py)')
    parser.add_argument('--lora-alpha', type=float, default=16.0)
    parser.add_argument('--lora-targets', default='wq,wk,wv,wo',
                        help='comma-separated projections to adapt')
    parser.add_argument('--adapter-out', default=None,
                        help='where to save the final adapter-only '
                             'checkpoint (LoRA runs)')
    parser.add_argument('--tp', type=int, default=None)
    parser.add_argument('--sp', type=int, default=1)
    parser.add_argument('--attn-impl', default='auto')
    parser.add_argument('--mu-dtype', default='float32')
    parser.add_argument('--log-every', type=int, default=10)
    args = parser.parse_args(argv)

    import jax

    from skypilot_tpu.models import configs
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train.data import TokenStream, packed_batches
    from skypilot_tpu.train.trainer import TrainConfig, Trainer

    cfg = configs.get_config(args.model)
    if args.lora_rank > 0:
        import dataclasses
        cfg = dataclasses.replace(
            cfg, lora_rank=args.lora_rank, lora_alpha=args.lora_alpha,
            lora_targets=tuple(
                t.strip() for t in args.lora_targets.split(',') if t))
    trainer = Trainer(
        cfg,
        mesh_spec=(mesh_lib.spec_from_env(tp=args.tp, sp=args.sp)
                   if (args.tp or args.sp > 1) else None),
        train_config=TrainConfig(learning_rate=args.lr,
                                 warmup_steps=args.warmup_steps,
                                 total_steps=args.steps,
                                 attn_impl=args.attn_impl,
                                 mu_dtype=args.mu_dtype))

    data_axis = mesh_lib.data_axis_size(trainer.mesh)
    if args.batch % data_axis:
        raise SystemExit(
            f'--batch {args.batch} must be divisible by the mesh data-'
            f'parallel degree {data_axis} (slice*dp*fsdp); pick a '
            f'multiple or reduce the mesh with --tp/--sp')

    # ---- state: restore > fine-tune > fresh ----
    start_step = 0
    state = None
    latest = _latest_checkpoint(args.ckpt_dir)
    if latest is not None:
        state = trainer.restore_checkpoint(latest)
        start_step = int(state.step)
        print(f'[train] resumed from {latest} at step {start_step}',
              flush=True)
    elif args.from_pretrained:
        state = trainer.init_from_pretrained(args.from_pretrained)
        print(f'[train] initialized from {args.from_pretrained}',
              flush=True)
    else:
        state = trainer.init(jax.random.PRNGKey(0))

    # ---- data: deterministic resume = start at the restored step ----
    stream = TokenStream(args.data,
                         load_tokenizer_or_none(args.tokenizer,
                                                cfg.vocab_size))
    # Per-process rank: under a multi-host launch each host feeds its
    # own stride of the stream (jax process == dp shard of the batch).
    it = packed_batches(stream, batch=args.batch, seq=args.seq,
                        dp_rank=jax.process_index(),
                        dp_size=jax.process_count(),
                        start_step=start_step)

    t0 = time.time()
    last_logged = start_step
    for step in range(start_step, args.steps):
        state, metrics = trainer.step(state, _to_jnp(next(it)))
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            dt = time.time() - t0
            window = step + 1 - last_logged     # actual steps elapsed
            m = host_scalars(metrics)   # explicit readback (GC202)
            print(json.dumps({
                'step': step + 1,
                'loss': round(m['loss'], 4),
                'accuracy': round(m['accuracy'], 4),
                'tok_s': round(args.batch * args.seq * window
                               / max(dt, 1e-9), 1),
            }), flush=True)
            t0 = time.time()
            last_logged = step + 1
        if (args.ckpt_dir and args.save_every
                and (step + 1) % args.save_every == 0
                and step + 1 < args.steps):
            _save(trainer, state, args.ckpt_dir)
    if args.ckpt_dir:
        _save(trainer, state, args.ckpt_dir)
    if args.lora_rank > 0 and args.adapter_out:
        trainer.save_adapter(os.path.abspath(args.adapter_out), state)
        print(f'[train] adapter saved: {args.adapter_out}', flush=True)
    print(f'[train] done at step {int(state.step)}', flush=True)


def load_tokenizer_or_none(path, vocab_size):
    from skypilot_tpu.models.tokenizer import load_tokenizer
    return load_tokenizer(path, model_vocab_size=vocab_size)


def _to_jnp(batch):
    import jax.numpy as jnp
    return {k: jnp.asarray(v) for k, v in batch.items()}


def _save(trainer, state, ckpt_dir: str) -> None:
    """Write step-addressed orbax checkpoints + a LATEST pointer.
    Step-addressed dirs make the save atomic from the reader's side: the
    pointer flips only after orbax finishes."""
    step = int(state.step)
    path = os.path.abspath(os.path.join(ckpt_dir, f'step_{step}'))
    trainer.save_checkpoint(path, state)
    tmp = os.path.join(ckpt_dir, 'LATEST.tmp')
    with open(tmp, 'w', encoding='utf-8') as f:
        f.write(f'step_{step}')
    os.replace(tmp, os.path.join(ckpt_dir, 'LATEST'))
    print(f'[train] checkpoint saved: {path}', flush=True)


def _latest_checkpoint(ckpt_dir):
    if not ckpt_dir:
        return None
    pointer = os.path.join(ckpt_dir, 'LATEST')
    if not os.path.exists(pointer):
        return None
    with open(pointer, encoding='utf-8') as f:
        name = f.read().strip()
    path = os.path.abspath(os.path.join(ckpt_dir, name))
    return path if os.path.isdir(path) else None


if __name__ == '__main__':
    main()
