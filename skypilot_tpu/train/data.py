"""Text -> tokenize -> pack pipeline feeding ``Trainer.fit``.

The reference trains through recipes that lean on external data stacks
(HF datasets in ``llm/llama-3_1-finetuning/lora.yaml``); our trainer is
in-tree, so the corpus pipeline is too. Design constraints are TPU-shaped:

- **Static shapes**: every batch is exactly ``[batch, seq]`` int32 —
  documents are concatenated (EOS-separated) into one token stream and
  sliced, never padded, so XLA compiles one train step.
- **Determinism == resumability**: batch contents are a pure function of
  ``(step, dp_rank)``. Resuming from a checkpoint at step N just means
  restarting the iterator at ``start_step=N`` — no iterator state to
  snapshot, no skew between data position and optimizer step.
- **dp sharding**: each rank reads only its stride of the stream
  (``dp_rank``/``dp_size``), so multi-host training feeds disjoint data
  with no coordination.

Corpus sources: local text files, directories (``*.txt`` sorted), or
``gs://`` URIs (downloaded via ``data.cloud_stores``).
"""
from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from skypilot_tpu.models.tokenizer import BaseTokenizer, load_tokenizer


def _resolve_sources(source: str) -> List[str]:
    if source.startswith(('gs://', 's3://', 'r2://')):
        import subprocess
        import tempfile

        from skypilot_tpu.data import cloud_stores
        dest = tempfile.mkdtemp(prefix='skytpu-corpus-')
        local = os.path.join(dest,
                             os.path.basename(source.rstrip('/'))
                             or 'corpus.txt')
        subprocess.run(cloud_stores.make_download_command(source, local),
                       shell=True, check=True)
        return [local]
    if os.path.isdir(source):
        files = sorted(glob.glob(os.path.join(source, '*.txt')))
        if not files:
            raise FileNotFoundError(f'no *.txt files under {source}')
        return files
    matched = sorted(glob.glob(source))
    if not matched:
        raise FileNotFoundError(f'corpus source {source!r} matched nothing')
    return matched


class TokenStream:
    """A corpus tokenized once into a single int32 stream (EOS-joined
    documents), held in host memory. For corpora past host RAM, shard
    files across dp ranks instead (``_resolve_sources`` per rank)."""

    def __init__(self, source: str,
                 tokenizer: Optional[BaseTokenizer] = None,
                 *, vocab_size: int = 258):
        self.tokenizer = tokenizer or load_tokenizer(
            None, model_vocab_size=vocab_size)
        pieces = []
        eos = self.tokenizer.eos_id
        for path in _resolve_sources(source):
            with open(path, encoding='utf-8', errors='replace') as f:
                ids = self.tokenizer.encode(f.read())
            if eos is not None:
                ids = ids + [eos]
            pieces.append(np.asarray(ids, np.int32))
        self.tokens = np.concatenate(pieces)
        if len(self.tokens) < 2:
            raise ValueError(f'corpus {source!r} tokenized to '
                             f'{len(self.tokens)} tokens; need >= 2')

    def __len__(self) -> int:
        return len(self.tokens)


def packed_batches(stream: TokenStream, *, batch: int, seq: int,
                   dp_rank: int = 0, dp_size: int = 1,
                   start_step: int = 0,
                   global_batch: Optional[int] = None
                   ) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite iterator of ``{'inputs','targets'}`` [batch, seq] int32.

    ``batch`` is the PER-RANK batch; ``global_batch`` (default
    batch*dp_size) positions each rank's slice inside the global step so
    ranks read disjoint stream windows. Row ``i`` of rank ``r`` at step
    ``t`` starts at token ``((t*G + r*batch + i) * seq) % (N - seq - 1)``
    — a pure function of (t, r), which is what makes mid-epoch resume
    exact: restart with ``start_step`` = the restored optimizer step.
    """
    if dp_rank >= dp_size:
        raise ValueError(f'dp_rank {dp_rank} >= dp_size {dp_size}')
    G = global_batch if global_batch is not None else batch * dp_size
    toks = stream.tokens
    n = len(toks)
    if n < seq + 2:
        raise ValueError(f'corpus has {n} tokens; need >= seq+2 '
                         f'({seq + 2}) for one window')
    span = n - seq - 1
    step = start_step
    while True:
        rows = np.empty((batch, seq + 1), np.int32)
        for i in range(batch):
            off = ((step * G + dp_rank * batch + i) * seq) % span
            rows[i] = toks[off:off + seq + 1]
        yield {'inputs': rows[:, :-1], 'targets': rows[:, 1:]}
        step += 1
