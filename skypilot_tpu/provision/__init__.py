"""Provisioner API: function dispatch routed by provider name.

Role of reference ``sky/provision/__init__.py:32``
(``_route_to_cloud_impl``): each provider module under
``skypilot_tpu.provision.<name>.instance`` implements the op functions;
callers use ``provision.<op>(provider_name, ...)``.
"""
from __future__ import annotations

import functools
import importlib
from typing import Callable, Dict, Optional

from skypilot_tpu.provision.common import (ClusterInfo, HostInfo,
                                           ProvisionConfig, ProvisionRecord,
                                           get_command_runners)

__all__ = [
    'ClusterInfo', 'HostInfo', 'ProvisionConfig', 'ProvisionRecord',
    'get_command_runners', 'run_instances', 'wait_instances',
    'stop_instances', 'terminate_instances', 'query_instances',
    'get_cluster_info',
]


def _impl(provider_name: str):
    mod = f'skypilot_tpu.provision.{provider_name.lower()}.instance'
    try:
        return importlib.import_module(mod)
    except ModuleNotFoundError as e:
        # Only the provisioner module itself being absent means "no such
        # provider"; a missing third-party dependency imported inside it
        # is an environment error the user must see as-is.
        if e.name is None or not mod.startswith(e.name):
            raise
        from skypilot_tpu import exceptions
        err = exceptions.ProvisionError(
            f'No provisioner implementation for {provider_name!r}: {e}')
        err.blocklist_scope = 'cloud'
        raise err from e


def _route(fn: Callable) -> Callable:
    @functools.wraps(fn)
    def wrapper(provider_name: str, *args, **kwargs):
        impl = _impl(provider_name)
        op = getattr(impl, fn.__name__, None)
        if op is None:
            raise NotImplementedError(
                f'{provider_name} provisioner has no op {fn.__name__}')
        return op(*args, **kwargs)
    return wrapper


@_route
def run_instances(provider_name: str, region: str, zone: Optional[str],
                  cluster_name: str,
                  config: ProvisionConfig) -> ProvisionRecord:
    """Create (or resume) the cluster's instances in one zone.

    All-or-nothing gang semantics: on partial failure the impl must clean
    up what it created and raise a ProvisionError subclass carrying the
    blocklist scope."""
    raise AssertionError  # dispatched


@_route
def wait_instances(provider_name: str, region: str, cluster_name: str,
                   state: str) -> None:
    """Block until every instance reaches ``state`` (e.g. RUNNING)."""
    raise AssertionError


@_route
def stop_instances(provider_name: str, region: str,
                   cluster_name: str) -> None:
    raise AssertionError


@_route
def terminate_instances(provider_name: str, region: str,
                        cluster_name: str) -> None:
    raise AssertionError


@_route
def query_instances(provider_name: str, region: str, cluster_name: str
                    ) -> Dict[str, str]:
    """instance_id -> status (common.STATUS_*); {} if cluster is gone."""
    raise AssertionError


@_route
def get_cluster_info(provider_name: str, region: str, cluster_name: str
                     ) -> ClusterInfo:
    raise AssertionError
