"""GCP provisioner ops: TPU slices (nodes + queued-resources) and GCE VMs.

Role of reference ``sky/provision/gcp/instance_utils.py`` (TPU VM path
``:1191-1607``): create/query/delete with gang semantics. TPU-first
redesign notes:

- A logical node is a whole TPU slice (possibly multi-host); the node's
  ``networkEndpoints`` become per-host ``HostInfo`` rows with global
  ranks (slice-major, worker-minor).
- On-demand/reserved slices go through ``nodes.create``; spot /
  best-effort capacity goes through the async queued-resources flow:
  create → ACCEPTED → PROVISIONING → ACTIVE, with FAILED/SUSPENDED and
  the "queued too long" timeout both surfaced as blocklist-scoped
  provision errors so the failover loop moves to the next zone.
- All-or-nothing: partial creations are cleaned up before the error
  propagates (``run_instances`` contract in provision/__init__.py).

Everything is driven through the injectable-transport REST clients in
``tpu_client`` — unit tests script the cloud's behavior per request.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import tpu_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import tpu_client as tc
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)

_LABEL_CLUSTER = 'skytpu-cluster'


# Per-cluster placement (project/zone/kind), written by run_instances and
# read by every later op — the dispatch API (provision/__init__.py) is
# (region, cluster_name)-shaped, so placement must be provider state, the
# same pattern as the local provider's meta.json.
def _placement_dir() -> str:
    d = os.path.join(common_utils.state_dir(), 'gcp_clusters')
    os.makedirs(d, exist_ok=True)
    return d


def _placement_path(cluster_name: str) -> str:
    return os.path.join(_placement_dir(), f'{cluster_name}.json')


def _save_placement(cluster_name: str, project: str, zone: str) -> None:
    with open(_placement_path(cluster_name), 'w', encoding='utf-8') as f:
        json.dump({'project': project, 'zone': zone}, f)


def _load_placement(cluster_name: str) -> Optional[Dict[str, str]]:
    try:
        with open(_placement_path(cluster_name), encoding='utf-8') as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _drop_placement(cluster_name: str) -> None:
    try:
        os.remove(_placement_path(cluster_name))
    except FileNotFoundError:
        pass

# TPU node state -> cloud-agnostic status.
_TPU_STATE_MAP = {
    'CREATING': common.STATUS_PENDING,
    'STARTING': common.STATUS_PENDING,
    'RESTARTING': common.STATUS_PENDING,
    'REPAIRING': common.STATUS_PENDING,
    'READY': common.STATUS_RUNNING,
    'STOPPING': common.STATUS_STOPPED,
    'STOPPED': common.STATUS_STOPPED,
    'DELETING': common.STATUS_TERMINATED,
    'PREEMPTED': common.STATUS_TERMINATED,
    'TERMINATED': common.STATUS_TERMINATED,
}
_GCE_STATE_MAP = {
    'PROVISIONING': common.STATUS_PENDING,
    'STAGING': common.STATUS_PENDING,
    'RUNNING': common.STATUS_RUNNING,
    'STOPPING': common.STATUS_STOPPED,
    'SUSPENDED': common.STATUS_STOPPED,
    'TERMINATED': common.STATUS_STOPPED,   # GCE TERMINATED == stopped VM
}

_QR_ACTIVE = 'ACTIVE'
_QR_DEAD = ('FAILED', 'SUSPENDED', 'SUSPENDING')


def _project(config_or_none: Optional[Dict[str, Any]]) -> str:
    project = (config_or_none or {}).get('project_id')
    if not project:
        raise exceptions.NoCloudAccessError(
            'GCP project_id is not configured (set gcp.project_id in '
            '~/.skytpu/config.yaml).')
    return str(project)


def _node_name(cluster_name: str, idx: int) -> str:
    return f'{cluster_name}-{idx}'


def _qr_name(cluster_name: str, idx: int) -> str:
    return f'{cluster_name}-qr-{idx}'


def _tpu_node_body(cluster_name: str, cfg: common.ProvisionConfig
                   ) -> Dict[str, Any]:
    node_config = cfg.node_config
    body: Dict[str, Any] = {
        'acceleratorType': node_config['accelerator_type'],
        'runtimeVersion': node_config.get('runtime_version',
                                          'tpu-ubuntu2204-base'),
        'labels': {
            _LABEL_CLUSTER: cluster_name,
            **(node_config.get('labels') or {}),
            **cfg.tags,
        },
    }
    if node_config.get('use_spot'):
        body['schedulingConfig'] = {'preemptible': True}
    if node_config.get('reserved'):
        body['schedulingConfig'] = {'reserved': True}
    from skypilot_tpu import authentication
    return authentication.configure_node_body(body, kind='tpu_vm')


# --------------------------------------------------------------------- ops
def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    if zone is None:
        raise exceptions.ProvisionError('GCP provisioning requires a zone.')
    _save_placement(cluster_name, _project(config.provider_config), zone)
    kind = config.node_config.get('kind', 'tpu_vm')
    if kind == 'tpu_vm':
        return _run_tpu(region, zone, cluster_name, config)
    return _run_gce(region, zone, cluster_name, config)


def _run_tpu(region: str, zone: str, cluster_name: str,
             config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = tc.TpuClient(_project(config.provider_config))
    node_config = config.node_config
    created: List[str] = []
    resumed: List[str] = []

    # Reconcile existing nodes: resume STOPPED ones (single-host slices
    # only — pods can't stop), and DELETE dead ones (PREEMPTED/FAILED/
    # TERMINATED are still listed by the API but hold no capacity — they
    # must be recreated, not skipped).
    _DEAD_STATES = ('PREEMPTED', 'TERMINATED', 'DELETING', 'FAILED')
    existing = _cluster_nodes(client, zone, cluster_name)
    for name in list(existing):
        node = existing[name]
        state = node.get('state')
        if state == 'STOPPED' and config.resume_stopped_nodes:
            op = client.start_node(zone, name)
            client.wait_operation(op, zone=zone, timeout=600)
            resumed.append(name)
        elif state in _DEAD_STATES:
            logger.info(f'Node {name} is {state}; recreating.')
            client.delete_node(zone, name)
            existing.pop(name)

    use_qr = bool(node_config.get('use_spot')
                  or node_config.get('best_effort'))
    created_qrs: List[str] = []
    try:
        for i in range(config.count):
            name = _node_name(cluster_name, i)
            if name in existing:
                continue
            # Record BEFORE waiting: a create op that fails mid-wait can
            # leave a half-made node this attempt must clean up.
            created.append(name)
            if use_qr:
                created_qrs.append(_qr_name(cluster_name, i))
                _create_via_queued_resource(client, zone, cluster_name,
                                            i, config)
            else:
                op = client.create_node(zone, name,
                                        _tpu_node_body(cluster_name, config))
                client.wait_operation(op, zone=zone, timeout=1800)
    except exceptions.SkyTpuError:
        # Gang semantics: a partially-created slice group is useless —
        # clean up what this attempt made, then let failover move on.
        # Only QRs from THIS attempt are deleted: force-deleting an
        # ACTIVE QR from a previous successful attempt (whose node was
        # skipped as 'existing') would tear down healthy capacity.
        for name in created:
            try:
                client.delete_node(zone, name)
            except exceptions.SkyTpuError:
                pass
        for qr_name in created_qrs:
            try:
                client.delete_queued_resource(zone, qr_name)
            except exceptions.SkyTpuError:
                pass
        raise

    return common.ProvisionRecord(
        provider_name='gcp', cluster_name=cluster_name, region=region,
        zone=zone, head_instance_id=_node_name(cluster_name, 0),
        created_instance_ids=created, resumed_instance_ids=resumed)


def _create_via_queued_resource(client: tc.TpuClient, zone: str,
                                cluster_name: str, idx: int,
                                config: common.ProvisionConfig) -> None:
    """The async spot path: create the QR, then poll until ACTIVE,
    failing over on FAILED/SUSPENDED or on sitting queued too long
    (reference ``instance_utils.py`` queued-resources flow)."""
    node_config = config.node_config
    node_name = _node_name(cluster_name, idx)
    qr_name = _qr_name(cluster_name, idx)
    body = {
        'tpu': {
            'nodeSpec': [{
                'parent': f'projects/{client.project}/locations/{zone}',
                'nodeId': node_name,
                'node': _tpu_node_body(cluster_name, config),
            }],
        },
    }
    if node_config.get('use_spot'):
        body['spot'] = {}
    if node_config.get('best_effort'):
        body.setdefault('queueingPolicy', {})
    client.create_queued_resource(zone, qr_name, body)

    deadline = time.time() + tc.queued_resource_timeout()
    while True:
        qr = client.get_queued_resource(zone, qr_name)
        state = ((qr or {}).get('state') or {}).get('state', 'UNKNOWN')
        if state == _QR_ACTIVE:
            return
        if state in _QR_DEAD:
            client.delete_queued_resource(zone, qr_name)
            err: exceptions.SkyTpuError = \
                exceptions.InsufficientCapacityError(
                    f'Queued resource {qr_name} ended {state} in {zone}.')
            err.blocklist_scope = 'zone'
            raise err
        if time.time() > deadline:
            # Queued too long: abandon this zone and fail over.
            client.delete_queued_resource(zone, qr_name)
            err = exceptions.QueuedResourceTimeoutError(
                f'Queued resource {qr_name} not ACTIVE after '
                f'{tc.queued_resource_timeout():.0f}s in {zone} '
                f'(last state: {state}).')
            err.blocklist_scope = 'zone'
            raise err
        time.sleep(tc.poll_interval())


def _gce_body(cluster_name: str, name: str,
              config: common.ProvisionConfig) -> Dict[str, Any]:
    node_config = config.node_config
    machine = node_config.get('machine_type', 'n2-standard-8')
    body: Dict[str, Any] = {
        'name': name,
        'machineType': f'zones/_/machineTypes/{machine}',
        'labels': {_LABEL_CLUSTER: cluster_name,
                   **(node_config.get('labels') or {}), **config.tags},
        'disks': [{
            'boot': True,
            'initializeParams': {
                'diskSizeGb': node_config.get('disk_size_gb', 256),
                'sourceImage': node_config.get(
                    'image_id',
                    'projects/debian-cloud/global/images/family/debian-12'),
            },
        }],
        'networkInterfaces': [{
            'network': (config.provider_config or {}).get(
                'vpc_name') or 'global/networks/default',
        }],
    }
    if node_config.get('use_spot'):
        body['scheduling'] = {'provisioningModel': 'SPOT',
                              'instanceTerminationAction': 'DELETE'}
    accels = node_config.get('guest_accelerators') or {}
    if accels:
        (accel_name, count), = accels.items()
        body['guestAccelerators'] = [{
            'acceleratorType': f'zones/_/acceleratorTypes/{accel_name}',
            'acceleratorCount': count,
        }]
        body['scheduling'] = dict(body.get('scheduling', {}),
                                  onHostMaintenance='TERMINATE')
    from skypilot_tpu import authentication
    return authentication.configure_node_body(body, kind='gce')


def _run_gce(region: str, zone: str, cluster_name: str,
             config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = tc.ComputeClient(_project(config.provider_config))
    created: List[str] = []
    resumed: List[str] = []
    existing = {i['name']: i for i in client.list_instances(zone)
                if (i.get('labels') or {}).get(_LABEL_CLUSTER)
                == cluster_name}
    for name, inst in existing.items():
        if not config.resume_stopped_nodes:
            continue
        status = inst.get('status')
        if status == 'TERMINATED':       # GCE TERMINATED == stopped VM
            client.start_instance(zone, name)
            resumed.append(name)
        elif status == 'SUSPENDED':      # suspended VMs need resume
            client.resume_instance(zone, name)
            resumed.append(name)
    try:
        for i in range(config.count):
            name = _node_name(cluster_name, i)
            if name in existing:
                continue
            client.insert_instance(zone, _gce_body(cluster_name, name,
                                                   config))
            created.append(name)
    except exceptions.SkyTpuError:
        for name in created:
            try:
                client.delete_instance(zone, name)
            except exceptions.SkyTpuError:
                pass
        raise
    return common.ProvisionRecord(
        provider_name='gcp', cluster_name=cluster_name, region=region,
        zone=zone, head_instance_id=_node_name(cluster_name, 0),
        created_instance_ids=created, resumed_instance_ids=resumed)


# ----------------------------------------------------------------- queries
def _cluster_nodes(client: tc.TpuClient, zone: str,
                   cluster_name: str) -> Dict[str, Dict[str, Any]]:
    return {n['name'].rsplit('/', 1)[-1]: n
            for n in client.list_nodes(zone)
            if (n.get('labels') or {}).get(_LABEL_CLUSTER) == cluster_name}


def _placed(cluster_name: str) -> Optional[Dict[str, str]]:
    return _load_placement(cluster_name)


def query_instances(region: str, cluster_name: str) -> Dict[str, str]:
    placement = _placed(cluster_name)
    if placement is None:
        return {}
    project, zone = placement['project'], placement['zone']
    out: Dict[str, str] = {}
    tpu = tc.TpuClient(project)
    for name, node in _cluster_nodes(tpu, zone, cluster_name).items():
        out[name] = _TPU_STATE_MAP.get(node.get('state', ''),
                                       common.STATUS_PENDING)
    gce = tc.ComputeClient(project)
    for inst in gce.list_instances(zone):
        if (inst.get('labels') or {}).get(_LABEL_CLUSTER) != cluster_name:
            continue
        out[inst['name']] = _GCE_STATE_MAP.get(inst.get('status', ''),
                                               common.STATUS_PENDING)
    return out


def wait_instances(region: str, cluster_name: str, state: str,
                   timeout: float = 1800) -> None:
    deadline = time.time() + timeout
    while True:
        statuses = query_instances(region, cluster_name)
        if statuses and all(s == state for s in statuses.values()):
            return
        if time.time() > deadline:
            err = exceptions.ProvisionError(
                f'{cluster_name}: instances not {state} after '
                f'{timeout:.0f}s (statuses: {statuses}).')
            err.blocklist_scope = 'zone'
            raise err
        time.sleep(tc.poll_interval())


def stop_instances(region: str, cluster_name: str) -> None:
    placement = _placed(cluster_name)
    if placement is None:
        return
    project, zone = placement['project'], placement['zone']
    tpu = tc.TpuClient(project)
    for name in _cluster_nodes(tpu, zone, cluster_name):
        op = tpu.stop_node(zone, name)
        tpu.wait_operation(op, zone=zone, timeout=600)
    gce = tc.ComputeClient(project)
    for inst in gce.list_instances(zone):
        if (inst.get('labels') or {}).get(_LABEL_CLUSTER) == cluster_name:
            gce.stop_instance(zone, inst['name'])


def terminate_instances(region: str, cluster_name: str) -> None:
    placement = _placed(cluster_name)
    if placement is None:
        return
    project, zone = placement['project'], placement['zone']
    tpu = tc.TpuClient(project)
    # Queued resources first: a pending QR would re-create its node.
    for qr in tpu.list_queued_resources(zone):
        qr_name = qr['name'].rsplit('/', 1)[-1]
        if qr_name.startswith(f'{cluster_name}-qr-'):
            tpu.delete_queued_resource(zone, qr_name)
    for name in _cluster_nodes(tpu, zone, cluster_name):
        tpu.delete_node(zone, name)
    gce = tc.ComputeClient(project)
    for inst in gce.list_instances(zone):
        if (inst.get('labels') or {}).get(_LABEL_CLUSTER) == cluster_name:
            gce.delete_instance(zone, inst['name'])
    _drop_placement(cluster_name)


def get_cluster_info(region: str, cluster_name: str) -> common.ClusterInfo:
    placement = _placed(cluster_name)
    if placement is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    project, zone = placement['project'], placement['zone']
    tpu = tc.TpuClient(project)
    nodes = _cluster_nodes(tpu, zone, cluster_name)
    hosts: List[common.HostInfo] = []
    accelerator = None
    chips_per_host = 0
    if nodes:
        rank = 0
        # Sort by the numeric index suffix ('name-<i>'), not
        # lexicographically: 'c-10' must rank after 'c-2' or global
        # ranks (slice-major) and the head instance come out wrong.
        def _node_key(name: str):
            suffix = name.rsplit('-', 1)[-1]
            return (0, int(suffix)) if suffix.isdigit() else (1, name)

        for slice_idx, node_idx in enumerate(sorted(nodes, key=_node_key)):
            node = nodes[node_idx]
            accelerator = node.get('acceleratorType', accelerator)
            endpoints = node.get('networkEndpoints') or []
            for worker_idx, ep in enumerate(endpoints):
                hosts.append(common.HostInfo(
                    instance_id=f'{node_idx}-w{worker_idx}',
                    rank=rank,
                    internal_ip=ep.get('ipAddress', ''),
                    external_ip=(ep.get('accessConfig') or {}).get(
                        'externalIp'),
                    slice_id=slice_idx,   # each TPU node/QR is one slice
                ))
                rank += 1
        chips = {'v2': 4, 'v3': 4, 'v4': 4, 'v5p': 4,
                 'v5litepod': 8, 'v6e': 8}
        for gen, c in chips.items():
            if accelerator and accelerator.startswith(gen):
                chips_per_host = c
    else:
        gce = tc.ComputeClient(project)
        rank = 0
        for inst in sorted(gce.list_instances(zone),
                           key=lambda i: i['name']):
            if (inst.get('labels') or {}).get(_LABEL_CLUSTER) != \
                    cluster_name:
                continue
            nic = (inst.get('networkInterfaces') or [{}])[0]
            access = (nic.get('accessConfigs') or [{}])[0]
            hosts.append(common.HostInfo(
                instance_id=inst['name'], rank=rank,
                internal_ip=nic.get('networkIP', ''),
                external_ip=access.get('natIP')))
            rank += 1
    if not hosts:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    from skypilot_tpu import authentication
    return common.ClusterInfo(
        cluster_name=cluster_name,
        provider_name='gcp',
        region=region,
        zone=zone,
        hosts=hosts,
        head_instance_id=hosts[0].instance_id,
        chips_per_host=chips_per_host,
        accelerator=accelerator,
        ssh_user=authentication.ssh_user(),
        ssh_private_key=authentication.private_key_path(),
        provider_config={'project_id': project, 'zone': zone},
    )
