"""Minimal REST clients for the GCP TPU and Compute APIs.

Role of reference ``sky/provision/gcp/instance_utils.py`` (GCPTpuVmInstance
``:1191-1607``) and its ``googleapiclient`` discovery stack: here a thin
urllib layer with an injectable ``transport`` callable so the provisioner
is unit-testable without network or credentials (the reference mocks at
the googleapiclient layer in its tests; SURVEY §4 calls for doing better
in-tree).

Transport contract: ``transport(method, url, body_dict_or_None) ->
(status_code, response_dict)``. The default transport attaches a gcloud
access token. HTTP errors are mapped onto the exception taxonomy here so
every caller sees blocklist-scoped ProvisionErrors, not raw HTTP.
"""
from __future__ import annotations

import json
import os
import subprocess
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Optional, Tuple

from skypilot_tpu import exceptions

TPU_API = 'https://tpu.googleapis.com/v2'
COMPUTE_API = 'https://compute.googleapis.com/compute/v1'

Transport = Callable[[str, str, Optional[Dict[str, Any]]],
                     Tuple[int, Dict[str, Any]]]

# Test hook: factory returning a Transport (see tests/test_gcp_provisioner).
_transport_factory: Optional[Callable[[], Transport]] = None


def set_transport_factory(fn: Optional[Callable[[], Transport]]) -> None:
    global _transport_factory
    _transport_factory = fn


# Access tokens are valid ~1h; cache one for 50 minutes so polling loops
# don't spawn a gcloud subprocess per request.
_token_cache: Dict[str, Any] = {'token': None, 'expires': 0.0}


def _gcloud_access_token() -> str:
    if _token_cache['token'] and time.time() < _token_cache['expires']:
        return _token_cache['token']
    try:
        out = subprocess.run(['gcloud', 'auth', 'print-access-token'],
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise exceptions.NoCloudAccessError(
            f'gcloud not available for GCP auth: {e}') from e
    if out.returncode != 0:
        raise exceptions.NoCloudAccessError(
            f'gcloud auth failed: {out.stderr.strip()}')
    _token_cache['token'] = out.stdout.strip()
    _token_cache['expires'] = time.time() + 50 * 60
    return _token_cache['token']


def _default_transport(method: str, url: str,
                       body: Optional[Dict[str, Any]]
                       ) -> Tuple[int, Dict[str, Any]]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={'Authorization': f'Bearer {_gcloud_access_token()}',
                 'Content-Type': 'application/json'})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            payload = resp.read()
            return resp.status, json.loads(payload) if payload else {}
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:  # pylint: disable=broad-except
            payload = {'error': {'message': str(e)}}
        return e.code, payload
    except (urllib.error.URLError, TimeoutError, OSError) as e:
        # Network-level failures must enter the taxonomy too, or they
        # bypass gang cleanup and the failover loop entirely.
        err = exceptions.ProvisionError(
            f'GCP API unreachable ({method} {url.split("?")[0]}): {e}')
        err.blocklist_scope = 'zone'
        raise err from e


def get_transport() -> Transport:
    if _transport_factory is not None:
        return _transport_factory()
    return _default_transport


def _error_message(payload: Dict[str, Any]) -> str:
    err = payload.get('error') or {}
    if isinstance(err, dict):
        return str(err.get('message') or payload)
    return str(err)


def raise_for_status(status: int, payload: Dict[str, Any], *,
                     zone: Optional[str] = None) -> None:
    """Map a GCP error onto the blocklist-scoped exception taxonomy
    (reference error discrimination:
    ``sky/backends/cloud_vm_ray_backend.py:1031-1086``)."""
    if status < 400:
        return
    msg = _error_message(payload)
    lower = msg.lower()
    where = f' in {zone}' if zone else ''
    if status in (401, 403):
        raise exceptions.NoCloudAccessError(
            f'GCP auth/permission error{where}: {msg}')
    if status == 429 or 'quota' in lower:
        err: exceptions.SkyTpuError = exceptions.QuotaExceededError(
            f'GCP quota exceeded{where}: {msg}')
        err.blocklist_scope = 'region'
        raise err
    if ('resource_exhausted' in lower or 'out of capacity' in lower
            or 'stockout' in lower or 'no more capacity' in lower
            or 'not enough resources' in lower):
        err = exceptions.InsufficientCapacityError(
            f'GCP capacity unavailable{where}: {msg}')
        err.blocklist_scope = 'zone'
        raise err
    err = exceptions.ProvisionError(f'GCP API error {status}{where}: {msg}')
    err.blocklist_scope = 'zone'
    raise err


class TpuClient:
    """tpu.googleapis.com v2: nodes + queuedResources + operations."""

    def __init__(self, project: str,
                 transport: Optional[Transport] = None):
        self.project = project
        self.transport = transport or get_transport()

    # ------------------------------------------------------------- urls
    def _zone_url(self, zone: str) -> str:
        return f'{TPU_API}/projects/{self.project}/locations/{zone}'

    # ------------------------------------------------------------ nodes
    def create_node(self, zone: str, node_id: str,
                    body: Dict[str, Any]) -> Dict[str, Any]:
        status, payload = self.transport(
            'POST', f'{self._zone_url(zone)}/nodes?nodeId={node_id}', body)
        raise_for_status(status, payload, zone=zone)
        return payload                      # long-running operation

    def get_node(self, zone: str, node_id: str) -> Optional[Dict[str, Any]]:
        status, payload = self.transport(
            'GET', f'{self._zone_url(zone)}/nodes/{node_id}', None)
        if status == 404:
            return None
        raise_for_status(status, payload, zone=zone)
        return payload

    def list_nodes(self, zone: str) -> list:
        status, payload = self.transport(
            'GET', f'{self._zone_url(zone)}/nodes', None)
        if status == 404:
            return []
        raise_for_status(status, payload, zone=zone)
        return payload.get('nodes', [])

    def delete_node(self, zone: str, node_id: str) -> Optional[Dict]:
        status, payload = self.transport(
            'DELETE', f'{self._zone_url(zone)}/nodes/{node_id}', None)
        if status == 404:
            return None
        raise_for_status(status, payload, zone=zone)
        return payload

    def stop_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        status, payload = self.transport(
            'POST', f'{self._zone_url(zone)}/nodes/{node_id}:stop', {})
        raise_for_status(status, payload, zone=zone)
        return payload

    def start_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        status, payload = self.transport(
            'POST', f'{self._zone_url(zone)}/nodes/{node_id}:start', {})
        raise_for_status(status, payload, zone=zone)
        return payload

    # -------------------------------------------------- queued resources
    def create_queued_resource(self, zone: str, qr_id: str,
                               body: Dict[str, Any]) -> Dict[str, Any]:
        status, payload = self.transport(
            'POST',
            f'{self._zone_url(zone)}/queuedResources?queuedResourceId='
            f'{qr_id}', body)
        raise_for_status(status, payload, zone=zone)
        return payload

    def get_queued_resource(self, zone: str,
                            qr_id: str) -> Optional[Dict[str, Any]]:
        status, payload = self.transport(
            'GET', f'{self._zone_url(zone)}/queuedResources/{qr_id}', None)
        if status == 404:
            return None
        raise_for_status(status, payload, zone=zone)
        return payload

    def list_queued_resources(self, zone: str) -> list:
        status, payload = self.transport(
            'GET', f'{self._zone_url(zone)}/queuedResources', None)
        if status == 404:
            return []
        raise_for_status(status, payload, zone=zone)
        return payload.get('queuedResources', [])

    def delete_queued_resource(self, zone: str,
                               qr_id: str, force: bool = True
                               ) -> Optional[Dict[str, Any]]:
        status, payload = self.transport(
            'DELETE',
            f'{self._zone_url(zone)}/queuedResources/{qr_id}'
            f'?force={"true" if force else "false"}', None)
        if status == 404:
            return None
        raise_for_status(status, payload, zone=zone)
        return payload

    # ------------------------------------------------------- operations
    def get_operation(self, op_name: str) -> Dict[str, Any]:
        status, payload = self.transport(
            'GET', f'{TPU_API}/{op_name.lstrip("/")}', None)
        raise_for_status(status, payload)
        return payload

    def wait_operation(self, op: Dict[str, Any], *, zone: Optional[str],
                       timeout: float) -> Dict[str, Any]:
        """Poll a long-running operation to completion; map its terminal
        error (if any) through raise_for_status."""
        deadline = time.time() + timeout
        while not op.get('done'):
            if time.time() > deadline:
                err = exceptions.ProvisionError(
                    f'GCP operation timed out after {timeout:.0f}s: '
                    f'{op.get("name")}')
                err.blocklist_scope = 'zone'
                raise err
            time.sleep(poll_interval())
            op = self.get_operation(op['name'])
        if 'error' in op:
            code = int(op['error'].get('code', 500))
            # Operation errors carry gRPC-ish codes; normalize to HTTP.
            http = {8: 429, 7: 403, 16: 401}.get(code, 500)
            raise_for_status(http, {'error': op['error']}, zone=zone)
        return op


class ComputeClient:
    """compute.googleapis.com v1: the GCE path (GPU/CPU VMs)."""

    def __init__(self, project: str,
                 transport: Optional[Transport] = None):
        self.project = project
        self.transport = transport or get_transport()

    def _zone_url(self, zone: str) -> str:
        return f'{COMPUTE_API}/projects/{self.project}/zones/{zone}'

    def insert_instance(self, zone: str,
                        body: Dict[str, Any]) -> Dict[str, Any]:
        status, payload = self.transport(
            'POST', f'{self._zone_url(zone)}/instances', body)
        raise_for_status(status, payload, zone=zone)
        return payload

    def get_instance(self, zone: str,
                     name: str) -> Optional[Dict[str, Any]]:
        status, payload = self.transport(
            'GET', f'{self._zone_url(zone)}/instances/{name}', None)
        if status == 404:
            return None
        raise_for_status(status, payload, zone=zone)
        return payload

    def list_instances(self, zone: str) -> list:
        status, payload = self.transport(
            'GET', f'{self._zone_url(zone)}/instances', None)
        if status == 404:
            return []
        raise_for_status(status, payload, zone=zone)
        return payload.get('items', [])

    def delete_instance(self, zone: str, name: str) -> Optional[Dict]:
        status, payload = self.transport(
            'DELETE', f'{self._zone_url(zone)}/instances/{name}', None)
        if status == 404:
            return None
        raise_for_status(status, payload, zone=zone)
        return payload

    def stop_instance(self, zone: str, name: str) -> Dict[str, Any]:
        status, payload = self.transport(
            'POST', f'{self._zone_url(zone)}/instances/{name}/stop', {})
        raise_for_status(status, payload, zone=zone)
        return payload

    def start_instance(self, zone: str, name: str) -> Dict[str, Any]:
        """For TERMINATED (stopped) VMs; SUSPENDED needs resume_instance."""
        status, payload = self.transport(
            'POST', f'{self._zone_url(zone)}/instances/{name}/start', {})
        raise_for_status(status, payload, zone=zone)
        return payload

    def resume_instance(self, zone: str, name: str) -> Dict[str, Any]:
        status, payload = self.transport(
            'POST', f'{self._zone_url(zone)}/instances/{name}/resume', {})
        raise_for_status(status, payload, zone=zone)
        return payload


def poll_interval() -> float:
    return float(os.environ.get('SKYTPU_GCP_POLL', '5'))


def queued_resource_timeout() -> float:
    """How long a queued resource may sit non-ACTIVE before the attempt
    is abandoned and failover moves on ("queued too long" — SURVEY §7
    hard-parts; reference provisions QRs with a wait loop)."""
    return float(os.environ.get('SKYTPU_GCP_QR_TIMEOUT', '900'))
