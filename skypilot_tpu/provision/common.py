"""Provisioner data model shared by client, provisioner, and head agent.

Role of reference ``sky/provision/common.py`` (ProvisionConfig /
ProvisionRecord / ClusterInfo dataclasses). TPU-first difference: one
logical node may be a multi-host slice — hosts are first-class here
(``ClusterInfo.hosts`` is the flat per-host list with ranks), instead of the
reference's ``num_ips_per_node`` bolt-on
(``sky/backends/cloud_vm_ray_backend.py:2550``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

# Instance status strings (cloud-agnostic).
STATUS_PENDING = 'PENDING'
STATUS_RUNNING = 'RUNNING'
STATUS_STOPPED = 'STOPPED'
STATUS_TERMINATED = 'TERMINATED'


@dataclasses.dataclass
class HostInfo:
    """One host (one VM / one TPU-VM worker) of the cluster."""
    instance_id: str
    rank: int                      # stable global host rank, 0 = head
    internal_ip: str
    external_ip: Optional[str] = None
    ssh_port: int = 22
    # Local provisioner: the directory acting as this host's HOME.
    node_dir: Optional[str] = None
    # Which pod slice this host belongs to (multi-slice DCN jobs; each
    # provisioned TPU node/queued-resource is one slice).
    slice_id: int = 0
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'HostInfo':
        return cls(**d)


@dataclasses.dataclass
class ClusterInfo:
    """Everything needed to reach and run on a provisioned cluster."""
    cluster_name: str
    provider_name: str             # 'local' | 'gcp'
    region: str
    zone: Optional[str]
    hosts: List[HostInfo]
    head_instance_id: str
    # chips visible to each host (TPU: 4 for v4/v5p hosts, 8 for v5e/v6e).
    chips_per_host: int = 0
    accelerator: Optional[str] = None   # e.g. 'tpu-v5e-16'
    ssh_user: Optional[str] = None
    ssh_private_key: Optional[str] = None
    ssh_proxy_command: Optional[str] = None
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Docker is out of scope for TPU VMs; kept for parity of the data model.
    docker_image: Optional[str] = None

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def num_slices(self) -> int:
        """Slices in this cluster (1 + max host slice_id)."""
        return 1 + max((h.slice_id for h in self.hosts), default=0)

    def head_host(self) -> HostInfo:
        for h in self.hosts:
            if h.instance_id == self.head_instance_id:
                return h
        raise ValueError(f'head instance {self.head_instance_id} not in '
                         f'host list of {self.cluster_name}')

    def worker_ips(self) -> List[str]:
        return [h.internal_ip for h in
                sorted(self.hosts, key=lambda h: h.rank)]

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'ClusterInfo':
        d = dict(d)
        d['hosts'] = [HostInfo.from_dict(h) for h in d.get('hosts', [])]
        return cls(**d)


@dataclasses.dataclass
class ProvisionConfig:
    """Input to ``run_instances`` for one (cluster, zone) attempt."""
    provider_config: Dict[str, Any]
    node_config: Dict[str, Any]          # accelerator/machine/disk/image...
    count: int                           # logical nodes (slices)
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    resume_stopped_nodes: bool = True
    ports_to_open: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ProvisionRecord:
    """Output of ``run_instances``."""
    provider_name: str
    cluster_name: str
    region: str
    zone: Optional[str]
    head_instance_id: str
    created_instance_ids: List[str] = dataclasses.field(default_factory=list)
    resumed_instance_ids: List[str] = dataclasses.field(default_factory=list)

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.created_instance_ids
                or instance_id in self.resumed_instance_ids)


def get_command_runners(cluster_info: ClusterInfo) -> List[Any]:
    """Build one CommandRunner per host, ordered by rank.

    Used by the client backend and by the head-side job driver (which runs
    the user program on every host of the slice)."""
    from skypilot_tpu.utils import command_runner as cr

    runners: List[Any] = []
    for host in sorted(cluster_info.hosts, key=lambda h: h.rank):
        if cluster_info.provider_name == 'local':
            assert host.node_dir, f'local host {host.instance_id} missing dir'
            runners.append(cr.LocalProcessRunner(host.instance_id,
                                                 host.node_dir))
        elif cluster_info.provider_name == 'kubernetes':
            import os as _os
            pc = cluster_info.provider_config or {}
            # In-cluster (head-pod driver fan-out): the client-side
            # kubeconfig context doesn't exist here — kubectl uses the
            # pod's service account instead. Requires an image with
            # kubectl + a role allowing pods/exec (see clouds/
            # kubernetes.py image contract).
            in_cluster = bool(_os.environ.get('KUBERNETES_SERVICE_HOST'))
            runners.append(cr.KubernetesPodRunner(
                host.instance_id,
                namespace=pc.get('namespace', 'default'),
                context=None if in_cluster else pc.get('context')))
        else:
            ip = host.external_ip or host.internal_ip
            runners.append(cr.SSHCommandRunner(
                ip,
                ssh_user=cluster_info.ssh_user or 'skytpu',
                ssh_private_key=(cluster_info.ssh_private_key
                                 or '~/.skytpu/keys/skytpu.pem'),
                ssh_proxy_command=cluster_info.ssh_proxy_command,
                node_id=host.instance_id))
    return runners
