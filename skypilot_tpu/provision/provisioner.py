"""Cloud-agnostic provisioning orchestration.

Role of reference ``sky/provision/provisioner.py`` (``bulk_provision``
``:100``, ``wait_for_ssh`` ``:348``, ``post_provision_runtime_setup``
``:631``): one retryable entry that creates instances in a zone, waits for
them, pushes the runtime onto every host in parallel, and starts the head
agent. Raises :class:`exceptions.ProvisionError` subclasses the failover
loop can blocklist on.
"""
from __future__ import annotations

import json
import os
import shlex
import tempfile
import time
from typing import Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import provision
from skypilot_tpu import tpu_logging
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.agent import rpc as agent_rpc
from skypilot_tpu.provision import common
from skypilot_tpu.utils import subprocess_utils

logger = tpu_logging.init_logger(__name__)

_AGENT_READY_TIMEOUT = float(os.environ.get('SKYTPU_AGENT_READY_TIMEOUT',
                                            '60'))


def _oneshot_rpc_timeout() -> float:
    """Bound on a one-shot RPC exec (interpreter start + handler), kept
    in line with the persistent channel's 120s request timeout."""
    return float(os.environ.get('SKYTPU_RPC_TIMEOUT', '120'))


def bulk_provision(provider_name: str,
                   region: str,
                   zone: Optional[str],
                   cluster_name: str,
                   config: common.ProvisionConfig) -> common.ClusterInfo:
    """Provision one cluster attempt in one zone, end to end.

    Steps: run_instances -> wait RUNNING -> get_cluster_info ->
    runtime setup on all hosts -> start agentd on head -> wait agent ready.
    """
    start = time.time()
    record = provision.run_instances(provider_name, region, zone,
                                     cluster_name, config)
    provision.wait_instances(provider_name, region, cluster_name,
                             common.STATUS_RUNNING)
    cluster_info = provision.get_cluster_info(provider_name, region,
                                              cluster_name)
    logger.debug(
        f'Provisioned {cluster_info.num_hosts} host(s) for '
        f'{cluster_name} in {zone or region} '
        f'({time.time() - start:.1f}s); setting up runtime.')
    post_provision_runtime_setup(cluster_info)
    return cluster_info


def post_provision_runtime_setup(
        cluster_info: common.ClusterInfo) -> None:
    """Push cluster_info to every host, start agentd on the head.

    (Reference ``_post_provision_setup``: internal file mounts + ray
    head/workers + skylet. No Ray here — the slice is the gang; only the
    head runs a daemon.)"""
    runners = common.get_command_runners(cluster_info)
    info_json = json.dumps(cluster_info.to_dict())

    # Remote hosts get the client's package as a hash-addressed source
    # zip on PYTHONPATH (version-skew restarts the agent); the local
    # provider already sees the repo via LocalProcessRunner's PYTHONPATH.
    ship_pkg = cluster_info.provider_name != 'local'
    if ship_pkg:
        from skypilot_tpu.utils import pkg_utils
        zip_path, digest = pkg_utils.build_package()

    with tempfile.NamedTemporaryFile('w', suffix='.json',
                                     delete=False) as f:
        f.write(info_json)
        tmp_path = f.name
    try:
        def push(runner) -> None:
            runner.run('mkdir -p ~/.skytpu_agent ~/sky_workdir '
                       '~/.skytpu_runtime',
                       log_path=os.devnull)
            runner.rsync(tmp_path, '~/.skytpu_agent/cluster_info.json',
                         up=True)
            if ship_pkg:
                runner.rsync(zip_path, pkg_utils.remote_zip_path(),
                             up=True)
                runner.run(pkg_utils.remote_setup_command(digest),
                           log_path=os.devnull)
        subprocess_utils.run_in_parallel(push, runners)
    finally:
        os.unlink(tmp_path)

    head = runners[0]
    start_agent_cmd = (
        'if [ -f ~/.skytpu_agent/agentd.pid ] && '
        'kill -0 $(cat ~/.skytpu_agent/agentd.pid) 2>/dev/null; then '
        '  echo "agentd already running"; '
        'else '
        f'  {agent_constants.control_plane_env_prefix()}'
        f'setsid {shlex.quote(head.remote_python)} -m '
        'skypilot_tpu.agent.agentd >> ~/.skytpu_agent/agentd.log 2>&1 '
        '< /dev/null & '
        'fi')
    head.run(start_agent_cmd, log_path=os.devnull)
    _wait_agent_ready(head)


def _wait_agent_ready(head_runner) -> None:
    deadline = time.time() + _AGENT_READY_TIMEOUT
    last_err = ''
    while time.time() < deadline:
        try:
            resp = agent_request(head_runner, {'op': 'agent_health'})
            if resp.get('agentd_alive'):
                return
            last_err = f'agentd not alive yet: {resp}'
        except exceptions.CommandError as e:
            last_err = str(e)
        time.sleep(0.2)
    raise exceptions.ProvisionError(
        f'Head agent failed to become ready in {_AGENT_READY_TIMEOUT}s: '
        f'{last_err}')


def agent_request(head_runner, request: Dict,
                  module: str = 'skypilot_tpu.agent.rpc',
                  error_cls: type = exceptions.ProvisionError) -> Dict:
    """Send one JSON RPC to a head-side module; return the parsed
    payload. The same wire protocol serves the agent RPC and the
    jobs/serve controller RPCs — pass ``module``/``error_cls``.

    Transport: a persistent ``--serve`` channel (one remote interpreter
    per client session, ``agent/channel.py``) when the runner supports
    it, falling back to a one-shot exec — so logs/cancel/status paths
    stop paying an interpreter start per op, and a broken channel never
    becomes a new failure mode. Raises CommandError / ``error_cls`` on
    failure."""
    from skypilot_tpu.agent import channel as channel_lib
    ch = channel_lib.channel_for(head_runner, module)
    if ch is not None:
        try:
            payload = ch.request(request)
            if not payload.get('ok'):
                raise error_cls(
                    f'RPC {module}:{request.get("op")} failed: '
                    f'{payload.get("error")}')
            return payload
        except channel_lib.ChannelError as e:
            if e.sent:
                # The op MAY have executed remotely: re-running it via
                # the fallback could double-submit writes (queue_job,
                # cancel). Surface the transport failure instead.
                raise error_cls(
                    f'RPC {module}:{request.get("op")}: channel failed '
                    f'after the request was sent ({e}); not retrying a '
                    f'possibly-executed op') from e
            # Startup failure (e.g. head running an older runtime):
            # negative-cache so later calls skip straight to one-shot.
            channel_lib.disable(head_runner, module)
            logger.debug(f'RPC channel unavailable '
                         f'({e}); falling back to one-shot exec')
    cmd = (f'{agent_constants.control_plane_env_prefix()}'
           f'{shlex.quote(head_runner.remote_python)} '
           f'-m {module} '
           f'{shlex.quote(json.dumps(request))}')
    # Bounded like the channel path (graftcheck GC103 discipline): a
    # wedged remote interpreter must not hang the caller's poll loop —
    # and any lock it holds — forever.
    out = head_runner.check_run(cmd, timeout=_oneshot_rpc_timeout())
    for line in out.splitlines():
        if line.startswith(agent_rpc.PAYLOAD_PREFIX):
            payload = json.loads(line[len(agent_rpc.PAYLOAD_PREFIX):])
            if not payload.get('ok'):
                raise error_cls(
                    f'RPC {module}:{request.get("op")} failed: '
                    f'{payload.get("error")}')
            return payload
    raise error_cls(
        f'RPC {module}:{request.get("op")}: no payload in output:\n'
        f'{out[-1000:]}')


def teardown_cluster(provider_name: str, region: str, cluster_name: str,
                     terminate: bool) -> None:
    if terminate:
        provision.terminate_instances(provider_name, region, cluster_name)
    else:
        provision.stop_instances(provider_name, region, cluster_name)
