"""Minimal Kubernetes API client over kubectl with an injectable runner.

Role of the reference's kubernetes adaptor + `sky/provision/kubernetes/`
API plumbing (it uses the `kubernetes` Python SDK; `sky/adaptors/
kubernetes.py`). Here: the only hard dependency is the `kubectl` binary
(standard on any machine that talks to a cluster), and the exec layer is
an injectable callable so the provisioner is unit-testable without a
cluster — the same design as the GCP REST transport
(``provision/gcp/tpu_client.py``).

Runner contract: ``runner(args: List[str], stdin: Optional[str]) ->
(returncode, stdout, stderr)`` where ``args`` are kubectl arguments
(without the leading 'kubectl').
"""
from __future__ import annotations

import json
import subprocess
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions

Runner = Callable[[List[str], Optional[str]], Tuple[int, str, str]]

# Test hook: factory returning a Runner (see tests/test_k8s_provisioner).
_runner_factory: Optional[Callable[[], Runner]] = None


def set_runner_factory(fn: Optional[Callable[[], Runner]]) -> None:
    global _runner_factory
    _runner_factory = fn


def _default_runner(args: List[str], stdin: Optional[str]
                    ) -> Tuple[int, str, str]:
    try:
        proc = subprocess.run(['kubectl'] + args, input=stdin,
                              capture_output=True, text=True, timeout=120)
    except FileNotFoundError as e:
        raise exceptions.NoCloudAccessError(
            'kubectl not found; install it to use the kubernetes '
            'cloud') from e
    except subprocess.TimeoutExpired as e:
        err = exceptions.ProvisionError(f'kubectl timed out: {e}')
        err.blocklist_scope = 'zone'
        raise err from e
    return proc.returncode, proc.stdout, proc.stderr


def get_runner() -> Runner:
    if _runner_factory is not None:
        return _runner_factory()
    return _default_runner


class K8sClient:
    """Pods + services in one namespace, optionally one kubeconfig
    context (the 'zone' of the kubernetes cloud)."""

    def __init__(self, namespace: str = 'default',
                 context: Optional[str] = None):
        self.namespace = namespace
        self.context = context
        self._run = get_runner()

    def _base(self) -> List[str]:
        args = ['--namespace', self.namespace]
        if self.context:
            args += ['--context', self.context]
        return args

    def _json(self, args: List[str], stdin: Optional[str] = None,
              allow_not_found: bool = False) -> Dict[str, Any]:
        rc, out, err = self._run(self._base() + args, stdin)
        if rc != 0:
            low = err.lower()
            if allow_not_found and 'not found' in low:
                return {}
            # Quota first: k8s phrases quota errors as 'forbidden:
            # exceeded quota', which must blocklist-scope, not abort.
            if 'exceeded quota' in low:
                raise exceptions.QuotaExceededError(
                    f'kubernetes quota exceeded: {err.strip()}')
            if ('forbidden' in low or 'unauthorized' in low
                    or 'unable to connect' in low
                    or 'connection refused' in low):
                raise exceptions.NoCloudAccessError(
                    f'kubernetes API error: {err.strip()}')
            e = exceptions.ProvisionError(
                f'kubectl {" ".join(args[:3])} failed: {err.strip()}')
            e.blocklist_scope = 'zone'
            raise e
        return json.loads(out) if out.strip() else {}

    # ------------------------------------------------------------- pods
    def apply(self, manifest: Dict[str, Any]) -> Dict[str, Any]:
        return self._json(['apply', '-f', '-', '-o', 'json'],
                          stdin=json.dumps(manifest))

    def get_pod(self, name: str) -> Dict[str, Any]:
        return self._json(['get', 'pod', name, '-o', 'json'],
                          allow_not_found=True)

    def list_pods(self, label_selector: str) -> List[Dict[str, Any]]:
        out = self._json(['get', 'pods', '-l', label_selector,
                          '-o', 'json'])
        return out.get('items', [])

    def delete_pod(self, name: str) -> None:
        self._json(['delete', 'pod', name, '--ignore-not-found=true',
                    '--wait=false', '-o', 'name'], allow_not_found=True)

    def delete_collection(self, label_selector: str) -> None:
        self._json(['delete', 'pods,services', '-l', label_selector,
                    '--ignore-not-found=true', '--wait=false',
                    '-o', 'name'], allow_not_found=True)

    # ---------------------------------------------------------- cluster
    def check_reachable(self) -> Tuple[bool, Optional[str]]:
        try:
            rc, _, err = self._run(self._base() + ['version', '-o', 'json'],
                                   None)
        except exceptions.SkyTpuError as e:
            return False, str(e)
        if rc != 0:
            return False, err.strip() or 'kubectl version failed'
        return True, None
