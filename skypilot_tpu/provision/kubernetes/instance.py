"""Kubernetes (GKE-TPU) provisioner: TPU slices as gangs of pods.

Role of reference ``sky/provision/kubernetes/instance.py`` (1,129 LoC) +
the GKE TPU parts of ``utils.py`` (labels ``cloud.google.com/
gke-tpu-accelerator`` / ``gke-tpu-topology`` at ``:340-390``,
``TPU_RESOURCE_KEY='google.com/tpu'`` at ``:57``). TPU-first design:

- One *slice* = ``hosts_per_node`` pods sharing a ``skytpu/slice``
  label; ``config.count`` slices form one logical cluster (the same
  shape as the GCP provisioner's one-QR-per-slice and the multi-slice
  env contract).
- GKE schedules all pods of a multi-host slice onto the same TPU node
  pool via the accelerator+topology node selectors; the ``google.com/
  tpu`` resource request claims the chips of each host.
- Gang semantics: a slice that cannot fully schedule is torn down and
  the error enters the blocklist-scoped taxonomy so the failover loop
  moves on (Unschedulable == stockout).
- A headless Service per cluster gives pods stable DNS names
  (``<pod>.<cluster>``) for the jax.distributed coordinator.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import tpu_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.kubernetes import k8s_client as kc
from skypilot_tpu.utils import common_utils

logger = tpu_logging.init_logger(__name__)

_LABEL_CLUSTER = 'skytpu/cluster'
_LABEL_SLICE = 'skytpu/slice'
_LABEL_HOST = 'skytpu/host'

# GKE TPU node-pool selector values per generation (reference
# ``sky/provision/kubernetes/utils.py:340-390``).
GKE_TPU_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}
TPU_RESOURCE_KEY = 'google.com/tpu'

_DEFAULT_IMAGE = 'python:3.11-slim'


def default_schedule_timeout() -> float:
    return float(os.environ.get('SKYTPU_K8S_SCHEDULE_TIMEOUT', '600'))


# ------------------------------------------------------------ placement
def _placement_dir() -> str:
    d = os.path.join(common_utils.state_dir(), 'k8s_clusters')
    os.makedirs(d, exist_ok=True)
    return d


def _placement_path(cluster_name: str) -> str:
    return os.path.join(_placement_dir(), f'{cluster_name}.json')


def _save_placement(cluster_name: str, namespace: str,
                    context: Optional[str],
                    node_config: Dict[str, Any]) -> None:
    with open(_placement_path(cluster_name), 'w', encoding='utf-8') as f:
        json.dump({'namespace': namespace, 'context': context,
                   'node_config': node_config}, f)


def _load_placement(cluster_name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_placement_path(cluster_name), encoding='utf-8') as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _drop_placement(cluster_name: str) -> None:
    try:
        os.remove(_placement_path(cluster_name))
    except FileNotFoundError:
        pass


def _client_for(cluster_name: str) -> kc.K8sClient:
    placement = _load_placement(cluster_name)
    if placement is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    return kc.K8sClient(namespace=placement['namespace'],
                        context=placement.get('context'))


# ------------------------------------------------------------ manifests
# Pinned per-generation chips -> topology selector values. GKE node
# pools expose SPECIFIC topology strings (cloud.google.com/tpu docs;
# reference pins the same values, sky/provision/kubernetes/utils.py:
# 340-390) — a computed "near-equal factorization" can produce a string
# no node pool carries (e.g. 4x2x1 for v4-16 instead of 2x2x2), which
# never schedules and surfaces as a phantom stockout.
# v5e/v6e are 2-D (chip-count naming); v4/v5p are 3-D torus slices
# (TensorCore naming halved to chips), dims ascending powers of two.
_GKE_TOPOLOGY_2D = {
    1: '1x1', 4: '2x2', 8: '2x4', 16: '4x4', 32: '4x8', 64: '8x8',
    128: '8x16', 256: '16x16',
}
_GKE_TOPOLOGY_3D = {
    4: '2x2x1', 8: '2x2x2', 16: '2x2x4', 32: '2x4x4', 64: '4x4x4',
    128: '4x4x8', 256: '4x8x8', 512: '8x8x8', 1024: '8x8x16',
    2048: '8x16x16', 4096: '16x16x16',
}
GKE_TPU_TOPOLOGIES = {
    'v4': _GKE_TOPOLOGY_3D,
    'v5p': _GKE_TOPOLOGY_3D,
    'v5e': _GKE_TOPOLOGY_2D,
    'v6e': _GKE_TOPOLOGY_2D,
}


def gke_topology(generation: str, num_chips: int,
                 chips_per_host: int) -> str:
    """GKE topology selector value for a slice size, from the pinned
    table; unknown sizes fail loudly with the valid options."""
    del chips_per_host
    table = GKE_TPU_TOPOLOGIES.get(generation)
    if table is None:
        raise exceptions.InvalidResourcesError(
            f'No GKE topology table for TPU generation {generation!r}; '
            f'known: {sorted(GKE_TPU_TOPOLOGIES)}')
    topo = table.get(num_chips)
    if topo is None:
        raise exceptions.InvalidResourcesError(
            f'{generation} has no GKE node-pool topology for '
            f'{num_chips} chips; valid sizes: {sorted(table)}')
    return topo


def _pod_name(cluster_name: str, slice_idx: int, host_idx: int) -> str:
    return f'{cluster_name}-{slice_idx}-{host_idx}'


def _pod_manifest(cluster_name: str, slice_idx: int, host_idx: int,
                  node_config: Dict[str, Any]) -> Dict[str, Any]:
    accel = node_config.get('accelerator')
    manifest: Dict[str, Any] = {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {
            'name': _pod_name(cluster_name, slice_idx, host_idx),
            'labels': {
                _LABEL_CLUSTER: cluster_name,
                _LABEL_SLICE: str(slice_idx),
                _LABEL_HOST: str(host_idx),
            },
        },
        'spec': {
            'restartPolicy': 'Never',
            'hostname': _pod_name(cluster_name, slice_idx, host_idx),
            'subdomain': cluster_name,
            'containers': [{
                'name': 'skytpu',
                'image': node_config.get('image') or _DEFAULT_IMAGE,
                'command': ['/bin/sh', '-c', 'sleep infinity'],
                'resources': {},
            }],
        },
    }
    if accel:
        gen = node_config['generation']
        chips = int(node_config.get('chips_per_host', 0))
        sel = GKE_TPU_ACCELERATOR.get(gen)
        if sel is None:
            raise exceptions.InvalidResourcesError(
                f'No GKE TPU node pool mapping for generation {gen!r}')
        manifest['spec']['nodeSelector'] = {
            'cloud.google.com/gke-tpu-accelerator': sel,
            'cloud.google.com/gke-tpu-topology': gke_topology(
                gen, int(node_config['num_chips']), chips),
        }
        req = {TPU_RESOURCE_KEY: str(chips)}
        manifest['spec']['containers'][0]['resources'] = {
            'requests': dict(req), 'limits': dict(req)}
    return manifest


def _service_manifest(cluster_name: str) -> Dict[str, Any]:
    """Headless service: stable pod DNS for the coordinator."""
    return {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {'name': cluster_name,
                     'labels': {_LABEL_CLUSTER: cluster_name}},
        'spec': {'clusterIP': 'None',
                 'selector': {_LABEL_CLUSTER: cluster_name}},
    }


# ------------------------------------------------------------------ ops
def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region
    node_config = dict(config.node_config)
    namespace = (config.provider_config or {}).get('namespace', 'default')
    context = zone if zone not in (None, 'default', 'in-cluster') else None
    client = kc.K8sClient(namespace=namespace, context=context)
    _save_placement(cluster_name, namespace, context, node_config)

    hosts_per_slice = int(node_config.get('hosts_per_node', 1)) or 1
    existing = {p['metadata']['name']
                for p in client.list_pods(f'{_LABEL_CLUSTER}={cluster_name}')
                if (p.get('status') or {}).get('phase')
                in ('Pending', 'Running')}
    created: List[str] = []
    try:
        client.apply(_service_manifest(cluster_name))
        for s in range(config.count):
            for h in range(hosts_per_slice):
                name = _pod_name(cluster_name, s, h)
                if name in existing:
                    continue
                created.append(name)
                client.apply(_pod_manifest(cluster_name, s, h, node_config))
    except exceptions.SkyTpuError:
        # Gang semantics: tear down what this attempt created.
        for name in created:
            try:
                client.delete_pod(name)
            except exceptions.SkyTpuError:
                pass
        raise
    return common.ProvisionRecord(
        provider_name='kubernetes', cluster_name=cluster_name,
        region='kubernetes', zone=zone,
        head_instance_id=_pod_name(cluster_name, 0, 0),
        created_instance_ids=created, resumed_instance_ids=[])


def _pod_unschedulable(pod: Dict[str, Any]) -> Optional[str]:
    for cond in ((pod.get('status') or {}).get('conditions') or []):
        if (cond.get('type') == 'PodScheduled'
                and cond.get('status') == 'False'
                and cond.get('reason') == 'Unschedulable'):
            return cond.get('message') or 'Unschedulable'
    return None


def wait_instances(region: str, cluster_name: str, state: str,
                   timeout: Optional[float] = None) -> None:
    """Wait until every pod of the cluster is Running. Unschedulable
    pods (no TPU node pool capacity) fail over zone-scoped — the k8s
    equivalent of a stockout."""
    del region, state
    client = _client_for(cluster_name)
    deadline = time.time() + (timeout if timeout is not None
                              else default_schedule_timeout())
    while True:
        pods = client.list_pods(f'{_LABEL_CLUSTER}={cluster_name}')
        phases = [(p.get('status') or {}).get('phase') for p in pods]
        if pods and all(ph == 'Running' for ph in phases):
            return
        for p in pods:
            if (p.get('status') or {}).get('phase') in ('Failed',
                                                        'Succeeded'):
                raise exceptions.ProvisionError(
                    f'pod {p["metadata"]["name"]} exited during '
                    f'provisioning')
        if time.time() > deadline:
            msgs = [m for m in (_pod_unschedulable(p) for p in pods) if m]
            err = exceptions.InsufficientCapacityError(
                f'kubernetes: cluster {cluster_name} did not schedule in '
                f'time{": " + msgs[0] if msgs else ""}')
            raise err
        time.sleep(min(2.0, max(0.05, deadline - time.time())))


def query_instances(region: str, cluster_name: str) -> Dict[str, str]:
    del region
    if _load_placement(cluster_name) is None:
        return {}
    client = _client_for(cluster_name)
    out = {}
    for p in client.list_pods(f'{_LABEL_CLUSTER}={cluster_name}'):
        phase = (p.get('status') or {}).get('phase')
        status = {
            'Pending': common.STATUS_PENDING,
            'Running': common.STATUS_RUNNING,
        }.get(phase, common.STATUS_TERMINATED)
        if p.get('metadata', {}).get('deletionTimestamp'):
            status = common.STATUS_TERMINATED
        out[p['metadata']['name']] = status
    return out


def stop_instances(region: str, cluster_name: str) -> None:
    raise exceptions.NotSupportedError(
        'kubernetes pods cannot be stopped; use down (terminate)')


def terminate_instances(region: str, cluster_name: str) -> None:
    del region
    if _load_placement(cluster_name) is None:
        return
    client = _client_for(cluster_name)
    client.delete_collection(f'{_LABEL_CLUSTER}={cluster_name}')
    _drop_placement(cluster_name)


def get_cluster_info(region: str, cluster_name: str) -> common.ClusterInfo:
    del region
    placement = _load_placement(cluster_name)
    if placement is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    client = _client_for(cluster_name)
    node_config = placement.get('node_config', {})
    pods = client.list_pods(f'{_LABEL_CLUSTER}={cluster_name}')

    def key(p):
        lbl = p['metadata'].get('labels', {})
        return (int(lbl.get(_LABEL_SLICE, 0)), int(lbl.get(_LABEL_HOST, 0)))

    hosts: List[common.HostInfo] = []
    for rank, p in enumerate(sorted(pods, key=key)):
        lbl = p['metadata'].get('labels', {})
        hosts.append(common.HostInfo(
            instance_id=p['metadata']['name'],
            rank=rank,
            internal_ip=(p.get('status') or {}).get('podIP', ''),
            slice_id=int(lbl.get(_LABEL_SLICE, 0)),
        ))
    return common.ClusterInfo(
        cluster_name=cluster_name,
        provider_name='kubernetes',
        region='kubernetes',
        zone=placement.get('context'),
        hosts=hosts,
        head_instance_id=_pod_name(cluster_name, 0, 0),
        chips_per_host=int(node_config.get('chips_per_host', 0)),
        accelerator=node_config.get('accelerator'),
        provider_config={'namespace': placement['namespace'],
                         'context': placement.get('context')},
    )
