"""Local provisioner: "instances" are directories + local processes.

The hermetic substrate SURVEY §4 calls for (the reference has no in-repo
fake cloud; its tests monkeypatch catalogs instead). A cluster is a
directory under ``{state_dir}/local_clusters/<name>/`` with one ``node-<i>``
dir per host; each dir acts as that host's HOME (see
``LocalProcessRunner``). Multi-host TPU slices are simulated by multiple
node dirs, so the rank/coordinator env contract is exercised for real.

Failure injection: tests register a hook via :func:`set_failure_injector`
to simulate stockouts/quota/preemption per zone — driving the same
failover loop real clouds do.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Callable, Dict, List, Optional

import filelock

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.utils import common_utils, subprocess_utils

_META = 'meta.json'

# test hook: fn(cluster_name, region, zone, config) -> None (may raise)
_failure_injector: Optional[Callable] = None


def set_failure_injector(fn: Optional[Callable]) -> None:
    global _failure_injector
    _failure_injector = fn


def _clusters_root() -> str:
    d = os.path.join(common_utils.state_dir(), 'local_clusters')
    os.makedirs(d, exist_ok=True)
    return d


def _cluster_dir(cluster_name: str) -> str:
    return os.path.join(_clusters_root(), cluster_name)


def _meta_path(cluster_name: str) -> str:
    return os.path.join(_cluster_dir(cluster_name), _META)


def _lock(cluster_name: str) -> filelock.FileLock:
    return filelock.FileLock(
        os.path.join(_clusters_root(), f'.{cluster_name}.lock'))


def _load_meta(cluster_name: str) -> Optional[dict]:
    try:
        with open(_meta_path(cluster_name), encoding='utf-8') as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _save_meta(cluster_name: str, meta: dict) -> None:
    os.makedirs(_cluster_dir(cluster_name), exist_ok=True)
    with open(_meta_path(cluster_name), 'w', encoding='utf-8') as f:
        json.dump(meta, f, indent=1)


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    if _failure_injector is not None:
        _failure_injector(cluster_name, region, zone, config)
    num_hosts = config.count * int(
        config.node_config.get('hosts_per_node', 1))
    with _lock(cluster_name):
        meta = _load_meta(cluster_name)
        created: List[str] = []
        resumed: List[str] = []
        if meta is None:
            meta = {
                'cluster_name': cluster_name,
                'region': region,
                'zone': zone,
                'status': common.STATUS_RUNNING,
                'num_hosts': num_hosts,
                'node_config': config.node_config,
                'created_at': time.time(),
            }
            for i in range(num_hosts):
                node_dir = os.path.join(_cluster_dir(cluster_name),
                                        f'node-{i}')
                os.makedirs(node_dir, exist_ok=True)
                created.append(f'{cluster_name}-node-{i}')
        else:
            if meta['num_hosts'] != num_hosts:
                raise exceptions.ResourcesMismatchError(
                    f'Cluster {cluster_name} exists with '
                    f'{meta["num_hosts"]} hosts, requested {num_hosts}.')
            if meta['status'] == common.STATUS_STOPPED:
                resumed = [f'{cluster_name}-node-{i}'
                           for i in range(num_hosts)]
            meta['status'] = common.STATUS_RUNNING
        _save_meta(cluster_name, meta)
    return common.ProvisionRecord(
        provider_name='local', cluster_name=cluster_name, region=region,
        zone=zone, head_instance_id=f'{cluster_name}-node-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def wait_instances(region: str, cluster_name: str, state: str) -> None:
    del region, state  # local instances are synchronous
    if _load_meta(cluster_name) is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)


def _collect_agent_pids(cluster_name: str) -> List[int]:
    """Pids whose trees a real cloud's VM-terminate would take down: the
    agentd AND every live job driver. Drivers are launched detached by
    whichever process ran ``schedule_step`` (often a short-lived RPC
    shell), so they reparent to init and are NOT under the agentd tree —
    they must be killed via the pids recorded in the node's jobs db."""
    cdir = _cluster_dir(cluster_name)
    pids: List[int] = []
    if not os.path.isdir(cdir):
        return pids
    for node in sorted(os.listdir(cdir)):
        if not node.startswith('node-'):
            continue
        agent_dir = os.path.join(cdir, node, '.skytpu_agent')
        try:
            with open(os.path.join(agent_dir, 'agentd.pid'),
                      encoding='utf-8') as f:
                pids.append(int(f.read().strip()))
        except (FileNotFoundError, NotADirectoryError, ValueError):
            pass
        pids.extend(_live_driver_pids(os.path.join(agent_dir, 'jobs.db')))
    return pids


def _live_driver_pids(jobs_db: str) -> List[int]:
    import sqlite3
    if not os.path.exists(jobs_db):
        return []
    try:
        conn = sqlite3.connect(jobs_db, timeout=5)
        rows = conn.execute(
            'SELECT driver_pid FROM jobs WHERE driver_pid IS NOT NULL '
            "AND status IN ('INIT','PENDING','STARTING','RUNNING')"
        ).fetchall()
        conn.close()
    except sqlite3.Error:
        return []
    return [int(r[0]) for r in rows if r[0]]


def _kill_pids(pids: List[int]) -> None:
    """Kill agent daemon trees, killing our own tree LAST — autostop runs
    this from the agentd itself (a cluster stopping itself must finish its
    state mutation before dying)."""
    import os as os_mod
    me = os_mod.getpid()
    own = []
    for pid in pids:
        if pid == me:
            own.append(pid)
            continue
        subprocess_utils.kill_process_tree(pid)
    for pid in own:
        subprocess_utils.kill_process_tree(pid)


def stop_instances(region: str, cluster_name: str) -> None:
    del region
    with _lock(cluster_name):
        meta = _load_meta(cluster_name)
        if meta is None:
            return
        pids = _collect_agent_pids(cluster_name)
        meta['status'] = common.STATUS_STOPPED
        _save_meta(cluster_name, meta)
    _kill_pids(pids)


def terminate_instances(region: str, cluster_name: str) -> None:
    del region
    with _lock(cluster_name):
        if _load_meta(cluster_name) is None:
            return
        pids = _collect_agent_pids(cluster_name)
        shutil.rmtree(_cluster_dir(cluster_name), ignore_errors=True)
    _kill_pids(pids)


def query_instances(region: str, cluster_name: str) -> Dict[str, str]:
    del region
    meta = _load_meta(cluster_name)
    if meta is None:
        return {}
    return {f'{cluster_name}-node-{i}': meta['status']
            for i in range(meta['num_hosts'])}


def get_cluster_info(region: str, cluster_name: str) -> common.ClusterInfo:
    meta = _load_meta(cluster_name)
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    node_config = meta.get('node_config', {})
    hosts_per_slice = int(node_config.get('hosts_per_node', 1)) or 1
    # Only TPU clusters have slices; multi-node CPU clusters are plain
    # separate nodes (slice_id 0 everywhere, matching the GCP provider).
    is_tpu = bool(node_config.get('accelerator'))
    hosts = []
    for i in range(meta['num_hosts']):
        hosts.append(common.HostInfo(
            instance_id=f'{cluster_name}-node-{i}',
            rank=i,
            internal_ip='127.0.0.1',
            slice_id=(i // hosts_per_slice) if is_tpu else 0,
            node_dir=os.path.join(_cluster_dir(cluster_name), f'node-{i}')))
    return common.ClusterInfo(
        cluster_name=cluster_name,
        provider_name='local',
        region=meta['region'],
        zone=meta.get('zone'),
        hosts=hosts,
        head_instance_id=f'{cluster_name}-node-0',
        chips_per_host=int(node_config.get('chips_per_host', 0)),
        accelerator=node_config.get('accelerator'),
    )
