"""DAG of Tasks with a thread-local ambient context.

Functional parity with reference ``sky/dag.py`` (``Dag`` at ``sky/dag.py:11``,
``_DagContext`` at ``:80``). Like the reference, managed-job pipelines only
support chain DAGs; the general graph is kept for the optimizer's ILP path.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from skypilot_tpu import exceptions


class Dag:
    """A graph of Tasks. Use as a context manager to collect tasks:

        with Dag() as dag:
            t1 = Task(...)
            t2 = Task(...)
            t1 >> t2
    """

    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.tasks: List = []
        # adjacency: edges[i] = set of task indices that depend on tasks[i]
        self._edges: List[tuple] = []  # (upstream_task, downstream_task)

    # ---------------- graph ops ----------------
    def add(self, task) -> None:
        if task not in self.tasks:
            self.tasks.append(task)
            task._dag = self

    def remove(self, task) -> None:
        self._edges = [(u, v) for (u, v) in self._edges
                       if u is not task and v is not task]
        self.tasks.remove(task)

    def add_edge(self, op1, op2) -> None:
        self.add(op1)
        self.add(op2)
        self._edges.append((op1, op2))

    def edges(self) -> List[tuple]:
        return list(self._edges)

    def successors(self, task) -> List:
        return [v for (u, v) in self._edges if u is task]

    def predecessors(self, task) -> List:
        return [u for (u, v) in self._edges if v is task]

    def get_graph(self):
        """NetworkX DiGraph view (lazy import, like the reference)."""
        import networkx as nx  # lazy: heavy import
        g = nx.DiGraph()
        g.add_nodes_from(self.tasks)
        g.add_edges_from(self._edges)
        return g

    # ---------------- validation / shape ----------------
    def is_chain(self) -> bool:
        if len(self.tasks) <= 1:
            return True
        order = self.topological_order()
        for i, t in enumerate(order):
            succ = self.successors(t)
            if i < len(order) - 1:
                if succ != [order[i + 1]]:
                    return False
            elif succ:
                return False
        return True

    def topological_order(self) -> List:
        indeg = {id(t): 0 for t in self.tasks}
        for (_, v) in self._edges:
            indeg[id(v)] += 1
        ready = [t for t in self.tasks if indeg[id(t)] == 0]
        out: List = []
        while ready:
            t = ready.pop(0)
            out.append(t)
            for v in self.successors(t):
                indeg[id(v)] -= 1
                if indeg[id(v)] == 0:
                    ready.append(v)
        if len(out) != len(self.tasks):
            raise exceptions.InvalidDagError('DAG has a cycle.')
        return out

    def validate(self) -> None:
        self.topological_order()

    def __len__(self) -> int:
        return len(self.tasks)

    def __enter__(self) -> 'Dag':
        push_dag(self)
        return self

    def __exit__(self, *args) -> None:
        pop_dag()

    def __repr__(self) -> str:
        return f'Dag(name={self.name!r}, tasks={len(self.tasks)})'


class _DagContext(threading.local):
    """Thread-local stack of active DAGs (reference ``sky/dag.py:80``)."""

    def __init__(self):
        super().__init__()
        self._stack: List[Dag] = []

    def push(self, dag: Dag) -> None:
        self._stack.append(dag)

    def pop(self) -> Dag:
        return self._stack.pop()

    def current(self) -> Optional[Dag]:
        return self._stack[-1] if self._stack else None


_context = _DagContext()


def push_dag(dag: Dag) -> None:
    _context.push(dag)


def pop_dag() -> Dag:
    return _context.pop()


def get_current_dag() -> Optional[Dag]:
    return _context.current()


def _current_dag_add_edge(t1, t2) -> None:
    dag = get_current_dag()
    if dag is None:
        raise exceptions.InvalidDagError(
            'Task >> Task requires an active `with Dag():` context.')
    dag.add_edge(t1, t2)
