"""TpuVmBackend: the orchestration engine.

Role of reference ``CloudVmRayBackend``
(``sky/backends/cloud_vm_ray_backend.py:2620``) redesigned TPU-first:

- No Ray. A slice is already a gang; jobs fan out from the head agent
  (:mod:`skypilot_tpu.agent.driver`) over every host.
- Provisioning failover: zone loop with blocklisting + re-optimize
  (reference ``RetryingVmProvisioner.provision_with_retries`` ``:1979``),
  consuming the :class:`exceptions.ProvisionError` taxonomy
  (``blocklist_scope``) instead of parsing cloud stdout.
- Client<->head control is the JSON RPC (:mod:`skypilot_tpu.agent.rpc`),
  replacing codegen-over-SSH.
"""
from __future__ import annotations

import os
import time
import typing
import uuid
from typing import Any, Dict, List, Optional

import filelock

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import provision
from skypilot_tpu import tpu_logging
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.backend import backend as backend_lib
from skypilot_tpu.dag import Dag
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import provisioner
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.utils import common_utils, subprocess_utils

logger = tpu_logging.init_logger(__name__)

WORKDIR_TARGET = agent_constants.WORKDIR_TARGET


class TpuVmResourceHandle(backend_lib.ResourceHandle):
    """Pickleable record of a launched cluster (reference
    ``CloudVmRayResourceHandle`` ``:2156``). Hosts are first-class via
    the embedded ClusterInfo."""

    _VERSION = 1

    def __init__(self, *, cluster_name: str,
                 launched_resources: Resources,
                 num_nodes: int,
                 cluster_info: provision_common.ClusterInfo):
        self.cluster_name = cluster_name
        self.launched_resources = launched_resources
        self.num_nodes = num_nodes
        self.cluster_info = cluster_info
        self.cluster_hash = f'{cluster_name}-{uuid.uuid4().hex[:8]}'
        self._version = self._VERSION

    def get_cluster_name(self) -> str:
        return self.cluster_name

    @property
    def num_hosts(self) -> int:
        return self.cluster_info.num_hosts

    def runners(self) -> List[Any]:
        return provision_common.get_command_runners(self.cluster_info)

    def head_runner(self) -> Any:
        return self.runners()[0]

    def __setstate__(self, state):
        version = state.get('_version', 0)
        if version < self._VERSION:
            # Forward-compat hook for controller/client skew.
            pass
        self.__dict__.update(state)

    def __repr__(self) -> str:
        return (f'TpuVmResourceHandle({self.cluster_name}, '
                f'{self.launched_resources}, hosts={self.num_hosts})')


class FailoverError(Exception):
    """Internal: zone attempts for one optimized choice all failed;
    carries the blocked resources accumulated so far."""

    def __init__(self, blocked: List[Resources]):
        super().__init__('all zones failed')
        self.blocked = blocked


class RetryingProvisioner:
    """Zone loop -> region/cloud failover via re-optimization
    (reference ``RetryingVmProvisioner`` ``:1155``)."""

    def __init__(self, max_optimize_rounds: int = 10):
        self.max_optimize_rounds = max_optimize_rounds

    def provision_with_retries(
            self, task: Task, cluster_name: str,
            retry_until_up: bool = False
    ) -> provision_common.ClusterInfo:
        blocked: List[Resources] = []
        rounds = 0
        while True:
            rounds += 1
            dag = Dag()
            dag.add(task)
            try:
                optimizer_lib.optimize(dag, blocked_resources=blocked)
            except exceptions.ResourcesUnavailableError:
                if retry_until_up:
                    logger.warning(
                        f'All candidate resources failed for '
                        f'{cluster_name}; retrying from scratch in 10s '
                        '(--retry-until-up).')
                    blocked = []
                    rounds = 0
                    time.sleep(10)
                    continue
                raise
            if rounds > self.max_optimize_rounds and not retry_until_up:
                raise exceptions.ResourcesUnavailableError(
                    f'Exceeded {self.max_optimize_rounds} optimize/failover '
                    f'rounds for {cluster_name}; giving up. Blocked: '
                    f'{blocked}')
            to_provision = task.best_resources
            try:
                return self._retry_zones(task, to_provision, cluster_name,
                                         blocked)
            except FailoverError as e:
                blocked.extend(e.blocked)
                logger.info(
                    f'Failing over {cluster_name}: re-optimizing with '
                    f'{len(blocked)} blocked resource filter(s).')

    def _retry_zones(self, task: Task, to_provision: Resources,
                     cluster_name: str,
                     already_blocked: List[Resources]
                     ) -> provision_common.ClusterInfo:
        cloud = clouds_lib.from_name(to_provision.cloud or 'gcp')
        blocked: List[Resources] = []
        zone_iter = [
            z for z in cloud.zones_provision_loop(to_provision)
            if not optimizer_lib.resources_blocked(
                Resources(cloud=cloud.NAME, region=z.region, zone=z.name),
                already_blocked)
        ]
        if not zone_iter:
            # Every zone of this choice is already blocked (or none
            # exist): escalate to region scope so re-optimization moves
            # to a different region instead of re-picking this one.
            if to_provision.region is not None:
                raise FailoverError([Resources(cloud=cloud.NAME,
                                               region=to_provision.region)])
            raise FailoverError([to_provision.copy(zone=None)])
        for zone in zone_iter:
            attempt = to_provision.copy(region=zone.region, zone=zone.name)
            config = cloud.make_provision_config(attempt, task.num_nodes,
                                                 cluster_name)
            try:
                logger.info(
                    f'Launching {cluster_name} '
                    f'({attempt}) in {zone.name}...')
                return provisioner.bulk_provision(
                    cloud.PROVISIONER, zone.region, zone.name, cluster_name,
                    config)
            except exceptions.ProvisionError as e:
                scope = getattr(e, 'blocklist_scope', 'zone')
                logger.warning(f'Provision attempt in {zone.name} failed '
                               f'({type(e).__name__}: {e}); '
                               f'blocklisting {scope}.')
                _cleanup_failed_attempt(cloud.PROVISIONER, zone.region,
                                        cluster_name)
                if scope == 'zone':
                    blocked.append(Resources(cloud=cloud.NAME,
                                             region=zone.region,
                                             zone=zone.name))
                elif scope == 'region':
                    blocked.append(Resources(cloud=cloud.NAME,
                                             region=zone.region))
                else:
                    blocked.append(Resources(cloud=cloud.NAME))
                if getattr(e, 'no_failover', False):
                    raise exceptions.ResourcesUnavailableError(
                        str(e), no_failover=True) from e
        # All remaining zones of this choice failed. Zone-scoped entries
        # alone would never match the optimizer's region-level candidates,
        # so also blocklist each region whose zones are now exhausted.
        all_blocked = already_blocked + blocked
        for region in {z.region for z in zone_iter}:
            region_res = Resources(cloud=cloud.NAME, region=region)
            if optimizer_lib.resources_blocked(region_res, all_blocked):
                continue  # already covered by a region/cloud-scope entry
            region_zones = [
                z for z in cloud.zones_provision_loop(to_provision)
                if z.region == region]
            if all(optimizer_lib.resources_blocked(
                    Resources(cloud=cloud.NAME, region=z.region,
                              zone=z.name), all_blocked)
                   for z in region_zones):
                blocked.append(region_res)
        raise FailoverError(blocked)


def _cleanup_failed_attempt(provider: str, region: str,
                            cluster_name: str) -> None:
    """TPU creates leave debris on failure (reference
    ``need_cleanup_after_preemption_or_failure``); terminate best-effort."""
    try:
        provision.terminate_instances(provider, region, cluster_name)
    except Exception:  # pylint: disable=broad-except
        logger.debug(f'cleanup of failed attempt {cluster_name} errored',
                     exc_info=True)


class TpuVmBackend(backend_lib.Backend[TpuVmResourceHandle]):
    NAME = 'tpuvm'

    def __init__(self):
        self._provisioner = RetryingProvisioner()

    # ------------------------------------------------------------ provision
    def provision(self, task: Task, to_provision: Optional[Resources],
                  *, cluster_name: str, dryrun: bool = False,
                  retry_until_up: bool = False
                  ) -> Optional[TpuVmResourceHandle]:
        del to_provision  # the retry loop re-optimizes internally
        if dryrun:
            return None
        lock = filelock.FileLock(os.path.join(
            common_utils.state_dir(), f'.{cluster_name}.launch.lock'))
        with lock:
            existing = global_state.get_cluster_from_name(cluster_name)
            if existing is not None and existing['handle'] is not None:
                handle = self._reuse_existing(task, existing)
                if handle is not None:
                    return handle
            cluster_info = self._provisioner.provision_with_retries(
                task, cluster_name, retry_until_up=retry_until_up)
            launched = task.best_resources
            handle = TpuVmResourceHandle(
                cluster_name=cluster_name,
                launched_resources=launched,
                num_nodes=task.num_nodes,
                cluster_info=cluster_info)
            global_state.add_or_update_cluster(cluster_name, handle,
                                              ready=True)
            return handle

    def _reuse_existing(self, task: Task,
                        record: Dict[str, Any]
                        ) -> Optional[TpuVmResourceHandle]:
        """Reuse an UP cluster whose resources satisfy the request
        (reference ``sky exec`` / relaunch semantics)."""
        from skypilot_tpu.backend import backend_utils
        cluster_name = record['name']
        record, handle = backend_utils.refresh_cluster_status(cluster_name)
        if record is None or handle is None:
            return None
        status = record['status']
        if status == global_state.ClusterStatus.STOPPED:
            # Restart instances then reuse.
            info = handle.cluster_info
            provision.run_instances(
                info.provider_name, info.region, info.zone, cluster_name,
                self._restart_config(handle))
            provisioner.post_provision_runtime_setup(info)
            global_state.add_or_update_cluster(cluster_name, handle,
                                              ready=True)
            return handle
        if status != global_state.ClusterStatus.UP:
            return None
        requested = task.resources[0]
        if not requested.less_demanding_than(handle.launched_resources):
            raise exceptions.ResourcesMismatchError(
                f'Cluster {cluster_name!r} exists with '
                f'{handle.launched_resources}, which does not satisfy the '
                f'request {requested}. Use a new cluster name or down the '
                'existing one.')
        if task.num_nodes > handle.num_nodes:
            # Resources alone don't carry node/slice count; a multi-slice
            # request must not silently reuse a smaller cluster.
            raise exceptions.ResourcesMismatchError(
                f'Cluster {cluster_name!r} has {handle.num_nodes} '
                f'node(s)/slice(s); the task requests {task.num_nodes}. '
                'Use a new cluster name or down the existing one.')
        self._ensure_runtime_current(handle)
        global_state.update_last_use(cluster_name)
        return handle

    def _ensure_runtime_current(self, handle: TpuVmResourceHandle) -> None:
        """Version-skew guard on cluster REUSE: a newer client must not
        drive an agent running old code (the reference re-rsyncs its
        wheel on every launch; ``sky/backends/wheel_utils.py:140`` +
        ``tests/backward_compatibility_tests.sh``). One agent_health RPC
        compares the remote runtime hash with the client's; on mismatch
        the runtime re-ships and the agent restarts on the new code."""
        info = handle.cluster_info
        if info.provider_name == 'local':
            return          # local nodes import the client's tree directly
        from skypilot_tpu.utils import pkg_utils
        try:
            resp = provisioner.agent_request(handle.head_runner(),
                                             {'op': 'agent_health'})
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'agent_health on {handle.cluster_name} failed '
                         f'({type(e).__name__}: {e}); unreachable '
                         'agents are the refresh\'s problem')
            return
        remote = resp.get('runtime_version')
        local = pkg_utils.package_hash()
        if remote is not None and remote != local:
            logger.info(f'Runtime version skew on {handle.cluster_name} '
                        f'(agent {remote}, client {local}); re-shipping '
                        'runtime and restarting the agent.')
            provisioner.post_provision_runtime_setup(info)

    def _restart_config(self, handle: TpuVmResourceHandle):
        cloud = clouds_lib.from_name(
            handle.launched_resources.cloud or 'gcp')
        return cloud.make_provision_config(
            handle.launched_resources, handle.num_nodes,
            handle.cluster_name)

    # ------------------------------------------------------------ sync
    def sync_workdir(self, handle: TpuVmResourceHandle,
                     workdir: str) -> None:
        source = os.path.abspath(os.path.expanduser(workdir))
        if not os.path.isdir(source):
            raise exceptions.InvalidTaskError(
                f'workdir {workdir!r} is not a directory')
        if not source.endswith('/'):
            source += '/'

        def sync_one(runner):
            runner.run(f'mkdir -p {WORKDIR_TARGET}', log_path=os.devnull)
            runner.rsync(source, WORKDIR_TARGET + '/', up=True)

        subprocess_utils.run_in_parallel(sync_one, handle.runners())

    def sync_file_mounts(self, handle: TpuVmResourceHandle,
                         file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        mounts = dict(file_mounts or {})

        def sync_host(runner):
            for dst, src in mounts.items():
                if _is_cloud_uri(src):
                    self._download_cloud_uri(runner, src, dst)
                else:
                    expanded = os.path.abspath(os.path.expanduser(src))
                    if os.path.isdir(expanded) and not expanded.endswith('/'):
                        expanded += '/'
                    parent = os.path.dirname(dst.rstrip('/')) or '.'
                    runner.run(f'mkdir -p {parent}', log_path=os.devnull)
                    runner.rsync(expanded, dst, up=True)

        if mounts:
            subprocess_utils.run_in_parallel(sync_host, handle.runners())
        if storage_mounts:
            from skypilot_tpu.data import storage_utils
            storage_utils.execute_storage_mounts(handle, storage_mounts)

    def _download_cloud_uri(self, runner, src: str, dst: str) -> None:
        from skypilot_tpu.data import cloud_stores
        cmd = cloud_stores.make_download_command(src, dst)
        runner.check_run(cmd)

    # ------------------------------------------------------------ setup
    def setup(self, handle: TpuVmResourceHandle, task: Task,
              detach_setup: bool = False) -> None:
        del detach_setup
        if not task.setup:
            return
        log_dir = os.path.join(common_utils.state_dir(), 'logs',
                               handle.cluster_name)
        env = dict(task.envs)
        # docker-runtime tasks run setup INSIDE the container image too,
        # or setup-installed deps would be invisible to the run command.
        # (Not on kubernetes: the pod already IS the container.)
        from skypilot_tpu.utils import docker_utils
        image = (docker_utils.docker_image_of(
                     handle.launched_resources.image_id)
                 if handle.cluster_info.provider_name != 'kubernetes'
                 else None)
        setup_cmd = (docker_utils.wrap_in_docker(task.setup, image, env)
                     if image else task.setup)

        def setup_one(rank_runner):
            rank, runner = rank_runner
            log_path = os.path.join(log_dir, f'setup-{rank}.log')
            rc = runner.run(setup_cmd, env=env, log_path=log_path,
                            cwd=None)
            rc = rc if isinstance(rc, int) else rc[0]
            if rc != 0:
                tail = common_utils.read_last_n_lines(log_path, 20)
                raise exceptions.CommandError(
                    rc, f'setup on host {rank}',
                    f'Setup failed. Log tail:\n{tail}')

        subprocess_utils.run_in_parallel(
            setup_one, list(enumerate(handle.runners())))

    # ------------------------------------------------------------ execute
    def execute(self, handle: TpuVmResourceHandle, task: Task,
                detach_run: bool = True,
                dryrun: bool = False) -> Optional[int]:
        if dryrun:
            return None
        if task.run is None:
            logger.info('Task has no run command; provisioning only.')
            return None
        run_cmd = task.run
        if not isinstance(run_cmd, str):
            raise exceptions.InvalidTaskError(
                'Command generators are resolved before execute().')
        from skypilot_tpu.utils import docker_utils
        spec = {
            'run': run_cmd,
            'env': {str(k): str(v) for k, v in task.envs.items()},
            # A workdir synced directly OR delivered via a translated
            # file_mount (controller_utils) both mean: run from there.
            'workdir_target': WORKDIR_TARGET
                              if (task.workdir
                                  or WORKDIR_TARGET in task.file_mounts)
                              else None,
            # 'docker:<image>' => the driver wraps the run command in a
            # container on each host (reference docker runtime,
            # ``sky/backends/local_docker_backend.py:47``). On
            # kubernetes the POD already runs that image — no second
            # docker layer.
            'docker_image': (
                docker_utils.docker_image_of(
                    handle.launched_resources.image_id)
                if handle.cluster_info.provider_name != 'kubernetes'
                else None),
        }
        resp = provisioner.agent_request(handle.head_runner(), {
            'op': 'queue_job',
            'name': task.name or 'task',
            'username': common_utils.get_cleaned_username(),
            'run_timestamp': common_utils.make_run_timestamp(),
            'resources': str(handle.launched_resources),
            'spec': spec,
        })
        job_id = int(resp['job_id'])
        logger.info(f'Job {job_id} submitted to {handle.cluster_name}.')
        if not detach_run:
            self.tail_logs(handle, job_id)
        return job_id

    # ------------------------------------------------------------ job ops
    def tail_logs(self, handle: TpuVmResourceHandle, job_id: int,
                  follow: bool = True) -> None:
        import json as json_lib
        import shlex
        req = {'op': 'tail', 'job_id': job_id, 'follow': follow}
        runner = handle.head_runner()
        from skypilot_tpu.agent import constants as agent_constants
        cmd = (f'{agent_constants.control_plane_env_prefix()}'
               f'{shlex.quote(runner.remote_python)} '
               f'-m skypilot_tpu.agent.rpc '
               f'{shlex.quote(json_lib.dumps(req))}')
        runner.run(cmd, stream_logs=True, log_path=os.devnull)

    def get_job_logs(self, handle: TpuVmResourceHandle, job_id: int,
                     tail: int = 0) -> str:
        resp = provisioner.agent_request(
            handle.head_runner(),
            {'op': 'logs', 'job_id': job_id, 'tail': tail})
        return resp['logs']

    def get_job_status(self, handle: TpuVmResourceHandle,
                       job_id: int) -> Optional[str]:
        resp = provisioner.agent_request(
            handle.head_runner(), {'op': 'job_status', 'job_id': job_id})
        return resp['status']

    def get_job_queue(self, handle: TpuVmResourceHandle) -> List[Dict]:
        resp = provisioner.agent_request(handle.head_runner(),
                                         {'op': 'job_table'})
        return resp['jobs']

    def cancel_jobs(self, handle: TpuVmResourceHandle,
                    job_id: Optional[int]) -> List[int]:
        if job_id is None:
            resp = provisioner.agent_request(handle.head_runner(),
                                             {'op': 'cancel_all'})
            return resp['cancelled']
        resp = provisioner.agent_request(
            handle.head_runner(), {'op': 'cancel', 'job_id': job_id})
        return [job_id] if resp['cancelled'] else []

    def set_autostop(self, handle: TpuVmResourceHandle, idle_minutes: int,
                     down: bool = False) -> None:
        if idle_minutes >= 0:
            stop_reason = None
            if not down:
                stop_reason = clouds_lib.from_name(
                    handle.launched_resources.cloud
                    or 'gcp').check_stop_supported(
                        handle.launched_resources)
            if stop_reason is not None:
                raise exceptions.NotSupportedError(stop_reason)
        provisioner.agent_request(handle.head_runner(), {
            'op': 'set_autostop', 'idle_minutes': idle_minutes,
            'to_down': down})
        global_state.set_cluster_autostop(handle.cluster_name,
                                          idle_minutes, down)

    # ------------------------------------------------------------ teardown
    def teardown(self, handle: TpuVmResourceHandle,
                 terminate: bool) -> None:
        info = handle.cluster_info
        if not terminate:
            reason = clouds_lib.from_name(
                handle.launched_resources.cloud
                or 'gcp').check_stop_supported(
                    handle.launched_resources)
            if reason is not None:
                raise exceptions.NotSupportedError(reason)
        provisioner.teardown_cluster(info.provider_name, info.region,
                                     handle.cluster_name,
                                     terminate=terminate)
        global_state.remove_cluster(handle.cluster_name,
                                    terminate=terminate)


def _is_cloud_uri(path: str) -> bool:
    # file:// is the LOCAL store's URI (a directory pretending to be a
    # bucket) — it must take the download path, not client-side rsync,
    # so translated controller file mounts resolve on the REMOTE host.
    return path.startswith(('gs://', 's3://', 'r2://', 'https://',
                            'http://', 'file://'))
