"""Backend ABC: the cluster lifecycle interface.

Role of reference ``sky/backends/backend.py:30`` (``Backend`` with
provision/sync_workdir/sync_file_mounts/setup/execute/post_execute/
teardown and a typed ``ResourceHandle``).
"""
from __future__ import annotations

from typing import Any, Dict, Generic, Optional, TypeVar

from skypilot_tpu.task import Task


class ResourceHandle:
    """Opaque, pickleable pointer to launched resources."""

    def get_cluster_name(self) -> str:
        raise NotImplementedError


_HandleT = TypeVar('_HandleT', bound=ResourceHandle)


class Backend(Generic[_HandleT]):
    NAME = 'backend'

    # --- lifecycle ---
    def provision(self,
                  task: Task,
                  to_provision: Optional[Any],
                  *,
                  cluster_name: str,
                  dryrun: bool = False,
                  retry_until_up: bool = False) -> Optional[_HandleT]:
        raise NotImplementedError

    def sync_workdir(self, handle: _HandleT, workdir: str) -> None:
        raise NotImplementedError

    def sync_file_mounts(self, handle: _HandleT,
                         file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def setup(self, handle: _HandleT, task: Task,
              detach_setup: bool = False) -> None:
        raise NotImplementedError

    def execute(self, handle: _HandleT, task: Task,
                detach_run: bool = True,
                dryrun: bool = False) -> Optional[int]:
        """Submit the task; returns job_id (None for dryrun)."""
        raise NotImplementedError

    def post_execute(self, handle: _HandleT, down: bool) -> None:
        del handle, down

    def teardown(self, handle: _HandleT, terminate: bool) -> None:
        raise NotImplementedError
