"""Backend helpers: cluster status refresh + reconciliation.

Role of reference ``sky/backends/backend_utils.py`` (status refresh via
runtime health + cloud query, ``refresh_cluster_status_handle``;
INIT/UP/STOPPED transition rules per
``sky/design_docs/cluster_status.md``). Instead of parsing ``ray status``
we ask the head agent for health over the RPC.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision
from skypilot_tpu import tpu_logging
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import provisioner

logger = tpu_logging.init_logger(__name__)


def refresh_cluster_status(
        cluster_name: str,
        *,
        force: bool = False) -> Tuple[Optional[Dict[str, Any]],
                                      Optional[Any]]:
    """Reconcile recorded status with cloud truth + agent health.

    Returns (record, handle); (None, None) if the cluster no longer
    exists anywhere (row removed)."""
    record = global_state.get_cluster_from_name(cluster_name)
    if record is None:
        return None, None
    handle = record['handle']
    if handle is None:
        return record, None
    del force  # one-shot reconcile; cache hints are future work

    info = handle.cluster_info
    statuses = provision.query_instances(info.provider_name, info.region,
                                         cluster_name)
    if not statuses:
        # Cloud says gone (terminated out-of-band or autodowned).
        logger.debug(f'Cluster {cluster_name} not found at provider; '
                     'removing from state.')
        global_state.remove_cluster(cluster_name, terminate=True)
        return None, None

    values = set(statuses.values())
    if values == {provision_common.STATUS_STOPPED}:
        new_status = global_state.ClusterStatus.STOPPED
    elif values == {provision_common.STATUS_RUNNING}:
        new_status = (global_state.ClusterStatus.UP
                      if _agent_healthy(handle)
                      else global_state.ClusterStatus.INIT)
    else:
        new_status = global_state.ClusterStatus.INIT
    if new_status != record['status']:
        if new_status == global_state.ClusterStatus.STOPPED:
            global_state.remove_cluster(cluster_name, terminate=False)
        else:
            global_state.update_cluster_status(cluster_name, new_status)
        record = global_state.get_cluster_from_name(cluster_name)
    return record, handle


def _agent_healthy(handle) -> bool:
    try:
        runner = handle.head_runner()
        resp = provisioner.agent_request(runner, {'op': 'agent_health'})
        return bool(resp.get('agentd_alive'))
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'agent_health probe failed: '
                     f'{type(e).__name__}: {e}')
        return False


def check_cluster_available(cluster_name: str):
    """Return a handle for an UP cluster or raise."""
    record, handle = refresh_cluster_status(cluster_name)
    if record is None or handle is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    if record['status'] != global_state.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}, '
            'not UP.')
    return handle
