"""Core SDK ops: status/start/stop/down/autostop/queue/cancel/logs/cost.

Role of reference ``sky/core.py`` (``status`` ``:41``, ``stop`` ``:396``,
``down`` ``:456``, ``autostop`` ``:491``, ``queue`` ``:600``, ``cancel``
``:662``, ``tail_logs`` ``:750``, ``cost_report`` ``:213``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import tpu_logging
from skypilot_tpu.backend import backend_utils
from skypilot_tpu.backend import tpu_backend
from skypilot_tpu.provision import provisioner

logger = tpu_logging.init_logger(__name__)


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records (optionally reconciled against the cloud)."""
    records = global_state.get_clusters()
    if cluster_names:
        records = [r for r in records if r['name'] in cluster_names]
    if refresh:
        refreshed = []
        for record in records:
            new_record, _ = backend_utils.refresh_cluster_status(
                record['name'])
            if new_record is not None:
                refreshed.append(new_record)
        records = refreshed
    return records


def _get_handle(cluster_name: str):
    record = global_state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    return record['handle']


def start(cluster_name: str,
          idle_minutes_to_autostop: Optional[int] = None,
          retry_until_up: bool = False) -> Any:
    """Restart a STOPPED cluster (reference ``sky.start``)."""
    from skypilot_tpu import execution
    from skypilot_tpu.task import Task
    record = global_state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle = record['handle']
    task = Task(name='start')
    task.set_resources(handle.launched_resources)
    _, new_handle = execution.launch(
        task, cluster_name=cluster_name,
        idle_minutes_to_autostop=idle_minutes_to_autostop,
        retry_until_up=retry_until_up,
        stream_logs=False)
    return new_handle


def stop(cluster_name: str) -> None:
    handle = _get_handle(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.teardown(handle, terminate=False)


def down(cluster_name: str) -> None:
    handle = _get_handle(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.teardown(handle, terminate=True)


def autostop(cluster_name: str, idle_minutes: int,
             down: bool = False) -> None:  # pylint: disable=redefined-outer-name
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.set_autostop(handle, idle_minutes, down=down)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    """Per-cluster job table. Health check + table read ride ONE
    batched RPC round trip (each remote call costs an ssh exec + python
    start against a real cluster)."""
    from skypilot_tpu.provision import provisioner
    record = global_state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle = record['handle']
    try:
        resp = provisioner.agent_request(handle.head_runner(), {
            'op': 'batch',
            'requests': [{'op': 'agent_health'}, {'op': 'job_table'}]})
        health, table = resp['results']
        if health.get('ok') and health.get('agentd_alive') \
                and table.get('ok'):
            return table['jobs']
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'Fast job-queue path on {cluster_name} failed '
                     f'({type(e).__name__}: {e}); falling back to full '
                     'status reconciliation.')
    # Fallback: full status reconciliation (cloud truth), then the
    # plain read — the slow path for unhealthy/stale clusters.
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    return backend.get_job_queue(handle)


def cancel(cluster_name: str,
           job_id: Optional[int] = None,
           all: bool = False) -> List[int]:  # pylint: disable=redefined-builtin
    if job_id is None and not all:
        raise ValueError('Specify job_id or all=True.')
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    return backend.cancel_jobs(handle, None if all else job_id)


def tail_logs(cluster_name: str, job_id: int,
              follow: bool = True) -> None:
    handle = backend_utils.check_cluster_available(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.tail_logs(handle, job_id, follow=follow)


def job_status(cluster_name: str, job_id: int,
               fast: bool = False) -> Optional[str]:
    """Agent job status. ``fast=True`` skips the cluster-health refresh
    (one RPC instead of two) and trusts the cached handle — the right
    mode for poll loops that already treat RPC failure as a possible
    preemption signal (the jobs controller's monitor)."""
    if fast:
        record = global_state.get_cluster_from_name(cluster_name)
        if record is None or record['handle'] is None:
            raise exceptions.ClusterDoesNotExist(
                f'Cluster {cluster_name!r} does not exist.')
        handle = record['handle']
    else:
        handle = backend_utils.check_cluster_available(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    return backend.get_job_status(handle, job_id)


def cost_report() -> List[Dict[str, Any]]:
    """Per-cluster cost from recorded usage intervals × catalog price
    (reference ``sky/core.py:213`` + usage intervals
    ``sky/global_user_state.py:469``)."""
    out = []
    rows = global_state.get_clusters() + global_state.get_cluster_history()
    seen = set()
    for record in rows:
        name = record['name']
        if name in seen:
            continue
        seen.add(name)
        launched = record.get('launched_resources')
        hours = global_state.get_cluster_usage_hours(name)
        cost_per_hr = 0.0
        if launched:
            try:
                from skypilot_tpu.resources import Resources
                res = Resources.from_yaml_config(launched)
                cloud = clouds_lib.from_name(res.cloud or 'gcp')
                cost_per_hr = cloud.instance_type_to_hourly_cost(
                    res, res.use_spot)
            except Exception:  # pylint: disable=broad-except
                logger.debug(f'cost lookup failed for {name}',
                             exc_info=True)
        out.append({
            'name': name,
            'duration_hours': hours,
            'cost_per_hour': cost_per_hr,
            'total_cost': hours * cost_per_hr,
            'resources': launched,
        })
    return out


def cluster_is_idle(cluster_name: str) -> bool:
    handle = backend_utils.check_cluster_available(cluster_name)
    resp = provisioner.agent_request(handle.head_runner(),
                                     {'op': 'is_idle'})
    return bool(resp['idle'])
