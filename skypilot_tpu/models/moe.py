"""Mixture-of-Experts FFN (Mixtral-class) with expert parallelism.

The reference only *launches* MoE models via recipes (``llm/mixtral/``); the
expert parallelism itself lives in the launched framework. Here it is
in-tree: experts are sharded over the mesh's expert axis (the ``'expert'``
logical axis maps to ``('fsdp','sp')`` by default — see
``parallel.mesh.DEFAULT_RULES``) so each device holds ``E/ep`` experts, and
routing uses a dense masked dispatch that XLA turns into a single batched
einsum per projection.

Round-1 note: dense dispatch computes every expert on every token (masked to
zero for unrouted pairs). This keeps the HLO static-shaped and MXU-friendly
and parallelizes over the expert axis, at k/E efficiency vs ideal top-k
dispatch; a capacity-based ragged dispatch (GShard-style) is the planned
optimization.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from skypilot_tpu.models.configs import ModelConfig

Params = Dict[str, Any]


def init_moe_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    d, f, E, L = cfg.dim, cfg.ffn_dim, cfg.n_experts, cfg.n_layers
    ks = jax.random.split(rng, 4)

    def init(key, shape, fan_in):
        layers = jax.random.split(key, L)
        return jnp.stack([
            (jax.random.normal(k, shape, jnp.float32) * fan_in ** -0.5
             ).astype(cfg.dtype) for k in layers])

    return {
        'router': init(ks[0], (d, E), d),
        'moe_gate': init(ks[1], (E, d, f), d),
        'moe_up': init(ks[2], (E, d, f), d),
        'moe_down': init(ks[3], (E, f, d), f),
    }


def moe_logical_axes(cfg: ModelConfig) -> Params:
    del cfg
    return {
        'router': ('layers', 'embed', None),
        'moe_gate': ('layers', 'expert', 'embed', 'mlp'),
        'moe_up': ('layers', 'expert', 'embed', 'mlp'),
        'moe_down': ('layers', 'expert', 'mlp', 'embed'),
    }


def moe_ffn(layer: Params, x: jax.Array, cfg: ModelConfig):
    """Top-k routed SwiGLU experts.

    x: [b, s, d] -> ([b, s, d], aux_loss scalar). The aux loss is the
    Switch-style load-balancing term; the trainer adds it to the CE loss
    with ``TrainConfig.moe_aux_weight``."""
    k = cfg.n_experts_per_token
    E = cfg.n_experts

    router_logits = jnp.einsum('bsd,de->bse', x, layer['router'],
                               preferred_element_type=jnp.float32)
    # Top-k routing weights, renormalized over the selected experts
    # (Mixtral convention).
    topk_vals, topk_idx = jax.lax.top_k(router_logits, k)      # [b,s,k]
    topk_w = jax.nn.softmax(topk_vals, axis=-1)                # [b,s,k]
    # Dense combine weights [b, s, E]: zero for unrouted experts.
    onehot = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)    # [b,s,k,E]
    combine = jnp.einsum('bsk,bske->bse', topk_w, onehot)

    # Dense expert compute, sharded over the expert axis.
    gate = jnp.einsum('bsd,edf->ebsf', x, layer['moe_gate'])
    up = jnp.einsum('bsd,edf->ebsf', x, layer['moe_up'])
    h = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    expert_out = jnp.einsum('ebsf,efd->ebsd', h, layer['moe_down'])
    out = jnp.einsum('ebsd,bse->bsd', expert_out,
                     combine.astype(expert_out.dtype))
    aux = load_balancing_loss(router_logits, topk_idx, E)
    return out, aux


def load_balancing_loss(router_logits: jax.Array, topk_idx: jax.Array,
                        n_experts: int) -> jax.Array:
    """Auxiliary load-balancing loss (Switch/Mixtral top-k formulation).

    ``frac_tokens`` counts every one of the k assignments per token (divided
    by k so it still sums to 1), so imbalance among non-first-choice
    assignments is penalized too.
    """
    probs = jax.nn.softmax(router_logits, axis=-1)             # [b,s,E]
    k = topk_idx.shape[-1]
    frac_tokens = jnp.mean(
        jax.nn.one_hot(topk_idx, n_experts).sum(axis=2), axis=(0, 1)) / k
    frac_probs = jnp.mean(probs, axis=(0, 1))
    return n_experts * jnp.sum(frac_tokens * frac_probs)
