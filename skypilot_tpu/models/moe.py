"""Mixture-of-Experts FFN (Mixtral-class) with expert parallelism.

The reference only *launches* MoE models via recipes (``llm/mixtral/``); the
expert parallelism itself lives in the launched framework. Here it is
in-tree: experts are sharded over the mesh's expert axis (the ``'expert'``
logical axis maps to ``('fsdp','sp')`` by default — see
``parallel.mesh.DEFAULT_RULES``) so each device holds ``E/ep`` experts.

Dispatch is GShard-style capacity-based top-k: each expert processes a
fixed [capacity, d] buffer (capacity = tokens*k/E*capacity_factor), so
per-step expert FLOPs scale with k/E instead of computing every expert on
every token. Shapes stay static (XLA/MXU-friendly); tokens routed past a
full expert buffer are dropped for that choice and ride the residual
connection (standard GShard semantics).
"""
from __future__ import annotations

import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from skypilot_tpu.models.configs import ModelConfig

Params = Dict[str, Any]


def init_moe_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    d, f, E, L = cfg.dim, cfg.ffn_dim, cfg.n_experts, cfg.n_layers
    ks = jax.random.split(rng, 4)

    def init(key, shape, fan_in):
        layers = jax.random.split(key, L)
        return jnp.stack([
            (jax.random.normal(k, shape, jnp.float32) * fan_in ** -0.5
             ).astype(cfg.dtype) for k in layers])

    return {
        'router': init(ks[0], (d, E), d),
        'moe_gate': init(ks[1], (E, d, f), d),
        'moe_up': init(ks[2], (E, d, f), d),
        'moe_down': init(ks[3], (E, f, d), f),
    }


def moe_logical_axes(cfg: ModelConfig) -> Params:
    del cfg
    return {
        'router': ('layers', 'embed', None),
        'moe_gate': ('layers', 'expert', 'embed', 'mlp'),
        'moe_up': ('layers', 'expert', 'embed', 'mlp'),
        'moe_down': ('layers', 'expert', 'mlp', 'embed'),
    }


def expert_capacity(num_tokens: int, cfg: ModelConfig) -> int:
    """Per-expert buffer size for a dispatch group: tokens*k/E scaled by
    the capacity factor, never below k (tiny groups must still fit one
    token's k choices)."""
    ideal = num_tokens * cfg.n_experts_per_token / cfg.n_experts
    return max(cfg.n_experts_per_token,
               int(math.ceil(ideal * cfg.moe_capacity_factor)))


# Tokens are dispatched within fixed-size groups (GShard G×S layout): the
# one-hot dispatch tensor is [groups, GROUP, k, E, C] with C ∝ GROUP, so
# its memory is linear in total tokens instead of quadratic.
_MOE_GROUP_SIZE = 512


def moe_ffn(layer: Params, x: jax.Array, cfg: ModelConfig):
    """Capacity-based top-k routed SwiGLU experts (GShard dispatch).

    x: [b, s, d] -> ([b, s, d], aux_loss scalar). The aux loss is the
    Switch-style load-balancing term; the trainer adds it to the CE loss
    with ``TrainConfig.moe_aux_weight``.

    Each expert computes a fixed [capacity, d] buffer; the dispatch and
    combine are one-hot einsums, so the HLO stays static-shaped while
    expert FLOPs scale with k/E (vs the all-experts dense fallback).
    Assignments that overflow an expert's buffer are dropped (their
    combine weight is zero — the token's residual passes through).
    """
    k = cfg.n_experts_per_token
    E = cfg.n_experts
    b, s, d = x.shape
    T = b * s
    xt = x.reshape(T, d)

    router_logits = jnp.einsum('td,de->te', xt, layer['router'],
                               preferred_element_type=jnp.float32)
    # Top-k routing weights, renormalized over the selected experts
    # (Mixtral convention).
    topk_vals, topk_idx = jax.lax.top_k(router_logits, k)      # [T, k]
    topk_w = jax.nn.softmax(topk_vals, axis=-1)                # [T, k]
    aux = load_balancing_loss(router_logits.reshape(b, s, E),
                              topk_idx.reshape(b, s, k), E)

    # Pad T up to a multiple of the group size; padded tokens carry zero
    # routing weight so they never claim a buffer slot's output.
    group = min(_MOE_GROUP_SIZE, T)
    pad = (-T) % group
    Tp = T + pad
    G = Tp // group
    C = expert_capacity(group, cfg)
    if pad:
        xt = jnp.pad(xt, ((0, pad), (0, 0)))
        topk_idx = jnp.pad(topk_idx, ((0, pad), (0, 0)))
        topk_w = jnp.pad(topk_w, ((0, pad), (0, 0)))   # zeros: no weight

    # Slot assignment per group: each (token, choice) pair's running
    # count within its expert is its buffer position; pairs at position
    # >= capacity (and padding) drop to the residual path.
    assign = jax.nn.one_hot(topk_idx, E, dtype=jnp.int32)   # [Tp, k, E]
    assign = assign.reshape(G, group * k, E)                # token-major
    position = jnp.cumsum(assign, axis=1) * assign - assign
    slot = position.sum(-1)                                 # [G, group*k]
    valid = topk_w.reshape(G, group * k) > 0
    kept = (slot < C) & valid

    # dispatch [G, group, k, E, C]: one-hot of (expert, slot) per pair.
    slot_oh = jax.nn.one_hot(slot, C, dtype=x.dtype) * \
        kept[..., None].astype(x.dtype)
    dispatch = (assign.astype(x.dtype)[..., None] *
                slot_oh[..., None, :]).reshape(G, group, k, E, C)
    # Pin the dispatch/combine tensors to expert-dim sharding: without
    # the constraint the partitioner propagates token-dim shardings into
    # them and pays an involuntary full rematerialization flipping to
    # the expert-sharded layout the expert matmuls need.
    dispatch = _shard_moe(dispatch, None, None, None, 'expert', None)
    dispatch_mask = dispatch.sum(2)                         # [G,group,E,C]
    combine = jnp.einsum('gtk,gtkec->gtec',
                         topk_w.reshape(G, group, k).astype(x.dtype),
                         dispatch)
    combine = _shard_moe(combine, None, None, 'expert', None)

    # Gather expert buffers, compute, scatter back — sharded over the
    # expert axis, batched over groups.
    xg = xt.reshape(G, group, d)
    expert_in = jnp.einsum('gtec,gtd->gecd', dispatch_mask, xg)
    expert_in = _shard_moe(expert_in, None, 'expert', None, 'embed')
    from skypilot_tpu.models.quantization import deq
    gate = jnp.einsum('gecd,edf->gecf', expert_in, deq(layer['moe_gate']))
    up = jnp.einsum('gecd,edf->gecf', expert_in, deq(layer['moe_up']))
    h = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    h = _shard_moe(h, None, 'expert', None, 'mlp')
    expert_out = jnp.einsum('gecf,efd->gecd', h, deq(layer['moe_down']))
    out = jnp.einsum('gtec,gecd->gtd', combine, expert_out)
    out = out.reshape(Tp, d)[:T]
    return out.reshape(b, s, d), aux


def _shard_moe(val: jax.Array, *logical_axes) -> jax.Array:
    from skypilot_tpu.models.llama import _shard
    return _shard(val, *logical_axes)


def load_balancing_loss(router_logits: jax.Array, topk_idx: jax.Array,
                        n_experts: int) -> jax.Array:
    """Auxiliary load-balancing loss (Switch/Mixtral top-k formulation).

    ``frac_tokens`` counts every one of the k assignments per token (divided
    by k so it still sums to 1), so imbalance among non-first-choice
    assignments is penalized too.
    """
    probs = jax.nn.softmax(router_logits, axis=-1)             # [b,s,E]
    k = topk_idx.shape[-1]
    frac_tokens = jnp.mean(
        jax.nn.one_hot(topk_idx, n_experts).sum(axis=2), axis=(0, 1)) / k
    frac_probs = jnp.mean(probs, axis=(0, 1))
    return n_experts * jnp.sum(frac_tokens * frac_probs)
