"""Weight-only int8 / int4 quantization for serving.

Decode on TPU is HBM-bound on the weight stream (see bench.py's
roofline); storing matmul weights as int8 + per-output-channel scales
halves that traffic, and int4 (two 4-bit codes packed per byte) halves
it AGAIN. Dequantization is expressed as convert+multiply immediately
before each einsum, which XLA fuses into the matmul's operand read —
the weight crosses HBM as int8 (or packed int4 nibbles). (The same
weight-only scheme JetStream/MaxText serve with; the reference
delegates serving to those engines, ``examples/tpu/v6e/README.md:119``.)

Quantized leaves are ``QuantizedWeight(int8, scale)`` /
``QuantizedWeight4(packed, scale)`` NamedTuples (jax pytrees);
``deq(w)`` is identity on plain arrays, so the model code calls it
unconditionally (int4 leaves dequantize only inside ``qeinsum`` — the
packed layout is contraction-specific).

int4 layout contract (the one place it is defined — graftcheck GC119
bans nibble bit-twiddling anywhere else in the compute dirs):

- codes are symmetric 4-bit, ``clip(round(w/scale), -7, 7)``, with
  ``scale = absmax/7`` per OUTPUT channel (or per ``SKYTPU_INT4_GROUP``
  -sized group along the last contracted axis);
- two codes pack into one uint8 byte along the LAST CONTRACTED axis
  (stride-1 in the flattened contraction order, so ``qeinsum`` unpacks
  a ``[k/2, n]`` byte matrix into ``[k, n]`` codes with one interleave
  reshape): byte ``j`` holds code ``2j`` in its low nibble and code
  ``2j+1`` in its high nibble;
- the MoE expert leaves stay int8 in int4 mode: ``models.moe``
  contracts them through generic ``deq()`` einsums whose packed axis
  ``deq`` cannot infer (and expert streams are gated, not hot).
"""
from __future__ import annotations

import os
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.utils.host import host_block

Params = Dict[str, Any]

# Per-layer matmul weights worth quantizing: everything except norms
# (tiny, fp32) and the embedding table (gather path, int8 gather is a
# different trick).
_QUANT_LEAVES = ('wq', 'wk', 'wv', 'wo', 'w_gate', 'w_up', 'w_down',
                 'moe_gate', 'moe_up', 'moe_down', 'unembed')


class QuantizedWeight(NamedTuple):
    int8: jax.Array           # same shape as the original weight
    scale: jax.Array          # original shape with contracted dims = 1

    @property
    def shape(self):
        return self.int8.shape

    @property
    def dtype(self):          # the COMPUTE dtype consumers see after deq
        return self.scale.dtype


class QuantizedWeight4(NamedTuple):
    """int4 weight leaf: ``packed`` is uint8 in the ORIGINAL weight's
    shape with the last contracted axis HALVED (two codes per byte, see
    the module docstring's layout contract); ``scale`` is the original
    shape with contracted dims = 1 — except the last contracted axis,
    which is ``n_groups`` under group-wise scales
    (``SKYTPU_INT4_GROUP``; 1 = per-output-channel)."""
    packed: jax.Array
    scale: jax.Array

    @property
    def dtype(self):          # the COMPUTE dtype consumers see after deq
        return self.scale.dtype


# Leaves quantized to int4 in int4 mode. MoE expert leaves are
# excluded (they dequantize through generic deq() einsums — see module
# docstring) and stay int8.
INT4_LEAVES = ('wq', 'wk', 'wv', 'wo', 'w_gate', 'w_up', 'w_down',
               'unembed')


def int4_group_size() -> int:
    """Group size (tokens of the last contracted axis) for int4 scales;
    0 (default) = one scale per output channel. Read at QUANTIZE time
    only — compiled programs bake in whatever the leaf carries."""
    return max(0, int(os.environ.get('SKYTPU_INT4_GROUP', '0') or 0))


def _xp(arr):
    """numpy for numpy inputs, jnp otherwise — the pack/unpack helpers
    serve both the host-side checkpoint loader and jitted programs."""
    return np if isinstance(arr, np.ndarray) else jnp


def pack_int4(codes, axis: int = -1):
    """Pack int8 codes in [-8, 7] two-per-byte along ``axis`` (must be
    even-sized): byte j = code 2j (low nibble) | code 2j+1 (high).
    Returns uint8 with ``axis`` halved; numpy in, numpy out."""
    xp = _xp(codes)
    if codes.shape[axis] % 2:
        raise ValueError(
            f'int4 pack axis must be even-sized, got shape '
            f'{codes.shape} axis {axis}')
    lo_sl = [slice(None)] * codes.ndim
    hi_sl = [slice(None)] * codes.ndim
    lo_sl[axis] = slice(0, None, 2)
    hi_sl[axis] = slice(1, None, 2)
    lo = codes[tuple(lo_sl)].astype(xp.uint8) & 0xF
    hi = codes[tuple(hi_sl)].astype(xp.uint8) & 0xF
    return lo | (hi << 4)


def unpack_int4(packed, axis: int = -1):
    """Inverse of :func:`pack_int4`: uint8 bytes -> sign-extended int8
    codes with ``axis`` doubled (low nibble first)."""
    xp = _xp(packed)
    lo = (packed & 0xF).astype(xp.int8)
    lo = xp.where(lo >= 8, lo - 16, lo)
    hi = (packed >> 4).astype(xp.int8)
    hi = xp.where(hi >= 8, hi - 16, hi)
    ax = axis if axis >= 0 else packed.ndim + axis
    st = xp.stack([lo, hi], axis=ax + 1)
    shape = packed.shape[:ax] + (packed.shape[ax] * 2,) \
        + packed.shape[ax + 1:]
    return st.reshape(shape)


import contextlib
import threading as _threading

_a8_region = _threading.local()


@contextlib.contextmanager
def w8a8_region():
    """TRACE-TIME flag: while active, ``qeinsum`` additionally
    quantizes the ACTIVATION operand per row (symmetric int8, scale =
    row absmax/127) and contracts int8 x int8 -> int32 — the MXU's
    native int8 path runs at 2x its bf16 rate (394 vs 197 TOPS on a
    v5e), which matters exactly where the matmuls are compute-bound:
    serving PREFILL. Decode stays W8A16 (bandwidth-bound; activation
    quantization would cost VPU work for nothing).

    Trace-time like ``llama._manual_region``: programs traced inside
    the region bake the int8 path in; the flag never affects already-
    compiled programs."""
    prev = getattr(_a8_region, 'active', False)
    _a8_region.active = True
    try:
        yield
    finally:
        _a8_region.active = prev


def deq(w) -> jax.Array:
    """Dequantize if quantized; identity otherwise. The convert+mul
    fuses into the consuming matmul's operand read."""
    if isinstance(w, QuantizedWeight):
        return w.int8.astype(w.scale.dtype) * w.scale
    if isinstance(w, QuantizedWeight4):
        # The packed axis is contraction-specific (last contracted
        # axis) — only qeinsum, which sees the einsum equation, can
        # unpack it. int4 mode deliberately leaves deq()-consumed
        # leaves (MoE experts) at int8.
        raise TypeError(
            'QuantizedWeight4 leaves dequantize only inside qeinsum '
            '(the packed axis is contraction-specific); deq() cannot '
            'recover the layout')
    return w


def qeinsum(eq: str, x: jax.Array, w, *, out_dtype=None) -> jax.Array:
    """``jnp.einsum(eq, x, w)`` that keeps int8 weights int8 across HBM.

    The pre-dequantize form (``einsum(x, deq(w))``) streams the weight at
    ~290 GB/s on a v5e — the scale-multiply keeps XLA from using its fast
    int8 operand path. Contracting the int8 CODES directly in the dot and
    applying the per-output-channel scale to the (tiny) output runs the
    same stream at ~430 GB/s measured, and is *more* accurate (the scale
    multiply happens once per output in fp32 instead of once per weight
    element in bf16). Supported ``eq`` shapes are the model's weight
    patterns: w's contracted axes lead and match x's trailing axes
    ('bsd,dhk->bshk', 'bshk,hkd->bsd', 'bsd,df->bsf', ...).

    Falls back to plain einsum for unquantized weights."""
    if not isinstance(w, (QuantizedWeight, QuantizedWeight4)):
        if out_dtype is not None:
            return jnp.einsum(eq, x, w, preferred_element_type=out_dtype)
        return jnp.einsum(eq, x, w)
    ins, _ = eq.split('->')
    xs, ws = ins.split(',')
    nc = sum(c in xs for c in ws)
    assert all(c in xs for c in ws[:nc]) and \
        xs[-nc:] == ws[:nc], f'unsupported qeinsum pattern {eq!r}'
    if isinstance(w, QuantizedWeight4):
        return _qeinsum4(x, w, nc, out_dtype)
    k = 1
    for d in w.shape[:nc]:
        k *= d
    n = 1
    for d in w.shape[nc:]:
        n *= d
    batch_shape = x.shape[:x.ndim - nc]
    x2 = x.reshape(batch_shape + (k,))
    w2 = w.int8.reshape(k, n)
    if getattr(_a8_region, 'active', False):
        # W8A8 (see w8a8_region): per-row symmetric int8 activations,
        # int8 x int8 -> int32 on the MXU's double-rate path; both
        # scales fold into the fp32 output.
        xf = x2.astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        xscale = jnp.maximum(amax, 1e-8) / 127.0
        x8 = jnp.clip(jnp.round(xf / xscale), -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            x8, w2, (((x8.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = y.astype(jnp.float32) * xscale
    else:
        y = jax.lax.dot_general(
            x2, w2, (((x2.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    y = y * w.scale.reshape(n).astype(jnp.float32)
    out_dtype = out_dtype if out_dtype is not None else x.dtype
    return y.astype(out_dtype).reshape(batch_shape + w.shape[nc:])


def _qeinsum4(x: jax.Array, w: QuantizedWeight4, nc: int,
              out_dtype) -> jax.Array:
    """The int4 fused-dequant contraction behind qeinsum: packed codes
    cross HBM as bytes; the nibble unpack + sign-extend fuses into the
    dot's operand read (no bf16 — and no unpacked-int8 — weight copy is
    ever materialized in HBM as a program output). Per-channel scales
    (G=1) fold into the fp32 output exactly like the int8 path; group-
    wise scales (G>1) contract per group and weight the group partials,
    so the scale still never touches a per-element multiply."""
    kp = 1
    for d in w.packed.shape[:nc]:
        kp *= d
    k = kp * 2                       # last contracted axis was halved
    n = 1
    for d in w.packed.shape[nc:]:
        n *= d
    batch_shape = x.shape[:x.ndim - nc]
    x2 = x.reshape(batch_shape + (k,))
    # [k/2, n] bytes -> [k, n] sign-extended codes; pairs along the
    # last contracted axis are stride-1 in the flattened k order, so
    # one interleave reshape restores element order exactly.
    codes = unpack_int4(w.packed.reshape(kp, n), axis=0)
    G = 1
    for d in w.scale.shape[:nc]:
        G *= d
    if G == 1:
        if getattr(_a8_region, 'active', False):
            # W4A8: per-row symmetric int8 activations against the
            # unpacked int4 codes on the MXU's int8 path (prefill).
            xf = x2.astype(jnp.float32)
            amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
            xscale = jnp.maximum(amax, 1e-8) / 127.0
            x8 = jnp.clip(jnp.round(xf / xscale), -127,
                          127).astype(jnp.int8)
            y = jax.lax.dot_general(
                x8, codes, (((x8.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = y.astype(jnp.float32) * xscale
        else:
            y = jax.lax.dot_general(
                x2, codes, (((x2.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        y = y * w.scale.reshape(n).astype(jnp.float32)
        out_dtype = out_dtype if out_dtype is not None else x.dtype
        return y.astype(out_dtype).reshape(batch_shape
                                           + w.packed.shape[nc:])
    # Group-wise scales: the scale varies ALONG the contraction, so it
    # cannot fold into the output alone. Contract each g-sized group
    # separately (group as a dot batch dim — codes stay int-typed in
    # the dot) and sum the scale-weighted partials in fp32. W4A8 is
    # per-channel-only; grouped mode takes the fp32 contraction.
    last = w.packed.shape[nc - 1] * 2
    g = last // G
    other = k // last
    kg = other * G
    xb = x2.reshape((-1, other, G, g)).reshape((-1, kg, g))
    wg = codes.reshape((other, G, g, n)).reshape((kg, g, n))
    y = jax.lax.dot_general(
        xb, wg, (((2,), (1,)), ((1,), (0,))),
        preferred_element_type=jnp.float32)          # [kg, B, n]
    sflat = jnp.broadcast_to(
        w.scale.reshape(1, G, n), (other, G, n)).reshape(kg, 1, n)
    y = jnp.sum(y * sflat.astype(jnp.float32), axis=0)   # [B, n]
    out_dtype = out_dtype if out_dtype is not None else x.dtype
    return y.astype(out_dtype).reshape(batch_shape
                                       + w.packed.shape[nc:])


def _quantize_array(w: jax.Array, reduce_axes) -> QuantizedWeight:
    """Symmetric per-channel int8: scale = absmax/127 over the
    CONTRACTING axes, so each output channel keeps its dynamic range."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    # Round the scale to the storage dtype FIRST so the codes are
    # computed against the exact scale dequantization will multiply by
    # (a bf16 scale differs from its fp32 parent by up to ~0.4%/channel).
    scale = (jnp.maximum(absmax, 1e-8) / 127.0).astype(w.dtype)
    q = jnp.clip(jnp.round(wf / scale.astype(jnp.float32)), -127,
                 127).astype(jnp.int8)
    return QuantizedWeight(int8=q, scale=scale)


def _quantize_array4(w: jax.Array, reduce_axes,
                     group: int = 0) -> QuantizedWeight4:
    """Symmetric 4-bit: scale = absmax/7 over the contracting axes
    (per output channel), or per ``group``-sized slice of the LAST
    contracting axis (group-wise). Codes pack two-per-byte along that
    same axis (see the module layout contract). Scale is rounded to
    the storage dtype FIRST, like the int8 path."""
    ax = reduce_axes[-1]
    m = w.shape[ax]
    wf = w.astype(jnp.float32)
    if group:
        if m % group or group % 2:
            raise ValueError(
                f'SKYTPU_INT4_GROUP={group} must be even and divide '
                f'the packed axis (size {m})')
        G = m // group
        split = w.shape[:ax] + (G, group) + w.shape[ax + 1:]
        wf_g = wf.reshape(split)
        red = tuple(a if a < ax else a + 1
                    for a in reduce_axes[:-1]) + (ax + 1,)
        absmax = jnp.max(jnp.abs(wf_g), axis=red, keepdims=True)
        scale = (jnp.maximum(absmax, 1e-8) / 7.0).astype(w.dtype)
        q = jnp.clip(jnp.round(wf_g / scale.astype(jnp.float32)),
                     -7, 7).astype(jnp.int8).reshape(w.shape)
        sshape = tuple(1 if a in reduce_axes else d
                       for a, d in enumerate(w.shape))
        sshape = sshape[:ax] + (G,) + sshape[ax + 1:]
        scale = scale.reshape(sshape)
    else:
        absmax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
        scale = (jnp.maximum(absmax, 1e-8) / 7.0).astype(w.dtype)
        q = jnp.clip(jnp.round(wf / scale.astype(jnp.float32)),
                     -7, 7).astype(jnp.int8)
    return QuantizedWeight4(packed=pack_int4(q, axis=ax), scale=scale)


# Contracting axes per leaf (leading axis 0 is the scanned layer stack
# for layer weights; it is never contracted). Shapes from
# ``llama.init_params`` / ``moe.init_moe_params``.
_REDUCE_AXES = {
    'wq': (1,),          # [L, d, h, hd]   contract d
    'wk': (1,),
    'wv': (1,),
    'wo': (1, 2),        # [L, h, hd, d]   contract h, hd
    'w_gate': (1,),      # [L, d, f]       contract d
    'w_up': (1,),
    'w_down': (1,),      # [L, f, d]       contract f
    'moe_gate': (2,),    # [L, E, d, f]    contract d
    'moe_up': (2,),
    'moe_down': (2,),    # [L, E, f, d]    contract f
    'unembed': (0,),     # [d, V]          contract d
}
# Public alias: the host-side loader (weights._host_quantize) quantizes
# against the same per-leaf contracting axes.
REDUCE_AXES = _REDUCE_AXES


_QUANT_LEAF_TYPES = (QuantizedWeight, QuantizedWeight4)


def is_quantized(params: Params) -> bool:
    """True if the pytree already carries quantized leaves (int8 OR
    int4 — e.g. loaded via ``weights.load_checkpoint(quantize=...)``)."""
    return quantized_mode(params) is not None


def quantized_mode(params: Params):
    """'int4' | 'int8' | None for a param tree: int4 wins when any
    packed leaf exists (int4 trees carry int8 MoE leaves alongside)."""
    leaves = jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, _QUANT_LEAF_TYPES))
    if any(isinstance(l, QuantizedWeight4) for l in leaves):
        return 'int4'
    if any(isinstance(l, QuantizedWeight) for l in leaves):
        return 'int8'
    return None


def _map_quant_leaves(tree: Params, leaf_fn) -> Params:
    """Single traversal shared by quantize_params and
    quantize_logical_axes — the two output trees MUST stay structurally
    in lockstep (tree_shardings tree-maps one over the other)."""
    out: Params = {}
    for key, val in tree.items():
        if key == 'layers':
            out[key] = {
                k: (leaf_fn(k, v) if k in _REDUCE_AXES else v)
                for k, v in val.items()
            }
        elif key in _REDUCE_AXES:
            out[key] = leaf_fn(key, val)
        else:
            out[key] = val
    return out


def quantize_params(params: Params, *, donate: bool = False,
                    mode: str = 'int8') -> Params:
    """Quantize the big matmul weights of a llama-family param pytree;
    embeddings/norms/router stay as-is. ``mode='int4'`` packs the dense
    leaves (:data:`INT4_LEAVES`) two codes per byte with per-channel
    (or ``SKYTPU_INT4_GROUP`` group-wise) scales; MoE expert leaves
    stay int8 (see module docstring).

    Leaves are quantized one at a time so the fp32 transient is
    per-leaf, not per-tree. With ``donate=True`` each source buffer is
    freed as soon as its quantized replacement exists — peak device
    memory stays ~(bf16 tree + one leaf) instead of (bf16 + quantized)
    trees, which is what lets a 7B bf16 checkpoint (~14 GB) quantize in
    place on a 16 GB v5e chip. Only donate buffers the caller will not
    reuse."""
    if mode not in ('int8', 'int4'):
        raise ValueError(f'unknown quantize mode {mode!r}')
    group = int4_group_size() if mode == 'int4' else 0

    def leaf(k, v):
        if mode == 'int4' and k in INT4_LEAVES:
            q = _quantize_array4(v, _REDUCE_AXES[k], group=group)
        else:
            q = _quantize_array(v, _REDUCE_AXES[k])
        if donate and isinstance(v, jax.Array):
            host_block(q)       # barrier only — q must exist before
            v.delete()          # its source buffer is freed
        return q

    return _map_quant_leaves(params, leaf)


def quantize_logical_axes(axes: Params, mode: str = 'int8') -> Params:
    """Map the bf16 param logical-axes tree (``llama.param_logical_axes``)
    to the quantized-param structure: each quantized leaf becomes a
    ``QuantizedWeight`` (or ``QuantizedWeight4`` under ``mode='int4'``,
    matching ``quantize_params``'s leaf choice) of axes tuples. Codes
    and scales reuse the parent's axes — the scale's contracted dims
    are size 1 (or the group count) and the packed axis is halved, and
    the divisibility-aware ``mesh.spec_for`` replicates non-dividing
    dims automatically, so scales land replicated over contracted mesh
    axes and sharded along the output-channel axes, exactly matching
    their parent."""

    def leaf(k, v):
        if mode == 'int4' and k in INT4_LEAVES:
            return QuantizedWeight4(packed=v, scale=v)
        return QuantizedWeight(int8=v, scale=v)

    return _map_quant_leaves(axes, leaf)


def quantized_bytes(params: Params) -> int:
    """Total parameter bytes as stored (int8 leaves count 1B/elem)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total


def per_device_bytes(params: Params) -> int:
    """Bytes ONE device holds of the tree: each sharded leaf counts its
    local shard shape (exact — divisibility fallbacks and replicated
    axes included via ``sharding.shard_shape``), unsharded leaves their
    full size. The HBM-budget divisor pool sizing must use: dividing
    global bytes by ``mesh.size`` is wrong whenever an axis REPLICATES
    (dp, or a dimension tp does not divide) — under dp=2 it halves the
    accounted weights that are in fact fully resident per chip."""
    import math
    total = 0
    for leaf in jax.tree.leaves(params):
        sharding = getattr(leaf, 'sharding', None)
        if sharding is not None and hasattr(sharding, 'shard_shape'):
            try:
                local = math.prod(sharding.shard_shape(leaf.shape))
            except Exception:  # pylint: disable=broad-except
                local = leaf.size       # exotic sharding: conservative
        else:
            local = leaf.size
        total += local * leaf.dtype.itemsize
    return total
