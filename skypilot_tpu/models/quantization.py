"""Weight-only int8 quantization for serving.

Decode on TPU is HBM-bound on the weight stream (see bench.py's
roofline); storing matmul weights as int8 + per-output-channel scales
halves that traffic. Dequantization is expressed as convert+multiply
immediately before each einsum, which XLA fuses into the matmul's
operand read — the weight crosses HBM as int8. (The same weight-only
scheme JetStream/MaxText serve with; the reference delegates serving to
those engines, ``examples/tpu/v6e/README.md:119``.)

Quantized leaves are ``QuantizedWeight(int8, scale)`` NamedTuples (a
jax pytree); ``deq(w)`` is identity on plain arrays, so the model code
calls it unconditionally.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

from skypilot_tpu.utils.host import host_block

Params = Dict[str, Any]

# Per-layer matmul weights worth quantizing: everything except norms
# (tiny, fp32) and the embedding table (gather path, int8 gather is a
# different trick).
_QUANT_LEAVES = ('wq', 'wk', 'wv', 'wo', 'w_gate', 'w_up', 'w_down',
                 'moe_gate', 'moe_up', 'moe_down', 'unembed')


class QuantizedWeight(NamedTuple):
    int8: jax.Array           # same shape as the original weight
    scale: jax.Array          # original shape with contracted dims = 1

    @property
    def shape(self):
        return self.int8.shape

    @property
    def dtype(self):          # the COMPUTE dtype consumers see after deq
        return self.scale.dtype


import contextlib
import threading as _threading

_a8_region = _threading.local()


@contextlib.contextmanager
def w8a8_region():
    """TRACE-TIME flag: while active, ``qeinsum`` additionally
    quantizes the ACTIVATION operand per row (symmetric int8, scale =
    row absmax/127) and contracts int8 x int8 -> int32 — the MXU's
    native int8 path runs at 2x its bf16 rate (394 vs 197 TOPS on a
    v5e), which matters exactly where the matmuls are compute-bound:
    serving PREFILL. Decode stays W8A16 (bandwidth-bound; activation
    quantization would cost VPU work for nothing).

    Trace-time like ``llama._manual_region``: programs traced inside
    the region bake the int8 path in; the flag never affects already-
    compiled programs."""
    prev = getattr(_a8_region, 'active', False)
    _a8_region.active = True
    try:
        yield
    finally:
        _a8_region.active = prev


def deq(w) -> jax.Array:
    """Dequantize if quantized; identity otherwise. The convert+mul
    fuses into the consuming matmul's operand read."""
    if isinstance(w, QuantizedWeight):
        return w.int8.astype(w.scale.dtype) * w.scale
    return w


def qeinsum(eq: str, x: jax.Array, w, *, out_dtype=None) -> jax.Array:
    """``jnp.einsum(eq, x, w)`` that keeps int8 weights int8 across HBM.

    The pre-dequantize form (``einsum(x, deq(w))``) streams the weight at
    ~290 GB/s on a v5e — the scale-multiply keeps XLA from using its fast
    int8 operand path. Contracting the int8 CODES directly in the dot and
    applying the per-output-channel scale to the (tiny) output runs the
    same stream at ~430 GB/s measured, and is *more* accurate (the scale
    multiply happens once per output in fp32 instead of once per weight
    element in bf16). Supported ``eq`` shapes are the model's weight
    patterns: w's contracted axes lead and match x's trailing axes
    ('bsd,dhk->bshk', 'bshk,hkd->bsd', 'bsd,df->bsf', ...).

    Falls back to plain einsum for unquantized weights."""
    if not isinstance(w, QuantizedWeight):
        if out_dtype is not None:
            return jnp.einsum(eq, x, w, preferred_element_type=out_dtype)
        return jnp.einsum(eq, x, w)
    ins, _ = eq.split('->')
    xs, ws = ins.split(',')
    nc = sum(c in xs for c in ws)
    assert all(c in xs for c in ws[:nc]) and \
        xs[-nc:] == ws[:nc], f'unsupported qeinsum pattern {eq!r}'
    k = 1
    for d in w.shape[:nc]:
        k *= d
    n = 1
    for d in w.shape[nc:]:
        n *= d
    batch_shape = x.shape[:x.ndim - nc]
    x2 = x.reshape(batch_shape + (k,))
    w2 = w.int8.reshape(k, n)
    if getattr(_a8_region, 'active', False):
        # W8A8 (see w8a8_region): per-row symmetric int8 activations,
        # int8 x int8 -> int32 on the MXU's double-rate path; both
        # scales fold into the fp32 output.
        xf = x2.astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
        xscale = jnp.maximum(amax, 1e-8) / 127.0
        x8 = jnp.clip(jnp.round(xf / xscale), -127, 127).astype(jnp.int8)
        y = jax.lax.dot_general(
            x8, w2, (((x8.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = y.astype(jnp.float32) * xscale
    else:
        y = jax.lax.dot_general(
            x2, w2, (((x2.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    y = y * w.scale.reshape(n).astype(jnp.float32)
    out_dtype = out_dtype if out_dtype is not None else x.dtype
    return y.astype(out_dtype).reshape(batch_shape + w.shape[nc:])


def _quantize_array(w: jax.Array, reduce_axes) -> QuantizedWeight:
    """Symmetric per-channel int8: scale = absmax/127 over the
    CONTRACTING axes, so each output channel keeps its dynamic range."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    # Round the scale to the storage dtype FIRST so the codes are
    # computed against the exact scale dequantization will multiply by
    # (a bf16 scale differs from its fp32 parent by up to ~0.4%/channel).
    scale = (jnp.maximum(absmax, 1e-8) / 127.0).astype(w.dtype)
    q = jnp.clip(jnp.round(wf / scale.astype(jnp.float32)), -127,
                 127).astype(jnp.int8)
    return QuantizedWeight(int8=q, scale=scale)


# Contracting axes per leaf (leading axis 0 is the scanned layer stack
# for layer weights; it is never contracted). Shapes from
# ``llama.init_params`` / ``moe.init_moe_params``.
_REDUCE_AXES = {
    'wq': (1,),          # [L, d, h, hd]   contract d
    'wk': (1,),
    'wv': (1,),
    'wo': (1, 2),        # [L, h, hd, d]   contract h, hd
    'w_gate': (1,),      # [L, d, f]       contract d
    'w_up': (1,),
    'w_down': (1,),      # [L, f, d]       contract f
    'moe_gate': (2,),    # [L, E, d, f]    contract d
    'moe_up': (2,),
    'moe_down': (2,),    # [L, E, f, d]    contract f
    'unembed': (0,),     # [d, V]          contract d
}
# Public alias: the host-side loader (weights._host_quantize) quantizes
# against the same per-leaf contracting axes.
REDUCE_AXES = _REDUCE_AXES


def is_quantized(params: Params) -> bool:
    """True if the pytree already carries QuantizedWeight leaves (e.g.
    loaded via ``weights.load_checkpoint(..., quantize='int8')``)."""
    leaves = jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, QuantizedWeight))
    return any(isinstance(l, QuantizedWeight) for l in leaves)


def _map_quant_leaves(tree: Params, leaf_fn) -> Params:
    """Single traversal shared by quantize_params and
    quantize_logical_axes — the two output trees MUST stay structurally
    in lockstep (tree_shardings tree-maps one over the other)."""
    out: Params = {}
    for key, val in tree.items():
        if key == 'layers':
            out[key] = {
                k: (leaf_fn(k, v) if k in _REDUCE_AXES else v)
                for k, v in val.items()
            }
        elif key in _REDUCE_AXES:
            out[key] = leaf_fn(key, val)
        else:
            out[key] = val
    return out


def quantize_params(params: Params, *, donate: bool = False) -> Params:
    """Quantize the big matmul weights of a llama-family param pytree;
    embeddings/norms/router stay as-is.

    Leaves are quantized one at a time so the fp32 transient is
    per-leaf, not per-tree. With ``donate=True`` each source buffer is
    freed as soon as its int8 replacement exists — peak device memory
    stays ~(bf16 tree + one leaf) instead of (bf16 + int8) trees, which
    is what lets a 7B bf16 checkpoint (~14 GB) quantize in place on a
    16 GB v5e chip. Only donate buffers the caller will not reuse."""

    def leaf(k, v):
        q = _quantize_array(v, _REDUCE_AXES[k])
        if donate and isinstance(v, jax.Array):
            host_block(q)       # barrier only — q must exist before
            v.delete()          # its source buffer is freed
        return q

    return _map_quant_leaves(params, leaf)


def quantize_logical_axes(axes: Params) -> Params:
    """Map the bf16 param logical-axes tree (``llama.param_logical_axes``)
    to the quantized-param structure: each quantized leaf becomes a
    ``QuantizedWeight`` of axes tuples. Both the int8 codes and the scale
    reuse the parent's axes — the scale's contracted dims are size 1, and
    the divisibility-aware ``mesh.spec_for`` replicates unit dims
    automatically, so scales land replicated over contracted mesh axes and
    sharded along the output-channel axes, exactly matching their parent."""
    return _map_quant_leaves(
        axes, lambda k, v: QuantizedWeight(int8=v, scale=v))


def quantized_bytes(params: Params) -> int:
    """Total parameter bytes as stored (int8 leaves count 1B/elem)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total


def per_device_bytes(params: Params) -> int:
    """Bytes ONE device holds of the tree: each sharded leaf counts its
    local shard shape (exact — divisibility fallbacks and replicated
    axes included via ``sharding.shard_shape``), unsharded leaves their
    full size. The HBM-budget divisor pool sizing must use: dividing
    global bytes by ``mesh.size`` is wrong whenever an axis REPLICATES
    (dp, or a dimension tp does not divide) — under dp=2 it halves the
    accounted weights that are in fact fully resident per chip."""
    import math
    total = 0
    for leaf in jax.tree.leaves(params):
        sharding = getattr(leaf, 'sharding', None)
        if sharding is not None and hasattr(sharding, 'shard_shape'):
            try:
                local = math.prod(sharding.shard_shape(leaf.shape))
            except Exception:  # pylint: disable=broad-except
                local = leaf.size       # exotic sharding: conservative
        else:
            local = leaf.size
        total += local * leaf.dtype.itemsize
    return total
