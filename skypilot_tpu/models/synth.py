"""Synthetic HF checkpoints at real-model scale.

The reference's headline serving numbers come from a *real* Llama-2-7B
checkpoint (``examples/tpu/v6e/README.md:119-125``). This environment has
zero egress, so real weights cannot be downloaded — but the perf
measurement only depends on the *config* (layer count, dims, dtype):
decode is HBM-bound on the weight/KV streams and the MXU doesn't care
what the bytes are. This module materializes an HF-format checkpoint
directory (``config.json`` + ``model.safetensors``) for any preset config
with fan-in-scaled random weights, so the full import path
(``weights.load_checkpoint`` → engine) and the benchmark run exactly as
they would on the real model.

To keep generation fast at 7B scale (~13 GB), one random block of
per-layer tensors is generated and reused for every layer — identical
layers are indistinguishable to the memory system and the MXU, which is
what the benchmark measures. ``unique_layers=True`` generates fresh
randomness per layer for numerical studies.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

from skypilot_tpu.models.configs import ModelConfig


def write_synthetic_hf_checkpoint(path: str, cfg: ModelConfig, *,
                                  seed: int = 0,
                                  unique_layers: bool = False,
                                  dtype=np.float16) -> str:
    """Write an HF checkpoint dir for ``cfg`` with synthetic weights.

    Idempotent: returns immediately if ``path`` already holds a complete
    checkpoint for the same config. Weights are fan-in-scaled normals
    (std = 1/sqrt(fan_in)) so forwards stay numerically sane through
    deep stacks.
    """
    from safetensors.numpy import save_file
    marker = os.path.join(path, '.synth_complete.json')
    request = {'name': cfg.name, 'seed': seed,
               'unique_layers': unique_layers}
    if os.path.exists(marker):
        with open(marker, encoding='utf-8') as f:
            if json.load(f) == request:
                return path
    if cfg.is_moe:
        raise NotImplementedError('synthetic MoE checkpoints not needed '
                                  'yet; dense families only')
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    d, f, hd = cfg.dim, cfg.ffn_dim, cfg.head_dim
    nh, nkv = cfg.n_heads, cfg.n_kv_heads

    def w(out_dim: int, in_dim: int) -> np.ndarray:
        a = rng.standard_normal((out_dim, in_dim), dtype=np.float32)
        return (a * (in_dim ** -0.5)).astype(dtype)

    def layer_block() -> Dict[str, np.ndarray]:
        blk = {
            'self_attn.q_proj.weight': w(nh * hd, d),
            'self_attn.k_proj.weight': w(nkv * hd, d),
            'self_attn.v_proj.weight': w(nkv * hd, d),
            'self_attn.o_proj.weight': w(d, nh * hd),
            'mlp.gate_proj.weight': w(f, d),
            'mlp.up_proj.weight': w(f, d),
            'mlp.down_proj.weight': w(d, f),
            'input_layernorm.weight': np.ones(d, np.float32),
            'post_attention_layernorm.weight': np.ones(d, np.float32),
        }
        if cfg.qkv_bias:
            blk.update({
                'self_attn.q_proj.bias': np.zeros(nh * hd, np.float32),
                'self_attn.k_proj.bias': np.zeros(nkv * hd, np.float32),
                'self_attn.v_proj.bias': np.zeros(nkv * hd, np.float32),
            })
        return blk

    tensors: Dict[str, np.ndarray] = {
        'model.embed_tokens.weight': w(cfg.vocab_size, d),
        'model.norm.weight': np.ones(d, np.float32),
    }
    if not cfg.tie_embeddings:
        tensors['lm_head.weight'] = w(cfg.vocab_size, d)
    shared: Optional[Dict[str, np.ndarray]] = None
    for i in range(cfg.n_layers):
        if unique_layers or shared is None:
            shared = layer_block()
        for suffix, arr in shared.items():
            tensors[f'model.layers.{i}.{suffix}'] = arr
    save_file(tensors, os.path.join(path, 'model.safetensors'))

    from skypilot_tpu.models.weights import hf_config_dict
    with open(os.path.join(path, 'config.json'), 'w',
              encoding='utf-8') as fp:
        json.dump(hf_config_dict(cfg, torch_dtype='float16'), fp,
                  indent=2)
    with open(marker, 'w', encoding='utf-8') as fp:
        json.dump(request, fp)
    return path
