"""Model configurations for the in-tree model layer.

The reference ships *recipes* that launch external frameworks
(``llm/llama-3/llama3.yaml``, ``llm/mixtral/``); we ship the engines in-tree
(SURVEY.md §2.3), so model configs are first-class here.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """A decoder-only transformer configuration (Llama-family)."""
    name: str
    vocab_size: int
    dim: int                    # model/embedding width
    n_layers: int
    n_heads: int
    n_kv_heads: int             # < n_heads => grouped-query attention
    ffn_dim: int                # SwiGLU hidden width
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    # MoE fields (None => dense FFN)
    n_experts: Optional[int] = None
    n_experts_per_token: int = 2
    # Expert buffer size = tokens * k / E * this factor (GShard capacity;
    # tokens routed past a full expert are dropped to the residual path).
    moe_capacity_factor: float = 1.25
    # Remat policy for training: 'none' | 'block' (checkpoint each layer)
    remat: str = 'block'
    # Gemma-family knobs: tied input/output embeddings, GeGLU instead of
    # SwiGLU, and RMSNorm computing x * (1 + w) instead of x * w.
    tie_embeddings: bool = False
    activation: str = 'silu'            # 'silu' | 'gelu'
    norm_plus_one: bool = False
    # Gemma scales embeddings by sqrt(dim) at the input.
    scale_embeddings: bool = False
    # Explicit per-head width (HF configs may set head_dim != dim//n_heads,
    # e.g. Gemma-7B uses 256 with dim=3072, n_heads=16).
    head_dim_override: Optional[int] = None
    # Qwen2-family: biases on the q/k/v projections (attention only).
    qkv_bias: bool = False
    # LoRA fine-tuning (reference recipe parity: torchtune LoRA at
    # ``llm/llama-3_1-finetuning/lora.yaml``). rank > 0 adds low-rank
    # adapter leaves under ``params['layers']['lora']``; the trainer
    # freezes the base and trains only the adapters. ``lora_targets``
    # names the projections to adapt ('wq','wk','wv','wo' always legal;
    # 'w_gate','w_up','w_down' for dense-FFN models).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ('wq', 'wk', 'wv', 'wo')

    @property
    def lora_enabled(self) -> bool:
        return self.lora_rank > 0

    @property
    def lora_scale(self) -> float:
        return self.lora_alpha / max(self.lora_rank, 1)

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.dim // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts is not None

    @property
    def num_params(self) -> int:
        """Approximate parameter count (embeddings + blocks + head)."""
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        q_dim = self.n_heads * self.head_dim
        kv_dim = self.n_kv_heads * self.head_dim
        attn = d * q_dim + 2 * d * kv_dim + q_dim * d   # wq, wk, wv, wo
        ffn = 3 * d * f
        if self.is_moe:
            ffn *= self.n_experts
            ffn += d * self.n_experts           # router
        per_layer = attn + ffn + 2 * d          # + 2 norms
        embeds = v * d if self.tie_embeddings else v * d * 2
        return embeds + self.n_layers * per_layer + d

    def flops_per_token(self, training: bool = False) -> float:
        """~2*N matmul FLOPs per token fwd (6*N with backward)."""
        n = self.num_params
        if self.is_moe:
            # only active experts count
            d, f = self.dim, self.ffn_dim
            dense_ffn = 3 * d * f * self.n_layers
            n = n - dense_ffn * self.n_experts + dense_ffn * self.n_experts_per_token
        return (6.0 if training else 2.0) * n


# --- Presets ---------------------------------------------------------------
def _cfg(**kw) -> ModelConfig:
    return ModelConfig(**kw)


LLAMA3_8B = _cfg(name='llama3-8b', vocab_size=128256, dim=4096, n_layers=32,
                 n_heads=32, n_kv_heads=8, ffn_dim=14336)

LLAMA3_70B = _cfg(name='llama3-70b', vocab_size=128256, dim=8192, n_layers=80,
                  n_heads=64, n_kv_heads=8, ffn_dim=28672)

LLAMA2_7B = _cfg(name='llama2-7b', vocab_size=32000, dim=4096, n_layers=32,
                 n_heads=32, n_kv_heads=32, ffn_dim=11008, rope_theta=10000.0,
                 max_seq_len=4096)

# ~1.1B-param config that fits one 16GB v5e chip in bf16 with room for a KV
# cache — the single-chip flagship for bench.py / __graft_entry__.entry().
LLAMA3_1B = _cfg(name='llama3-1b', vocab_size=128256, dim=2048, n_layers=16,
                 n_heads=32, n_kv_heads=8, ffn_dim=8192)

MIXTRAL_8X7B = _cfg(name='mixtral-8x7b', vocab_size=32000, dim=4096,
                    n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14336,
                    rope_theta=1000000.0, n_experts=8, n_experts_per_token=2)

# Tiny configs for CPU-mesh tests.
TINY = _cfg(name='tiny', vocab_size=256, dim=64, n_layers=2, n_heads=4,
            n_kv_heads=2, ffn_dim=128, max_seq_len=128, remat='none')

TINY_MOE = _cfg(name='tiny-moe', vocab_size=256, dim=64, n_layers=2, n_heads=4,
                n_kv_heads=2, ffn_dim=128, max_seq_len=128, n_experts=4,
                n_experts_per_token=2, remat='none')

GEMMA_2B = _cfg(name='gemma-2b', vocab_size=256128, dim=2048, n_layers=18,
                n_heads=8, n_kv_heads=1, ffn_dim=16384,
                rope_theta=10000.0, tie_embeddings=True, activation='gelu',
                norm_plus_one=True, scale_embeddings=True)

GEMMA_7B = _cfg(name='gemma-7b', vocab_size=256128, dim=3072, n_layers=28,
                n_heads=16, n_kv_heads=16, ffn_dim=24576,
                rope_theta=10000.0, tie_embeddings=True, activation='gelu',
                norm_plus_one=True, scale_embeddings=True)

TINY_GEMMA = _cfg(name='tiny-gemma', vocab_size=256, dim=64, n_layers=2,
                  n_heads=4, n_kv_heads=1, ffn_dim=128, max_seq_len=128,
                  remat='none', tie_embeddings=True, activation='gelu',
                  norm_plus_one=True, scale_embeddings=True)

QWEN2_7B = _cfg(name='qwen2-7b', vocab_size=152064, dim=3584, n_layers=28,
                n_heads=28, n_kv_heads=4, ffn_dim=18944,
                rope_theta=1000000.0, qkv_bias=True, max_seq_len=32768)

TINY_QWEN = _cfg(name='tiny-qwen', vocab_size=256, dim=64, n_layers=2,
                 n_heads=4, n_kv_heads=2, ffn_dim=128, max_seq_len=128,
                 remat='none', qkv_bias=True)

PRESETS = {c.name: c for c in [
    LLAMA3_8B, LLAMA3_70B, LLAMA2_7B, LLAMA3_1B, MIXTRAL_8X7B,
    GEMMA_2B, GEMMA_7B, QWEN2_7B, TINY, TINY_MOE, TINY_GEMMA,
    TINY_QWEN]}


def get_config(name: str) -> ModelConfig:
    if name not in PRESETS:
        raise ValueError(f'Unknown model {name!r}. Known: {sorted(PRESETS)}')
    return PRESETS[name]
