"""Batched multi-LoRA: a device-resident adapter bank for multi-tenant
serving on ONE engine.

Single-adapter LoRA (:mod:`skypilot_tpu.models.lora`) merges into the
base weights at load — one engine per fine-tune, an N× chip-cost
multiplier at fleet scale. Here the adapters stay UNMERGED in a stacked
bank and every decode/prefill step applies each slot's own adapter via
one batched gather-of-adapters matmul (the S-LoRA/Punica consolidation
result):

- Bank layout: ``params['layers']['mlora'][target]['a'|'b']`` with
  leaves ``a: [L, A, *in, r]`` / ``b: [L, A, r, *out]`` plus a
  per-(layer, adapter) ``scale: [L, A]`` — the layer axis leads so the
  bank rides the existing layer ``lax.scan`` exactly like the base
  weights and the single-adapter 'lora' subtree before it (each scan
  step sees ``layer['mlora']`` with the layer axis consumed). ``A`` is
  the slot axis: the engine's adapter capacity.
- Per-slot adapter indices (``mlora_idx: [b] int32``, -1 = no adapter)
  gather each row's factors along the slot axis, so the low-rank
  correction ``(x·Aᵀ)·Bᵀ`` is two thin BATCHED matmuls riding next to
  the base projection — the jit program depends only on the bank
  SHAPE, never on which adapters occupy it. Adapter load/evict
  re-uploads bank rows (:func:`set_bank_row`, donated, traced slot
  index); it never recompiles.
- Zero-adapter rows are BIT-exact base model: :func:`adjusted`
  where-selects the untouched base projection for rows with idx < 0
  rather than adding a zero delta (a + 0.0 is not bitwise identity
  under -0.0/NaN, and the bank rows a row gathers are arbitrary live
  adapters).

Rank discipline: the bank has ONE static rank; adapters with smaller
rank zero-pad (sound — zero factor columns contribute nothing), larger
ranks are rejected at registry load.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.models.configs import ModelConfig

Params = Dict[str, Any]

ATTN_TARGETS = lora_lib._ATTN_TARGETS
MLP_TARGETS = lora_lib._MLP_TARGETS


def default_targets(cfg: ModelConfig) -> Tuple[str, ...]:
    """Bank targets when the service spec doesn't pin them: all
    attention projections, plus the dense-FFN targets (MoE configs have
    no dense FFN to adapt — same rule as lora.resolve_targets)."""
    return ATTN_TARGETS + (() if cfg.is_moe else MLP_TARGETS)


def target_shapes(cfg: ModelConfig, target: str, rank: int):
    """(a_shape, b_shape) without layer/slot axes, at an explicit rank
    (the bank rank is an engine knob, not cfg.lora_rank)."""
    return lora_lib._target_shapes(
        dataclasses.replace(cfg, lora_rank=rank), target)


def init_bank(cfg: ModelConfig, slots: int, rank: int, *,
              targets: Optional[Sequence[str]] = None,
              dtype=jnp.bfloat16) -> Params:
    """The ``params['layers']['mlora']`` subtree: all-zero factors (an
    empty slot is a no-op even if gathered) and zero scales."""
    if slots <= 0 or rank <= 0:
        raise ValueError(f'bank needs slots>0, rank>0; got {slots}, {rank}')
    targets = tuple(targets) if targets is not None \
        else default_targets(cfg)
    for t in targets:
        if t not in ATTN_TARGETS + MLP_TARGETS:
            raise ValueError(f'unknown multi-LoRA target {t!r}')
        if t in MLP_TARGETS and cfg.is_moe:
            raise ValueError(
                f'multi-LoRA target {t!r} needs a dense FFN; '
                f'{cfg.name} is MoE')
    L = cfg.n_layers
    bank: Params = {}
    for t in targets:
        a_shape, b_shape = target_shapes(cfg, t, rank)
        bank[t] = {
            'a': jnp.zeros((L, slots) + a_shape, dtype),
            'b': jnp.zeros((L, slots) + b_shape, dtype),
        }
    # Per-layer copies of the per-adapter scale, so the leaf scans the
    # layer axis like every other xs leaf ([L, A], layer-invariant).
    bank['scale'] = jnp.zeros((L, slots), jnp.float32)
    return bank


def bank_slots(bank: Params) -> int:
    return int(bank['scale'].shape[1])


def bank_targets(bank: Params) -> Tuple[str, ...]:
    return tuple(t for t in bank if t != 'scale')


def _gather_delta(ml: Params, target: str, x: jax.Array,
                  idx: jax.Array) -> jax.Array:
    """The scaled low-rank delta, per-row gathered from the bank slice
    of ONE layer (slot axis leads; layer axis already consumed by the
    scan). idx is clipped — negative rows gather slot 0's factors but
    :func:`adjusted` where-selects their result away."""
    dt = x.dtype
    n_slots = ml['scale'].shape[0]
    g = jnp.clip(idx, 0, n_slots - 1)
    a = ml[target]['a'][g].astype(dt)          # [b, *in, r]
    b = ml[target]['b'][g].astype(dt)          # [b, r, *out]
    if target == 'wo':                         # x: [b, s, h, k]
        z = jnp.einsum('bshk,bhkr->bsr', x, a)
        d = jnp.einsum('bsr,brd->bsd', z, b)
    elif target in ('wq', 'wk', 'wv'):
        z = jnp.einsum('bsd,bdr->bsr', x, a)
        d = jnp.einsum('bsr,brhk->bshk', z, b)
    elif target == 'w_down':                   # x: [b, s, f]
        z = jnp.einsum('bsf,bfr->bsr', x, a)
        d = jnp.einsum('bsr,brd->bsd', z, b)
    else:                                      # w_gate / w_up
        z = jnp.einsum('bsd,bdr->bsr', x, a)
        d = jnp.einsum('bsr,brf->bsf', z, b)
    s = ml['scale'][g]                         # [b] f32
    return d * s.reshape((-1,) + (1,) * (d.ndim - 1)).astype(dt)


def adjusted(ml: Optional[Params], target: str, x: jax.Array,
             base: jax.Array, idx: Optional[jax.Array]) -> jax.Array:
    """``base`` with each row's gathered adapter delta applied; rows
    with idx < 0 return base BIT-exactly (where-select, not +0)."""
    if ml is None or idx is None or target not in ml:
        return base
    delta = _gather_delta(ml, target, x, idx)
    keep = (idx >= 0).reshape((-1,) + (1,) * (base.ndim - 1))
    return jnp.where(keep, base + delta.astype(base.dtype), base)


@functools.partial(jax.jit, donate_argnums=(0,))
def set_bank_row(bank: Params, row: Params, slot: jax.Array) -> Params:
    """Overwrite one bank slot with an adapter's factors. ``slot`` is
    TRACED (one compile covers every slot) and ``bank`` is DONATED (the
    update is in-place across churn: no recompile, no transient second
    bank). ``row`` leaves are the bank leaves minus the slot axis."""
    return jax.tree.map(
        lambda b, r: jax.lax.dynamic_update_index_in_dim(
            b, r.astype(b.dtype), slot, 1),
        bank, row)


def clear_bank_row(bank: Params, slot: jax.Array) -> Params:
    """Zero one slot (evict): reuses :func:`set_bank_row`'s compiled
    update with an all-zero row (f32, the same host dtype
    :func:`adapter_row_from_tree` emits, so load and evict share ONE
    compiled program)."""
    zero = jax.tree.map(
        lambda b: np.zeros(b.shape[:1] + b.shape[2:], np.float32), bank)
    return set_bank_row(bank, zero, slot)


def adapter_row_from_tree(cfg: ModelConfig, lora_tree: Params,
                          bank_rank: int, scale: float, *,
                          targets: Sequence[str]) -> Params:
    """Convert a trainer-format adapter (``lora.split_lora`` layout:
    ``{target: {'a': [L, *in, r], 'b': [L, r, *out]}}``) into a bank
    row (host numpy; :func:`set_bank_row` uploads it). Targets the bank
    carries but the adapter doesn't are zero (no-op); ranks below the
    bank rank zero-pad; ranks above are a hard error."""
    L = cfg.n_layers
    row: Params = {}
    for t in targets:
        a_shape, b_shape = target_shapes(cfg, t, bank_rank)
        if t in lora_tree:
            a = np.asarray(lora_tree[t]['a'], np.float32)
            b = np.asarray(lora_tree[t]['b'], np.float32)
            r = a.shape[-1]
            if r > bank_rank:
                raise ValueError(
                    f'adapter rank {r} exceeds bank rank {bank_rank} '
                    f'for target {t!r}')
            if a.shape[0] != L:
                raise ValueError(
                    f'adapter {t!r} has {a.shape[0]} layers; '
                    f'model has {L}')
            if r < bank_rank:
                a = np.concatenate(
                    [a, np.zeros(a.shape[:-1] + (bank_rank - r,),
                                 np.float32)], axis=-1)
                b = np.concatenate(
                    [b, np.zeros((b.shape[0], bank_rank - r)
                                 + b.shape[2:], np.float32)], axis=1)
            if a.shape != (L,) + a_shape or b.shape != (L,) + b_shape:
                raise ValueError(
                    f'adapter {t!r} shapes {a.shape}/{b.shape} do not '
                    f'match bank {(L,) + a_shape}/{(L,) + b_shape}')
            row[t] = {'a': a, 'b': b}
        else:
            row[t] = {'a': np.zeros((L,) + a_shape, np.float32),
                      'b': np.zeros((L,) + b_shape, np.float32)}
    row['scale'] = np.full((L,), scale, np.float32)
    return row


def save_adapter(path: str, cfg: ModelConfig, lora_tree: Params, *,
                 scale: Optional[float] = None) -> None:
    """One adapter -> one ``.npz`` (the registry's checkpoint unit).
    ``scale`` defaults to the config's alpha/rank fold scale — the same
    number ``lora.merge`` folds with, so a bank-served adapter and its
    offline-merged reference agree."""
    if scale is None:
        first = next(iter(lora_tree.values()))
        rank = int(np.shape(first['a'])[-1])
        scale = float(cfg.lora_alpha) / rank
    arrays = {'__scale__': np.float32(scale)}
    for t, ab in lora_tree.items():
        arrays[f'{t}.a'] = np.asarray(ab['a'], np.float32)
        arrays[f'{t}.b'] = np.asarray(ab['b'], np.float32)
    np.savez(path, **arrays)


def load_adapter(path: str) -> Tuple[Params, float]:
    """(trainer-format adapter tree, fold scale) from a ``.npz``."""
    data = np.load(path)
    tree: Params = {}
    # npz entries are host ndarrays, not device values.
    scale = float(data['__scale__']) if '__scale__' in data else 1.0  # graftcheck: disable=GC202
    for key in data.files:
        if key == '__scale__':
            continue
        target, _, leaf = key.partition('.')
        if leaf not in ('a', 'b'):
            raise ValueError(f'unrecognized adapter array {key!r}')
        tree.setdefault(target, {})[leaf] = data[key]
    for t, ab in tree.items():
        if set(ab) != {'a', 'b'}:
            raise ValueError(f'adapter target {t!r} missing a/b factors')
    return tree, scale
