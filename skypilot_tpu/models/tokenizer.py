"""Tokenizer for the in-tree serving/training engines.

The reference delegates tokenization to the engines it launches (vLLM /
JetStream read the HF tokenizer next to the checkpoint, e.g.
``llm/llama-3/llama3.yaml:109``); ours is in-tree. Two implementations:

- ``HFTokenizer``: wraps a ``tokenizer.json`` via the ``tokenizers``
  runtime (pure-local, no network) — covers Llama-3/Gemma/Mixtral
  checkpoints, which all ship one.
- ``ByteTokenizer``: ids are raw UTF-8 bytes (+BOS/EOS at 256/257).
  Deterministic, vocab 258 — the test/demo fallback when no
  ``tokenizer.json`` exists.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence


class BaseTokenizer:
    bos_id: Optional[int] = None
    eos_id: Optional[int] = None

    def encode(self, text: str, *, bos: bool = True) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int]) -> str:
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError


class ByteTokenizer(BaseTokenizer):
    """UTF-8 bytes as token ids; 256=BOS, 257=EOS when the model's vocab
    has room for them (``model_vocab_size >= 258``), omitted otherwise —
    emitting id 256 at a 256-vocab model would silently clamp the
    embedding gather."""

    def __init__(self, model_vocab_size: int = 258):
        if model_vocab_size >= 258:
            self.bos_id, self.eos_id = 256, 257
        else:
            self.bos_id = self.eos_id = None

    @property
    def vocab_size(self) -> int:
        return 258

    def encode(self, text: str, *, bos: bool = True) -> List[int]:
        ids = list(text.encode('utf-8'))
        return ([self.bos_id] + ids) if bos and self.bos_id is not None \
            else ids

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode(
            'utf-8', errors='replace')


class HFTokenizer(BaseTokenizer):
    """A HuggingFace ``tokenizer.json`` loaded with the ``tokenizers``
    runtime. BOS/EOS ids come from ``tokenizer_config.json`` /
    ``generation_config.json`` when present, else common special-token
    names are probed."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer
        self._tk = Tokenizer.from_file(os.path.join(path, 'tokenizer.json'))
        self.bos_id, self.eos_id = self._find_special_ids(path)

    def _find_special_ids(self, path: str):
        bos = eos = None
        for fname in ('tokenizer_config.json', 'generation_config.json'):
            fpath = os.path.join(path, fname)
            if not os.path.exists(fpath):
                continue
            with open(fpath, encoding='utf-8') as f:
                cfg = json.load(f)
            for key, cur in (('bos_token', bos), ('eos_token', eos)):
                tok = cfg.get(key)
                if isinstance(tok, dict):
                    tok = tok.get('content')
                if tok is not None and cur is None:
                    tid = self._tk.token_to_id(tok)
                    if key == 'bos_token':
                        bos = tid
                    else:
                        eos = tid
            if bos is None and 'bos_token_id' in cfg:
                bos = cfg['bos_token_id']
            if eos is None and 'eos_token_id' in cfg:
                eid = cfg['eos_token_id']
                eos = eid[0] if isinstance(eid, list) else eid
        if bos is None or eos is None:
            for cand in ('<|begin_of_text|>', '<s>', '<bos>'):
                if bos is None:
                    bos = self._tk.token_to_id(cand)
            for cand in ('<|end_of_text|>', '</s>', '<eos>',
                         '<|eot_id|>'):
                if eos is None:
                    eos = self._tk.token_to_id(cand)
        return bos, eos

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def encode(self, text: str, *, bos: bool = True) -> List[int]:
        ids = self._tk.encode(text, add_special_tokens=False).ids
        if bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tk.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(path: Optional[str],
                   model_vocab_size: int = 258) -> BaseTokenizer:
    """Tokenizer for a checkpoint dir: ``tokenizer.json`` if present,
    byte-level fallback otherwise. ``model_vocab_size`` lets the byte
    fallback drop BOS/EOS ids the model's embedding can't represent."""
    if path and os.path.exists(os.path.join(path, 'tokenizer.json')):
        return HFTokenizer(path)
    return ByteTokenizer(model_vocab_size)
