"""LoRA adapters for the in-tree models (TPU-native fine-tuning).

Reference parity: the reference fine-tunes via an external framework
(torchtune recipe ``llm/llama-3_1-finetuning/lora.yaml``); here LoRA is
in-tree and mesh-native:

- Adapter leaves live under ``params['layers']['lora'][target]['a'|'b']``
  with the layer dimension stacked on the leading axis — they ride the
  existing layer ``lax.scan``, the pipeline stage split, and the
  logical-axis sharding machinery with zero special cases.
- ``a`` contracts the projection's input axes down to ``rank`` (Gaussian
  init), ``b`` expands ``rank`` to the output axes (zero init), so the
  delta starts at exactly 0 and the adapted model's first forward equals
  the base model bit-for-bit.
- Sharding: ``b``'s output axes use the SAME logical names as the parent
  weight (heads/head_dim, mlp, embed), ``a``'s input axes likewise, and
  the rank axis replicates — under tp the low-rank matmuls compose with
  the parent's sharding without extra collectives.
- ``merge(cfg, params)`` folds ``W + (alpha/rank) * A @ B`` for serving;
  the engines call ``maybe_merge`` so a LoRA checkpoint can be served
  directly.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models.configs import ModelConfig

Params = Dict[str, Any]

_ATTN_TARGETS = ('wq', 'wk', 'wv', 'wo')
_MLP_TARGETS = ('w_gate', 'w_up', 'w_down')


def resolve_targets(cfg: ModelConfig) -> Tuple[str, ...]:
    """Validated adapter targets for this config."""
    targets = tuple(cfg.lora_targets)
    for t in targets:
        if t not in _ATTN_TARGETS + _MLP_TARGETS:
            raise ValueError(
                f'unknown LoRA target {t!r}; legal: '
                f'{_ATTN_TARGETS + _MLP_TARGETS}')
        if t in _MLP_TARGETS and cfg.is_moe:
            raise ValueError(
                f'LoRA target {t!r} needs a dense FFN; {cfg.name} is MoE '
                f'(adapt the attention projections instead)')
    return targets


def _target_shapes(cfg: ModelConfig, target: str):
    """(a_shape, b_shape) WITHOUT the leading layer axis. ``a`` ends in
    rank; ``b`` starts with rank."""
    d, hd, r = cfg.dim, cfg.head_dim, cfg.lora_rank
    n_h, n_kv, f = cfg.n_heads, cfg.n_kv_heads, cfg.ffn_dim
    return {
        'wq': ((d, r), (r, n_h, hd)),
        'wk': ((d, r), (r, n_kv, hd)),
        'wv': ((d, r), (r, n_kv, hd)),
        'wo': ((n_h, hd, r), (r, d)),
        'w_gate': ((d, r), (r, f)),
        'w_up': ((d, r), (r, f)),
        'w_down': ((f, r), (r, d)),
    }[target]


def _target_axes(target: str):
    """Logical axes for (a, b), leading 'layers' axis included. The rank
    axis is None (replicated); input/output axes mirror the parent's."""
    axes = {
        'wq': (('embed', None), (None, 'heads', 'head_dim')),
        'wk': (('embed', None), (None, 'kv_heads', 'head_dim')),
        'wv': (('embed', None), (None, 'kv_heads', 'head_dim')),
        'wo': (('heads', 'head_dim', None), (None, 'embed')),
        'w_gate': (('embed', None), (None, 'mlp')),
        'w_up': (('embed', None), (None, 'mlp')),
        'w_down': (('mlp', None), (None, 'embed')),
    }[target]
    return tuple(('layers',) + a for a in axes)


def init_lora_layers(rng: jax.Array, cfg: ModelConfig) -> Params:
    """The ``params['layers']['lora']`` subtree: per-target a/b stacks.

    ``a`` ~ N(0, 1/fan_in), ``b`` = 0 (standard LoRA init: the delta is
    exactly zero until training moves ``b``). Adapters train in fp32 —
    they are tiny next to the base, and the low-rank product is cast to
    the activation dtype at apply time."""
    targets = resolve_targets(cfg)
    L = cfg.n_layers
    out: Params = {}
    keys = jax.random.split(rng, len(targets))
    for key, t in zip(keys, targets):
        a_shape, b_shape = _target_shapes(cfg, t)
        fan_in = 1
        for s in a_shape[:-1]:
            fan_in *= s
        out[t] = {
            'a': (jax.random.normal(key, (L,) + a_shape, jnp.float32)
                  * fan_in ** -0.5),
            'b': jnp.zeros((L,) + b_shape, jnp.float32),
        }
    return out


def lora_logical_axes(cfg: ModelConfig) -> Params:
    return {t: {'a': _target_axes(t)[0], 'b': _target_axes(t)[1]}
            for t in resolve_targets(cfg)}


def _ab_matmul(x: jax.Array, a: jax.Array, b: jax.Array,
               target: str) -> jax.Array:
    """x -> (x @ a) @ b for one UNSTACKED layer's adapter (inside the
    layer scan the leading layer axis is already consumed)."""
    dt = x.dtype
    if target == 'wo':                       # x: [b,s,h,k]
        z = jnp.einsum('bshk,hkr->bsr', x, a.astype(dt))
        return jnp.einsum('bsr,rd->bsd', z, b.astype(dt))
    z = jnp.einsum('bsd,dr->bsr', x, a.astype(dt))
    if target in ('wq', 'wk', 'wv'):
        return jnp.einsum('bsr,rhk->bshk', z, b.astype(dt))
    return jnp.einsum('bsr,rf->bsf', z, b.astype(dt))


def apply(lora_layer: Params, target: str, x: jax.Array,
          cfg: ModelConfig) -> jax.Array:
    """The scaled low-rank delta for ``target``, or 0 if not adapted."""
    if lora_layer is None or target not in lora_layer:
        return jnp.zeros((), x.dtype)
    ab = lora_layer[target]
    return cfg.lora_scale * _ab_matmul(x, ab['a'], ab['b'], target)


def merge(cfg: ModelConfig, params: Params, *,
          donate: bool = False) -> Tuple[ModelConfig, Params]:
    """Fold the adapters into the base weights for serving:
    ``W <- W + (alpha/rank) * A @ B`` per target, per layer (stacked
    einsum). Returns (cfg with lora off, params without 'lora').

    Only a bf16/fp32 base can be merged — quantize AFTER merging."""
    from skypilot_tpu.models.quantization import is_quantized
    layers = params['layers']
    if 'lora' not in layers:
        return dataclasses.replace(cfg, lora_rank=0), params
    if is_quantized(params):
        raise ValueError('cannot merge LoRA into an int8 base; load the '
                         'bf16 checkpoint, merge, then quantize')
    # The fold scale comes from the CONFIG (alpha/rank): refuse to guess
    # when the config says no-LoRA but the tree carries adapters (e.g. a
    # trainer checkpoint served with the stock base config) — a silent
    # alpha/1 fold would corrupt every adapted weight.
    first_ab = next(iter(layers['lora'].values()))
    tree_rank = int(first_ab['a'].shape[-1])
    if not cfg.lora_enabled:
        raise ValueError(
            f'params carry LoRA adapters (rank {tree_rank}) but '
            f'cfg.lora_rank == 0; pass the training config, e.g. '
            f'dataclasses.replace(cfg, lora_rank={tree_rank}, '
            f'lora_alpha=<alpha used in training>)')
    if tree_rank != cfg.lora_rank:
        raise ValueError(
            f'adapter rank in params ({tree_rank}) != cfg.lora_rank '
            f'({cfg.lora_rank})')
    scale = cfg.lora_scale
    specs = {
        'wq': 'dr,rhk->dhk', 'wk': 'dr,rhk->dhk',
        'wv': 'dr,rhk->dhk', 'wo': 'hkr,rd->hkd',
        'w_gate': 'dr,rf->df', 'w_up': 'dr,rf->df',
        'w_down': 'fr,rd->fd',
    }

    def fold(w, a, b, spec):
        # Per-layer map in the BASE dtype: the fp32 stacked delta of a
        # 7B MLP target would be ~6 GB — a transient the serving load
        # path must never materialize (merge runs before mesh
        # sharding). With ``donate`` the base stack's buffer is reused,
        # keeping the peak at |W| + one layer's delta; without it the
        # caller keeps its tree (tests, REPL) at a |W| copy's cost.
        def per_layer(args):
            w_l, a_l, b_l = args
            d = jnp.einsum(spec, a_l.astype(w_l.dtype),
                           b_l.astype(w_l.dtype))
            return w_l + (scale * d).astype(w_l.dtype)
        return jax.lax.map(per_layer, (w, a, b))

    new_layers = dict(layers)
    lora_tree = new_layers.pop('lora')
    fold_jit = jax.jit(fold, static_argnums=3,
                       donate_argnums=(0,) if donate else ())
    for t, ab in lora_tree.items():
        new_layers[t] = fold_jit(new_layers[t], ab['a'], ab['b'],
                                 specs[t])
    merged = dict(params, layers=new_layers)
    return dataclasses.replace(cfg, lora_rank=0), merged


def maybe_merge(cfg: ModelConfig, params, *,
                donate: bool = False) -> Tuple[ModelConfig, Any]:
    """Engine entry: serve a LoRA checkpoint by folding its adapters.
    No-op when params is None or carries no adapters."""
    if params is None or 'lora' not in params.get('layers', {}):
        if cfg.lora_enabled:
            cfg = dataclasses.replace(cfg, lora_rank=0)
        return cfg, params
    return merge(cfg, params, donate=donate)


def split_lora(params: Params) -> Params:
    """The trainable adapter subtree (shared structure with params)."""
    return params['layers']['lora']


def with_lora(params: Params, lora_tree: Params) -> Params:
    """params with its adapter subtree replaced (pure; no mutation)."""
    return dict(params, layers=dict(params['layers'], lora=lora_tree))
