"""HF-format checkpoint import/export for the in-tree engines.

The reference never loads weights itself — its recipes point external
engines at HF checkpoints (vLLM `--model` in ``llm/llama-3/llama3.yaml:109``,
JetStream converting Llama-2-7B in ``examples/tpu/v6e/README.md:119``).
Since our engines are in-tree (SURVEY.md §2.3), the weight import is too:
this module maps a HuggingFace checkpoint directory
(``config.json`` + ``*.safetensors`` [+ index]) onto the stacked-layer
param pytree used by ``models/llama.py``.

Layout notes:
- HF stores per-layer weights under ``model.layers.{i}.*`` as
  ``[out, in]`` Linear matrices; we stack all layers on a leading
  ``layers`` axis (for ``lax.scan``) and keep matrices input-major
  (``[in, out]``), so every projection is transposed on import.
- Our RoPE uses the split-half ("rotate_half") convention, identical to
  HF Llama/Gemma/Mixtral — no head permutation is needed.
- Norm weights stay float32; matmul weights cast to ``cfg.dtype``.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models.configs import ModelConfig
from skypilot_tpu.utils.host import host_sync

Params = Dict[str, Any]

_ARCH_FAMILY = {
    'LlamaForCausalLM': 'llama',
    'MistralForCausalLM': 'llama',
    'GemmaForCausalLM': 'gemma',
    'MixtralForCausalLM': 'mixtral',
    'Qwen2ForCausalLM': 'qwen2',
}


def config_from_hf(hf: Dict[str, Any],
                   name: Optional[str] = None,
                   dtype: Any = jnp.bfloat16) -> ModelConfig:
    """Build a ModelConfig from an HF ``config.json`` dict."""
    archs = hf.get('architectures') or []
    family = next((_ARCH_FAMILY[a] for a in archs if a in _ARCH_FAMILY),
                  None)
    if family is None:
        raise ValueError(
            f'Unsupported architectures {archs!r}; supported: '
            f'{sorted(_ARCH_FAMILY)}')
    dim = hf['hidden_size']
    n_heads = hf['num_attention_heads']
    head_dim = hf.get('head_dim')
    kw: Dict[str, Any] = dict(
        name=name or hf.get('model_type', family),
        vocab_size=hf['vocab_size'],
        dim=dim,
        n_layers=hf['num_hidden_layers'],
        n_heads=n_heads,
        n_kv_heads=hf.get('num_key_value_heads', n_heads),
        ffn_dim=hf['intermediate_size'],
        max_seq_len=hf.get('max_position_embeddings', 8192),
        rope_theta=float(hf.get('rope_theta', 10000.0)),
        norm_eps=float(hf.get('rms_norm_eps', 1e-5)),
        dtype=dtype,
        tie_embeddings=bool(hf.get('tie_word_embeddings', False)),
    )
    if head_dim is not None and head_dim != dim // n_heads:
        kw['head_dim_override'] = head_dim
    if family == 'gemma':
        kw.update(tie_embeddings=True, activation='gelu',
                  norm_plus_one=True, scale_embeddings=True)
    if family == 'qwen2':
        kw.update(qkv_bias=True)
    if family == 'mixtral':
        kw.update(n_experts=hf['num_local_experts'],
                  n_experts_per_token=hf.get('num_experts_per_tok', 2))
    return ModelConfig(**kw)


def _read_hf_config(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, 'config.json'), encoding='utf-8') as f:
        return json.load(f)


def _safetensor_files(path: str) -> list:
    index = os.path.join(path, 'model.safetensors.index.json')
    if os.path.exists(index):
        with open(index, encoding='utf-8') as f:
            weight_map = json.load(f)['weight_map']
        return sorted({os.path.join(path, v) for v in weight_map.values()})
    single = os.path.join(path, 'model.safetensors')
    if os.path.exists(single):
        return [single]
    files = sorted(f for f in os.listdir(path) if f.endswith('.safetensors'))
    if not files:
        raise FileNotFoundError(f'No .safetensors files under {path}')
    return [os.path.join(path, f) for f in files]


def load_workers() -> int:
    """Checkpoint-load parallelism (threads reading safetensors
    shards). ``SKYTPU_LOAD_WORKERS`` overrides; default
    min(8, cpu count). 1 disables threading entirely. The bench
    records this so load-time trajectories stay attributable."""
    env = os.environ.get('SKYTPU_LOAD_WORKERS')
    if env:
        return max(1, int(env))
    return min(8, os.cpu_count() or 1)


def _iter_tensors(path: str) -> Iterator[Tuple[str, np.ndarray]]:
    from safetensors import safe_open
    for fname in _safetensor_files(path):
        with safe_open(fname, framework='np') as f:
            for key in f.keys():
                yield key, f.get_tensor(key)


def _for_each_tensor(path: str, process) -> None:
    """Apply ``process(key, tensor)`` to every tensor in the checkpoint,
    reading shards with a thread pool of :func:`load_workers` threads.
    Each worker holds its own ``safe_open`` handles and AT MOST ONE
    decoded tensor at a time, so peak extra host memory is bounded by
    ``workers x largest tensor`` — not the checkpoint size. safetensors
    reads release the GIL for the file I/O + memcpy, so ``ckpt_load_s``
    scales with workers until the disk saturates (BENCH_r05 measured
    10.6 s serial for the 7B). ``process`` must be thread-safe for
    DISTINCT keys (each key is processed exactly once)."""
    from concurrent.futures import ThreadPoolExecutor

    from safetensors import safe_open
    workers = load_workers()
    files = _safetensor_files(path)
    per_file: list = []
    for fname in files:
        with safe_open(fname, framework='np') as f:
            per_file.append((fname, list(f.keys())))
    pairs = [(fname, key) for fname, keys in per_file for key in keys]
    if workers <= 1 or len(pairs) <= 1:
        for key, w in _iter_tensors(path):
            process(key, w)
        return

    def run_shard(shard) -> None:
        import contextlib
        with contextlib.ExitStack() as stack:
            handles = {}
            for fname, key in shard:
                f = handles.get(fname)
                if f is None:
                    f = stack.enter_context(
                        safe_open(fname, framework='np'))
                    handles[fname] = f
                process(key, f.get_tensor(key))

    shards = [pairs[i::workers] for i in range(workers)]
    with ThreadPoolExecutor(max_workers=workers) as ex:
        # list() re-raises the first worker exception.
        list(ex.map(run_shard, [s for s in shards if s]))


def _hf_key_map(cfg: ModelConfig) -> Dict[str, Tuple[str, ...]]:
    """HF tensor suffix (within ``model.layers.{i}.``) -> our leaf path.
    The transform per suffix is applied in ``load_hf_params``."""
    m = {
        'input_layernorm.weight': ('layers', 'attn_norm'),
        'post_attention_layernorm.weight': ('layers', 'ffn_norm'),
        'self_attn.q_proj.weight': ('layers', 'wq'),
        'self_attn.k_proj.weight': ('layers', 'wk'),
        'self_attn.v_proj.weight': ('layers', 'wv'),
        'self_attn.o_proj.weight': ('layers', 'wo'),
    }
    if cfg.qkv_bias:
        m.update({
            'self_attn.q_proj.bias': ('layers', 'bq'),
            'self_attn.k_proj.bias': ('layers', 'bk'),
            'self_attn.v_proj.bias': ('layers', 'bv'),
        })
    if cfg.is_moe:
        m['block_sparse_moe.gate.weight'] = ('layers', 'router')
        for e in range(cfg.n_experts):
            m[f'block_sparse_moe.experts.{e}.w1.weight'] = (
                'layers', 'moe_gate', e)
            m[f'block_sparse_moe.experts.{e}.w3.weight'] = (
                'layers', 'moe_up', e)
            m[f'block_sparse_moe.experts.{e}.w2.weight'] = (
                'layers', 'moe_down', e)
    else:
        m['mlp.gate_proj.weight'] = ('layers', 'w_gate')
        m['mlp.up_proj.weight'] = ('layers', 'w_up')
        m['mlp.down_proj.weight'] = ('layers', 'w_down')
    return m


def _transform(leaf: Tuple[str, ...], w: np.ndarray,
               cfg: ModelConfig) -> np.ndarray:
    """HF [out, in] Linear -> our input-major layout (+ head reshapes)."""
    name = leaf[1]
    hd = cfg.head_dim
    if name in ('attn_norm', 'ffn_norm'):
        return w.astype(np.float32)
    if name == 'bq':
        return w.reshape(cfg.n_heads, hd).astype(np.float32)
    if name in ('bk', 'bv'):
        return w.reshape(cfg.n_kv_heads, hd).astype(np.float32)
    if name == 'wq':
        return w.T.reshape(cfg.dim, cfg.n_heads, hd)
    if name in ('wk', 'wv'):
        return w.T.reshape(cfg.dim, cfg.n_kv_heads, hd)
    if name == 'wo':
        return w.T.reshape(cfg.n_heads, hd, cfg.dim)
    if name == 'router':
        return w.T                      # [E, d] -> [d, E]
    # All FFN projections (dense + expert): [out, in] -> [in, out].
    return w.T


def load_hf_params(path: str, cfg: ModelConfig,
                   quantize: Optional[str] = None) -> Params:
    """Load an HF checkpoint directory into the stacked-layer pytree.

    Layer tensors are accumulated into preallocated numpy buffers
    ([n_layers, ...]) so peak host memory stays ~1× checkpoint size, then
    cast to ``cfg.dtype`` (norms stay fp32) as jax arrays.

    ``quantize='int8'`` quantizes the matmul weights ON THE HOST before
    any device transfer: only int8 codes + scales ever reach the chip, so
    a 7B checkpoint costs ~7 GB of HBM and tunnel traffic instead of
    ~14 GB bf16 followed by an on-device quantization pass. (An fp32
    upcast of the stacked 7B MLP leaf alone is ~5.8 GB — quantizing
    on-device after a bf16 load cannot fit a 16 GB v5e.)
    """
    if quantize is not None and quantize not in ('int8', 'int4'):
        # Validate BEFORE streaming gigabytes of tensors.
        raise ValueError(f'unknown quantize mode {quantize!r}')
    key_map = _hf_key_map(cfg)
    L = cfg.n_layers
    stacked: Dict[str, np.ndarray] = {}     # our layer-leaf name -> buffer
    expert_bufs: Dict[str, np.ndarray] = {}
    top: Dict[str, np.ndarray] = {}
    seen = set()
    # Tensors stream in from a thread pool (_for_each_tensor; bounded
    # memory — each worker decodes one tensor at a time). Buffer ROW
    # writes are disjoint per key; only the shared-dict mutations
    # (buffer allocation, the seen set) need the lock.
    import threading
    alloc_lock = threading.Lock()

    def process(key: str, w: np.ndarray) -> None:
        if key == 'model.embed_tokens.weight':
            with alloc_lock:
                top['embed'] = w
                seen.add(key)
            return
        if key == 'model.norm.weight':
            w = w.astype(np.float32)
            with alloc_lock:
                top['final_norm'] = w
                seen.add(key)
            return
        if key == 'lm_head.weight':
            if not cfg.tie_embeddings:
                with alloc_lock:
                    top['unembed'] = w.T
                    seen.add(key)
            return
        if not key.startswith('model.layers.'):
            return
        rest = key[len('model.layers.'):]
        idx_str, suffix = rest.split('.', 1)
        i = int(idx_str)
        leaf = key_map.get(suffix)
        if leaf is None:
            return
        w = _transform(leaf, w, cfg)
        name = leaf[1]
        if len(leaf) == 3:                   # per-expert tensor
            e = leaf[2]
            with alloc_lock:
                buf = expert_bufs.setdefault(
                    name,
                    np.zeros((L, cfg.n_experts) + w.shape, w.dtype))
                seen.add(key)
            buf[i, e] = w
        else:
            with alloc_lock:
                buf = stacked.setdefault(
                    name, np.zeros((L,) + w.shape, w.dtype))
                seen.add(key)
            buf[i] = w

    _for_each_tensor(path, process)

    # Completeness: every expected tensor must have been seen, per layer —
    # a missing layer tensor would otherwise silently load as zeros.
    expected = {'model.embed_tokens.weight', 'model.norm.weight'}
    if not cfg.tie_embeddings:
        expected.add('lm_head.weight')
    for i in range(L):
        for suffix in key_map:
            expected.add(f'model.layers.{i}.{suffix}')
    missing = sorted(expected - seen)
    if missing:
        raise ValueError(
            f'Checkpoint at {path} is missing {len(missing)} tensors, '
            f'first: {missing[:6]}')

    from skypilot_tpu.models import quantization

    def cast(name: str, a: np.ndarray) -> Any:
        if name in ('attn_norm', 'ffn_norm', 'final_norm',
                    'bq', 'bk', 'bv'):
            return jnp.asarray(a, jnp.float32)
        if quantize is not None and name in quantization.REDUCE_AXES:
            # int4 packs the dense leaves; MoE expert leaves stay int8
            # even in int4 mode (quantization module docstring).
            int4 = (quantize == 'int4'
                    and name in quantization.INT4_LEAVES)
            return _host_quantize(a, quantization.REDUCE_AXES[name],
                                  cfg.dtype, int4=int4)
        # Cast on host (numpy handles ml_dtypes) so only ONE device
        # buffer per leaf is ever live, not fp16+bf16 copies.
        return jnp.asarray(np.asarray(a, cfg.dtype))

    params: Params = {
        'embed': cast('embed', top['embed']),
        'final_norm': cast('final_norm', top['final_norm']),
        'layers': {k: cast(k, v) for k, v in stacked.items()},
    }
    params['layers'].update(
        {k: cast(k, v) for k, v in expert_bufs.items()})
    if not cfg.tie_embeddings:
        params['unembed'] = cast('unembed', top['unembed'])
    return params


def _host_quantize(a: np.ndarray, reduce_axes, scale_dtype,
                   int4: bool = False):
    """Numpy twin of ``quantization._quantize_array`` (same rounded-scale
    contract; ``int4=True`` mirrors ``_quantize_array4`` — packed codes
    + per-channel/group scales): quantizes on the host so only codes +
    scales hit the device. Stacked layer leaves quantize one
    layer-slice at a time — the fp32 transient stays ~1/L of the leaf
    (a 7B MLP leaf upcast whole is ~5.8 GB), with reduce axes always
    excluding axis 0."""
    from skypilot_tpu.models.quantization import (QuantizedWeight,
                                                  QuantizedWeight4)
    cls = QuantizedWeight4 if int4 else QuantizedWeight

    if a.ndim >= 3 and 0 not in reduce_axes:
        sub_axes = tuple(ax - 1 for ax in reduce_axes)
        codes = []
        scales = []
        for i in range(a.shape[0]):
            qi, si = _host_quantize_slice(a[i], sub_axes, scale_dtype,
                                          int4=int4)
            codes.append(qi)
            scales.append(si)
        return cls(jnp.asarray(np.stack(codes)),
                   jnp.asarray(np.stack(scales)))
    q, scale = _host_quantize_slice(a, reduce_axes, scale_dtype,
                                    int4=int4)
    return cls(jnp.asarray(q), jnp.asarray(scale))


def _host_quantize_slice(a: np.ndarray, reduce_axes, scale_dtype,
                         int4: bool = False):
    """Round-scale-first quantize of one array (fp32 transient = this
    slice only). int8: codes in [-127, 127]. int4: codes in [-7, 7]
    packed two-per-byte along the last reduce axis (group-wise scales
    under SKYTPU_INT4_GROUP), the exact on-device layout."""
    from skypilot_tpu.models import quantization
    af = np.asarray(a, np.float32)
    if int4:
        ax = reduce_axes[-1] % af.ndim
        group = quantization.int4_group_size()
        if group:
            m = af.shape[ax]
            if m % group or group % 2:
                raise ValueError(
                    f'SKYTPU_INT4_GROUP={group} must be even and '
                    f'divide the packed axis (size {m})')
            split = af.shape[:ax] + (m // group, group) + af.shape[ax + 1:]
            ag = af.reshape(split)
            red = tuple(x if x % af.ndim < ax else x % af.ndim + 1
                        for x in reduce_axes[:-1]) + (ax + 1,)
            absmax = np.max(np.abs(ag), axis=red, keepdims=True)
            scale = (np.maximum(absmax, 1e-8) / 7.0).astype(scale_dtype)
            q = np.clip(np.rint(ag / scale.astype(np.float32)), -7,
                        7).astype(np.int8).reshape(af.shape)
            sshape = tuple(1 if x in [r % af.ndim for r in reduce_axes]
                           else d for x, d in enumerate(af.shape))
            sshape = sshape[:ax] + (m // group,) + sshape[ax + 1:]
            scale = scale.reshape(sshape)
        else:
            absmax = np.max(np.abs(af), axis=reduce_axes, keepdims=True)
            scale = (np.maximum(absmax, 1e-8) / 7.0).astype(scale_dtype)
            q = np.clip(np.rint(af / scale.astype(np.float32)), -7,
                        7).astype(np.int8)
        return quantization.pack_int4(q, axis=ax), scale
    absmax = np.max(np.abs(af), axis=reduce_axes, keepdims=True)
    scale = (np.maximum(absmax, 1e-8) / 127.0).astype(scale_dtype)
    q = np.clip(np.rint(af / scale.astype(np.float32)), -127,
                127).astype(np.int8)
    return q, scale


def load_checkpoint(path: str,
                    dtype: Any = jnp.bfloat16,
                    name: Optional[str] = None,
                    quantize: Optional[str] = None,
                    use_cache: bool = True
                    ) -> Tuple[ModelConfig, Params]:
    """One-call import: HF dir -> (ModelConfig, params).

    With ``quantize='int8'`` (or ``'int4'``) the quantized tree is
    cached next to the checkpoint (``.int8_cache.bin`` /
    ``.int4_cache.bin`` + ``.meta.json`` manifest): the first load pays
    the full fp16-read + host-quantize pass; reruns mmap the smaller
    quantized tree (packed int4 codes ride as raw uint8) and device_put
    leaves in parallel. Best-effort — a read-only checkpoint dir just
    skips the cache."""
    cfg = config_from_hf(_read_hf_config(path), name=name, dtype=dtype)
    quantized = quantize in ('int8', 'int4')
    cache_file = os.path.join(path, f'.{quantize}_cache.bin')
    fingerprint = _cache_fingerprint(path, dtype)
    if quantized and use_cache and os.path.exists(cache_file):
        try:
            if _read_cache_meta(cache_file) == fingerprint:
                return cfg, _load_int8_cache(cache_file, cfg)
            print(f'[weights] {quantize} cache stale (checkpoint or '
                  'dtype changed); requantizing', flush=True)
        except Exception as e:  # pylint: disable=broad-except
            print(f'[weights] {quantize} cache unreadable ({e}); '
                  'reloading', flush=True)
    params = load_hf_params(path, cfg, quantize=quantize)
    if quantized and use_cache:
        try:
            _save_int8_cache(cache_file, params, fingerprint)
        except OSError as e:
            print(f'[weights] {quantize} cache not written: {e}',
                  flush=True)
    return cfg, params


def _cache_fingerprint(path: str, dtype: Any) -> Dict[str, Any]:
    """Validity key for the int8 cache: requested dtype + the size/mtime
    of every safetensors shard (a re-exported checkpoint or a different
    compute dtype must invalidate)."""
    files = [(os.path.basename(f), os.path.getsize(f),
              int(os.path.getmtime(f)))
             for f in _safetensor_files(path)]
    return {'dtype': str(jnp.dtype(dtype)), 'files': files}


def _read_cache_meta(cache_file: str) -> Optional[Dict[str, Any]]:
    """The saved fingerprint (for staleness checks)."""
    meta = _read_cache_manifest(cache_file)
    if meta is None:
        return None
    fp = meta['fingerprint']
    fp['files'] = [tuple(e) for e in fp.get('files', [])]
    return fp


def _read_cache_manifest(cache_file: str) -> Optional[Dict[str, Any]]:
    meta_file = cache_file + '.meta.json'
    if not os.path.exists(meta_file):
        return None
    with open(meta_file, encoding='utf-8') as f:
        return json.load(f)


def _flatten_leaves(params: Params, prefix: str = ''):
    from skypilot_tpu.models.quantization import (QuantizedWeight,
                                                  QuantizedWeight4)
    for k, v in params.items():
        if isinstance(v, dict):
            yield from _flatten_leaves(v, f'{prefix}{k}/')
        elif isinstance(v, QuantizedWeight):
            yield f'{prefix}{k}.int8', v.int8
            yield f'{prefix}{k}.scale', v.scale
        elif isinstance(v, QuantizedWeight4):
            yield f'{prefix}{k}.int4', v.packed
            yield f'{prefix}{k}.scale', v.scale
        else:
            yield f'{prefix}{k}', v


def _save_int8_cache(cache_file: str, params: Params,
                     fingerprint: Dict[str, Any]) -> None:
    """Flat binary + JSON manifest: each leaf's raw little-endian
    buffer at a 128-byte-aligned offset. The loader np.memmaps the file
    and hands zero-copy views straight to ``jax.device_put`` — the
    round-4 npz (zip-container) cache decompressed through a single
    thread at ~0.25 GB/s (27.9 s for the 7B int8 tree, which is
    replica scale-up latency). bf16 arrays ride as uint16 with a
    ``view`` tag (numpy has no native bf16). The meta file is written
    LAST so a crashed save never yields a valid-looking cache."""
    align = 128
    manifest = []
    entries = []
    off = 0
    for name, leaf in _flatten_leaves(params):
        a = np.ascontiguousarray(host_sync(leaf))
        view = None
        if a.dtype == jnp.bfloat16:
            a = a.view(np.uint16)
            view = 'bfloat16'
        off = (off + align - 1) // align * align
        manifest.append({'name': name, 'dtype': str(a.dtype),
                         'view': view, 'shape': list(a.shape),
                         'offset': off, 'nbytes': int(a.nbytes)})
        entries.append((off, a))
        off += a.nbytes
    tmp = cache_file + '.tmp'
    with open(tmp, 'wb') as f:
        for o, a in entries:
            f.seek(o)
            a.tofile(f)
    os.replace(tmp, cache_file)
    meta_tmp = cache_file + '.meta.json.tmp'
    with open(meta_tmp, 'w', encoding='utf-8') as f:
        json.dump({'version': 2, 'fingerprint': fingerprint,
                   'manifest': manifest}, f)
    os.replace(meta_tmp, cache_file + '.meta.json')
    # Drop the round-4 zip-container cache (superseded; multi-GB).
    legacy = cache_file[:-len('.bin')] + '.npz'
    for f in (legacy, legacy + '.meta.json'):
        try:
            os.remove(f)
        except OSError:
            pass


def _load_int8_cache(cache_file: str, cfg: ModelConfig) -> Params:
    """Loads int8 AND int4 quantized-tree caches (the leaf class is
    recovered from the ``.int8`` / ``.int4`` name suffix)."""
    from concurrent.futures import ThreadPoolExecutor

    from skypilot_tpu.models.quantization import (QuantizedWeight,
                                                  QuantizedWeight4)
    meta = _read_cache_manifest(cache_file)
    mm = np.memmap(cache_file, dtype=np.uint8, mode='r')

    def fetch(entry):
        raw = mm[entry['offset']:entry['offset'] + entry['nbytes']]
        a = raw.view(np.dtype(entry['dtype'])).reshape(entry['shape'])
        if entry['view'] == 'bfloat16':
            a = a.view(jnp.bfloat16)
        return entry['name'], jnp.asarray(a)

    # Parallel device puts: each leaf streams disk -> page cache ->
    # device independently; the load_workers() pool overlaps the host
    # read with the transfer (the serialized per-leaf put was the
    # other half of the 27.9 s).
    with ThreadPoolExecutor(max_workers=load_workers()) as ex:
        flat = dict(ex.map(fetch, meta['manifest']))
    params: Params = {}
    pending: Dict[str, Dict[str, Any]] = {}
    for name, arr in flat.items():
        parts = name.split('/')
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        leaf = parts[-1]
        if leaf.endswith(('.int8', '.int4', '.scale')):
            base, field = leaf.rsplit('.', 1)
            slot = pending.setdefault(f'{"/".join(parts[:-1])}/{base}',
                                      {'node': node, 'base': base})
            slot[field] = arr
        else:
            node[leaf] = arr
    for slot in pending.values():
        if 'int4' in slot:
            slot['node'][slot['base']] = QuantizedWeight4(
                packed=slot['int4'], scale=slot['scale'])
        else:
            slot['node'][slot['base']] = QuantizedWeight(
                int8=slot['int8'], scale=slot['scale'])
    return params


# ---------------------------------------------------------------- export
def hf_config_dict(cfg: ModelConfig,
                   torch_dtype: str = 'float32') -> Dict[str, Any]:
    """The HF ``config.json`` dict for a ModelConfig — single source for
    the export path and the synthetic-checkpoint generator (must stay
    the exact inverse of ``config_from_hf``)."""
    arch = {'llama': 'LlamaForCausalLM', 'gemma': 'GemmaForCausalLM',
            'mixtral': 'MixtralForCausalLM',
            'qwen2': 'Qwen2ForCausalLM'}
    family = ('mixtral' if cfg.is_moe else
              'gemma' if cfg.norm_plus_one else
              'qwen2' if cfg.qkv_bias else 'llama')
    hf_cfg: Dict[str, Any] = {
        'architectures': [arch[family]],
        'model_type': family,
        'hidden_size': cfg.dim,
        'intermediate_size': cfg.ffn_dim,
        'num_hidden_layers': cfg.n_layers,
        'num_attention_heads': cfg.n_heads,
        'num_key_value_heads': cfg.n_kv_heads,
        'head_dim': cfg.head_dim,
        'vocab_size': cfg.vocab_size,
        'max_position_embeddings': cfg.max_seq_len,
        'rope_theta': cfg.rope_theta,
        'rms_norm_eps': cfg.norm_eps,
        'tie_word_embeddings': cfg.tie_embeddings,
        'torch_dtype': torch_dtype,
    }
    if cfg.is_moe:
        hf_cfg.update(num_local_experts=cfg.n_experts,
                      num_experts_per_tok=cfg.n_experts_per_token)
    if family == 'gemma':
        hf_cfg['hidden_act'] = 'gelu_pytorch_tanh'
    return hf_cfg


def save_hf_checkpoint(path: str, cfg: ModelConfig, params: Params) -> None:
    """Inverse of ``load_hf_params``: write ``config.json`` +
    ``model.safetensors`` in HF layout (used by tests and for handing
    trained weights back to HF-ecosystem tools)."""
    from safetensors.numpy import save_file
    os.makedirs(path, exist_ok=True)
    hd = cfg.head_dim
    out: Dict[str, np.ndarray] = {}

    def np_(a) -> np.ndarray:
        # Must be C-contiguous: the host copy of a TPU-backed jax array
        # can carry non-C strides (np.array keeps order='K'), and
        # safetensors serializes the raw buffer while assuming C order —
        # silently scrambling strided input.
        return np.ascontiguousarray(
            host_sync(jnp.asarray(a, jnp.float32)), dtype=np.float32)

    out['model.embed_tokens.weight'] = np_(params['embed'])
    out['model.norm.weight'] = np_(params['final_norm'])
    if not cfg.tie_embeddings:
        out['lm_head.weight'] = np_(params['unembed']).T
    lp = params['layers']
    for i in range(cfg.n_layers):
        p = f'model.layers.{i}.'
        out[p + 'input_layernorm.weight'] = np_(lp['attn_norm'][i])
        out[p + 'post_attention_layernorm.weight'] = np_(lp['ffn_norm'][i])
        out[p + 'self_attn.q_proj.weight'] = (
            np_(lp['wq'][i]).reshape(cfg.dim, cfg.n_heads * hd).T)
        out[p + 'self_attn.k_proj.weight'] = (
            np_(lp['wk'][i]).reshape(cfg.dim, cfg.n_kv_heads * hd).T)
        out[p + 'self_attn.v_proj.weight'] = (
            np_(lp['wv'][i]).reshape(cfg.dim, cfg.n_kv_heads * hd).T)
        out[p + 'self_attn.o_proj.weight'] = (
            np_(lp['wo'][i]).reshape(cfg.n_heads * hd, cfg.dim).T)
        if cfg.qkv_bias:
            out[p + 'self_attn.q_proj.bias'] = (
                np_(lp['bq'][i]).reshape(cfg.n_heads * hd))
            out[p + 'self_attn.k_proj.bias'] = (
                np_(lp['bk'][i]).reshape(cfg.n_kv_heads * hd))
            out[p + 'self_attn.v_proj.bias'] = (
                np_(lp['bv'][i]).reshape(cfg.n_kv_heads * hd))
        if cfg.is_moe:
            out[p + 'block_sparse_moe.gate.weight'] = np_(lp['router'][i]).T
            for e in range(cfg.n_experts):
                ep = p + f'block_sparse_moe.experts.{e}.'
                out[ep + 'w1.weight'] = np_(lp['moe_gate'][i, e]).T
                out[ep + 'w3.weight'] = np_(lp['moe_up'][i, e]).T
                out[ep + 'w2.weight'] = np_(lp['moe_down'][i, e]).T
        else:
            out[p + 'mlp.gate_proj.weight'] = np_(lp['w_gate'][i]).T
            out[p + 'mlp.up_proj.weight'] = np_(lp['w_up'][i]).T
            out[p + 'mlp.down_proj.weight'] = np_(lp['w_down'][i]).T
    # Transposed views are not C-contiguous; safetensors assumes C order.
    out = {k: np.ascontiguousarray(v) for k, v in out.items()}
    save_file(out, os.path.join(path, 'model.safetensors'))

    with open(os.path.join(path, 'config.json'), 'w',
              encoding='utf-8') as f:
        json.dump(hf_config_dict(cfg, torch_dtype='float32'), f, indent=2)
